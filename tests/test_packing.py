"""Packed-sequence cross-document masking (segment semantics).

The attention mask operand is generalized: nonzero = real token, EQUAL
nonzero values = same document. Plain 0/1 padding masks are the
one-segment special case, so every existing masked path keeps its
behavior; segment ids > 1 make attention block-diagonal-within-causal
and the data modules emit them via ``data.extra.split_documents``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from llmtrain_tpu.models.gpt import GPT, dense_attention
from llmtrain_tpu.ops.blockwise_attention import blockwise_attention
from llmtrain_tpu.ops.pallas_attention import (
    pallas_flash_attention,
    pallas_flash_attention_bwd,
    pallas_flash_attention_fwd,
)


def _qkv(b=2, t=32, h=2, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32) for k in ks)


def _segments(b=2, t=32):
    """Two documents + trailing padding: seg 1 | seg 2 | 0."""
    seg = np.ones((b, t), np.int32)
    seg[:, 14:28] = 2
    seg[:, 28:] = 0
    return jnp.asarray(seg)


class TestSegmentOps:
    def test_dense_isolates_documents(self):
        """Each document's rows equal attention over that document alone."""
        q, k, v = _qkv()
        seg = _segments()
        out = dense_attention(q, k, v, attention_mask=seg)
        doc1 = dense_attention(q[:, :14], k[:, :14], v[:, :14], attention_mask=None)
        doc2 = dense_attention(q[:, 14:28], k[:, 14:28], v[:, 14:28], attention_mask=None)
        np.testing.assert_allclose(np.asarray(out)[:, :14], np.asarray(doc1), atol=1e-5)
        np.testing.assert_allclose(np.asarray(out)[:, 14:28], np.asarray(doc2), atol=1e-5)

    def test_pallas_and_blockwise_match_dense(self):
        q, k, v = _qkv(seed=1)
        seg = _segments()
        ref = dense_attention(q, k, v, attention_mask=seg)
        pal = pallas_flash_attention(q, k, v, seg, block_q=8, block_k=8, interpret=True)
        blk = blockwise_attention(
            q, k, v, causal=True, q_chunk=8, kv_chunk=8,
            key_mask=seg, query_mask=seg,
        )
        live = np.asarray(seg != 0)[:, :, None, None]
        for got in (pal, blk):
            np.testing.assert_allclose(
                np.asarray(got) * live, np.asarray(ref) * live, atol=1e-5
            )

    def test_pallas_bwd_matches_dense_grads(self):
        q, k, v = _qkv(seed=2)
        seg = _segments()
        g = jax.random.normal(jax.random.key(3), q.shape, jnp.float32)
        g = g * (seg != 0)[:, :, None, None].astype(jnp.float32)

        def loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, attention_mask=seg) * g)

        out, lse = pallas_flash_attention_fwd(
            q, k, v, seg, block_q=8, block_k=8, interpret=True
        )
        dq, dk, dv = pallas_flash_attention_bwd(
            q, k, v, out, lse, g, seg, block_q=8, block_k=8, interpret=True
        )
        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=1e-4)

    def test_zero_one_masks_unchanged(self):
        """Plain padding masks (the one-segment case) keep their exact
        pre-segment behavior on real-query rows."""
        q, k, v = _qkv(seed=4)
        mask = jnp.asarray(
            np.concatenate([np.ones((2, 20), np.int32), np.zeros((2, 12), np.int32)], 1)
        )
        out = dense_attention(q, k, v, attention_mask=mask)
        # Key-only reference (the old semantics) on real rows.
        big = jnp.finfo(jnp.float32).min
        import math

        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(q.shape[-1])
        t = q.shape[1]
        causal = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(causal[None, None], s.astype(jnp.float32), big)
        s = jnp.where((mask != 0)[:, None, None, :], s, big)
        ref = jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(s, -1).astype(q.dtype), v
        )
        np.testing.assert_allclose(
            np.asarray(out)[:, :20], np.asarray(ref)[:, :20], atol=1e-5
        )


class TestSegmentModel:
    def test_doc_b_logits_independent_of_doc_a(self):
        """Perturbing document A's tokens must not change document B's
        logits when the mask carries segments — and must change them
        under a plain all-ones mask."""
        m = GPT(vocab_size=64, block_size=16, d_model=32, n_layers=2,
                n_heads=4, d_ff=64, dropout=0.0, attention="flash")
        from flax.linen import meta as nn_meta

        p = nn_meta.unbox(
            m.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32),
                   deterministic=True)["params"]
        )
        seg = jnp.asarray([[1] * 8 + [2] * 8])
        a = jnp.asarray([np.r_[np.arange(1, 9), np.arange(20, 28)]]).astype(jnp.int32)
        b = a.at[0, 2].set(44)  # perturb doc A only
        la = m.apply({"params": p}, a, attention_mask=seg, deterministic=True)
        lb = m.apply({"params": p}, b, attention_mask=seg, deterministic=True)
        np.testing.assert_allclose(
            np.asarray(la)[:, 8:], np.asarray(lb)[:, 8:], atol=1e-5
        )
        ones = jnp.ones_like(seg)
        fa = m.apply({"params": p}, a, attention_mask=ones, deterministic=True)
        fb = m.apply({"params": p}, b, attention_mask=ones, deterministic=True)
        assert np.abs(np.asarray(fa)[:, 8:] - np.asarray(fb)[:, 8:]).max() > 1e-4

    def test_boundary_positions_are_loss_masked(self):
        """The loss ignores positions whose label is the next document's
        first token (mask 0 there, boolean loss weights)."""
        from llmtrain_tpu.models.base import masked_ce_components

        logits = jax.random.normal(jax.random.key(5), (1, 6, 16))
        labels = jnp.zeros((1, 6), jnp.int32)
        mask = jnp.asarray([[1, 1, 0, 2, 2, 2]])  # boundary at position 2
        loss_sum, tokens = masked_ce_components(logits, labels, mask)
        assert float(tokens[0]) == 5.0  # boolean count, not 1+1+0+2+2+2


class TestSplitDocumentsData:
    def test_window_dataset_emits_segments_and_boundary_zeros(self):
        from llmtrain_tpu.data.hf_text import TokenWindowDataset

        tokens = np.arange(20, dtype=np.int32)
        # Docs: [0..6), [6..15), [15..20) — window 0 covers 0..8 (chunk 9).
        ds = TokenWindowDataset(
            tokens, block_size=8, doc_starts=np.asarray([0, 6, 15]),
            split_documents=True,
        )
        ex = ds.get_examples(np.asarray([0]))
        # Positions 0..7: docs 1,1,1,1,1,1,2,2; labels are positions 1..8.
        # Boundary at position 5 (label = position 6 = doc 2) -> 0.
        assert ex["attention_mask"][0].tolist() == [1, 1, 1, 1, 1, 0, 2, 2]
        assert ex["input_ids"][0].tolist() == list(range(8))

    def test_local_text_split_documents_end_to_end(self, tmp_path):
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.data.local_text import LocalTextDataModule
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        corpus = tmp_path / "corpus"
        corpus.mkdir()
        (corpus / "a.txt").write_text("a" * 30)
        (corpus / "b.txt").write_text("b" * 30)
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "pk", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt", "block_size": 16, "d_model": 32,
                    "n_layers": 1, "n_heads": 2, "d_ff": 64, "dropout": 0.0,
                    "vocab_size": 257,
                },
                "data": {
                    "name": "local_text",
                    "cache_dir": str(tmp_path / "cache"),
                    "extra": {
                        "globs": [str(corpus / "*.txt")],
                        "val_fraction": 0.0,
                        "split_documents": True,
                    },
                },
                "trainer": {"max_steps": 2, "micro_batch_size": 1,
                            "warmup_steps": 0},
                "mlflow": {"enabled": False},
            }
        )
        dm = LocalTextDataModule()
        dm.setup(cfg, ByteTokenizer())
        ex = dm.train_dataset().get_examples(np.asarray([1]))
        mask = ex["attention_mask"][0]
        # Window 1 covers positions 17..33: doc a (0..31 incl. separator)
        # then doc b — two distinct nonzero segments with one boundary 0.
        vals = set(mask.tolist())
        assert 0 in vals and len(vals - {0}) == 2

    def test_jsonl_records_are_separate_documents(self, tmp_path):
        """split_documents boundaries are per JSON record, not per file —
        two records in ONE file must land in different segments."""
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.data.local_text import LocalTextDataModule
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        corpus = tmp_path / "corpus"
        corpus.mkdir()
        (corpus / "data.jsonl").write_text(
            '{"text": "' + "x" * 20 + '"}\n{"text": "' + "y" * 20 + '"}\n'
        )
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "jl", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt", "block_size": 30, "d_model": 32,
                    "n_layers": 1, "n_heads": 2, "d_ff": 64, "dropout": 0.0,
                    "vocab_size": 257,
                },
                "data": {
                    "name": "local_text",
                    "cache_dir": str(tmp_path / "cache"),
                    "extra": {
                        "globs": [str(corpus / "*.jsonl")],
                        "val_fraction": 0.0,
                        "format": "jsonl",
                        "split_documents": True,
                    },
                },
                "trainer": {"max_steps": 1, "micro_batch_size": 1,
                            "warmup_steps": 0},
                "mlflow": {"enabled": False},
            }
        )
        dm = LocalTextDataModule()
        dm.setup(cfg, ByteTokenizer())
        mask = dm.train_dataset().get_examples(np.asarray([0]))["attention_mask"][0]
        # Window 0 spans both records: two distinct nonzero segment ids.
        assert len(set(mask.tolist()) - {0}) == 2

    def test_split_documents_validation(self, tmp_path):
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.data.base import validate_split_documents as _validate_split_documents

        def cfg(**model_extra_or_attention):
            attention = model_extra_or_attention.pop("attention", "flash")
            return RunConfig.model_validate(
                {
                    "run": {"name": "x", "seed": 0, "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 16, "d_model": 32,
                        "n_layers": 1, "n_heads": 2, "d_ff": 64,
                        "dropout": 0.0, "vocab_size": 64,
                        "attention": attention,
                        "extra": model_extra_or_attention,
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 1,
                                "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                }
            )

        # ring/ulysses are supported (segment masks ride both SP
        # schemes); only assume_packed conflicts.
        _validate_split_documents(cfg(attention="ring"))
        _validate_split_documents(cfg(attention="ulysses"))
        with pytest.raises(ValueError, match="assume_packed"):
            _validate_split_documents(cfg(assume_packed=True))
        _validate_split_documents(cfg())  # flash: fine


class TestTrainerEndToEnd:
    def test_training_runs_with_split_documents(self, tmp_path):
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        corpus = tmp_path / "corpus"
        corpus.mkdir()
        for i, ch in enumerate("abcd"):
            (corpus / f"{ch}.txt").write_text(ch * 120)
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "pk-train", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt", "block_size": 32, "d_model": 32,
                    "n_layers": 2, "n_heads": 4, "d_ff": 64, "dropout": 0.0,
                    "vocab_size": 257, "attention": "flash",
                    "extra": {"tokenizer": "byte"},
                },
                "data": {
                    "name": "local_text",
                    "cache_dir": str(tmp_path / "cache"),
                    "extra": {
                        "globs": [str(corpus / "*.txt")],
                        "val_fraction": 0.2,
                        "split_documents": True,
                    },
                },
                "trainer": {
                    "max_steps": 8, "micro_batch_size": 2,
                    "grad_accum_steps": 1, "lr": 5e-3, "warmup_steps": 0,
                    "log_every_steps": 4, "eval_every_steps": 8,
                    "save_every_steps": 100,
                },
                "mlflow": {"enabled": False},
            }
        )
        initialize_registries()
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert np.isfinite(res.final_loss)
        assert res.final_loss < res.first_step_loss
