"""Trainer behavior tests (parity with reference tests/test_trainer.py):
loss decreases, exact LR schedule values, log cadence, tracker contract,
per-rank (per-data-shard) metric naming."""

import math
from unittest.mock import Mock

import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig, TrainerConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import Trainer, lr_schedule


def _cfg(**overrides):
    base = {
        "run": {"name": "t", "seed": 3},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 64,
            "n_heads": 2,
            "d_ff": 128,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 30,
            "micro_batch_size": 2,
            "grad_accum_steps": 2,
            "lr": 3e-3,
            "warmup_steps": 0,
            "log_every_steps": 10,
            "eval_every_steps": 15,
            "save_every_steps": 10,
        },
        "mlflow": {"enabled": False},
    }
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


class TestLossDecreases:
    def test_dummy_model(self):
        trainer = Trainer(_cfg(), None, NullTracker(), None)
        res = trainer.fit()
        assert res.first_step_loss is not None
        assert res.final_loss < res.first_step_loss
        assert np.isfinite(res.final_loss)
        assert res.final_val_loss is not None and np.isfinite(res.final_val_loss)

    def test_real_gpt(self):
        cfg = _cfg(
            model={
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 32,
                "d_model": 32,
                "n_heads": 4,
                "d_ff": 64,
                "n_layers": 2,
                "dropout": 0.0,
            },
            trainer={"max_steps": 40, "lr": 1e-2},
        )
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.final_loss < res.first_step_loss

    def test_grad_accum_consumes_distinct_batches(self):
        """total_tokens reflects accum * global_micro * seq per step."""
        cfg = _cfg(trainer={"max_steps": 4, "grad_accum_steps": 3})
        trainer = Trainer(cfg, None, NullTracker(), None)
        res = trainer.fit()
        assert res.total_tokens == 4 * 3 * (2 * 8) * 8  # steps*accum*(micro*dp)*seq


class TestAdafactor:
    """trainer.extra.optimizer: adafactor — factored second moment."""

    def test_loss_decreases(self):
        cfg = _cfg(trainer={"max_steps": 20, "lr": 1e-2,
                            "extra": {"optimizer": "adafactor"}})
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.final_loss < res.first_step_loss

    def test_real_gpt_with_boxed_metadata_on_fsdp_mesh(self):
        """The REAL gpt carries logical-axis boxes: adafactor's factored
        v_row/v_col inherit full-param specs through them, which must be
        repaired to replicated (parallel/sharding.py) — this exact config
        crashed pjit before the repair (r4; the dummy model's metadata-
        free tree couldn't catch it)."""
        cfg = _cfg(
            model={
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 32,
                "d_model": 32,
                "n_heads": 4,
                "d_ff": 64,
                "n_layers": 1,
                "dropout": 0.0,
            },
            trainer={"max_steps": 2, "extra": {"optimizer": "adafactor"}},
            distributed={"mesh": {"data": 2, "fsdp": 2, "tensor": 2}},
        )
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert np.isfinite(res.final_loss)

    def test_state_is_factored(self):
        """For an (n, m) matrix the second moment must be stored as
        row+column vectors (O(n+m)), vs AdamW's two full (n, m) moments."""
        import jax
        import jax.numpy as jnp

        from llmtrain_tpu.config.schemas import TrainerConfig
        from llmtrain_tpu.training.optimizer import build_optimizer

        params = {"w": jnp.zeros((256, 512))}

        def state_size(extra):
            tx = build_optimizer(TrainerConfig(max_steps=10, warmup_steps=0, extra=extra))
            state = tx.init(params)
            return sum(
                int(np.prod(np.shape(leaf)))
                for leaf in jax.tree.leaves(state)
                if hasattr(leaf, "shape")
            )

        adamw = state_size({})
        adafactor = state_size({"optimizer": "adafactor"})
        assert adamw >= 2 * 256 * 512  # two dense moments
        assert adafactor < 256 * 512  # factored: ~n+m per matrix

    def test_lion_loss_decreases_with_half_the_state(self):
        """trainer.extra.optimizer: lion — sign-momentum, one moment."""
        import jax
        import jax.numpy as jnp

        from llmtrain_tpu.config.schemas import TrainerConfig
        from llmtrain_tpu.training.optimizer import build_optimizer

        cfg = _cfg(trainer={"max_steps": 20, "lr": 1e-3,
                            "extra": {"optimizer": "lion"}})
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.final_loss < res.first_step_loss

        params = {"w": jnp.zeros((256, 512))}

        def state_size(extra):
            tx = build_optimizer(
                TrainerConfig(max_steps=10, warmup_steps=0, extra=extra)
            )
            return sum(
                int(np.prod(np.shape(leaf)))
                for leaf in jax.tree.leaves(tx.init(params))
                if hasattr(leaf, "shape")
            )

        # One moment vs AdamW's two.
        assert state_size({"optimizer": "lion"}) <= state_size({}) - 256 * 512

    def test_resume_matches_continuous(self, tmp_path):
        """The factored optimizer state survives checkpoint save/resume
        with the flagship guarantee: 20 straight == 10 + resume 10."""
        cfg = _cfg(
            trainer={"max_steps": 20, "save_every_steps": 10,
                     "extra": {"optimizer": "adafactor"}},
        )
        run_a = tmp_path / "cont"
        run_a.mkdir()
        res_full = Trainer(cfg, run_a, NullTracker(), None).fit()

        run_b = tmp_path / "resumed"
        run_b.mkdir()
        Trainer(cfg, run_b, NullTracker(), None).fit(max_steps_override=10)
        res_resumed = Trainer(cfg, run_b, NullTracker(), None).fit(
            resume_from=str(run_b / "checkpoints" / "step_000010.ckpt")
        )
        assert res_resumed.resumed_from_step == 10
        assert res_resumed.final_loss == pytest.approx(
            res_full.final_loss, abs=1e-5
        )

    def test_decay_is_lr_scaled(self):
        """Decoupled decay must scale with the SCHEDULED lr (AdamW
        semantics): at warmup start (lr=0) zero grads produce zero
        updates — optax.adafactor's own weight_decay_rate would emit
        -wd*param (10%/step at the schema default) regardless of lr."""
        import jax.numpy as jnp

        from llmtrain_tpu.config.schemas import TrainerConfig
        from llmtrain_tpu.training.optimizer import build_optimizer

        tx = build_optimizer(
            TrainerConfig(max_steps=10, warmup_steps=5, lr=1.0,
                          weight_decay=0.1, extra={"optimizer": "adafactor"})
        )
        params = {"w": jnp.ones((4, 4))}
        state = tx.init(params)
        updates, _ = tx.update({"w": jnp.zeros((4, 4))}, state, params)
        assert float(np.abs(np.asarray(updates["w"])).max()) < 1e-9

    def test_sharding_repair_paths(self):
        """Factored/placeholder moments replicate; a full-rank param with
        a non-divisible dim also falls back to replicated — WITH a
        one-time warning naming the leaf (tests/test_zero.py pins the
        warning; it used to fail at jit time with an opaque pjit error,
        which broke indivisible opt-state leaves under trainer.zero)."""
        import jax
        import jax.numpy as jnp
        from flax import linen as nn
        from jax.sharding import Mesh, PartitionSpec as P

        from llmtrain_tpu.parallel.sharding import state_shardings

        mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(2, 2, 2, 1, 1, 1),
                    ("data", "fsdp", "tensor", "sequence", "pipeline", "expert"))
        box = nn.Partitioned  # flax metadata box
        tree = {
            "placeholder": box(jnp.zeros((1,)), names=("embed",)),
            "reduced": box(jnp.zeros((8,)), names=("embed", "mlp")),
            "nondivisible": box(jnp.zeros((5, 8)), names=("embed", "mlp")),
            "divisible": box(jnp.zeros((4, 8)), names=("embed", "mlp")),
        }
        sh = state_shardings(mesh, tree)
        assert sh["placeholder"].spec == P()   # replicated
        assert sh["reduced"].spec == P()       # rank mismatch → replicated
        assert sh["nondivisible"].spec == P()  # repaired (warned) → replicated
        assert sh["divisible"].spec == P("fsdp", "tensor")  # kept

    def test_shape_one_param_with_satisfiable_spec_keeps_it(self):
        """ADVICE r4: the (1,)-leaf repair replicates ONLY unsatisfiable
        specs (adafactor placeholders carrying an 'embed' spec on a mesh
        where fsdp>1); a genuine (1,) param whose mapped axes are size 1
        keeps its logical sharding instead of silently losing it."""
        import jax
        import jax.numpy as jnp
        from flax import linen as nn
        from jax.sharding import Mesh, PartitionSpec as P

        from llmtrain_tpu.parallel.sharding import state_shardings

        # fsdp=1 here: an "embed"→fsdp spec on a (1,) param IS satisfiable.
        mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(8, 1, 1, 1, 1, 1),
                    ("data", "fsdp", "tensor", "sequence", "pipeline", "expert"))
        box = nn.Partitioned
        tree = {"tiny": box(jnp.zeros((1,)), names=("embed",))}
        sh = state_shardings(mesh, tree)
        assert sh["tiny"].spec == P("fsdp")  # kept, not silently replicated

    def test_unknown_optimizer_rejected(self):
        from llmtrain_tpu.config.schemas import TrainerConfig
        from llmtrain_tpu.training.optimizer import build_optimizer

        with pytest.raises(ValueError, match="optimizer"):
            build_optimizer(
                TrainerConfig(max_steps=10, warmup_steps=0, extra={"optimizer": "sgd"})
            )


class TestLRSchedule:
    def test_exact_values(self):
        cfg = TrainerConfig(max_steps=100, warmup_steps=10, lr=1.0)
        sched = lr_schedule(cfg)
        # optimizer step N (1-indexed) uses count N-1
        assert float(sched(0)) == pytest.approx(0.0)  # first step, warmup start
        assert float(sched(5)) == pytest.approx(0.5)  # mid-warmup
        assert float(sched(10)) == pytest.approx(1.0)  # warmup end
        mid = 10 + (100 - 10) / 2
        assert float(sched(mid)) == pytest.approx(0.5)  # cosine midpoint
        assert float(sched(100)) == pytest.approx(0.0, abs=1e-6)  # decayed to 0

    def test_no_warmup(self):
        sched = lr_schedule(TrainerConfig(max_steps=10, warmup_steps=0, lr=2.0))
        assert float(sched(0)) == pytest.approx(2.0)

    def test_warmup_equals_max(self):
        sched = lr_schedule(TrainerConfig(max_steps=10, warmup_steps=10, lr=1.0))
        assert float(sched(10)) == pytest.approx(1.0)
        assert float(sched(5)) == pytest.approx(0.5)


class TestLoggingCadence:
    def _tracked_steps(self, tracker, prefix="train/loss"):
        steps = []
        for call in tracker.log_metrics.call_args_list:
            metrics = call.args[0] if call.args else call.kwargs["metrics"]
            if prefix in metrics:
                steps.append(call.kwargs.get("step"))
        return steps

    def test_log_every_and_final(self):
        tracker = Mock()
        cfg = _cfg(trainer={"max_steps": 25, "log_every_steps": 10, "eval_every_steps": 100})
        # eval_every > max_steps would break the <= validator? no such validator; fine
        Trainer(cfg, None, tracker, None).fit()
        assert self._tracked_steps(tracker) == [10, 20, 25]

    def test_per_rank_metrics_present(self):
        tracker = Mock()
        cfg = _cfg(trainer={"max_steps": 10, "log_every_steps": 10, "eval_every_steps": 10})
        Trainer(cfg, None, tracker, None).fit()
        all_keys = set()
        for call in tracker.log_metrics.call_args_list:
            metrics = call.args[0] if call.args else call.kwargs["metrics"]
            all_keys.update(metrics)
        # 8 virtual devices -> 8 data shards ("ranks")
        assert "train/loss_rank_0" in all_keys
        assert "train/loss_rank_7" in all_keys
        assert "val/loss_rank_0" in all_keys
        assert "train/loss" in all_keys and "val/loss" in all_keys
        assert "train/tokens_per_sec" in all_keys
        assert "train/step_time_sec" in all_keys
        assert "train/tokens_total" in all_keys
        assert "train/lr" in all_keys
        assert "train/mfu" in all_keys

    def test_mfu_metric_positive_and_finite(self):
        tracker = Mock()
        cfg = _cfg(trainer={"max_steps": 10, "log_every_steps": 10})
        Trainer(cfg, None, tracker, None).fit()
        mfus = []
        for call in tracker.log_metrics.call_args_list:
            metrics = call.args[0] if call.args else call.kwargs["metrics"]
            if "train/mfu" in metrics:
                mfus.append(metrics["train/mfu"])
        assert mfus and all(math.isfinite(m) and m > 0 for m in mfus)

    def test_params_logged_once(self):
        tracker = Mock()
        Trainer(_cfg(trainer={"max_steps": 2}), None, tracker, None).fit()
        assert tracker.log_params.call_count == 1
        logged = tracker.log_params.call_args.args[0]
        assert logged["model"]["name"] == "dummy_gpt"

    def test_shard_losses_are_per_shard(self):
        """Per-rank losses differ across shards (different data)."""
        tracker = Mock()
        cfg = _cfg(trainer={"max_steps": 10, "log_every_steps": 10})
        Trainer(cfg, None, tracker, None).fit()
        rank_losses = {}
        for call in tracker.log_metrics.call_args_list:
            metrics = call.args[0] if call.args else call.kwargs["metrics"]
            for k, v in metrics.items():
                if k.startswith("train/loss_rank_"):
                    rank_losses[k] = v
        assert len(rank_losses) == 8
        assert len({round(v, 9) for v in rank_losses.values()}) > 1


class TestValEval:
    def test_token_weighted_val_loss_finite(self):
        cfg = _cfg(trainer={"max_steps": 15, "eval_every_steps": 5})
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.val_metrics is not None
        assert np.isfinite(res.val_metrics["val/loss"])


class TestRingRematTrainer:
    def test_ring_attention_with_remat_trains(self):
        """gpt_longctx_ring.yaml's feature combination (ring attention +
        remat + sequence-parallel mesh) runs end-to-end; regression for the
        param-init batch=1 shard_map failure in ring_or_blockwise."""
        cfg = _cfg(
            model={
                "name": "gpt",
                "d_model": 16,
                "n_heads": 4,
                "d_ff": 32,
                "attention": "ring",
                "remat": True,
            },
            trainer={"max_steps": 3, "micro_batch_size": 4, "log_every_steps": 3,
                     "eval_every_steps": 3},
        )
        cfg = cfg.model_copy(
            update={
                "distributed": cfg.distributed.model_copy(
                    update={
                        "mesh": cfg.distributed.mesh.model_copy(
                            update={"data": 4, "sequence": 2}
                        )
                    }
                )
            }
        )
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert math.isfinite(res.final_loss) and res.final_step == 3


class TestProfiler:
    def test_profile_window_writes_trace(self, tmp_path):
        run_dir = tmp_path / "run"
        (run_dir / "logs").mkdir(parents=True)
        cfg = _cfg(
            trainer={
                "max_steps": 5,
                "extra": {"profile_start_step": 2, "profile_num_steps": 2},
            }
        )
        Trainer(cfg, run_dir, NullTracker(), None).fit()
        profile_dir = run_dir / "logs" / "profile"
        assert profile_dir.is_dir()
        assert any(profile_dir.rglob("*"))  # xplane trace files written

    def test_profiler_disabled_by_default(self, tmp_path):
        run_dir = tmp_path / "run"
        (run_dir / "logs").mkdir(parents=True)
        cfg = _cfg(trainer={"max_steps": 3})
        Trainer(cfg, run_dir, NullTracker(), None).fit()
        assert not (run_dir / "logs" / "profile").exists()

    @pytest.mark.slow  # ~10s: edge case of the window lifecycle; the
    # main trace-writing contract stays tier-1 via
    # test_profile_window_writes_trace.
    def test_profile_window_past_max_steps_still_closes(self, tmp_path):
        """Window extends past the end of training: close() must stop the trace."""
        run_dir = tmp_path / "run"
        (run_dir / "logs").mkdir(parents=True)
        cfg = _cfg(
            trainer={
                "max_steps": 3,
                "extra": {"profile_start_step": 2, "profile_num_steps": 100},
            }
        )
        Trainer(cfg, run_dir, NullTracker(), None).fit()
        assert (run_dir / "logs" / "profile").is_dir()
        # A second run must be able to start a fresh trace (no dangling session).
        cfg2 = _cfg(
            trainer={
                "max_steps": 3,
                "extra": {"profile_start_step": 1, "profile_num_steps": 1},
            }
        )
        run_dir2 = tmp_path / "run2"
        (run_dir2 / "logs").mkdir(parents=True)
        Trainer(cfg2, run_dir2, NullTracker(), None).fit()
        assert any((run_dir2 / "logs" / "profile").rglob("*"))
