"""Real-MLflow SQLite round-trip (parity with reference tests/test_cli.py:628-704).

A full CLI train against a ``sqlite:///`` tracking URI, then the runs,
params, metrics, and artifacts queried back via ``MlflowClient``, asserting
the ``llmtrain.run_id`` tag. Plus the crash-restart story: an
``--auto-resume`` relaunch with the same stable run id must CONTINUE the
original MLflow run (join by tag), not open a second one.

Skips when the optional mlflow extra is not installed (this image ships
without it); runs for real wherever ``pip install .[mlflow]`` happened —
e.g. the k8s image (k8s/Dockerfile).
"""

import json
import os
import subprocess
import sys

import pytest
import yaml

mlflow = pytest.importorskip("mlflow")

from mlflow.tracking import MlflowClient  # noqa: E402

pytestmark = pytest.mark.slow

CFG = {
    "schema_version": 1,
    "run": {"name": "mlflow-rt", "seed": 11, "device": "cpu", "deterministic": True},
    "model": {
        "name": "dummy_gpt",
        "block_size": 8,
        "d_model": 48,
        "n_layers": 1,
        "n_heads": 2,
        "d_ff": 96,
        "dropout": 0.0,
        "vocab_size": 32,
    },
    "data": {"name": "dummy_text"},
    "trainer": {
        "max_steps": 6,
        "micro_batch_size": 2,
        "grad_accum_steps": 1,
        "lr": 0.003,
        "warmup_steps": 0,
        "log_every_steps": 3,
        "eval_every_steps": 3,
        "save_every_steps": 3,
    },
    "logging": {"level": "INFO", "json_output": True, "log_to_file": True},
    "output": {"root_dir": "runs"},
}


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=420,
    )


@pytest.fixture()
def workdir(tmp_path):
    db = tmp_path / "mlflow.db"
    cfg = {
        **CFG,
        "mlflow": {
            "enabled": True,
            "tracking_uri": f"sqlite:///{db}",
            "experiment": "rt-exp",
        },
    }
    (tmp_path / "config.yaml").write_text(yaml.safe_dump(cfg))
    return tmp_path


class TestMLflowRoundTrip:
    def test_train_then_query_back(self, workdir):
        proc = _run_cli(
            ["train", "--config", "config.yaml", "--json", "--run-id", "rt1"], workdir
        )
        assert proc.returncode == 0, proc.stderr
        summary = json.loads(proc.stdout)
        assert summary["train_result"]["final_step"] == 6

        client = MlflowClient(tracking_uri=f"sqlite:///{workdir / 'mlflow.db'}")
        experiment = client.get_experiment_by_name("rt-exp")
        assert experiment is not None
        runs = client.search_runs([experiment.experiment_id])
        assert len(runs) == 1
        run = runs[0]

        assert run.data.tags["llmtrain.run_id"] == "rt1"
        assert run.data.params["model.d_model"] == "48"
        assert run.data.params["trainer.max_steps"] == "6"
        assert "train/loss" in run.data.metrics
        assert "val/loss" in run.data.metrics
        history = client.get_metric_history(run.info.run_id, "train/loss")
        assert [m.step for m in history] == [3, 6]

        artifacts = {a.path for a in client.list_artifacts(run.info.run_id)}
        assert "config.yaml" in artifacts
        assert "meta.json" in artifacts
        assert run.info.status == "FINISHED"

    def test_auto_resume_continues_same_mlflow_run(self, workdir):
        first = _run_cli(
            [
                "train", "--config", "config.yaml", "--json",
                "--run-id", "rt2", "--auto-resume",
            ],
            workdir,
        )
        assert first.returncode == 0, first.stderr
        second = _run_cli(
            [
                "train", "--config", "config.yaml", "--json",
                "--run-id", "rt2", "--auto-resume",
            ],
            workdir,
        )
        assert second.returncode == 0, second.stderr

        client = MlflowClient(tracking_uri=f"sqlite:///{workdir / 'mlflow.db'}")
        experiment = client.get_experiment_by_name("rt-exp")
        runs = client.search_runs([experiment.experiment_id])
        # The relaunch joined the original run via the llmtrain.run_id tag.
        assert len(runs) == 1
        assert runs[0].data.tags["llmtrain.run_id"] == "rt2"
