"""Native TensorBoard event-file backend (tracking/tensorboard.py).

Beyond-reference tracking backend. The writer is hand-rolled (TFRecord
framing + protobuf wire format, zero deps); these tests verify it two
ways — a standalone TFRecord/proto parser that checks the CRC math
bit-for-bit, and the REAL ``tensorboard`` reader when the package is
installed (it is in this image), which is the interoperability proof.
"""

from __future__ import annotations

import struct

import pytest

from llmtrain_tpu.config.schemas import MLflowConfig
from llmtrain_tpu.tracking import TensorBoardTracker, build_tracker
from llmtrain_tpu.tracking.tensorboard import (
    _crc32c,
    _masked_crc,
    resolve_logdir,
)


def _read_records(path):
    """Standalone TFRecord parser verifying both CRCs of every record."""
    records = []
    data = path.read_bytes()
    off = 0
    while off < len(data):
        (length,) = struct.unpack_from("<Q", data, off)
        (len_crc,) = struct.unpack_from("<I", data, off + 8)
        assert len_crc == _masked_crc(data[off : off + 8]), "length CRC mismatch"
        payload = data[off + 12 : off + 12 + length]
        (crc,) = struct.unpack_from("<I", data, off + 12 + length)
        assert crc == _masked_crc(payload), "payload CRC mismatch"
        records.append(payload)
        off += 12 + length + 4
    return records


def _parse_scalars(records):
    """Minimal Event/Summary decoder for simple_value scalars."""
    out = []
    for rec in records:
        step, scalars = 0, []
        i = 0
        while i < len(rec):
            key = rec[i]
            field, wire = key >> 3, key & 7
            i += 1
            if wire == 0:
                v = 0
                shift = 0
                while True:
                    b = rec[i]
                    i += 1
                    v |= (b & 0x7F) << shift
                    shift += 7
                    if not b & 0x80:
                        break
                if field == 2:
                    step = v
            elif wire == 1:
                i += 8
            elif wire == 5:
                i += 4
            elif wire == 2:
                ln = 0
                shift = 0
                while True:
                    b = rec[i]
                    i += 1
                    ln |= (b & 0x7F) << shift
                    shift += 7
                    if not b & 0x80:
                        break
                if field == 5:  # summary
                    scalars.extend(_parse_summary(rec[i : i + ln]))
                i += ln
            else:  # pragma: no cover - unknown wire type
                raise AssertionError(f"wire type {wire}")
        for tag, val in scalars:
            out.append((step, tag, val))
    return out


def _parse_summary(buf):
    vals = []
    i = 0
    while i < len(buf):
        key = buf[i]
        i += 1
        ln = 0
        shift = 0
        while True:
            b = buf[i]
            i += 1
            ln |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        if key >> 3 == 1:  # Summary.value
            val = buf[i : i + ln]
            tag, simple = None, None
            j = 0
            while j < len(val):
                k = val[j]
                f, w = k >> 3, k & 7
                j += 1
                if w == 2:
                    vln = 0
                    shift = 0
                    while True:
                        b = val[j]
                        j += 1
                        vln |= (b & 0x7F) << shift
                        shift += 7
                        if not b & 0x80:
                            break
                    if f == 1:
                        tag = val[j : j + vln].decode()
                    j += vln
                elif w == 5:
                    if f == 2:
                        (simple,) = struct.unpack_from("<f", val, j)
                    j += 4
                elif w == 1:
                    j += 8
                elif w == 0:
                    while val[j] & 0x80:
                        j += 1
                    j += 1
            if tag is not None and simple is not None:
                vals.append((tag, simple))
        i += ln
    return vals


def _event_file(run_dir):
    files = list(run_dir.glob("events.out.tfevents.*"))
    assert len(files) == 1
    return files[0]


class TestWireFormat:
    def test_crc32c_test_vector(self):
        # The canonical Castagnoli check value.
        assert _crc32c(b"123456789") == 0xE3069283

    def test_records_carry_valid_crcs_and_version_header(self, tmp_path):
        t = TensorBoardTracker(str(tmp_path), "exp", run_name="r1")
        t.start_run("r1")
        t.log_metrics({"train/loss": 2.5}, step=1)
        t.end_run()
        records = _read_records(_event_file(tmp_path / "exp" / "r1"))
        assert len(records) == 2
        assert b"brain.Event:2" in records[0]

    def test_scalars_roundtrip_through_standalone_parser(self, tmp_path):
        t = TensorBoardTracker(str(tmp_path), "exp", run_name="r2")
        t.start_run("r2")
        t.log_metrics({"train/loss": 2.5, "train/lr": 1e-3}, step=7)
        t.log_metrics({"val/loss": 3.25}, step=10)
        t.end_run()
        rows = _parse_scalars(_read_records(_event_file(tmp_path / "exp" / "r2")))
        assert (7, "train/loss", 2.5) in rows
        assert (10, "val/loss", 3.25) in rows
        lr = [r for r in rows if r[1] == "train/lr"]
        assert lr and abs(lr[0][2] - 1e-3) < 1e-9


class TestRealTensorBoardReader:
    """Interop proof: the installed tensorboard package reads our files."""

    def _accumulate(self, run_dir):
        ea_mod = pytest.importorskip(
            "tensorboard.backend.event_processing.event_accumulator"
        )
        acc = ea_mod.EventAccumulator(str(run_dir))
        acc.Reload()
        return acc

    def test_scalars_visible_to_tensorboard(self, tmp_path):
        t = TensorBoardTracker(str(tmp_path), "exp", run_name="run")
        t.start_run("run")
        for step in (1, 2, 3):
            t.log_metrics({"train/loss": 4.0 - step}, step=step)
        t.end_run()
        acc = self._accumulate(tmp_path / "exp" / "run")
        assert "train/loss" in acc.Tags()["scalars"]
        events = acc.Scalars("train/loss")
        assert [e.step for e in events] == [1, 2, 3]
        assert [round(e.value, 5) for e in events] == [3.0, 2.0, 1.0]

    def test_params_and_artifacts_visible_as_text(self, tmp_path):
        t = TensorBoardTracker(str(tmp_path), "exp", run_name="run2")
        t.start_run("run2")
        t.log_params({"model.name": "gpt", "trainer.lr": 0.001})
        t.log_artifact("/runs/x/summary.txt", "summary.txt")
        t.end_run()
        acc = self._accumulate(tmp_path / "exp" / "run2")
        tags = acc.Tags()["tensors"]
        assert any(tag.startswith("params/config") for tag in tags)
        assert any(tag.startswith("artifacts/summary.txt") for tag in tags)
        [params_tag] = [tag for tag in tags if tag.startswith("params/config")]
        payload = acc.Tensors(params_tag)[0].tensor_proto.string_val[0]
        assert b"model.name" in payload and b"gpt" in payload


class TestBackendSelection:
    def test_build_tracker_tensorboard(self, tmp_path):
        cfg = MLflowConfig(
            enabled=True,
            tracking_uri=str(tmp_path / "tb"),
            experiment="e",
            backend="tensorboard",
        )
        tracker = build_tracker(cfg, "rid")
        assert isinstance(tracker, TensorBoardTracker)

    def test_resolve_logdir_strips_file_scheme(self):
        assert str(resolve_logdir("file:./tb")) == "tb"
        assert str(resolve_logdir("./tb")) == "tb"

    def test_trainer_end_to_end(self, tmp_path):
        """A real (tiny) training run tracked straight into event files."""
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.training import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "tbrun", "seed": 0},
                "model": {
                    "name": "dummy_gpt",
                    "block_size": 8,
                    "vocab_size": 32,
                    "dropout": 0.0,
                    "d_model": 32,
                    "n_heads": 2,
                    "d_ff": 64,
                    "n_layers": 1,
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 6,
                    "micro_batch_size": 2,
                    "grad_accum_steps": 1,
                    "warmup_steps": 0,
                    "log_every_steps": 3,
                    "eval_every_steps": 6,
                    "save_every_steps": 6,
                },
                "mlflow": {
                    "enabled": True,
                    "tracking_uri": str(tmp_path / "tb"),
                    "experiment": "smoke",
                    "backend": "tensorboard",
                },
            }
        )
        tracker = build_tracker(cfg.mlflow, "tbrun")
        tracker.start_run("tbrun")
        trainer = Trainer(cfg, None, tracker, None)
        trainer.fit()
        tracker.end_run()
        rows = _parse_scalars(
            _read_records(_event_file(tmp_path / "tb" / "smoke" / "tbrun"))
        )
        tags = {r[1] for r in rows}
        assert "train/loss" in tags
        assert "val/loss" in tags
