"""Offline BPE tokenizer (data/bpe.py) + train-tokenizer CLI.

New capability over the reference (its only tokenizer is the downloaded
tiktoken gpt2, reference models/gpt.py:210-212); tested in the reference's
style: unit behavior, determinism, persistence, CLI subprocess, and an
end-to-end train through the real data path.
"""

from __future__ import annotations

import json
import subprocess
import sys

import numpy as np
import pytest

from llmtrain_tpu.data.bpe import BPETokenizer, train_bpe

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "the quicker brown foxes jump over lazier dogs!\n"
) * 50 + "def quick_fn(arg1, arg2):\n    return arg1 + arg2\n" * 30


class TestTraining:
    def test_vocab_size_and_interface(self):
        tok = train_bpe(CORPUS, 512)
        assert tok.n_vocab <= 512
        assert tok.n_vocab > 256  # learned at least some merges
        assert tok.eot_token == tok.n_vocab - 1

    def test_deterministic(self):
        a = train_bpe(CORPUS, 400)
        b = train_bpe(CORPUS, 400)
        assert a.fingerprint == b.fingerprint
        assert a.encode(CORPUS[:500]) == b.encode(CORPUS[:500])

    def test_compresses_repeated_text(self):
        tok = train_bpe(CORPUS, 512)
        ids = tok.encode("the quick brown fox")
        assert len(ids) < len("the quick brown fox".encode())

    def test_too_small_vocab_raises(self):
        with pytest.raises(ValueError, match="vocab_size"):
            train_bpe(CORPUS, 200)

    def test_stops_early_on_tiny_corpus(self):
        tok = train_bpe("ab", 10_000)
        assert tok.n_vocab < 300


class TestRoundtrip:
    def test_encode_decode_exact(self):
        tok = train_bpe(CORPUS, 512)
        for text in (
            "the quick brown fox",
            "unseen words zyxw!",
            "tabs\tand\nnewlines  spaces",
            "unicode: café ✓ \U0001f600",
            "",
        ):
            assert tok.decode(tok.encode(text)) == text

    def test_encode_np_matches_encode(self):
        tok = train_bpe(CORPUS, 400)
        np.testing.assert_array_equal(
            tok.encode_np(CORPUS[:300]), np.asarray(tok.encode(CORPUS[:300]), np.int32)
        )

    def test_decode_rejects_out_of_range(self):
        tok = train_bpe(CORPUS, 400)
        with pytest.raises(ValueError, match="out of range"):
            tok.decode([tok.n_vocab])

    def test_decode_special_token(self):
        tok = train_bpe(CORPUS, 400)
        assert tok.decode([tok.eot_token]) == "<|endoftext|>"


class TestPersistence:
    def test_save_load_identical(self, tmp_path):
        tok = train_bpe(CORPUS, 512)
        path = tmp_path / "tok.json"
        tok.save(path)
        loaded = BPETokenizer.load(path)
        assert loaded.fingerprint == tok.fingerprint
        assert loaded.n_vocab == tok.n_vocab
        assert loaded.encode(CORPUS[:400]) == tok.encode(CORPUS[:400])

    def test_load_rejects_other_json(self, tmp_path):
        path = tmp_path / "x.json"
        path.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError, match="not a llmtrain-bpe"):
            BPETokenizer.load(path)

    def test_build_tokenizer_bpe_spec(self, tmp_path):
        from llmtrain_tpu.data.tokenizers import build_tokenizer

        path = tmp_path / "tok.json"
        train_bpe(CORPUS, 400).save(path)
        tok = build_tokenizer(f"bpe:{path}")
        assert isinstance(tok, BPETokenizer)


class TestCLI:
    def test_train_tokenizer_subcommand(self, tmp_path):
        corpus = tmp_path / "corpus.txt"
        corpus.write_text(CORPUS)
        out = tmp_path / "tok.json"
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "llmtrain_tpu",
                "train-tokenizer",
                "--input",
                str(corpus),
                "--vocab-size",
                "512",
                "--output",
                str(out),
                "--json",
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        stats = json.loads(proc.stdout)
        assert stats["vocab_size"] <= 512
        assert out.exists()
        assert BPETokenizer.load(out).n_vocab == stats["vocab_size"]

    def test_missing_input_is_config_error(self, tmp_path):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "llmtrain_tpu",
                "train-tokenizer",
                "--input",
                str(tmp_path / "nope.txt"),
                "--output",
                str(tmp_path / "tok.json"),
            ],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 2


def test_end_to_end_train_with_bpe(tmp_path):
    """Full Trainer run through local_text with a bpe:<path> tokenizer:
    the vocabulary sizes the model and the loss decreases."""
    from llmtrain_tpu.config.schemas import RunConfig
    from llmtrain_tpu.registry import initialize_registries
    from llmtrain_tpu.tracking.base import NullTracker
    from llmtrain_tpu.training.trainer import Trainer

    initialize_registries()
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(CORPUS)
    vocab = tmp_path / "tok.json"
    train_bpe(CORPUS, 384).save(vocab)

    cfg = RunConfig.model_validate(
        {
            "run": {"name": "bpe-e2e", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 32,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 4,
                "d_ff": 64,
                "dropout": 0.0,
                "extra": {"tokenizer": f"bpe:{vocab}"},
            },
            "data": {
                "name": "local_text",
                "cache_dir": str(tmp_path / "cache"),
                "extra": {"globs": [str(corpus)]},
            },
            "trainer": {
                "max_steps": 12,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 2,
                "log_every_steps": 6,
                "eval_every_steps": 12,
                "save_every_steps": 12,
            },
            "mlflow": {"enabled": False},
        }
    )
    trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
    # The trained vocabulary sized the model (adapter pulls n_vocab).
    assert trainer.model.vocab_size == BPETokenizer.load(vocab).n_vocab
    result = trainer.fit()
    assert result.final_step == 12
    assert result.final_loss < result.first_step_loss


class TestCLIHardening:
    """Regression tests for review findings on the train-tokenizer CLI."""

    def test_output_into_missing_directory(self, tmp_path):
        corpus = tmp_path / "c.txt"
        corpus.write_text(CORPUS)
        out = tmp_path / "deep" / "nested" / "tok.json"  # parent doesn't exist
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "train-tokenizer",
                "--input", str(corpus), "--vocab-size", "384",
                "--output", str(out),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert out.exists()

    def test_overlapping_inputs_deduplicated(self, tmp_path):
        corpus = tmp_path / "c.txt"
        corpus.write_text(CORPUS)
        out = tmp_path / "tok.json"
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "train-tokenizer",
                "--input", str(tmp_path), "--input", str(corpus),  # dir + file inside it
                "--vocab-size", "384", "--output", str(out), "--json",
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        stats = json.loads(proc.stdout)
        assert stats["files"] == 1  # not double-counted

    def test_max_bytes_is_bytes_not_chars(self, tmp_path):
        corpus = tmp_path / "c.txt"
        corpus.write_text("é" * 4096)  # 2 bytes/char UTF-8
        out = tmp_path / "tok.json"
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "train-tokenizer",
                "--input", str(corpus), "--vocab-size", "300",
                "--output", str(out), "--max-bytes", "1000", "--json",
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        stats = json.loads(proc.stdout)
        assert stats["corpus_bytes"] <= 1000


def test_roundtrip_fuzz_random_unicode():
    """Property: decode(encode(x)) == x for arbitrary unicode, including
    codepoints and byte sequences never seen during training."""
    import random

    tok = train_bpe(CORPUS, 384)
    rng = random.Random(1234)
    alphabets = [
        (0x20, 0x7E),      # ASCII
        (0xA0, 0x2FF),     # Latin supplements
        (0x400, 0x4FF),    # Cyrillic
        (0x4E00, 0x4FFF),  # CJK slice
        (0x1F300, 0x1F64F),  # emoji
    ]
    for _ in range(100):
        lo, hi = rng.choice(alphabets)
        text = "".join(chr(rng.randint(lo, hi)) for _ in range(rng.randint(0, 64)))
        assert tok.decode(tok.encode(text)) == text
    # Mixed-alphabet long string
    mixed = "".join(
        chr(rng.randint(*rng.choice(alphabets))) for _ in range(2000)
    )
    assert tok.decode(tok.encode(mixed)) == mixed


class TestNativeEncoder:
    """The C fastbpe encoder (llmtrain_tpu/native) against the pure-Python
    merge loop — bit-identical token streams, or skip when no compiler."""

    def _pair(self):
        tok = train_bpe(CORPUS, 512)
        if tok._native is None:
            pytest.skip("no C compiler available for the native encoder")
        ref = BPETokenizer(tok._merges, special_tokens=tok._special)
        ref._native = None  # force the Python reference loop
        return tok, ref

    def test_word_level_equivalence(self):
        tok, ref = self._pair()
        words = [
            "the", "quick", "foxes", "lazier", "quick_fn", "arg1",
            "supercalifragilistic", "x", "", "émigré", "日本語", "a" * 50,
            "\n", "    ", "mixedCASE_words123",
        ]
        for w in words:
            assert tok._native.encode_word(w) == ref._encode_word(w), w

    def test_full_text_equivalence_and_roundtrip(self):
        tok, ref = self._pair()
        text = CORPUS[:500] + " unseen wörds αβγ and_some_new_identifiers_42"
        native_ids = tok.encode(text)
        assert native_ids == ref.encode(text)
        assert tok.decode(native_ids) == text

    def test_env_kill_switch(self, monkeypatch):
        """LLMTRAIN_NO_NATIVE=1 forces the Python path."""
        import llmtrain_tpu.native as native

        monkeypatch.setenv("LLMTRAIN_NO_NATIVE", "1")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_tried", False)
        tok = train_bpe(CORPUS, 400)
        assert tok._native is None
        assert tok.decode(tok.encode("the quick fox")) == "the quick fox"


class TestHFTokenizer:
    """tokenizer: "hf:<tokenizer.json>" — the HF-Llama interop companion."""

    @pytest.fixture(scope="class")
    def tok_file(self, tmp_path_factory):
        tokenizers = pytest.importorskip("tokenizers")
        from tokenizers import Tokenizer, models, pre_tokenizers, trainers

        del tokenizers

        path = tmp_path_factory.mktemp("hftok") / "tokenizer.json"
        tok = Tokenizer(models.BPE(unk_token="<unk>"))
        tok.pre_tokenizer = pre_tokenizers.Whitespace()
        trainer = trainers.BpeTrainer(
            vocab_size=64, special_tokens=["<unk>", "</s>"]
        )
        tok.train_from_iterator(
            ["hello world hello there", "world of tokens and text"], trainer
        )
        tok.save(str(path))
        return str(path)

    def test_build_and_roundtrip(self, tok_file):
        from llmtrain_tpu.data.tokenizers import build_tokenizer

        tok = build_tokenizer(f"hf:{tok_file}")
        assert tok.n_vocab > 0
        ids = tok.encode("hello world")
        assert ids and all(0 <= i < tok.n_vocab for i in ids)
        assert "hello" in tok.decode(ids)

    def test_eos_detected_and_cache_id(self, tok_file):
        from llmtrain_tpu.data.tokenizers import (
            build_tokenizer,
            tokenizer_cache_id,
        )

        tok = build_tokenizer(f"hf:{tok_file}")
        assert isinstance(getattr(tok, "eot_token", None), int)  # </s>
        cid = tokenizer_cache_id(tok)
        assert "HFTokenizer" in cid and tok.fingerprint in cid

    def test_unknown_scheme_still_rejected(self):
        from llmtrain_tpu.data.tokenizers import build_tokenizer

        with pytest.raises(ValueError, match="hf:<tokenizer.json>"):
            build_tokenizer("sentencepiece:x")

    def test_trains_a_model_end_to_end(self, tok_file, tmp_path):
        """local_text + hf tokenizer + gpt: the full offline loop for an
        HF-ecosystem vocabulary."""
        import numpy as np

        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        corpus = tmp_path / "corpus"
        corpus.mkdir()
        (corpus / "a.txt").write_text("hello world of tokens and text " * 40)
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "hf-tok", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "llama", "block_size": 16, "d_model": 32,
                    "n_layers": 1, "n_heads": 2, "d_ff": 64, "dropout": 0.0,
                    "extra": {"tokenizer": f"hf:{tok_file}"},
                },
                "data": {
                    "name": "local_text",
                    "cache_dir": str(tmp_path / "cache"),
                    "extra": {"globs": [str(corpus / "*.txt")],
                               "val_fraction": 0.0},
                },
                "trainer": {"max_steps": 4, "micro_batch_size": 2,
                            "lr": 5e-3, "warmup_steps": 0,
                            "log_every_steps": 2, "eval_every_steps": 100,
                            "save_every_steps": 100},
                "mlflow": {"enabled": False},
            }
        )
        initialize_registries()
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert np.isfinite(res.final_loss)
