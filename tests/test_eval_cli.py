"""Eval-only path: Trainer.evaluate + the ``eval`` CLI subcommand.

New capability over the reference (eval there exists only inside the
train loop, reference trainer.py:243-289). The key invariant: evaluating
a saved checkpoint standalone reproduces the val loss the training run
reported at that step.
"""

from __future__ import annotations

import json
import subprocess
import sys

import pytest

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking.base import NullTracker
from llmtrain_tpu.training.trainer import Trainer


def _cfg(tmp_path, **overrides):
    base = {
        "run": {"name": "eval-cli", "seed": 0, "device": "cpu"},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "d_model": 16,
            "n_layers": 1,
            "n_heads": 4,
            "d_ff": 32,
            "dropout": 0.0,
            "vocab_size": 64,
            "extra": {"tokenizer": "byte"},
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 6,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "warmup_steps": 0,
            "log_every_steps": 3,
            "eval_every_steps": 6,
            "save_every_steps": 6,
        },
        "mlflow": {"enabled": False},
        "output": {"root_dir": str(tmp_path / "runs")},
    }
    base.update(overrides)
    return RunConfig.model_validate(base)


class TestTrainerEvaluate:
    def test_standalone_eval_matches_training_eval(self, tmp_path):
        """fit() saves at step 6 and reports final_val_loss; a fresh Trainer
        restoring that checkpoint must reproduce it exactly."""
        initialize_registries()
        cfg = _cfg(tmp_path)
        run_dir = tmp_path / "runs" / "r1"
        (run_dir / "checkpoints").mkdir(parents=True)
        trainer = Trainer(cfg, run_dir=run_dir, tracker=NullTracker())
        result = trainer.fit()
        assert result.final_val_loss is not None

        fresh = Trainer(cfg, run_dir=None, tracker=NullTracker())
        metrics = fresh.evaluate(resume_from=str(run_dir / "checkpoints"))
        assert metrics is not None
        assert abs(metrics["val/loss"] - result.final_val_loss) < 1e-6

    def test_fresh_init_eval_runs(self, tmp_path):
        initialize_registries()
        trainer = Trainer(_cfg(tmp_path), run_dir=None, tracker=NullTracker())
        metrics = trainer.evaluate()
        assert metrics is not None and metrics["val/loss"] > 0


class TestEvalCLI:
    def _write_cfg(self, tmp_path) -> str:
        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(_cfg(tmp_path).model_dump(mode="json"), sort_keys=False)
        )
        return str(cfg_path)

    def _run(self, *argv, timeout=300):
        return subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", *argv],
            capture_output=True,
            text=True,
            timeout=timeout,
        )

    def test_eval_checkpoint_roundtrip(self, tmp_path):
        cfg_path = self._write_cfg(tmp_path)
        train = self._run(
            "train", "--config", cfg_path, "--run-id", "evalrun", "--json"
        )
        assert train.returncode == 0, train.stderr
        trained_val = json.loads(train.stdout)["train_result"]["final_val_loss"]

        ev = self._run(
            "eval", "--config", cfg_path, "--from", "evalrun", "--json"
        )
        assert ev.returncode == 0, ev.stderr
        payload = json.loads(ev.stdout)
        assert abs(payload["metrics"]["val/loss"] - trained_val) < 1e-6

    @pytest.mark.slow  # budget: tier-1 siblings test_quant TestTrainerEvalQuantized + test_cli test_generate_quantized_int8
    def test_eval_quantized_close_to_full(self, tmp_path):
        """--quantize int8 reports the serving-path quality: close to the
        full-precision loss, but not the identical number (the weights
        really are int8). The model must clear quantize_tree's min_size
        gate — the default eval test model is below it everywhere."""
        import yaml

        cfg = _cfg(tmp_path)
        big = cfg.model_dump(mode="json")
        big["model"].update({"d_model": 64, "d_ff": 128})
        cfg_path = str(tmp_path / "cfg_q.yaml")
        with open(cfg_path, "w") as f:
            yaml.safe_dump(big, f, sort_keys=False)
        train = self._run(
            "train", "--config", cfg_path, "--run-id", "qrun", "--json"
        )
        assert train.returncode == 0, train.stderr

        full = self._run("eval", "--config", cfg_path, "--from", "qrun", "--json")
        assert full.returncode == 0, full.stderr
        quant = self._run(
            "eval", "--config", cfg_path, "--from", "qrun",
            "--quantize", "int8", "--json",
        )
        assert quant.returncode == 0, quant.stderr
        full_loss = json.loads(full.stdout)["metrics"]["val/loss"]
        quant_loss = json.loads(quant.stdout)["metrics"]["val/loss"]
        assert abs(quant_loss - full_loss) / full_loss < 0.05
        assert quant_loss != full_loss

    def test_eval_without_checkpoint(self, tmp_path):
        cfg_path = self._write_cfg(tmp_path)
        ev = self._run("eval", "--config", cfg_path, "--json")
        assert ev.returncode == 0, ev.stderr
        assert json.loads(ev.stdout)["metrics"]["val/loss"] > 0

    def test_bad_config_exit_2(self, tmp_path):
        missing = tmp_path / "nope.yaml"
        ev = self._run("eval", "--config", str(missing))
        assert ev.returncode == 2

    def test_bad_checkpoint_exit_1(self, tmp_path):
        cfg_path = self._write_cfg(tmp_path)
        ev = self._run("eval", "--config", cfg_path, "--from", "no-such-run")
        assert ev.returncode == 1


@pytest.mark.parametrize("data_name", ["local_text"])
def test_eval_no_val_split_errors(tmp_path, data_name):
    """A data module configured without a validation split is a loud error,
    not a silent success."""
    corpus = tmp_path / "c.txt"
    corpus.write_text("hello world " * 500)
    cfg = _cfg(
        tmp_path,
        data={
            "name": data_name,
            "cache_dir": str(tmp_path / "cache"),
            "extra": {"globs": [str(corpus)], "val_fraction": 0.0},
        },
    )
    initialize_registries()
    trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
    assert trainer.evaluate() is None
