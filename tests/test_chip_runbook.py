"""Offline regression coverage for tools/run_chip_phase2.sh resume logic.

The runbook refires on every live tunnel window (tools/chip_watch.sh), so
its banked/skip/give-up accounting must be exactly right offline:

- a step is banked iff its artifact holds its TERMINAL marker (a window
  dying mid-step must re-run that step — r5 saw mask_ab-style tools die
  after their first row);
- a step that burned MAX_ATTEMPTS windows without banking is given up
  (a deterministically failing step must not refire for the whole watch
  budget);
- a fully banked/given-up outdir stands down (exit 0) WITHOUT needing a
  live tunnel, so the watch loop can end even when the tunnel is dead;
- anything still open goes through the compile-verified start gate,
  which aborts exit-1 fast on a dead tunnel (forced here by pinning the
  probe child to CPU).

These run the real script against synthesized artifact dirs; no TPU and
no jax import in-process (the open-dir cases pay one probe-child jax
import each).
"""

from __future__ import annotations

import json
import os
import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

# Terminal markers as each tool actually emits them (key order matters:
# the runbook banks on literal substring greps).
_BANKED = {
    "tpu_compiled.log": "===== 22 passed in 188.13s (0:03:08) =====\n",
    "mask_ab.json": json.dumps({"mask_overhead_pct+mha": 6.01}) + "\n",
    # the failed-attempts error line also carries "vs_baseline" (0.0),
    # so the bench predicates key on the success-only backend detail
    "bench_sweep.json": json.dumps({"metric": "tokens_per_sec_per_chip",
                                    "vs_baseline": 1.5,
                                    "detail": {"backend": "tpu"}}) + "\n",
    "bench_c128.json": json.dumps({"metric": "tokens_per_sec_per_chip",
                                   "vs_baseline": 1.4,
                                   "detail": {"backend": "tpu"}}) + "\n",
    "family.json": (json.dumps({"family": "gpt", "mfu": 0.45}) + "\n"
                    + json.dumps({"family": "llama", "mfu": 0.41}) + "\n"),
    "speculative.json": json.dumps({"cell": "speculative_fresh_draft",
                                    "ms_per_token": 1.9}) + "\n",
    "lora_ab.json": json.dumps({"speedup_lora_vs_full": 1.4,
                                "predicted_speedup": 1.3}) + "\n",
    "diag_decode.json": json.dumps({"backend": "tpu", "batch": 32,
                                    "n_kv_heads": 4}) + "\n",
    "bpe_headline.json": json.dumps({"final_val_loss": 3.21}) + "\n",
    "longctx.json": "".join(
        json.dumps({"seq": t, "batch": 1, "attention": "flash",
                    "window": 0, "backend": "tpu"}) + "\n"
        for t in (8192, 16384, 32768)
    ),
    "longctx_window.json": json.dumps(
        {"seq": 16384, "batch": 1, "attention": "flash", "window": 1024,
         "backend": "tpu"}) + "\n",
}


def _write_banked(outdir: Path, *, except_for: set[str] = frozenset()) -> None:
    for name, content in _BANKED.items():
        if name not in except_for:
            (outdir / name).write_text(content)


def _run(outdir: Path, timeout: float = 300,
         fake_dead_probe: bool = False) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # probe child asserts backend == tpu
    if fake_dead_probe:
        # The gate invokes `python tools/tpu_probe.py` via PATH; shadowing
        # `python` makes the dead-tunnel abort instant instead of paying a
        # real jax import just to learn the backend is cpu.
        stub = outdir / ".bin"
        stub.mkdir(exist_ok=True)
        (stub / "python").write_text("#!/bin/sh\nexit 1\n")
        (stub / "python").chmod(0o755)
        env["PATH"] = f"{stub}{os.pathsep}{env['PATH']}"
    return subprocess.run(
        ["bash", "tools/run_chip_phase2.sh", str(outdir)],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=timeout,
    )


def test_fully_banked_dir_stands_down_without_tunnel(tmp_path):
    _write_banked(tmp_path)
    proc = _run(tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "standing down" in proc.stderr
    # Stand-down must not have needed a probe: no probe artifact written.
    assert not (tmp_path / "probe.log").exists()


def test_partial_artifact_is_not_banked(tmp_path):
    """A first-row-only artifact (window died mid-step) keeps the step
    open: the runbook must reach its start gate, not stand down."""
    _write_banked(tmp_path, except_for={"mask_ab.json"})
    # One measured row but no terminal summary line:
    (tmp_path / "mask_ab.json").write_text(
        json.dumps({"cell": "packed", "backend": "tpu", "mfu": 0.38}) + "\n")
    proc = _run(tmp_path, fake_dead_probe=True)
    assert proc.returncode == 1
    assert "tunnel dead before step start" in proc.stderr


def test_failed_suite_log_is_not_banked(tmp_path):
    _write_banked(tmp_path, except_for={"tpu_compiled.log"})
    (tmp_path / "tpu_compiled.log").write_text(
        "==== 2 failed, 20 passed in 201.0s ====\n")
    proc = _run(tmp_path, fake_dead_probe=True)
    assert proc.returncode == 1
    assert "tunnel dead before step start" in proc.stderr


def test_failed_bench_error_line_is_not_banked(tmp_path):
    """bench.py's all-attempts-failed line carries "vs_baseline": 0.0 —
    it must keep the step open (r5 window 1 banked exactly this)."""
    _write_banked(tmp_path, except_for={"bench_c128.json"})
    (tmp_path / "bench_c128.json").write_text(json.dumps(
        {"metric": "tokens_per_sec_per_chip", "value": 0.0,
         "vs_baseline": 0.0,
         "detail": {"error": "all bench attempts failed"}}) + "\n")
    proc = _run(tmp_path, fake_dead_probe=True)
    assert proc.returncode == 1
    assert "tunnel dead before step start" in proc.stderr


def test_attempt_cap_gives_up_and_stands_down(tmp_path):
    """An unbanked step that already burned MAX_ATTEMPTS windows is given
    up; with nothing else open the runbook stands down offline."""
    _write_banked(tmp_path, except_for={"speculative.json"})
    (tmp_path / ".attempts_spec").write_text("2\n")
    proc = _run(tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "standing down" in proc.stderr


def test_banked_suite_marker_is_count_independent(tmp_path):
    """Banking must not hardcode a pass count: a grown suite still banks."""
    _write_banked(tmp_path, except_for={"tpu_compiled.log"})
    (tmp_path / "tpu_compiled.log").write_text(
        "===== 31 passed in 240.00s (0:04:00) =====\n")
    proc = _run(tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "standing down" in proc.stderr
