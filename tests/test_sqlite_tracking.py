"""Native-SQLite tracking round-trip — runs UNCONDITIONALLY.

The reference exercises its tracker against a real SQLite backend in
every test run (reference tests/test_cli.py:628-704, mlflow in its dev
extras). This image ships without mlflow, so the twin test
(tests/test_mlflow_roundtrip.py) skips — leaving the tracker otherwise
untested against real persistence. The native backend
(tracking/sqlite.py) closes that gap with zero dependencies: a full CLI
train writes runs/params/metrics/tags/artifacts to a SQLite file, and
raw-SQL queries verify the round trip, including --auto-resume run
continuity. These tests run everywhere the suite runs.
"""

import json
import os
import sqlite3
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

from llmtrain_tpu.tracking import SqliteTracker, build_tracker
from llmtrain_tpu.tracking.sqlite import (
    read_metrics,
    read_params,
    read_runs,
    resolve_db_path,
)

REPO_ROOT = Path(__file__).resolve().parents[1]

CFG = {
    "schema_version": 1,
    "run": {"name": "sqlite-rt", "seed": 11, "device": "cpu", "deterministic": True},
    "model": {
        "name": "dummy_gpt",
        "block_size": 8,
        "d_model": 48,
        "n_layers": 1,
        "n_heads": 2,
        "d_ff": 96,
        "dropout": 0.0,
        "vocab_size": 32,
    },
    "data": {"name": "dummy_text"},
    "trainer": {
        "max_steps": 6,
        "micro_batch_size": 2,
        "grad_accum_steps": 1,
        "lr": 0.003,
        "warmup_steps": 0,
        "log_every_steps": 3,
        "eval_every_steps": 3,
        "save_every_steps": 3,
    },
    "logging": {"level": "INFO", "json_output": True, "log_to_file": True},
    "output": {"root_dir": "runs"},
}


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=420,
    )


@pytest.fixture()
def workdir(tmp_path):
    cfg = {
        **CFG,
        "mlflow": {
            "enabled": True,
            "backend": "native",
            "tracking_uri": f"sqlite:///{tmp_path / 'track.db'}",
            "experiment": "rt-exp",
        },
    }
    (tmp_path / "config.yaml").write_text(yaml.safe_dump(cfg))
    return tmp_path


class TestResolveDbPath:
    def test_sqlite_uri_absolute(self):
        assert resolve_db_path("sqlite:////mlflow/mlflow.db") == Path("/mlflow/mlflow.db")

    def test_sqlite_uri_relative(self):
        assert resolve_db_path("sqlite:///x.db") == Path("x.db")

    def test_file_uri_gets_db_inside(self):
        assert resolve_db_path("file:./mlruns") == Path("./mlruns/llmtrain.db")

    def test_plain_path(self):
        assert resolve_db_path("/tmp/track") == Path("/tmp/track/llmtrain.db")


class TestSqliteTrackerUnit:
    def test_full_protocol_roundtrip(self, tmp_path):
        db = tmp_path / "t.db"
        t = SqliteTracker(f"sqlite:///{db}", "exp", run_name="pretty")
        t.start_run("r1")
        t.log_params({"model": {"d_model": 48, "sizes": [1, 2]}, "lr": 0.1})
        t.log_metrics({"train/loss": 2.5}, step=1)
        t.log_metrics({"train/loss": 2.0, "val/loss": 2.2}, step=2)
        t.log_artifact("/tmp/config.yaml", "config.yaml")
        t.end_run()

        runs = read_runs(db, "exp")
        assert len(runs) == 1
        assert runs[0]["run_id"] == "r1"
        assert runs[0]["run_name"] == "pretty"
        assert runs[0]["status"] == "FINISHED"
        assert runs[0]["end_time"] is not None

        params = read_params(db, "r1")
        # Same dot-flattening as the MLflow tracker (shared helper).
        assert params["model.d_model"] == "48"
        assert params["model.sizes"] == "[1, 2]"
        assert params["lr"] == "0.1"

        losses = read_metrics(db, "r1", "train/loss")
        assert [(m["step"], m["value"]) for m in losses] == [(1, 2.5), (2, 2.0)]

    def test_start_run_joins_existing(self, tmp_path):
        db = tmp_path / "t.db"
        t = SqliteTracker(f"sqlite:///{db}", "exp")
        t.start_run("stable-id")
        t.log_metrics({"m": 1.0}, step=1)
        t.end_run(status="KILLED")

        t2 = SqliteTracker(f"sqlite:///{db}", "exp")
        t2.start_run("stable-id")
        t2.log_metrics({"m": 2.0}, step=2)
        t2.end_run()

        runs = read_runs(db)
        assert len(runs) == 1  # joined, not duplicated
        assert runs[0]["status"] == "FINISHED"
        assert [(m["step"], m["value"]) for m in read_metrics(db, "stable-id", "m")] == [
            (1, 1.0),
            (2, 2.0),
        ]

    def test_same_run_id_across_experiments(self, tmp_path):
        """One DB file can hold the same run id under different
        experiments — the uniqueness constraint is (run_id, experiment),
        so switching mlflow.experiment mid-project doesn't crash — and
        the query helpers scope by experiment to keep them apart."""
        db = tmp_path / "t.db"
        for i, exp in enumerate(("exp-a", "exp-b")):
            t = SqliteTracker(f"sqlite:///{db}", exp)
            t.start_run("my-run")
            t.log_params({"which": exp})
            t.log_metrics({"m": float(i)}, step=1)
            t.end_run()
        assert len(read_runs(db, "exp-a")) == 1
        assert len(read_runs(db, "exp-b")) == 1
        assert read_params(db, "my-run", experiment="exp-b")["which"] == "exp-b"
        ms = read_metrics(db, "my-run", "m", experiment="exp-a")
        assert [(m["step"], m["value"]) for m in ms] == [(1, 0.0)]

    def test_nan_metric_logs_instead_of_crashing(self, tmp_path):
        """A diverged run logging loss=nan must keep training alive:
        sqlite3 binds NaN as NULL, the column is nullable, and reads map
        NULL back to nan."""
        import math

        db = tmp_path / "t.db"
        t = SqliteTracker(f"sqlite:///{db}", "exp")
        t.start_run("r-nan")
        t.log_metrics({"train/loss": float("nan"), "ok": 1.5}, step=1)
        t.end_run()
        rows = {m["key"]: m["value"] for m in read_metrics(db, "r-nan")}
        assert math.isnan(rows["train/loss"])
        assert rows["ok"] == 1.5

    def test_migrates_v1_not_null_metrics_schema(self, tmp_path):
        """A DB created by the v1 schema (metrics.value NOT NULL) is
        rebuilt on connect so NaN logging works on resumed runs too."""
        import math

        db = tmp_path / "old.db"
        with sqlite3.connect(db) as conn:
            conn.executescript(
                """
                CREATE TABLE runs (
                    run_uuid TEXT PRIMARY KEY, run_id TEXT NOT NULL,
                    experiment TEXT NOT NULL, run_name TEXT,
                    status TEXT NOT NULL, start_time REAL NOT NULL,
                    end_time REAL, UNIQUE (run_id, experiment));
                CREATE TABLE params (
                    run_uuid TEXT NOT NULL, key TEXT NOT NULL,
                    value TEXT NOT NULL, PRIMARY KEY (run_uuid, key));
                CREATE TABLE metrics (
                    run_uuid TEXT NOT NULL, key TEXT NOT NULL,
                    value REAL NOT NULL, step INTEGER, timestamp REAL NOT NULL);
                CREATE INDEX idx_metrics_run_key ON metrics (run_uuid, key, step);
                CREATE TABLE tags (
                    run_uuid TEXT NOT NULL, key TEXT NOT NULL,
                    value TEXT NOT NULL, PRIMARY KEY (run_uuid, key));
                CREATE TABLE artifacts (
                    run_uuid TEXT NOT NULL, local_path TEXT NOT NULL,
                    artifact_path TEXT);
                INSERT INTO runs VALUES ('u1', 'old-run', 'exp', 'n',
                    'FINISHED', 1.0, 2.0);
                INSERT INTO metrics VALUES ('u1', 'm', 7.5, 1, 1.5);
                """
            )
        t = SqliteTracker(f"sqlite:///{db}", "exp")
        t.start_run("old-run")  # joins the v1 row after migration
        t.log_metrics({"m": float("nan")}, step=2)  # crashed pre-migration
        t.end_run()
        vals = [m["value"] for m in read_metrics(db, "old-run", "m")]
        assert vals[0] == 7.5  # preserved through the rebuild
        assert math.isnan(vals[1])

    def test_rejects_mlflow_owned_db(self, tmp_path):
        """Pointing the native backend at a file whose runs table has
        MLflow's column set must fail up front with a message naming the
        backend conflict — not on the first INSERT mid-training."""
        db = tmp_path / "mlflow.db"
        with sqlite3.connect(db) as conn:
            # The identifying subset of MLflow's own `runs` table.
            conn.executescript(
                """
                CREATE TABLE runs (
                    run_uuid VARCHAR(32) PRIMARY KEY, name VARCHAR(250),
                    experiment_id INTEGER, status VARCHAR(9),
                    start_time BIGINT, end_time BIGINT,
                    lifecycle_stage VARCHAR(20), artifact_uri VARCHAR(200));
                """
            )
        t = SqliteTracker(f"sqlite:///{db}", "exp")
        with pytest.raises(RuntimeError, match="different product"):
            t.start_run("r1")
        # The file is untouched — the foreign schema was not "migrated".
        with sqlite3.connect(db) as conn:
            cols = {r[1] for r in conn.execute("PRAGMA table_info(runs)")}
        assert "experiment_id" in cols and "run_id" not in cols

    def test_build_tracker_backend_selection(self):
        from types import SimpleNamespace

        cfg = SimpleNamespace(
            tracking_uri="sqlite:///x.db",
            experiment="e",
            run_name=None,
            backend="native",
        )
        assert isinstance(build_tracker(cfg, "rid"), SqliteTracker)
        # auto in THIS image (no mlflow) also lands on the native store.
        cfg.backend = "auto"
        import importlib.util

        if importlib.util.find_spec("mlflow") is None:
            assert isinstance(build_tracker(cfg, "rid"), SqliteTracker)


@pytest.mark.slow
class TestSqliteCliRoundTrip:
    def test_train_then_query_back(self, workdir):
        proc = _run_cli(
            ["train", "--config", "config.yaml", "--json", "--run-id", "rt1"], workdir
        )
        assert proc.returncode == 0, proc.stderr
        summary = json.loads(proc.stdout)
        assert summary["train_result"]["final_step"] == 6

        db = workdir / "track.db"
        runs = read_runs(db, "rt-exp")
        assert len(runs) == 1
        assert runs[0]["run_id"] == "rt1"
        assert runs[0]["status"] == "FINISHED"

        params = read_params(db, "rt1")
        assert params["model.d_model"] == "48"
        assert params["trainer.max_steps"] == "6"

        history = read_metrics(db, "rt1", "train/loss")
        assert [m["step"] for m in history] == [3, 6]
        assert {m["key"] for m in read_metrics(db, "rt1")} >= {
            "train/loss",
            "train/lr",
            "train/tokens_per_sec",
            "val/loss",
        }

        with sqlite3.connect(db) as conn:
            arts = {
                Path(row[0]).name
                for row in conn.execute("SELECT local_path FROM artifacts")
            }
        assert "config.yaml" in arts
        assert "meta.json" in arts

    def test_auto_resume_continues_same_run(self, workdir):
        args = [
            "train", "--config", "config.yaml", "--json",
            "--run-id", "rt2", "--auto-resume",
        ]
        first = _run_cli(args, workdir)
        assert first.returncode == 0, first.stderr
        second = _run_cli(args, workdir)
        assert second.returncode == 0, second.stderr
        # resume-past-end relaunch: still exactly ONE tracked run.
        db = workdir / "track.db"
        runs = read_runs(db, "rt-exp")
        assert len(runs) == 1
        assert runs[0]["run_id"] == "rt2"
        assert runs[0]["status"] == "FINISHED"
