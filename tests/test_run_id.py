"""Run-id generation tests (parity with reference tests/test_run_id.py)."""

import pytest

from llmtrain_tpu.utils import run_id as run_id_mod
from llmtrain_tpu.utils.run_id import generate_run_id, slugify_run_name


def test_slugify_lowercases_and_squashes():
    assert slugify_run_name("My Fancy RUN!!") == "my-fancy-run"
    assert slugify_run_name("a__b--c") == "a__b-c"


def test_slugify_truncates_to_40():
    assert len(slugify_run_name("x" * 100)) == 40


def test_slugify_empty_falls_back():
    assert slugify_run_name("!!!") == "run"


class _FixedDatetime:
    @classmethod
    def now(cls, tz=None):
        import datetime as dt

        return dt.datetime(2026, 1, 2, 3, 4, 5, tzinfo=tz)


def test_generate_run_id_format(tmp_path, monkeypatch):
    monkeypatch.setattr(run_id_mod, "datetime", _FixedDatetime)
    monkeypatch.setattr(run_id_mod, "_git_short_sha", lambda: "abc1234")
    rid = generate_run_id("Hello World", tmp_path)
    assert rid == "20260102_030405_abc1234_hello-world"


def test_generate_run_id_collision_suffix(tmp_path, monkeypatch):
    monkeypatch.setattr(run_id_mod, "datetime", _FixedDatetime)
    monkeypatch.setattr(run_id_mod, "_git_short_sha", lambda: "abc1234")
    base = generate_run_id("x", tmp_path)
    (tmp_path / base).mkdir()
    second = generate_run_id("x", tmp_path)
    assert second == base + "__01"
    (tmp_path / second).mkdir()
    assert generate_run_id("x", tmp_path) == base + "__02"


def test_generate_run_id_collision_exhaustion(tmp_path, monkeypatch):
    monkeypatch.setattr(run_id_mod, "datetime", _FixedDatetime)
    monkeypatch.setattr(run_id_mod, "_git_short_sha", lambda: "abc1234")
    monkeypatch.setattr(run_id_mod, "_MAX_COLLISION_SUFFIX", 2)
    base = generate_run_id("x", tmp_path)
    for suffix in ["", "__01", "__02"]:
        (tmp_path / (base + suffix)).mkdir()
    with pytest.raises(RuntimeError):
        generate_run_id("x", tmp_path)


def test_generate_run_id_nogit(tmp_path, monkeypatch):
    monkeypatch.setattr(run_id_mod, "datetime", _FixedDatetime)
    monkeypatch.setattr(run_id_mod, "git_sha", lambda *, short: None)
    assert "_nogit_" in generate_run_id("x", tmp_path)
