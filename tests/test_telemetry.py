"""Unified telemetry subsystem tests (llmtrain_tpu/telemetry/).

Covers the ISSUE-4 contract end to end:

* EventTimeline — span/instant recording, monotonic timestamps, JSONL
  persistence, Perfetto export format (loadable JSON, pid/tid mapping,
  thread-name metadata), rollback tagging (events TAGGED, never dropped),
  bounded retention.
* MemoryMonitor — hbm metrics from memory_stats, the live-array fallback
  when the backend reports None (CPU PJRT — the tier-1 environment), and
  the headroom warning channel.
* MetricsRegistry — publish/flush to the tracker, the degrade-to-warning
  path for failing backends (regression: backend exceptions used to
  propagate out of log_metrics into the step loop), flush ordering under
  rollback.
* Prometheus — naming convention, exposition rendering, the stdlib HTTP
  endpoint, the textfile snapshot.
* Report — aggregation fields + markdown rendering.
* Trainer integration smoke (`make verify-telemetry` acceptance): a real
  fit produces report.json / report.md / Perfetto-loadable trace.json;
  train/mfu, mem/hbm_peak and span metrics land in the tracker AND in one
  live Prometheus scrape.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from pathlib import Path

import pytest

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.telemetry.memory import MemoryMonitor
from llmtrain_tpu.telemetry.prometheus import (
    PrometheusEndpoint,
    prometheus_name,
    render_prometheus,
    write_textfile,
)
from llmtrain_tpu.telemetry.registry import MetricsRegistry
from llmtrain_tpu.telemetry.report import build_report, render_markdown, write_reports
from llmtrain_tpu.telemetry.timeline import EventTimeline


# ---------------------------------------------------------------- timeline


class TestEventTimeline:
    def test_span_records_duration_event(self):
        tl = EventTimeline()
        with tl.span("work", cat="test", step=3, detail="x"):
            pass
        (event,) = tl.events()
        assert event["name"] == "work"
        assert event["ph"] == "X"
        assert event["step"] == 3
        assert event["dur_us"] >= 0
        assert event["args"] == {"detail": "x"}

    def test_span_propagates_body_exception_but_still_records(self):
        tl = EventTimeline()
        with pytest.raises(ValueError):
            with tl.span("boom"):
                raise ValueError("body")
        assert [e["name"] for e in tl.events()] == ["boom"]

    def test_timestamps_monotonic_nondecreasing(self):
        tl = EventTimeline()
        for i in range(50):
            with tl.span("s", step=i):
                pass
            tl.instant("i", step=i)
        stamps = [e["ts_us"] for e in tl.events()]
        assert stamps == sorted(stamps)

    def test_jsonl_flush_appends_once_per_event(self, tmp_path):
        path = tmp_path / "t" / "timeline.jsonl"
        tl = EventTimeline(path)
        tl.instant("a")
        tl.flush()
        tl.instant("b")
        tl.flush()
        tl.flush()  # idempotent: nothing pending
        lines = path.read_text().strip().splitlines()
        # The segment_start header (goodput ledger) is written eagerly at
        # construction, before any flush; events append exactly once after.
        assert [json.loads(ln)["name"] for ln in lines] == [
            "segment_start",
            "a",
            "b",
        ]

    def test_rollback_window_tagged_not_dropped(self, tmp_path):
        """Satellite contract: events of a rolled-back window stay in the
        stream, tagged — and the tag lands in the JSONL because tagging
        happens before the boundary flush (flush ordering)."""
        path = tmp_path / "timeline.jsonl"
        tl = EventTimeline(path)
        for step in range(1, 11):
            with tl.span("host_dispatch", step=step):
                pass
        tl.tag_rollback(6, 10)
        tl.instant("rollback", step=10, restored_step=5)
        tl.flush()
        rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
        dispatch = [r for r in rows if r["name"] == "host_dispatch"]
        assert len(dispatch) == 10  # nothing dropped
        tagged = {r["step"] for r in dispatch if r.get("rolled_back")}
        assert tagged == {6, 7, 8, 9, 10}
        assert any(r["name"] == "rollback" for r in rows)

    def test_perfetto_export_loadable_with_pid_tid_mapping(self, tmp_path):
        tl = EventTimeline(process_index=2)
        with tl.span("main_work", step=1):
            pass

        done = threading.Event()

        def worker():
            tl.instant("bg_event")
            done.set()

        threading.Thread(target=worker, name="bg-thread").start()
        assert done.wait(5)
        target = tmp_path / "trace.json"
        assert tl.export_perfetto(target) == target
        trace = json.loads(target.read_text())
        events = trace["traceEvents"]
        assert isinstance(events, list) and events
        # every event carries the process index as pid and an int tid
        real = [e for e in events if e["ph"] in ("X", "i")]
        assert real and all(e["pid"] == 2 for e in real)
        assert all(isinstance(e["tid"], int) for e in real)
        assert all(isinstance(e["ts"], int) and e["ts"] >= 0 for e in real)
        # duration events carry dur; metadata names both threads
        assert all("dur" in e for e in real if e["ph"] == "X")
        names = {
            e["args"]["name"] for e in events if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "bg-thread" in names and len(names) == 2

    def test_max_events_cap_counts_drops(self):
        tl = EventTimeline(max_events=1000)
        for i in range(1100):
            tl.instant("e", step=i)
        assert len(tl.events()) == 1000
        assert tl.dropped == 100

    def test_span_totals_and_event_counts(self):
        tl = EventTimeline()
        for _ in range(3):
            with tl.span("a"):
                pass
        tl.instant("warned")
        totals = tl.span_totals()
        assert totals["a"]["count"] == 3
        assert totals["a"]["total_ms"] >= 0
        assert tl.event_counts() == {"warned": 1}


# ------------------------------------------------------------------ memory


class TestMemoryMonitor:
    def test_cpu_backend_falls_back_to_live_arrays(self):
        """Tier-1 environment: CPU PJRT memory_stats() is None/empty — the
        sample must still produce hbm metrics (live-array estimator) and
        host metrics, and must not raise."""
        import jax.numpy as jnp

        anchor = jnp.ones((64, 64))  # keep at least one live array around
        mon = MemoryMonitor()
        sample = mon.sample(step=1)
        assert sample["mem/hbm_used"] >= anchor.nbytes
        assert sample["mem/hbm_peak"] >= sample["mem/hbm_used"]
        assert sample["mem/live_arrays"] >= 1
        assert sample.get("mem/host_rss", 0) > 0
        assert mon.source == "live_arrays"
        del anchor

    def test_memory_stats_none_direct(self, monkeypatch):
        """Explicit fallback unit: a device whose memory_stats() returns
        None (the satellite's named failure shape)."""
        from llmtrain_tpu.telemetry import memory as mem_mod

        monkeypatch.setattr(mem_mod, "_device_memory_stats", lambda: None)
        sample = MemoryMonitor().sample()
        assert "mem/hbm_used" in sample and "mem/hbm_limit" not in sample

    def test_device_stats_and_headroom_warning(self, monkeypatch, caplog):
        from llmtrain_tpu.telemetry import memory as mem_mod

        stats = {
            "bytes_in_use": 95.0e9,
            "peak_bytes_in_use": 96.0e9,
            "bytes_limit": 100.0e9,
        }
        monkeypatch.setattr(mem_mod, "_device_memory_stats", lambda: dict(stats))
        tl = EventTimeline()
        mon = MemoryMonitor(headroom_warn_frac=0.9, timeline=tl)
        with caplog.at_level("WARNING"):
            sample = mon.sample(step=7)
            # second sample in the same excursion must NOT re-warn
            mon.sample(step=8)
        assert sample["mem/hbm_used"] == 95.0e9
        assert sample["mem/hbm_peak"] == 96.0e9
        assert sample["mem/hbm_limit"] == 100.0e9
        assert mon.source == "memory_stats"
        assert mon.headroom_warnings == 1
        assert sum("HBM headroom low" in r.message for r in caplog.records) == 1
        assert tl.event_counts().get("hbm_headroom") == 1
        # drop below threshold -> excursion resets -> warns again
        stats["bytes_in_use"] = 10.0e9
        mon.sample(step=9)
        stats["bytes_in_use"] = 95.0e9
        mon.sample(step=10)
        assert mon.headroom_warnings == 2


# ---------------------------------------------------------------- registry


class _RecordingTracker:
    def __init__(self):
        self.calls: list[tuple[dict, int | None]] = []
        self.params: list[dict] = []
        self.artifacts: list[tuple[str, str | None]] = []

    def start_run(self, run_id, run_name=None):
        pass

    def log_params(self, params):
        self.params.append(params)

    def log_metrics(self, metrics, step=None):
        self.calls.append((dict(metrics), step))

    def log_artifact(self, local_path, artifact_path=None):
        self.artifacts.append((local_path, artifact_path))

    def end_run(self, status="FINISHED"):
        pass


class _FailingTracker(_RecordingTracker):
    def __init__(self, fail_times: int = 10**9):
        super().__init__()
        self.fail_times = fail_times
        self.attempts = 0

    def log_metrics(self, metrics, step=None):
        self.attempts += 1
        if self.attempts <= self.fail_times:
            raise RuntimeError("backend down")
        super().log_metrics(metrics, step)

    def log_params(self, params):
        raise RuntimeError("backend down")

    def log_artifact(self, local_path, artifact_path=None):
        raise RuntimeError("backend down")


class TestMetricsRegistry:
    def test_publish_then_flush_single_tracker_call(self):
        tracker = _RecordingTracker()
        reg = MetricsRegistry(tracker)
        reg.publish({"train/loss": 2.0}, step=5)
        reg.publish({"train/mfu": 0.3}, step=5)
        assert tracker.calls == []  # buffered until the flush point
        assert reg.flush(step=5)
        ((metrics, step),) = tracker.calls
        assert metrics == {"train/loss": 2.0, "train/mfu": 0.3}
        assert step == 5
        assert reg.latest()["train/loss"] == (2.0, 5)

    def test_failing_backend_degrades_to_warning(self, caplog):
        """Regression (satellite): a tracker backend exception must not
        escape the flush — the old direct log_metrics calls propagated it
        into the step loop and killed the run."""
        tracker = _FailingTracker()
        reg = MetricsRegistry(tracker)
        with caplog.at_level("WARNING"):
            for step in range(1, 4):
                reg.publish({"train/loss": 1.0}, step=step)
                assert reg.flush(step=step) is False  # degraded, not raised
        assert reg.tracker_errors == 3
        assert reg.counters()["telemetry/tracker_errors"] == 3
        # rate-limited: first failure warns, the streak does not spam
        warns = [r for r in caplog.records if "log_metrics failed" in r.message]
        assert len(warns) == 1
        # registry state stays queryable while the backend is down
        assert reg.latest()["train/loss"][0] == 1.0
        assert not reg.safe_log_params({"a": 1})
        assert not reg.safe_log_artifact("/nope")

    def test_recovery_resets_streak(self, caplog):
        tracker = _FailingTracker(fail_times=2)
        reg = MetricsRegistry(tracker)
        for step in range(1, 4):
            reg.publish({"m": 1.0}, step=step)
            reg.flush(step=step)
        assert len(tracker.calls) == 1  # third flush landed
        assert reg.tracker_errors == 2

    def test_counters_and_history(self):
        reg = MetricsRegistry(_RecordingTracker())
        reg.inc("resilience/rollbacks")
        reg.inc("resilience/rollbacks")
        reg.publish({"train/loss": 3.0, "other": 1.0}, step=1)
        reg.flush(step=1)
        assert reg.counters()["resilience/rollbacks"] == 2
        assert reg.history() == [(1, {"train/loss": 3.0})]

    def test_flush_ordering_under_rollback(self, tmp_path):
        """Registry flush + timeline flush at a boundary where a rollback
        fired: the tagged window must be on disk after the SAME flush that
        pushes the boundary's metrics — not an interval later."""
        tracker = _RecordingTracker()
        reg = MetricsRegistry(tracker)
        tl = EventTimeline(tmp_path / "timeline.jsonl")
        for step in range(1, 6):
            with tl.span("host_dispatch", step=step):
                pass
        # boundary at step 5: rollback to 2 detected BEFORE the flush
        tl.tag_rollback(3, 5)
        tl.instant("rollback", step=5, restored_step=2)
        reg.publish({"train/loss": 9.9}, step=5)
        reg.flush(step=5)
        tl.flush()
        rows = [
            json.loads(ln)
            for ln in (tmp_path / "timeline.jsonl").read_text().strip().splitlines()
        ]
        assert {r["step"] for r in rows if r.get("rolled_back")} == {3, 4, 5}
        assert tracker.calls == [({"train/loss": 9.9}, 5)]


# -------------------------------------------------------------- prometheus


class TestPrometheus:
    def test_name_convention(self):
        assert prometheus_name("train/loss") == "llmtrain_train_loss"
        assert prometheus_name("mem/hbm_peak") == "llmtrain_mem_hbm_peak"
        assert prometheus_name("train/loss_rank_0") == "llmtrain_train_loss_rank_0"
        # idempotent + safe on weird input
        assert prometheus_name("llmtrain_train_loss") == "llmtrain_train_loss"
        assert prometheus_name("a b/c-d") == "llmtrain_a_b_c_d"

    def test_render_format(self):
        text = render_prometheus(
            {"train/loss": (2.5, 10), "train/mfu": (float("nan"), 10)},
            {"resilience/rollbacks": 1.0},
            info={"run_name": 'he"llo'},
        )
        assert "# TYPE llmtrain_train_loss gauge" in text
        assert "llmtrain_train_loss 2.5" in text
        assert "llmtrain_train_mfu NaN" in text
        assert "llmtrain_resilience_rollbacks_total 1.0" in text
        assert 'run_name="he\\"llo"' in text
        assert text.endswith("\n")

    def test_endpoint_serves_metrics(self):
        reg = MetricsRegistry(None)
        reg.publish({"train/loss": 1.25}, step=3)
        reg.flush(step=3)
        endpoint = PrometheusEndpoint(
            lambda: render_prometheus(reg.latest(), reg.counters()),
            host="127.0.0.1",
            port=0,
        )
        try:
            url = f"http://127.0.0.1:{endpoint.port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                body = resp.read().decode()
            assert "llmtrain_train_loss 1.25" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{endpoint.port}/nope", timeout=10
                )
        finally:
            endpoint.close()

    def test_textfile_atomic_write(self, tmp_path):
        target = tmp_path / "tele" / "metrics.prom"
        assert write_textfile(target, "llmtrain_x 1\n")
        assert target.read_text() == "llmtrain_x 1\n"
        assert not target.with_name("metrics.prom.tmp").exists()


# ------------------------------------------------------------------ report


class TestReport:
    def _populated(self, tmp_path):
        reg = MetricsRegistry(_RecordingTracker())
        tl = EventTimeline()
        for step in (5, 10):
            with tl.span("host_dispatch", step=step):
                pass
            reg.publish(
                {
                    "train/loss": 3.0 - step / 10,
                    "train/tokens_per_sec": 1000.0,
                    "train/mfu": 0.21,
                },
                step=step,
            )
            reg.flush(step=step)
        reg.inc("resilience/rollbacks")
        tl.instant("rollback", step=10)
        return build_report(
            run_id="rid-1",
            run_name="unit",
            registry=reg,
            timeline=tl,
            memory=MemoryMonitor(),
            wall_time_sec=12.0,
            train_result={"final_step": 10, "final_loss": 2.0},
        )

    def test_report_fields(self, tmp_path):
        report = self._populated(tmp_path)
        assert report["schema"].startswith("llmtrain-telemetry-report/")
        assert report["run"] == {"run_id": "rid-1", "name": "unit"}
        assert report["loss"]["trajectory"] == [[5, 2.5], [10, 2.0]]
        assert report["loss"]["final"] == 2.0 and report["loss"]["min"] == 2.0
        assert report["throughput"]["mfu"] == 0.21
        assert report["spans"]["host_dispatch"]["count"] == 2
        assert 0 <= report["spans"]["host_dispatch"]["frac_of_wall"] <= 1
        assert report["events"]["instants"] == {"rollback": 1}
        assert report["events"]["counters"]["resilience/rollbacks"] == 1
        assert report["train_result"]["final_step"] == 10

    def test_markdown_survives_inf_and_nan(self, tmp_path):
        """Diverged runs put inf/nan in the result — the report must render
        anyway (int(inf) raises OverflowError)."""
        report = self._populated(tmp_path)
        report["train_result"] = {
            "final_step": 10,
            "final_loss": float("inf"),
            "final_val_loss": float("nan"),
        }
        report["memory"]["hbm_peak_bytes"] = float("inf")
        md = render_markdown(report)
        assert "inf" in md and "NaN" in md

    def test_write_and_markdown(self, tmp_path):
        report = self._populated(tmp_path)
        json_path, md_path = write_reports(tmp_path, report)
        assert json.loads(json_path.read_text())["run"]["run_id"] == "rid-1"
        md = md_path.read_text()
        assert md.startswith("# Run report — unit (rid-1)")
        assert "host_dispatch" in md and "rollback: 1" in md
        assert render_markdown(report) == md


# --------------------------------------------------- trainer integration


def _smoke_cfg(tmp_path, **telemetry):
    return RunConfig.model_validate(
        {
            "run": {"name": "tele-e2e"},
            "model": {
                "name": "dummy_gpt",
                "block_size": 8,
                "d_model": 16,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 32,
                "dropout": 0.0,
                "vocab_size": 32,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 12,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "log_every_steps": 5,
                "eval_every_steps": 10,
                "save_every_steps": 10,
                "warmup_steps": 0,
            },
            "telemetry": telemetry or {},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
    )


def _make_run_dir(tmp_path) -> Path:
    run_dir = tmp_path / "runs" / "tele-e2e"
    (run_dir / "logs").mkdir(parents=True)
    return run_dir


class TestTrainerIntegration:
    def test_smoke_fit_produces_reports_trace_and_scrape(self, tmp_path):
        """`make verify-telemetry` acceptance: one smoke fit produces
        report.json + report.md + a Perfetto-loadable trace.json; train/mfu,
        mem/hbm_peak and the span metrics appear in the TRACKER sample and
        in one live Prometheus scrape taken during the run."""
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = _smoke_cfg(
            tmp_path,
            prometheus=True,
            prometheus_port=0,  # ephemeral: parallel test runs must not collide
            prometheus_host="127.0.0.1",
        )
        run_dir = _make_run_dir(tmp_path)
        tracker = _RecordingTracker()
        trainer = Trainer(cfg, run_dir, tracker)

        scraped: list[str] = []
        result_box: list = []

        def run_fit():
            result_box.append(trainer.fit())

        # fit runs in a worker so the main thread can scrape mid-run (the
        # trainer warns that SIGTERM handling is disabled — irrelevant here)
        fit_thread = threading.Thread(target=run_fit, name="fit")
        fit_thread.start()
        try:
            import time as _time

            deadline = _time.monotonic() + 120
            while _time.monotonic() < deadline and fit_thread.is_alive():
                port = trainer._telemetry.prometheus_port
                if port is not None:
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics", timeout=5
                        ) as resp:
                            text = resp.read().decode()
                        if "llmtrain_train_mfu" in text:
                            scraped.append(text)
                            break
                    except OSError:
                        pass
                _time.sleep(0.05)
        finally:
            fit_thread.join(timeout=180)
        assert not fit_thread.is_alive()
        assert result_box and result_box[0].final_step == 12

        # --- tracker: train/mfu, mem/hbm_peak, span metrics in the sample
        all_keys = set()
        for metrics, _step in tracker.calls:
            all_keys.update(metrics)
        assert {"train/loss", "train/mfu", "mem/hbm_peak", "mem/hbm_used"} <= all_keys
        assert {"train/data_wait_ms", "train/host_dispatch_ms"} <= all_keys

        # --- one Prometheus scrape carried the same gauges live
        assert scraped, "no successful /metrics scrape during the run"
        scrape = scraped[0]
        for gauge in (
            "llmtrain_train_mfu",
            "llmtrain_train_loss",
            "llmtrain_mem_hbm_peak",
            "llmtrain_train_data_wait_ms",
        ):
            assert gauge in scrape, f"{gauge} missing from scrape"
        assert 'llmtrain_run_info{' in scrape

        # --- run-dir artifacts: reports + Perfetto-loadable trace + JSONL
        report = json.loads((run_dir / "report.json").read_text())
        assert report["run"]["run_id"] == "tele-e2e"
        assert report["loss"]["final"] is not None
        assert report["throughput"]["mfu"] is not None
        assert report["memory"]["hbm_peak_bytes"] > 0
        assert {"data_wait", "host_dispatch", "checkpoint_save", "eval"} <= set(
            report["spans"]
        )
        assert (run_dir / "report.md").read_text().startswith("# Run report")
        trace = json.loads((run_dir / "telemetry" / "trace.json").read_text())
        assert any(e.get("name") == "host_dispatch" for e in trace["traceEvents"])
        jsonl = (run_dir / "telemetry" / "timeline.jsonl").read_text()
        assert any(
            json.loads(ln)["name"] == "prefetch_assemble"
            for ln in jsonl.strip().splitlines()
        )
        prom_file = (run_dir / "telemetry" / "metrics.prom").read_text()
        assert "llmtrain_mem_hbm_peak" in prom_file
        # telemetry artifacts registered with the tracker (satellite)
        registered = {a for a, _ in tracker.artifacts}
        assert str(run_dir / "report.json") in registered
        assert str(run_dir / "telemetry" / "trace.json") in registered

    def test_fit_survives_failing_tracker_backend(self, tmp_path, caplog):
        """Satellite regression: a tracker whose every method raises must
        cost warnings, not the run."""
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = _smoke_cfg(tmp_path)
        tracker = _FailingTracker()
        with caplog.at_level("WARNING"):
            result = Trainer(cfg, None, tracker).fit()
        assert result.final_step == 12
        assert tracker.attempts > 0  # the backend WAS exercised
        assert any("log_metrics failed" in r.message for r in caplog.records)

    def test_telemetry_disabled_writes_nothing_but_tracker_still_logs(
        self, tmp_path
    ):
        """The master switch removes the telemetry extras (files, timeline
        recording, memory sampling) — NOT experiment tracking, which now
        flows through the registry."""
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = _smoke_cfg(tmp_path, enabled=False)
        run_dir = _make_run_dir(tmp_path)
        tracker = _RecordingTracker()
        trainer = Trainer(cfg, run_dir, tracker)
        result = trainer.fit()
        assert result.final_step == 12
        assert not (run_dir / "report.json").exists()
        assert not (run_dir / "telemetry").exists()
        # the timeline is a true no-op, not an unbounded in-memory buffer
        assert trainer._telemetry.timeline.events() == []
        # tracker logging is unaffected by the telemetry switch
        assert tracker.params, "log_params lost with telemetry disabled"
        all_keys = {k for metrics, _ in tracker.calls for k in metrics}
        assert {"train/loss", "train/mfu"} <= all_keys
        assert not any(k.startswith("mem/") for k in all_keys)

    def test_rollback_run_tags_timeline_and_counts(self, tmp_path):
        """Registry/timeline behavior under a REAL spike rollback: the
        replayed window's events are tagged in the JSONL, the rollback
        instant + counter land in the report."""
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = _smoke_cfg(tmp_path)
        cfg = RunConfig.model_validate(
            {
                **cfg.model_dump(),
                "trainer": {
                    **cfg.trainer.model_dump(),
                    "max_steps": 40,
                    "save_every_steps": 10,
                    "log_every_steps": 5,
                    "eval_every_steps": 40,
                },
                "resilience": {
                    "spike_detection": True,
                    "spike_factor": 4.0,
                    "spike_min_history": 5,
                    "max_rollbacks": 2,
                    "faults": {"spike_loss_at_step": 23, "spike_loss_scale": 1e4},
                },
            }
        )
        run_dir = _make_run_dir(tmp_path)
        result = Trainer(cfg, run_dir, _RecordingTracker()).fit()
        assert result.rollbacks == 1
        rows = [
            json.loads(ln)
            for ln in (run_dir / "telemetry" / "timeline.jsonl")
            .read_text()
            .strip()
            .splitlines()
        ]
        assert any(r["name"] == "rollback" for r in rows)
        assert any(r["name"] == "fault_spike_loss" for r in rows)
        tagged = [r for r in rows if r.get("rolled_back")]
        assert tagged, "rolled-back window events missing their tag"
        assert all(r["step"] > 20 for r in tagged if "step" in r)
        report = json.loads((run_dir / "report.json").read_text())
        assert report["events"]["counters"]["resilience/rollbacks"] == 1
        assert report["events"]["instants"]["rollback"] == 1
