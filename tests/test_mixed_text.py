"""Weighted corpus mixture (data/mixed_text.py).

The properties that make mixing safe in this framework:

* the epoch is a pure function of (run.seed, sources) — identical on
  every process and across resume, like data/sampler.py;
* weights steer the source histogram; a small source with a large
  weight repeats (wraps) rather than starving;
* validation is the plain concatenation of the sources' val splits;
* misconfiguration (no sources, bad weight, unknown keys, disagreeing
  split_documents) fails loudly at setup.
"""

from __future__ import annotations

import numpy as np
import pytest

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.data.mixed_text import (
    ConcatDataset,
    MixedTextDataModule,
    WeightedMixDataset,
)
from llmtrain_tpu.registry import get_data_module, initialize_registries

initialize_registries()


class _Toy:
    """IndexedDataset stub emitting its own id so reads are traceable."""

    def __init__(self, ident: int, n: int, width: int = 4) -> None:
        self._ident = ident
        self._n = n
        self._width = width

    def __len__(self) -> int:
        return self._n

    def get_examples(self, indices: np.ndarray) -> dict[str, np.ndarray]:
        indices = np.asarray(indices)
        ids = np.full((len(indices), self._width), self._ident, np.int32)
        # encode the local index so wraparound is observable
        ids[:, 0] = indices.astype(np.int32)
        return {"input_ids": ids, "labels": ids.copy()}


class TestWeightedMix:
    def test_deterministic_across_instances(self):
        a = WeightedMixDataset([_Toy(0, 50), _Toy(1, 50)], [1.0, 1.0], seed=9)
        b = WeightedMixDataset([_Toy(0, 50), _Toy(1, 50)], [1.0, 1.0], seed=9)
        idx = np.arange(len(a))
        np.testing.assert_array_equal(
            a.get_examples(idx)["input_ids"], b.get_examples(idx)["input_ids"]
        )

    def test_weights_are_exact_by_construction(self):
        mix = WeightedMixDataset(
            [_Toy(0, 500), _Toy(1, 500)], [3.0, 1.0], seed=0
        )
        hist = mix.source_histogram()
        # epoch = ceil(500 / 0.25) = 2000; exact shares 1500/500
        assert len(mix) == 2000
        np.testing.assert_array_equal(hist, [1500, 500])

    def test_under_weighted_source_is_fully_covered(self):
        """The whole point of the epoch formula: an under-weighted
        source's TAIL must still be reachable — every one of its local
        indices appears in the epoch."""
        mix = WeightedMixDataset(
            [_Toy(0, 40), _Toy(1, 40)], [3.0, 1.0], seed=2
        )
        rows = mix.get_examples(np.arange(len(mix)))["input_ids"]
        light = rows[rows[:, 1] == 1]
        assert set(np.unique(light[:, 0])) == set(range(40))

    def test_pathological_weights_fail_loudly(self):
        with pytest.raises(ValueError, match="rebalance"):
            WeightedMixDataset(
                [_Toy(0, 1 << 22), _Toy(1, 4)], [1e-9, 1.0], seed=0
            )

    def test_small_heavy_source_wraps(self):
        small, big = _Toy(7, 5), _Toy(8, 200)
        mix = WeightedMixDataset([small, big], [5.0, 1.0], seed=1)
        rows = mix.get_examples(np.arange(len(mix)))["input_ids"]
        small_rows = rows[rows[:, 1] == 7]
        # far more draws from the small source than it has examples —
        # local indices must wrap into [0, 5)
        assert len(small_rows) > 50
        assert set(np.unique(small_rows[:, 0])) == {0, 1, 2, 3, 4}

    def test_rows_land_in_request_order(self):
        mix = WeightedMixDataset([_Toy(0, 30), _Toy(1, 30)], [1.0, 1.0], seed=3)
        idx = np.asarray([5, 0, 17, 2])
        got = mix.get_examples(idx)["input_ids"][:, 1]
        want = np.asarray(
            [mix.get_examples(np.asarray([i]))["input_ids"][0, 1] for i in idx]
        )
        np.testing.assert_array_equal(got, want)


class TestConcat:
    def test_spans_boundaries(self):
        cat = ConcatDataset([_Toy(0, 3), _Toy(1, 4)])
        assert len(cat) == 7
        rows = cat.get_examples(np.asarray([0, 2, 3, 6]))["input_ids"]
        np.testing.assert_array_equal(rows[:, 1], [0, 0, 1, 1])
        np.testing.assert_array_equal(rows[:, 0], [0, 2, 0, 3])


def _cfg(tmp_path, sources):
    (tmp_path / "a").mkdir(exist_ok=True)
    (tmp_path / "b").mkdir(exist_ok=True)
    (tmp_path / "a" / "x.txt").write_text("alpha " * 800)
    (tmp_path / "b" / "y.txt").write_text("beta " * 800)
    return RunConfig.model_validate(
        {
            "run": {"name": "mix", "device": "cpu", "seed": 4},
            "model": {
                "name": "gpt",
                "block_size": 16,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "vocab_size": 260,
                "extra": {"tokenizer": "byte"},
            },
            "data": {
                "name": "mixed_text",
                "cache_dir": str(tmp_path / "cache"),
                "extra": {"sources": sources},
            },
            "trainer": {"max_steps": 10, "warmup_steps": 0, "micro_batch_size": 2},
            "mlflow": {"enabled": False},
        }
    )


class _ByteTok:
    def encode(self, text: str) -> list[int]:
        return list(text.encode())


class TestModule:
    def test_end_to_end_mixture(self, tmp_path):
        cfg = _cfg(
            tmp_path,
            [
                {"globs": [str(tmp_path / "a" / "*.txt")], "weight": 3.0},
                {"globs": [str(tmp_path / "b" / "*.txt")], "weight": 1.0},
            ],
        )
        module = get_data_module("mixed_text")()
        assert isinstance(module, MixedTextDataModule)
        module.setup(cfg, _ByteTok())
        train = module.train_dataset()
        assert len(train) > 0
        batch = train.get_examples(np.arange(min(8, len(train))))
        assert batch["input_ids"].shape[1] == 16
        hist = train.source_histogram()
        assert hist[0] > hist[1]  # weight 3 vs 1
        val = module.val_dataset()
        assert val is not None and len(val) > 0

    def test_same_seed_same_epoch(self, tmp_path):
        sources = [
            {"globs": [str(tmp_path / "a" / "*.txt")]},
            {"globs": [str(tmp_path / "b" / "*.txt")]},
        ]
        cfg = _cfg(tmp_path, sources)
        m1, m2 = MixedTextDataModule(), MixedTextDataModule()
        m1.setup(cfg, _ByteTok())
        m2.setup(cfg, _ByteTok())
        idx = np.arange(len(m1.train_dataset()))
        np.testing.assert_array_equal(
            m1.train_dataset().get_examples(idx)["input_ids"],
            m2.train_dataset().get_examples(idx)["input_ids"],
        )

    @pytest.mark.parametrize(
        "sources, match",
        [
            ([], "non-empty list"),
            ([{"globs": ["x"], "weight": 0}], "weight"),
            ([{"globs": ["x"], "wieght": 2}], "unknown keys"),
            (["just-a-string"], "mapping"),
        ],
    )
    def test_bad_sources_fail_loudly(self, tmp_path, sources, match):
        cfg = _cfg(tmp_path, sources)
        module = MixedTextDataModule()
        with pytest.raises(ValueError, match=match):
            module.setup(cfg, _ByteTok())

    def test_disagreeing_split_documents_rejected(self, tmp_path):
        cfg = _cfg(
            tmp_path,
            [
                {
                    "globs": [str(tmp_path / "a" / "*.txt")],
                    "split_documents": True,
                },
                {"globs": [str(tmp_path / "b" / "*.txt")]},
            ],
        )
        module = MixedTextDataModule()
        with pytest.raises(ValueError, match="split_documents"):
            module.setup(cfg, _ByteTok())

    def test_trains_via_trainer(self, tmp_path):
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        cfg = _cfg(
            tmp_path,
            [
                {"globs": [str(tmp_path / "a" / "*.txt")], "weight": 2.0},
                {"globs": [str(tmp_path / "b" / "*.txt")]},
            ],
        )
        result = Trainer(cfg, run_dir=None, tracker=NullTracker()).fit()
        assert np.isfinite(result.final_loss)
        assert result.final_step == 10
