"""Preemption-safe checkpointing (trainer.py SIGTERM handling).

The k8s spot/maintenance story: a SIGTERM mid-training must produce a
durable checkpoint and a clean exit inside the pod's termination grace
period, and --resume must continue exactly where the evicted run
stopped. Complements the failure-detection machinery the reference
handles with restart policies alone (its trainer has no signal
handling — an evicted pod loses everything since the last periodic
save; reference trainer.py:402-406).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking.base import NullTracker
from llmtrain_tpu.training.trainer import Trainer


def _cfg(tmp_path, max_steps=4000, save_every=1000):
    return RunConfig.model_validate(
        {
            "run": {"name": "pre", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 8,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "dropout": 0.0,
                "vocab_size": 64,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": max_steps,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 50,
                "eval_every_steps": max_steps,
                "save_every_steps": save_every,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
    )


class _SigtermAtFirstInterval(NullTracker):
    """Deterministic in-process trigger: the first log_metrics call runs
    ON the training thread at a log boundary, so os.kill here delivers
    SIGTERM to ourselves and the (main-thread) handler latches the flag
    before the next step's check — no wall-clock race against jit warmup
    or host speed."""

    def __init__(self):
        self.fired = False

    def log_metrics(self, metrics, step=None):
        if not self.fired and step and step >= 1:
            self.fired = True
            os.kill(os.getpid(), signal.SIGTERM)


class TestInProcess:
    def test_sigterm_saves_and_stops_cleanly(self, tmp_path):
        initialize_registries()
        cfg = _cfg(tmp_path)
        run_dir = tmp_path / "runs" / "r1"
        (run_dir / "checkpoints").mkdir(parents=True)
        before = signal.getsignal(signal.SIGTERM)
        trainer = Trainer(cfg, run_dir, _SigtermAtFirstInterval(), None)
        res = trainer.fit()
        assert res.preempted is True
        assert 0 < res.final_step < cfg.trainer.max_steps
        assert np.isfinite(res.final_loss)
        ckpt = run_dir / "checkpoints" / f"step_{res.final_step:06d}.ckpt"
        assert ckpt.exists(), sorted((run_dir / "checkpoints").iterdir())

        # The pre-fit handler is restored — fit's own handler must not
        # leak past the run (it would swallow later SIGTERMs).
        assert signal.getsignal(signal.SIGTERM) == before

    def test_resume_continues_from_preemption_step(self, tmp_path):
        initialize_registries()
        cfg = _cfg(tmp_path)
        run_dir = tmp_path / "runs" / "r2"
        (run_dir / "checkpoints").mkdir(parents=True)
        trainer = Trainer(cfg, run_dir, _SigtermAtFirstInterval(), None)
        res = trainer.fit()
        assert res.preempted

        short = _cfg(tmp_path, max_steps=res.final_step + 3)
        resumed = Trainer(short, None, NullTracker(), None).fit(
            resume_from=str(run_dir / "checkpoints")
        )
        assert resumed.resumed_from_step == res.final_step
        assert resumed.final_step == res.final_step + 3
        assert not resumed.preempted

    def test_completed_run_reports_not_preempted(self, tmp_path):
        initialize_registries()
        cfg = _cfg(tmp_path, max_steps=3, save_every=3)
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.preempted is False
        assert res.final_step == 3


class TestCLI:
    def test_sigterm_to_train_subprocess_exits_zero_with_checkpoint(
        self, tmp_path
    ):
        import yaml

        cfg = _cfg(tmp_path)
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(cfg.model_dump(mode="json"), sort_keys=False)
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "llmtrain_tpu", "train", "--config",
             str(cfg_path), "--run-id", "prerun", "--json"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        run_dir = tmp_path / "runs" / "prerun"
        # Wait until training is demonstrably underway (train.log exists
        # and grows), then deliver the pod-eviction signal.
        deadline = time.monotonic() + 240
        log = run_dir / "logs" / "train.log"
        while time.monotonic() < deadline:
            if log.exists() and "step" in log.read_text():
                break
            if proc.poll() is not None:
                pytest.fail(f"train exited early: {proc.communicate()}")
            time.sleep(1)
        else:
            proc.kill()
            pytest.fail("training never started")
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=300)
        assert proc.returncode == 0, err
        summary = json.loads(out.splitlines()[-1])
        tr = summary["train_result"]
        assert tr["preempted"] is True
        assert tr["final_step"] < cfg.trainer.max_steps
        ckpts = sorted((run_dir / "checkpoints").glob("step_*.ckpt"))
        assert ckpts, "no checkpoint written on preemption"
        assert ckpts[-1].name == f"step_{tr['final_step']:06d}.ckpt"
