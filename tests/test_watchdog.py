"""Hang watchdog, straggler telemetry, and exit-code taxonomy tests
(llmtrain_tpu/resilience/watchdog.py + exit_codes.py).

The acceptance pillar runs END TO END through a real CLI subprocess: a
config-injected host hang (``resilience.faults.hang_at_step`` blocks the
step loop for real) is detected by the armed watchdog within a sub-second
stall timeout, produces a ``hang_report_*.txt`` with every thread's stack,
and exits with the documented retryable code — while an identical clean
run exits 0 with the watchdog armed and never firing.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import yaml

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.distributed import DistState
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.resilience import (
    EXIT_HANG_DETECTED,
    EXIT_RETRYABLE_INFRA,
    EXIT_TRAIN_FAILURE,
    HangWatchdog,
    InjectedFault,
    NonFiniteLossError,
    ProgressBeacon,
    RetryableInfraError,
    RollbackBudgetExceededError,
    StragglerTracker,
    exit_code_for_exception,
    heartbeat_age_seconds,
    is_retryable,
)
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import CheckpointManager, Trainer

pytestmark = []  # deliberately unmarked: tier-1 must exercise hang recovery


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


@pytest.fixture(autouse=True)
def _capture_llmtrain_logs():
    """Earlier test files may have run configure_logging in-process, which
    sets the 'llmtrain' logger's propagate=False — silently breaking every
    caplog assertion below. Force propagation for this module's tests."""
    logger = logging.getLogger("llmtrain")
    prev = logger.propagate
    logger.propagate = True
    yield
    logger.propagate = prev


def _cfg(tmp_path=None, **overrides):
    base = {
        "run": {"name": "wdog", "seed": 7},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 48,
            "n_heads": 2,
            "d_ff": 96,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 6,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "lr": 3e-3,
            "warmup_steps": 0,
            "log_every_steps": 2,
            "eval_every_steps": 100,
            "save_every_steps": 100,
        },
        "mlflow": {"enabled": False},
    }
    if tmp_path is not None:
        base["output"] = {"root_dir": str(tmp_path)}
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


def _run_cli_main(argv: list[str]) -> int:
    """cli.main in-process, preserving the 'llmtrain' logger state: the CLI
    reconfigures it (propagate=False, handlers), which would break caplog
    for every later test in this process."""
    from llmtrain_tpu import cli

    logger = logging.getLogger("llmtrain")
    prev_propagate = logger.propagate
    prev_level = logger.level
    prev_handlers = list(logger.handlers)
    try:
        return cli.main(argv)
    finally:
        logger.propagate = prev_propagate
        logger.setLevel(prev_level)
        for h in list(logger.handlers):
            if h not in prev_handlers:
                logger.removeHandler(h)


# --------------------------------------------------------------------------
# progress beacon + heartbeat freshness
# --------------------------------------------------------------------------


class TestProgressBeacon:
    def test_touch_records_step_and_creates_heartbeat(self, tmp_path):
        hb = tmp_path / "hb"
        beacon = ProgressBeacon(hb, heartbeat_interval_sec=0.0)
        assert heartbeat_age_seconds(hb) is None  # not yet created
        beacon.touch(3)
        step, age = beacon.snapshot()
        assert step == 3
        assert age < 1.0
        fresh = heartbeat_age_seconds(hb)
        assert fresh is not None and fresh < 5.0

    def test_heartbeat_staleness_is_observable(self, tmp_path):
        """The freshness computation the k8s livenessProbe exec performs:
        a back-dated mtime reads as stale."""
        hb = tmp_path / "hb"
        ProgressBeacon(hb, heartbeat_interval_sec=0.0).touch(1)
        past = time.time() - 3600
        os.utime(hb, (past, past))
        assert heartbeat_age_seconds(hb) > 3000

    def test_heartbeat_rate_limit(self, tmp_path):
        hb = tmp_path / "hb"
        beacon = ProgressBeacon(hb, heartbeat_interval_sec=3600.0)
        beacon.touch(1)
        first = hb.stat().st_mtime_ns
        time.sleep(0.05)
        beacon.touch(2)  # inside the interval: no second write
        assert hb.stat().st_mtime_ns == first
        assert beacon.snapshot()[0] == 2  # progress still recorded

    def test_no_heartbeat_path_is_fine(self):
        beacon = ProgressBeacon(None)
        beacon.touch(1)
        assert beacon.snapshot()[0] == 1


# --------------------------------------------------------------------------
# watchdog unit behavior (exit_fn injected; the REAL os._exit path is
# exercised by the subprocess e2e below)
# --------------------------------------------------------------------------


class TestHangWatchdog:
    def test_stall_fires_report_and_exit(self, tmp_path):
        beacon = ProgressBeacon(None)
        exits: list[int] = []
        drained = {"called": False}

        def fake_exit(code):
            exits.append(code)

        marker = threading.Event()
        helper = threading.Thread(
            target=marker.wait, name="stuck-collective-stand-in", daemon=True
        )
        helper.start()
        try:
            wd = HangWatchdog(
                beacon,
                stall_timeout_sec=0.2,
                report_dir=tmp_path,
                exit_fn=fake_exit,
                on_hang=lambda: drained.__setitem__("called", True),
            )
            wd.arm()
            deadline = time.monotonic() + 5.0
            while not wd.fired and time.monotonic() < deadline:
                time.sleep(0.02)
            wd.disarm()
            assert wd.fired
            assert exits == [EXIT_HANG_DETECTED]
            assert drained["called"]
            report = list(tmp_path.glob("hang_report_*.txt"))
            assert report == [wd.report_path]
            text = report[0].read_text()
            # All-thread stacks: the main thread AND the named helper.
            assert "MainThread" in text
            assert "stuck-collective-stand-in" in text
            assert "jax" in text  # device diagnostics section
        finally:
            marker.set()

    def test_live_beacon_never_fires(self, tmp_path):
        beacon = ProgressBeacon(None)
        exits: list[int] = []
        wd = HangWatchdog(
            beacon,
            stall_timeout_sec=0.3,
            report_dir=tmp_path,
            exit_fn=exits.append,
        )
        with wd:
            for step in range(10):
                beacon.touch(step)
                time.sleep(0.05)
        assert not wd.fired
        assert exits == []
        assert list(tmp_path.glob("hang_report_*.txt")) == []

    def test_on_hang_failure_does_not_block_exit(self, tmp_path):
        beacon = ProgressBeacon(None)
        exits: list[int] = []

        def broken_hook():
            raise RuntimeError("drain failed")

        wd = HangWatchdog(
            beacon,
            stall_timeout_sec=0.1,
            report_dir=tmp_path,
            exit_fn=exits.append,
            on_hang=broken_hook,
        )
        wd.arm()
        deadline = time.monotonic() + 5.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.02)
        wd.disarm()
        assert exits == [EXIT_HANG_DETECTED]


# --------------------------------------------------------------------------
# straggler telemetry
# --------------------------------------------------------------------------


class TestStragglerTracker:
    def test_skew_and_slowest_host(self):
        t = StragglerTracker(skew_factor=2.0, patience=3)
        rep = t.observe(np.array([0.10, 0.11, 0.35, 0.10]))
        assert rep["slowest_host"] == 2
        assert rep["max_sec"] == pytest.approx(0.35)
        # Skew is measured against the median of the OTHER hosts, so the
        # straggler cannot dilute its own signal on small host counts.
        assert rep["skew"] == pytest.approx(0.35 / 0.10)
        assert not rep["persistent"]

    def test_persistent_straggler_needs_same_host_and_patience(self):
        t = StragglerTracker(skew_factor=2.0, patience=2)
        assert not t.observe(np.array([0.1, 0.5]))["persistent"]
        assert t.observe(np.array([0.1, 0.5]))["persistent"]  # streak = 2
        # A different slowest host resets the streak.
        assert not t.observe(np.array([0.5, 0.1]))["persistent"]
        # Balanced intervals clear it entirely.
        rep = t.observe(np.array([0.1, 0.1]))
        assert rep["streak"] == 0 and not rep["persistent"]

    def test_single_host_degenerates_cleanly(self):
        rep = StragglerTracker().observe(np.array([0.2]))
        assert rep["skew"] == pytest.approx(1.0)
        assert not rep["persistent"]


# --------------------------------------------------------------------------
# exit-code taxonomy
# --------------------------------------------------------------------------


class TestExitCodeTaxonomy:
    @pytest.mark.parametrize(
        "exc,code",
        [
            (TimeoutError("rendezvous"), EXIT_RETRYABLE_INFRA),
            (ConnectionError("coordinator"), EXIT_RETRYABLE_INFRA),
            (RetryableInfraError("nfs blip"), EXIT_RETRYABLE_INFRA),
            (InjectedFault("flaky"), EXIT_RETRYABLE_INFRA),
            (NonFiniteLossError("diverged"), EXIT_TRAIN_FAILURE),
            (RollbackBudgetExceededError("budget"), EXIT_TRAIN_FAILURE),
            (RuntimeError("bug"), EXIT_TRAIN_FAILURE),
            (ValueError("bad arg"), EXIT_TRAIN_FAILURE),
        ],
    )
    def test_direct_mapping(self, exc, code):
        assert exit_code_for_exception(exc) == code

    def test_wrapped_retryable_cause_classifies_retryable(self):
        try:
            try:
                raise TimeoutError("coordinator never answered")
            except TimeoutError as inner:
                raise RuntimeError("training failed") from inner
        except RuntimeError as outer:
            assert exit_code_for_exception(outer) == EXIT_RETRYABLE_INFRA

    def test_divergence_beats_wrapped_transient(self):
        """A deterministic divergence wrapping a transient error must stay
        fatal — retrying replays the same math."""
        try:
            try:
                raise TimeoutError("incidental")
            except TimeoutError as inner:
                raise NonFiniteLossError("diverged") from inner
        except NonFiniteLossError as outer:
            assert exit_code_for_exception(outer) == EXIT_TRAIN_FAILURE

    def test_suppressed_context_does_not_leak_retryable(self):
        """`raise X from None` severs the chain: a deterministic error
        raised while handling a transient one must stay fatal."""
        try:
            try:
                raise TimeoutError("transient")
            except TimeoutError:
                raise ValueError("split not found") from None
        except ValueError as exc:
            assert exit_code_for_exception(exc) == EXIT_TRAIN_FAILURE

    def test_unsuppressed_context_still_classifies(self):
        """A plain re-raise inside an except block keeps the implicit
        chain, so the transient root cause is still visible."""
        try:
            try:
                raise ConnectionError("coordinator reset")
            except ConnectionError:
                raise RuntimeError("training failed")
        except RuntimeError as exc:
            assert exit_code_for_exception(exc) == EXIT_RETRYABLE_INFRA

    def test_retryable_set(self):
        assert is_retryable(EXIT_RETRYABLE_INFRA)
        assert is_retryable(EXIT_HANG_DETECTED)
        assert not is_retryable(0)
        assert not is_retryable(1)
        assert not is_retryable(2)

    def test_cli_maps_injected_infra_failure_to_retryable(self, tmp_path):
        """The train handler classifies a flaky dataset load (InjectedFault
        past the retry budget) as retryable infra, not generic failure."""
        cfg = _cfg(tmp_path)
        raw = cfg.model_dump()
        raw["resilience"]["retry_attempts"] = 1
        raw["resilience"]["faults"]["dataset_load_failures"] = 5
        cfg_path = tmp_path / "flaky.yaml"
        cfg_path.write_text(yaml.safe_dump(raw))
        assert _run_cli_main(["train", "--config", str(cfg_path)]) == (
            EXIT_RETRYABLE_INFRA
        )

    def test_cli_maps_distributed_misconfig_to_config_error(
        self, tmp_path, monkeypatch
    ):
        """A deterministic rendezvous misconfiguration (multi-process with
        no process id) must exit fatal-config, not retryable — restarting
        the pod would replay it forever."""
        from llmtrain_tpu.distributed import teardown_distributed

        teardown_distributed()  # clear any stale idempotency latch
        for var in (
            "RANK",
            "JAX_PROCESS_ID",
            "WORLD_SIZE",
            "JAX_NUM_PROCESSES",
            "MASTER_ADDR",
            "JAX_COORDINATOR_ADDRESS",
        ):
            monkeypatch.delenv(var, raising=False)
        cfg = _cfg(tmp_path)
        raw = cfg.model_dump()
        raw["distributed"]["enabled"] = True
        raw["distributed"]["num_processes"] = 2  # process_id left unset
        raw["resilience"]["retry_attempts"] = 1
        raw["resilience"]["retry_base_delay"] = 0.0
        cfg_path = tmp_path / "misconf.yaml"
        cfg_path.write_text(yaml.safe_dump(raw))
        from llmtrain_tpu.resilience import EXIT_CONFIG_ERROR

        assert _run_cli_main(["train", "--config", str(cfg_path)]) == (
            EXIT_CONFIG_ERROR
        )


# --------------------------------------------------------------------------
# bounded drain of the in-flight async checkpoint write (satellite)
# --------------------------------------------------------------------------


class TestBoundedCheckpointDrain:
    def test_wait_pending_and_close_abandon_a_wedged_write(
        self, tmp_path, monkeypatch, caplog
    ):
        """A write wedged on dead storage must not deadlock wait_pending or
        close when the caller bounds them — the watchdog/abort contract."""
        mgr = CheckpointManager(tmp_path / "ck")
        release = threading.Event()
        monkeypatch.setattr(
            mgr, "save_host", lambda *a, **k: release.wait(), raising=False
        )
        try:
            mgr.save_host_async(1, {}, {})
            start = time.monotonic()
            assert mgr.wait_pending(timeout=0.2) is False
            with caplog.at_level(logging.ERROR, logger="llmtrain"):
                mgr.close(timeout=0.2)
            elapsed = time.monotonic() - start
            assert elapsed < 5.0, f"bounded drain took {elapsed:.1f}s"
            assert any("abandoning" in r.message for r in caplog.records)
        finally:
            release.set()

    def test_unbounded_close_still_drains(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save_host_async(
            1,
            {"params": {"w": np.zeros(2, np.float32)}, "opt_state": {}},
            {"a": 1},
        )
        mgr.close()
        assert (tmp_path / "ck" / "step_000001.ckpt").is_file()


# --------------------------------------------------------------------------
# trainer integration (in-process; injected exit_fn is NOT used here — the
# real os._exit path runs in the subprocess e2e below)
# --------------------------------------------------------------------------


class TestTrainerIntegration:
    def test_bounded_hang_injection_blocks_for_real(self, tmp_path, caplog):
        """hang_duration_sec actually stalls the host loop (wall clock
        proves it) and the run then completes — the injection is real,
        not a flag."""
        cfg = _cfg(
            tmp_path,
            resilience={
                "faults": {"hang_at_step": 2, "hang_duration_sec": 0.4}
            },
        )
        start = time.monotonic()
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.final_step == cfg.trainer.max_steps
        assert time.monotonic() - start >= 0.4
        assert any("hanging the host step loop" in r.message for r in caplog.records)

    def test_watchdog_armed_run_completes_and_heartbeats(self, tmp_path):
        cfg = _cfg(
            tmp_path,
            resilience={
                "watchdog": {
                    "enabled": True,
                    "stall_timeout_sec": 60.0,
                    "heartbeat_interval_sec": 0.0,
                }
            },
        )
        run_dir = tmp_path / "armed"
        run_dir.mkdir()
        res = Trainer(cfg, run_dir, NullTracker(), None).fit()
        assert res.final_step == cfg.trainer.max_steps
        hb = run_dir / "heartbeat"
        assert hb.is_file()
        assert heartbeat_age_seconds(hb) < 60.0
        assert list(run_dir.glob("hang_report_*.txt")) == []

    def test_off_main_thread_fit_warns_about_sigterm(self, tmp_path, caplog):
        """Embedding the trainer off the main thread silently loses
        preemption handling — it must now be loudly visible (satellite)."""
        cfg = _cfg(tmp_path, trainer={"max_steps": 2, "log_every_steps": 1})
        result: dict = {}

        def run():
            with caplog.at_level(logging.WARNING, logger="llmtrain"):
                result["res"] = Trainer(cfg, None, NullTracker(), None).fit()

        t = threading.Thread(target=run)
        t.start()
        t.join(timeout=300)
        assert not t.is_alive()
        assert result["res"].final_step == 2
        assert any(
            "off the main thread" in r.message and "SIGTERM" in r.message
            for r in caplog.records
        )

    def test_spike_rollback_consensus_path_multi_process(self, tmp_path, caplog):
        """The multi-process disabling branch is gone: with a (degenerate
        single-jax-process) 2-process DistState the detector stays active
        and the rollback goes through the consensus all-gather + rank-0
        target broadcast code path."""
        cfg = _cfg(
            tmp_path,
            trainer={
                "max_steps": 12,
                "log_every_steps": 2,
                "save_every_steps": 5,
            },
            resilience={
                "spike_detection": True,
                "spike_factor": 4.0,
                "spike_min_history": 4,
                "max_rollbacks": 2,
                "faults": {"spike_loss_at_step": 8, "spike_loss_scale": 100.0},
            },
        )
        dist = DistState(
            process_index=0, num_processes=2, local_device_count=1, is_main=True
        )
        run_dir = tmp_path / "consensus"
        run_dir.mkdir()
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, run_dir, NullTracker(), dist).fit()
        assert res.rollbacks == 1
        assert res.final_step == 12
        assert not any(
            "disabling" in r.message and "detector" in r.message
            for r in caplog.records
        )

    def test_multi_process_spike_detection_without_ckpt_dir_fails_fast(
        self, tmp_path
    ):
        cfg = _cfg(tmp_path, resilience={"spike_detection": True})
        dist = DistState(
            process_index=0, num_processes=2, local_device_count=1, is_main=True
        )
        with pytest.raises(ValueError, match="shared run directory"):
            Trainer(cfg, None, NullTracker(), dist).fit()


# --------------------------------------------------------------------------
# end-to-end: the acceptance pillar, through a real CLI subprocess
# --------------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=_cli_env(),
        timeout=420,
    )


def _e2e_cfg(**resilience):
    cfg = _cfg()
    raw = cfg.model_dump()
    raw["output"] = {"root_dir": "runs"}
    raw["resilience"] = {**raw["resilience"], **resilience}
    return raw


class TestWatchdogEndToEnd:
    def test_injected_hang_is_killed_with_report_and_retryable_code(
        self, tmp_path
    ):
        """hang_at_step blocks the step loop for real; the watchdog must
        detect the stall within the sub-second timeout, write a hang
        report containing every thread's stack, and hard-exit with the
        documented retryable code."""
        raw = _e2e_cfg(
            watchdog={
                "enabled": True,
                "stall_timeout_sec": 0.8,
                "heartbeat_interval_sec": 0.0,
            },
            faults={"hang_at_step": 3},
        )
        (tmp_path / "hang.yaml").write_text(yaml.safe_dump(raw))
        start = time.monotonic()
        proc = _run_cli(
            ["train", "--config", "hang.yaml", "--run-id", "hangrun"], tmp_path
        )
        elapsed = time.monotonic() - start
        assert proc.returncode == EXIT_HANG_DETECTED, (
            f"expected exit {EXIT_HANG_DETECTED}, got {proc.returncode}\n"
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
        # Detection latency is bounded by timeout + poll + report, far
        # under the 420 s subprocess ceiling; assert it did not sit around.
        assert elapsed < 300
        run_dir = tmp_path / "runs" / "hangrun"
        reports = list(run_dir.glob("hang_report_*.txt"))
        assert len(reports) == 1, proc.stderr
        text = reports[0].read_text()
        assert "MainThread" in text  # the blocked step loop's stack
        assert "maybe_hang" in text  # ... pointing at the actual stall site
        assert "hang-watchdog" in text  # all threads, including the watchdog
        assert "jax" in text  # device diagnostics section
        assert "HANG DETECTED" in proc.stderr
        # Beacon progressed to the hang step before stalling.
        assert (run_dir / "heartbeat").is_file()

    def test_clean_run_exits_zero_with_watchdog_armed(self, tmp_path):
        raw = _e2e_cfg(
            watchdog={
                "enabled": True,
                "stall_timeout_sec": 60.0,
                "heartbeat_interval_sec": 0.0,
            }
        )
        (tmp_path / "clean.yaml").write_text(yaml.safe_dump(raw))
        proc = _run_cli(
            ["train", "--config", "clean.yaml", "--run-id", "cleanrun"], tmp_path
        )
        assert proc.returncode == 0, proc.stderr
        assert "hang watchdog armed" in proc.stderr
        run_dir = tmp_path / "runs" / "cleanrun"
        assert (run_dir / "heartbeat").is_file()
        assert list(run_dir.glob("hang_report_*.txt")) == []
        # The run trained to completion: the final checkpoint exists.
        assert (run_dir / "checkpoints" / "step_000006.ckpt").is_file()
