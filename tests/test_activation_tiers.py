"""Per-layer activation policy tiers (model.extra.activation_tiers).

The tier ladder replaces the global ``model.remat`` boolean: every
transformer block gets one of ``none | selective | full | offload``
(docs/perf.md "Activation tiers and host offload"). Covered here:

* the spec grammar — parse tables, canonicalization round-trips, and the
  full rejection catalogue (unknown tier, overlap, inversion, range);
* jaxpr evidence that the ladder pins remat boundaries per layer (N
  ``remat`` equations for N rematerialized layers, zero for all-none);
* bitwise forward parity — tiers change what is recomputed, never the
  math;
* the ``model.remat: true`` deprecation shim and the remat/tiers
  conflict, at both the schema and the adapter layer;
* the planner's per-tier HBM model: monotone none > full > offload
  ladders, host-offload bytes tracked outside the device total, and the
  fits/doesn't-fit ordering the bench offload scenario pins a cap from;
* candidate enumeration producing tier-ladder candidates with the
  ``|act=`` key suffix (and pre-tier keys byte-identical to before);
* ``@pytest.mark.slow``: real Trainer fits under a ladder (CPU
  pinned_host fallback warning, mem/activation_bytes gauges) and the
  checkpoint/elastic-resume contract with tiers CHANGED between save
  and resume (tiers are resume-mutable, like loss_impl).
  ``make verify-offload`` runs everything including the slow fits.
"""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.config.activation_tiers import (
    TIERS,
    canonical_tier_spec,
    parse_activation_tiers,
)
from llmtrain_tpu.models.gpt import GPT
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking import NullTracker


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


VOCAB = 64
BLOCK = 16


def _tiny_gpt(**overrides):
    kwargs = dict(
        vocab_size=VOCAB,
        block_size=BLOCK,
        d_model=32,
        n_layers=2,
        n_heads=4,
        d_ff=64,
        dropout=0.0,
    )
    kwargs.update(overrides)
    return GPT(**kwargs)


def _run_cfg(n_layers=2, model_extra=None, remat=False, **sections):
    base = {
        "run": {"name": "tiers", "seed": 3, "device": "cpu"},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 32,
            "n_heads": 4,
            "d_ff": 64,
            "n_layers": n_layers,
            "remat": remat,
            "extra": {**(model_extra or {})},
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 6,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "lr": 3e-3,
            "warmup_steps": 0,
            "log_every_steps": 3,
            "eval_every_steps": 100,
            "save_every_steps": 100,
        },
        "mlflow": {"enabled": False},
    }
    for section, values in sections.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


# --------------------------------------------------------------------------
# Spec grammar
# --------------------------------------------------------------------------


class TestParseTable:
    @pytest.mark.parametrize(
        ("spec", "n_layers", "expected"),
        [
            ("none:*", 3, ("none", "none", "none")),
            ("full:*", 2, ("full", "full")),
            ("offload:*", 1, ("offload",)),
            ("selective:1", 3, ("none", "selective", "none")),
            ("full:0-1", 4, ("full", "full", "none", "none")),
            (
                "offload:0-1,full:2-3",
                4,
                ("offload", "offload", "full", "full"),
            ),
            # Out-of-order entries and single-layer ranges are fine.
            ("full:3,offload:0-2", 4, ("offload", "offload", "offload", "full")),
            # Unassigned layers default to none (cheapest tier).
            ("full:1", 3, ("none", "full", "none")),
        ],
    )
    def test_parse(self, spec, n_layers, expected):
        assert parse_activation_tiers(spec, n_layers) == expected

    @pytest.mark.parametrize(
        "spec",
        [
            "",  # empty
            "turbo:*",  # unknown tier
            "full",  # missing range
            "full:",  # empty range
            "full:a-b",  # non-numeric
            "full:3-1",  # inverted
            "full:0-9",  # out of range for n_layers=2
            "full:2",  # out of range (0-based)
            "full:0,none:0",  # overlap
            "full:0-1,offload:1",  # overlap via range
            "full:*,none:0",  # * must be the sole entry
            "full:-1",  # negative
        ],
    )
    def test_rejects(self, spec):
        with pytest.raises(ValueError):
            parse_activation_tiers(spec, 2)

    def test_canonical_round_trip(self):
        for spec, n_layers in [
            ("none:*", 4),
            ("full:*", 4),
            ("offload:0-1,full:2-3", 4),
            ("selective:1,full:2-3", 4),
        ]:
            tiers = parse_activation_tiers(spec, n_layers)
            canon = canonical_tier_spec(tiers)
            assert parse_activation_tiers(canon, n_layers) == tiers

    def test_canonical_compresses_runs(self):
        assert canonical_tier_spec(("full", "full", "full")) == "full:*"
        assert (
            canonical_tier_spec(("offload", "full", "full", "none"))
            == "offload:0,full:1-2,none:3"
        )

    def test_tier_names_are_stable(self):
        # The config surface: renaming a tier is a breaking change.
        assert TIERS == ("none", "selective", "full", "offload")


# --------------------------------------------------------------------------
# Remat boundaries in the jaxpr + forward parity
# --------------------------------------------------------------------------


def _remat_eqn_count(model, params, tokens) -> int:
    def loss(p):
        logits = model.apply({"params": p}, tokens, deterministic=True)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
    return sum(
        1 for eqn in jaxpr.jaxpr.eqns if "remat" in eqn.primitive.name
    )


class TestJaxprBoundaries:
    """The ladder must be visible in the lowered program: one remat scope
    per rematerialized layer, none for ``none`` layers."""

    def _params(self, model):
        from flax.linen import meta as nn_meta

        ids = jnp.zeros((1, BLOCK), jnp.int32)
        return nn_meta.unbox(
            model.init(jax.random.key(0), ids, deterministic=True)
        )["params"]

    def test_counts_per_ladder(self):
        base = _tiny_gpt()
        params = self._params(base)
        tokens = jnp.asarray(
            np.random.default_rng(5).integers(0, VOCAB, (2, BLOCK)), jnp.int32
        )
        cases = {
            ("none", "none"): 0,
            ("full", "full"): 2,
            ("full", "none"): 1,
            ("selective", "selective"): 2,
        }
        for tiers, expected in cases.items():
            model = _tiny_gpt(activation_tiers=tiers)
            assert _remat_eqn_count(model, params, tokens) == expected, tiers

    def test_offload_ladder_traces_and_pins_boundaries(self):
        """On this CPU container offload degrades to full remat (no
        pinned_host memory space) BEFORE reaching the model, so exercise
        the resolver path end to end via the adapter."""
        from llmtrain_tpu.models.gpt import resolve_config_activation_tiers

        cfg = _run_cfg(model_extra={"activation_tiers": "offload:0,full:1"})
        tiers = resolve_config_activation_tiers(cfg)
        assert tiers is not None and len(tiers) == 2
        assert all(t in ("full", "offload") for t in tiers)
        model = _tiny_gpt(activation_tiers=tiers)
        params = self._params(_tiny_gpt())
        tokens = jnp.zeros((1, BLOCK), jnp.int32)
        assert _remat_eqn_count(model, params, tokens) == 2

    def test_forward_bitwise_parity_across_ladders(self):
        """Tiers only change what the BACKWARD pass recomputes; forward
        logits must be bit-identical across every ladder."""
        base = _tiny_gpt()
        params = self._params(base)
        tokens = jnp.asarray(
            np.random.default_rng(9).integers(0, VOCAB, (2, BLOCK)), jnp.int32
        )
        ref = np.asarray(base.apply({"params": params}, tokens, deterministic=True))
        for tiers in [
            ("full", "full"),
            ("selective", "none"),
            ("full", "selective"),
        ]:
            got = np.asarray(
                _tiny_gpt(activation_tiers=tiers).apply(
                    {"params": params}, tokens, deterministic=True
                )
            )
            assert (ref == got).all(), tiers

    def test_grads_flow_and_are_close(self):
        """Gradients under any ladder stay finite and match the no-remat
        baseline to fp noise (remat may reassociate reductions, so this is
        allclose, not bitwise — the bench gates bitwise on the LOSS)."""
        base = _tiny_gpt()
        params = self._params(base)
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, VOCAB, (2, BLOCK)), jnp.int32
        )

        def grads_of(model):
            def loss(p):
                logits = model.apply({"params": p}, tokens, deterministic=True)
                return jnp.mean(logits.astype(jnp.float32) ** 2)

            return jax.grad(loss)(params)

        g_ref = grads_of(base)
        g_tiered = grads_of(_tiny_gpt(activation_tiers=("full", "selective")))
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_tiered)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


# --------------------------------------------------------------------------
# Config surface: shim, conflicts, strict validation
# --------------------------------------------------------------------------


class TestConfigResolution:
    def test_tiers_spec_wins(self):
        from llmtrain_tpu.models.gpt import resolve_config_activation_tiers

        cfg = _run_cfg(n_layers=4, model_extra={"activation_tiers": "full:0-1"})
        assert resolve_config_activation_tiers(cfg) == (
            "full",
            "full",
            "none",
            "none",
        )

    def test_no_remat_no_tiers_is_none(self):
        from llmtrain_tpu.models.gpt import resolve_config_activation_tiers

        assert resolve_config_activation_tiers(_run_cfg()) is None

    def test_remat_true_migrates_to_full_star(self, caplog):
        """Deprecation shim: model.remat true (default policy) maps to
        ``full:*`` with a one-time INFO."""
        import llmtrain_tpu.models.gpt as gpt_mod

        gpt_mod._TIER_MIGRATION_LOGGED = False
        cfg = _run_cfg(remat=True)
        with caplog.at_level(logging.INFO):
            assert gpt_mod.resolve_config_activation_tiers(cfg) == ("full", "full")
            gpt_mod.resolve_config_activation_tiers(cfg)
        msgs = [r for r in caplog.records if "deprecated" in r.getMessage()]
        assert len(msgs) == 1  # once per process, not per call

    def test_remat_dots_migrates_to_selective(self):
        import llmtrain_tpu.models.gpt as gpt_mod

        cfg = _run_cfg(remat=True, model_extra={"remat_policy": "dots"})
        assert gpt_mod.resolve_config_activation_tiers(cfg) == (
            "selective",
            "selective",
        )

    def test_remat_dots_no_batch_stays_legacy(self):
        """dots_no_batch has no tier equivalent; the legacy remat path
        keeps handling it (returns None -> model uses remat/remat_policy)."""
        from llmtrain_tpu.models.gpt import resolve_config_activation_tiers

        cfg = _run_cfg(remat=True, model_extra={"remat_policy": "dots_no_batch"})
        assert resolve_config_activation_tiers(cfg) is None

    def test_schema_rejects_bad_spec(self):
        with pytest.raises(ValueError, match="activation_tiers"):
            _run_cfg(model_extra={"activation_tiers": "turbo:*"})

    def test_schema_rejects_out_of_range(self):
        with pytest.raises(ValueError, match="activation_tiers"):
            _run_cfg(n_layers=2, model_extra={"activation_tiers": "full:0-7"})

    def test_schema_rejects_remat_conflict(self):
        with pytest.raises(ValueError, match="conflict"):
            _run_cfg(remat=True, model_extra={"activation_tiers": "full:*"})

    def test_offload_spec_is_not_a_config_error_without_pinned_host(self):
        """Missing pinned_host is a RUNTIME downgrade (offload -> full with
        a warning), never a config validation failure — the same YAML must
        validate on a laptop and run offloaded on a TPU host."""
        cfg = _run_cfg(model_extra={"activation_tiers": "offload:*"})
        assert cfg.model.extra["activation_tiers"] == "offload:*"

    def test_runtime_fallback_warns_once(self, caplog):
        from llmtrain_tpu.models import activation_policy

        activation_policy._FALLBACK_WARNED.clear()
        with caplog.at_level(logging.WARNING):
            out1 = activation_policy.resolve_activation_tiers(("offload", "full"))
            out2 = activation_policy.resolve_activation_tiers(("offload", "none"))
        if activation_policy.offload_supported():  # pragma: no cover - TPU host
            assert out1 == ("offload", "full")
            return
        assert out1 == ("full", "full")
        assert out2 == ("full", "none")
        warned = [r for r in caplog.records if "pinned_host" in r.getMessage()]
        assert len(warned) == 1  # once per process, not per resolve

    def test_adapter_builds_tiered_model(self):
        from llmtrain_tpu.models.gpt import GPTAdapter

        cfg = _run_cfg(n_layers=2, model_extra={"activation_tiers": "full:0"})
        model = GPTAdapter().build_model(cfg)
        assert model.activation_tiers == ("full", "none")


# --------------------------------------------------------------------------
# Planner HBM model + candidate enumeration
# --------------------------------------------------------------------------


class TestHbmModel:
    def _hbm(self, cfg, devices=4):
        from llmtrain_tpu.autotune.plan import plan_from_config, predict_hbm_bytes
        from llmtrain_tpu.models.gpt import GPTAdapter

        plan = plan_from_config(cfg, devices, adapter=GPTAdapter())
        return predict_hbm_bytes(
            plan,
            n_params=1_000_000,
            d_model=cfg.model.d_model,
            n_layers=cfg.model.n_layers,
            vocab_size=int(cfg.model.vocab_size),
            block_size=cfg.model.block_size,
        )

    def test_ladder_monotonicity(self):
        """The reason tiers exist: none > selective > full >= offload
        ladder in device-resident activation bytes; offload alone parks
        bytes in host RAM."""
        n = {"activation_tiers": "none:*"}
        s = {"activation_tiers": "selective:*"}
        f = {"activation_tiers": "full:*"}
        o = {"activation_tiers": "offload:0,full:1"}
        h_n = self._hbm(_run_cfg(model_extra=n))
        h_s = self._hbm(_run_cfg(model_extra=s))
        h_f = self._hbm(_run_cfg(model_extra=f))
        h_o = self._hbm(_run_cfg(model_extra=o))
        assert h_n["activation_bytes"] > h_s["activation_bytes"]
        assert h_s["activation_bytes"] > h_f["activation_bytes"]
        assert h_o["activation_bytes"] < h_f["activation_bytes"]
        assert h_n["total_bytes"] > h_f["total_bytes"] > h_o["total_bytes"]
        # Host bytes appear ONLY under offload, and never in the total.
        assert h_n["activation_host_bytes"] == 0
        assert h_f["activation_host_bytes"] == 0
        assert h_o["activation_host_bytes"] > 0
        parts = (
            h_o["params_bytes"]
            + h_o["grads_bytes"]
            + h_o["opt_state_bytes"]
            + h_o["activation_bytes"]
            + h_o["logits_bytes"]
        )
        assert h_o["total_bytes"] == pytest.approx(parts, abs=2)

    def test_per_tier_breakdown_keys(self):
        hbm = self._hbm(
            _run_cfg(n_layers=4, model_extra={"activation_tiers": "offload:0-1,full:2-3"})
        )
        assert set(hbm["activation_bytes_by_tier"]) == {"offload", "full"}
        assert sum(hbm["activation_bytes_by_tier"].values()) == pytest.approx(
            hbm["activation_bytes"], abs=2
        )

    def test_cap_ordering_matches_bench_scenario(self):
        """The bench offload scenario derives its HBM cap as the midpoint
        of the two predictions; pin the fits/doesn't-fit ordering here so
        `llmtrain plan` and the bench line can never disagree."""
        h_none = self._hbm(_run_cfg(model_extra={"activation_tiers": "none:*"}))
        h_tier = self._hbm(
            _run_cfg(model_extra={"activation_tiers": "offload:0,full:1"})
        )
        cap = (h_none["total_bytes"] + h_tier["total_bytes"]) // 2
        assert not h_none["total_bytes"] <= cap  # all-none does NOT fit
        assert h_tier["total_bytes"] <= cap  # the ladder fits

    def test_plan_cli_fits_verdict_for_both_configs(self, tmp_path, capsys):
        """`llmtrain plan` itself (not just the HBM model it wraps) must
        call fits/doesn't-fit correctly under a cap between the all-none
        and tiered predictions: exit 2 + feasible=false for all-none,
        exit 0 + feasible=true for the ladder."""
        import argparse
        import json

        import yaml

        from llmtrain_tpu.cli import _handle_plan

        def plan_rc(extra, cap, tag):
            cfg = _run_cfg(model_extra=extra)
            data = cfg.model_dump(mode="json", exclude_none=True)
            if cap is not None:
                data.setdefault("tune", {})["hbm_limit_bytes"] = float(cap)
            path = tmp_path / f"{tag}.yaml"
            path.write_text(yaml.safe_dump(data, sort_keys=False))
            rc = _handle_plan(
                argparse.Namespace(config=str(path), devices=1, json=True)
            )
            payload = json.loads(capsys.readouterr().out)
            return rc, payload

        _, none_free = plan_rc({"activation_tiers": "none:*"}, None, "n0")
        _, tier_free = plan_rc(
            {"activation_tiers": "offload:0,full:1"}, None, "t0"
        )
        cap = (
            none_free["predicted_hbm"]["total_bytes"]
            + tier_free["predicted_hbm"]["total_bytes"]
        ) / 2
        rc_none, p_none = plan_rc({"activation_tiers": "none:*"}, cap, "n1")
        rc_tier, p_tier = plan_rc(
            {"activation_tiers": "offload:0,full:1"}, cap, "t1"
        )
        assert rc_none == 2 and p_none["feasible"] is False
        assert rc_tier == 0 and p_tier["feasible"] is True

    def test_bad_spec_raises_mesh_plan_error(self):
        from llmtrain_tpu.autotune.plan import (
            MeshPlanError,
            ModelCaps,
            resolve_plan,
        )

        with pytest.raises(MeshPlanError, match="activation_tiers"):
            resolve_plan(
                mesh_sizes={"data": 4},
                device_count=4,
                micro_batch_size=2,
                caps=ModelCaps(n_heads=4, block_size=8, n_layers=2),
                activation_tiers="full:0-7",
            )

    def test_remat_conflict_raises(self):
        from llmtrain_tpu.autotune.plan import (
            MeshPlanError,
            ModelCaps,
            resolve_plan,
        )

        with pytest.raises(MeshPlanError, match="remat"):
            resolve_plan(
                mesh_sizes={"data": 4},
                device_count=4,
                micro_batch_size=2,
                caps=ModelCaps(n_heads=4, block_size=8, n_layers=2),
                remat=True,
                activation_tiers="full:*",
            )

    def test_key_suffix_only_when_tiers_set(self):
        from llmtrain_tpu.autotune.plan import plan_from_config
        from llmtrain_tpu.models.gpt import GPTAdapter

        plain = plan_from_config(_run_cfg(), 4, adapter=GPTAdapter())
        assert "act=" not in plain.key()  # pre-tier keys stay byte-stable
        tiered = plan_from_config(
            _run_cfg(model_extra={"activation_tiers": "offload:0,full:1"}),
            4,
            adapter=GPTAdapter(),
        )
        assert tiered.key().endswith("|act=offload:0,full:1")


class TestSearchLadders:
    def test_enumeration_includes_offload_ladder(self):
        from llmtrain_tpu.autotune.search import enumerate_candidates

        cands = enumerate_candidates(
            _run_cfg(n_layers=4),
            8,
            seed=0,
            microbatch_candidates=[2],
            search_mesh=False,
            search_remat=True,
            search_zero=False,
        )
        specs = {c.activation_tiers for c in cands}
        assert "" in specs  # the legacy remat on/off axis is still there
        assert any("offload:" in s for s in specs)
        ladder = next(s for s in specs if "offload:" in s)
        keyed = [c for c in cands if c.activation_tiers == ladder]
        assert all(c.key().endswith(f"|act={ladder}") for c in keyed)

    def test_base_spec_carried_through_all_candidates(self):
        """When the base config already runs a ladder, every enumerated
        candidate carries an EXPLICIT spec — a tier-less override merged
        over the base would silently inherit the base ladder under a
        misleading key."""
        from llmtrain_tpu.autotune.search import enumerate_candidates

        cfg = _run_cfg(n_layers=4, model_extra={"activation_tiers": "full:0-1"})
        cands = enumerate_candidates(
            cfg,
            8,
            seed=0,
            microbatch_candidates=[2],
            search_mesh=False,
            search_remat=True,
            search_zero=False,
        )
        assert all(c.activation_tiers for c in cands)
        assert any(c.activation_tiers == "full:0-1,none:2-3" for c in cands)

    def test_plan_overrides_round_trip(self):
        """config_overrides() of a tiered plan re-validates and resolves to
        the same ladder (the tune emit path)."""
        from llmtrain_tpu.autotune.plan import plan_from_config
        from llmtrain_tpu.models.gpt import GPTAdapter
        from llmtrain_tpu.resilience.harness import deep_merge

        cfg = _run_cfg(model_extra={"activation_tiers": "offload:0,full:1"})
        plan = plan_from_config(cfg, 4, adapter=GPTAdapter())
        merged = deep_merge(
            cfg.model_dump(exclude_none=True), plan.config_overrides()
        )
        cfg2 = RunConfig.model_validate(merged)
        assert cfg2.model.extra["activation_tiers"] == "offload:0,full:1"
        assert cfg2.model.remat is False


# --------------------------------------------------------------------------
# Slow: real fits under a ladder + resume with tiers changed
# --------------------------------------------------------------------------


@pytest.mark.slow
class TestTieredFits:
    def test_offload_ladder_fits_and_publishes_gauges(self, caplog):
        """End-to-end: a Trainer fit under an offload-bottom ladder on this
        CPU container (a) downgrades offload -> full with the one-time
        warning, (b) trains to a finite decreasing loss, (c) publishes the
        mem/activation_bytes{,_offloaded} gauges into the memory block."""
        from llmtrain_tpu.models import activation_policy
        from llmtrain_tpu.training import Trainer

        activation_policy._FALLBACK_WARNED.clear()
        cfg = _run_cfg(model_extra={"activation_tiers": "offload:0,full:1"})
        with caplog.at_level(logging.WARNING):
            trainer = Trainer(cfg, None, NullTracker(), None)
            res = trainer.fit()
        if not activation_policy.offload_supported():
            assert any("pinned_host" in r.getMessage() for r in caplog.records)
        assert np.isfinite(res.final_loss)
        assert res.final_loss < res.first_step_loss
        latest = trainer._telemetry.metrics.latest()
        assert latest["mem/activation_bytes"][0] > 0
        assert latest["mem/activation_bytes_offloaded"][0] > 0
        monitor = trainer._telemetry.memory
        assert monitor is not None
        peaks = monitor.peaks()
        assert peaks["activation_bytes"] == latest["mem/activation_bytes"][0]

    def test_loss_bitwise_parity_tiered_vs_none_first_step(self):
        """The bench offload scenario's bitwise claim, pinned as a test:
        step-1 loss (pure forward on identical init) is bit-identical
        between all-none and the ladder."""
        from llmtrain_tpu.training import Trainer

        runs = {}
        for name, extra in [
            ("none", {"activation_tiers": "none:*"}),
            ("ladder", {"activation_tiers": "offload:0,full:1"}),
        ]:
            cfg = _run_cfg(model_extra=extra, trainer={"max_steps": 2})
            runs[name] = Trainer(cfg, None, NullTracker(), None).fit()
        assert runs["none"].first_step_loss == runs["ladder"].first_step_loss

    def test_resume_with_tiers_changed(self, tmp_path):
        """Tiers are resume-mutable (like loss_impl): params/opt_state are
        tier-independent, so a checkpoint saved under ``full:*`` resumes
        under ``none:*`` (and vice versa) with only the config-mismatch
        warning."""
        from llmtrain_tpu.training import Trainer

        cfg_a = _run_cfg(
            model_extra={"activation_tiers": "full:*"},
            trainer={"max_steps": 6, "save_every_steps": 3},
        )
        run_a = tmp_path / "save"
        run_a.mkdir()
        Trainer(cfg_a, run_a, NullTracker(), None).fit(max_steps_override=3)

        cfg_b = _run_cfg(
            model_extra={"activation_tiers": "none:*"},
            trainer={"max_steps": 6, "save_every_steps": 3},
        )
        res = Trainer(cfg_b, None, NullTracker(), None).fit(
            resume_from=str(run_a / "checkpoints" / "step_000003.ckpt")
        )
        assert res.resumed_from_step == 3
        assert res.final_step == 6
        assert np.isfinite(res.final_loss)

    def test_elastic_resume_with_tiers_changed(self, tmp_path):
        """Elastic world-size change AND a tier-ladder change in the same
        resume: save on an emulated 2-device data mesh under ``full:*``,
        resume on 1 device (global micro-batch preserved, 2x2 -> 4x1)
        under the offload ladder."""
        import jax as _jax

        from llmtrain_tpu.training import Trainer

        all_cpu = _jax.devices("cpu")
        if len(all_cpu) < 2:
            pytest.skip("needs >= 2 emulated devices")

        # Topology-independent dataset (test_elastic.py corpus pattern:
        # local_text sizes itself from the file, dummy_text from the
        # batch topology).
        corpus = tmp_path / "corpus.txt"
        corpus.write_text("the quick brown fox jumps over the lazy dog. " * 200)

        def cfg_for(tiers, micro, mesh):
            return _run_cfg(
                model_extra={"activation_tiers": tiers, "tokenizer": "byte"},
                model={"vocab_size": 256},
                data={
                    "name": "local_text",
                    "cache_dir": str(tmp_path / "cache"),
                    "extra": {"globs": [str(corpus)], "val_fraction": 0.1},
                },
                trainer={"max_steps": 6, "save_every_steps": 3,
                         "micro_batch_size": micro},
                distributed={"mesh": mesh},
            )

        real = _jax.devices
        _jax.devices = lambda *a, **k: all_cpu[:2]
        try:
            run_a = tmp_path / "ws2"
            run_a.mkdir()
            Trainer(
                cfg_for("full:*", 2, {"data": 2}), run_a, NullTracker(), None
            ).fit(max_steps_override=3)
        finally:
            _jax.devices = real

        _jax.devices = lambda *a, **k: all_cpu[:1]
        try:
            res = Trainer(
                cfg_for("offload:0,full:1", 4, {"data": 1}),
                None,
                NullTracker(),
                None,
            ).fit(resume_from=str(run_a / "checkpoints" / "step_000003.ckpt"))
        finally:
            _jax.devices = real
        assert res.resumed_from_step == 3
        assert np.isfinite(res.final_loss)
