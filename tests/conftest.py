"""Test bootstrap: force an 8-virtual-device CPU platform before JAX imports.

This is the TPU-build analogue of the reference's mocked-collective technique
(reference tests/test_distributed.py:609-619): instead of faking
``all_gather``/``all_reduce``, we give XLA eight real host devices so mesh
shardings and collectives execute for real in a single process.
"""

import os
import sys

# Make the in-repo package importable without an editable install, both here
# and in every subprocess the tests spawn (CLI and multi-process tests run
# ``python -m llmtrain_tpu`` from temp dirs).
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)
os.environ["PYTHONPATH"] = (
    _REPO_ROOT + os.pathsep + os.environ["PYTHONPATH"]
    if os.environ.get("PYTHONPATH")
    else _REPO_ROOT
)

# Force CPU even when the host pre-sets JAX_PLATFORMS to a real TPU platform:
# unit tests must be hermetic and use the 8-device virtual mesh. The host's
# sitecustomize pre-imports jax, so the env var alone is too late — update the
# config directly (the backend itself is still uninitialized at this point).
# Escape hatch: LLMTRAIN_TEST_TPU=1 keeps the real accelerator so the
# TPU-gated compiled-kernel tests (tests/test_tpu_compiled.py) can run in the
# bench environment.
_use_tpu = os.environ.get("LLMTRAIN_TEST_TPU") == "1"
if not _use_tpu:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _use_tpu:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compilation cache for the suite (VERDICT r4 item 4): the gate
# is dominated by jit compiles of shapes that never change between runs.
# Subprocesses (CLI / multi-process tests) inherit the env var and hit the
# same cache. An explicit LLMTRAIN_COMPILATION_CACHE (incl. "off") wins.
if "LLMTRAIN_COMPILATION_CACHE" not in os.environ:
    os.environ["LLMTRAIN_COMPILATION_CACHE"] = os.path.join(
        os.path.expanduser("~"), ".cache", "llmtrain_tpu", "jax-tests"
    )
from llmtrain_tpu.distributed import configure_compilation_cache  # noqa: E402

configure_compilation_cache()

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Under LLMTRAIN_TEST_TPU=1 run ONLY the TPU-gated compiled tests.

    Everything else assumes the hermetic 8-virtual-device CPU mesh this
    flag disables, so running it against the real backend would fail (or
    pass against the wrong topology)."""
    if not _use_tpu:
        return
    # Fail loudly rather than silently skipping everything: an all-skipped
    # run exits 0 and would record the compiled-kernel suite as green when
    # nothing executed (e.g. the TPU tunnel is down).
    try:
        backend = jax.default_backend()
    except Exception as exc:  # backend init failure
        raise pytest.UsageError(
            f"LLMTRAIN_TEST_TPU=1 but the TPU backend failed to initialize: {exc}"
        ) from exc
    if backend != "tpu":
        raise pytest.UsageError(
            f"LLMTRAIN_TEST_TPU=1 but jax.default_backend() is {backend!r}, not 'tpu'"
        )
    skip = pytest.mark.skip(
        reason="LLMTRAIN_TEST_TPU=1 runs only tests/test_tpu_compiled.py"
    )
    for item in items:
        if "test_tpu_compiled" not in str(item.fspath):
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _reset_distributed_state():
    """Guarantee distributed-state teardown between tests.

    Analogue of the reference's autouse teardown fixture
    (reference tests/test_distributed.py:31-35).
    """
    yield
    from llmtrain_tpu.distributed import teardown_distributed

    teardown_distributed()
