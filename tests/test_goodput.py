"""Goodput-ledger tests (llmtrain_tpu/telemetry/goodput.py).

Covers the ISSUE-12 contract:

* Synthetic-timeline taxonomy tables — hand-written segment JSONL with
  known second splits must attribute EXACTLY (compile, data_wait,
  checkpoint, eval, productive vs recomputed via the last-execution
  rule, restart_overhead from cross-segment gaps, suspension carving).
* The ledger-balances invariant: categories sum to the wall clock —
  through the synthetic tables, the real Telemetry facade end to end
  (finalize -> report.json goodput block -> `llmtrain goodput` CLI
  reproducing the same numbers), and a simulated crash (no footer).
* Durability details: torn tail lines tolerated, legacy no-header
  timelines return None (never a wrong ledger), heartbeat mtime extends
  the final crashed segment, timeline_dropped surfaces as a counter.
* @slow drills (`make verify-goodput`): a REAL mid-interval SIGKILL
  leaving a torn timeline that still balances; the 3-cycle chaos drill
  with recomputed_sec > 0 and post-mortem CLI reproducibility; the
  3-tenant fleet storm with per-tenant ledgers, suspension attribution,
  and the fleet-wide second-weighted goodput_frac.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest
import yaml

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.resilience.exit_codes import (
    EXIT_CONFIG_ERROR,
    EXIT_OK,
    EXIT_TRAIN_FAILURE,
)
from llmtrain_tpu.telemetry.goodput import (
    CATEGORIES,
    _carve_suspensions,
    compute_goodput,
    final_committed_step,
    goodput_gauges,
    render_goodput_md,
)

_PRESETS = Path(__file__).resolve().parents[1] / "configs" / "presets"
_CHAOS_PRESET = _PRESETS / "gpt_chaos_smoke.yaml"
_FLEET_PRESET = _PRESETS / "gpt_fleet_smoke.yaml"

# Balance tolerance for ledgers built from 3-decimal-rounded categories:
# 9 categories x 0.0005 rounding error, plus a little slack.
_EPS = 0.02


# ------------------------------------------------------- synthetic timelines


def _header(seg_id: int, start: float) -> dict:
    return {
        "name": "segment_start",
        "ph": "seg",
        "segment_id": seg_id,
        "start_unix_time": start,
        "process_index": 0,
        "pid": 12345,
    }


def _footer(seg_id: int, end: float) -> dict:
    return {
        "name": "segment_end",
        "ph": "seg",
        "segment_id": seg_id,
        "end_unix_time": end,
    }


def _span(name: str, ts: float, dur: float, step: int | None = None) -> dict:
    event = {
        "name": name,
        "cat": "train",
        "ph": "X",
        "ts_us": int(ts * 1e6),
        "dur_us": int(dur * 1e6),
        "thread": "MainThread",
    }
    if step is not None:
        event["step"] = step
    return event


def _write_timeline(run_dir: Path, events: list[dict], tail: str = "") -> Path:
    path = run_dir / "telemetry" / "timeline.jsonl"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        "".join(json.dumps(e, sort_keys=True) + "\n" for e in events) + tail,
        encoding="utf-8",
    )
    return path


def _assert_balances(ledger: dict, tol: float = _EPS) -> None:
    attributed = sum(ledger["categories"].values())
    assert abs(attributed - ledger["wall_clock_sec"]) <= tol, ledger


class TestTaxonomyTables:
    def test_single_clean_segment_exact_split(self, tmp_path):
        """One clean segment with hand-placed spans: every category lands
        its exact seconds and the residual is unattributed."""
        _write_timeline(
            tmp_path,
            [
                _header(0, 1000.0),
                _span("data_wait", 2.0, 0.5, step=1),
                _span("host_dispatch", 2.5, 1.0, step=1),
                _span("data_wait", 3.5, 0.25, step=2),
                _span("host_dispatch", 3.75, 1.0, step=2),
                _span("interval_sync", 4.75, 0.5),
                _span("eval", 5.25, 0.75),
                _span("checkpoint_save", 6.0, 0.5),
                _span("checkpoint_wait", 6.5, 0.25),
                _footer(0, 1010.0),
            ],
        )
        ledger = compute_goodput(tmp_path)
        assert ledger is not None
        cats = ledger["categories"]
        # Pre-step window ends at the FIRST data_wait/host_dispatch span —
        # step 1's batch assembly must not be double-counted as compile.
        assert cats["compile"] == pytest.approx(2.0, abs=1e-3)
        assert cats["data_wait"] == pytest.approx(0.75, abs=1e-3)
        assert cats["checkpoint"] == pytest.approx(0.75, abs=1e-3)
        assert cats["eval"] == pytest.approx(0.75, abs=1e-3)
        # All executions survive -> dispatch + full sync share productive.
        assert cats["productive_train"] == pytest.approx(2.5, abs=1e-3)
        assert cats["recomputed"] == 0.0
        assert cats["restart_overhead"] == 0.0
        assert cats["suspended"] == 0.0
        assert cats["unattributed"] == pytest.approx(3.25, abs=1e-2)
        assert ledger["wall_clock_sec"] == pytest.approx(10.0, abs=1e-3)
        assert ledger["goodput_frac"] == pytest.approx(0.25, abs=1e-3)
        assert ledger["num_segments"] == 1
        assert ledger["segments"][0]["clean_end"] is True
        _assert_balances(ledger)

    def test_two_segments_recomputed_and_restart_overhead(self, tmp_path):
        """Crash + resume-from-older-commit: the re-run step is recomputed
        (last-execution rule), the death->first-dispatch window is
        restart_overhead, and the run still sums to the wall clock."""
        _write_timeline(
            tmp_path,
            [
                _header(0, 1000.0),
                _span("host_dispatch", 1.0, 1.0, step=1),
                _span("host_dispatch", 2.0, 1.0, step=2),
                _span("host_dispatch", 3.0, 1.0, step=3),
                # no footer: SIGKILLed; inferred end = 1004.0
                _header(1, 1010.0),
                _span("host_dispatch", 2.0, 1.0, step=3),  # replay of step 3
                _span("host_dispatch", 3.0, 1.0, step=4),
                _footer(1, 1015.0),
            ],
        )
        ledger = compute_goodput(tmp_path)
        assert ledger is not None
        cats = ledger["categories"]
        assert cats["compile"] == pytest.approx(1.0, abs=1e-3)
        # Step 3's segment-0 execution was superseded by segment 1's.
        assert cats["recomputed"] == pytest.approx(1.0, abs=1e-3)
        assert cats["productive_train"] == pytest.approx(4.0, abs=1e-3)
        # Gap (1004 -> 1010) + segment 1's pre-dispatch warmup (2.0).
        assert cats["restart_overhead"] == pytest.approx(8.0, abs=1e-3)
        assert cats["suspended"] == 0.0
        assert ledger["wall_clock_sec"] == pytest.approx(15.0, abs=1e-3)
        assert ledger["num_segments"] == 2
        seg0, seg1 = ledger["segments"]
        assert seg0["clean_end"] is False and seg1["clean_end"] is True
        assert seg0["last_step"] == 3 and seg1["first_step"] == 3
        _assert_balances(ledger)

    def test_suspension_windows_carve_restart_overhead(self, tmp_path):
        """Fleet allocation-0 windows overlapping the cross-segment gap
        move seconds from restart_overhead to suspended — and ONLY the
        overlap with the gap counts."""
        _write_timeline(
            tmp_path,
            [
                _header(0, 1000.0),
                _span("host_dispatch", 1.0, 1.0, step=1),
                _header(1, 1010.0),  # gap: 1002 -> 1010
                _span("host_dispatch", 2.0, 1.0, step=2),
                _footer(1, 1014.0),
            ],
        )
        # 3s inside the gap + 100s far outside it (must clamp to 0).
        ledger = compute_goodput(
            tmp_path, suspensions=[(1005.0, 1008.0), (1100.0, 1200.0)]
        )
        assert ledger is not None
        cats = ledger["categories"]
        assert cats["suspended"] == pytest.approx(3.0, abs=1e-3)
        # gap 8.0 - suspended 3.0 + segment-1 pre-step 2.0
        assert cats["restart_overhead"] == pytest.approx(7.0, abs=1e-3)
        assert ledger["source"]["suspension_windows"] == 2
        _assert_balances(ledger)

    def test_heartbeat_mtime_extends_final_crashed_segment(self, tmp_path):
        """The beacon often outlives the last flushed event on a SIGKILL:
        that stranded wall-clock is real and must land in the ledger."""
        _write_timeline(
            tmp_path,
            [
                _header(0, 1000.0),
                _span("host_dispatch", 1.0, 1.0, step=1),
            ],
        )
        hb = tmp_path / "heartbeat"
        hb.write_text("beacon", encoding="utf-8")
        os.utime(hb, (1008.0, 1008.0))
        ledger = compute_goodput(tmp_path)
        assert ledger is not None
        assert ledger["wall_clock_sec"] == pytest.approx(8.0, abs=1e-3)
        assert ledger["source"]["heartbeat_used"] is True
        _assert_balances(ledger)

    def test_torn_tail_line_is_tolerated(self, tmp_path):
        """A SIGKILL mid-write tears the last JSONL line; the ledger must
        parse everything before it."""
        _write_timeline(
            tmp_path,
            [
                _header(0, 1000.0),
                _span("host_dispatch", 1.0, 1.0, step=1),
                _footer(0, 1003.0),
            ],
            tail='{"name": "host_disp',
        )
        ledger = compute_goodput(tmp_path)
        assert ledger is not None
        assert ledger["wall_clock_sec"] == pytest.approx(3.0, abs=1e-3)
        _assert_balances(ledger)

    def test_legacy_timeline_without_headers_returns_none(self, tmp_path):
        """Pre-ledger runs: unavailable beats wrong."""
        _write_timeline(tmp_path, [_span("host_dispatch", 1.0, 1.0, step=1)])
        assert compute_goodput(tmp_path) is None

    def test_missing_timeline_returns_none(self, tmp_path):
        assert compute_goodput(tmp_path) is None

    def test_carve_suspensions_clamps_to_gap(self):
        assert _carve_suspensions(10.0, 20.0, [(12.0, 15.0)]) == 3.0
        assert _carve_suspensions(10.0, 20.0, [(0.0, 100.0)]) == 10.0
        assert _carve_suspensions(10.0, 20.0, [(30.0, 40.0)]) == 0.0
        assert _carve_suspensions(10.0, 20.0, []) == 0.0

    def test_final_committed_step_reads_manifests(self, tmp_path):
        ckpt = tmp_path / "checkpoints"
        ckpt.mkdir()
        (ckpt / "step_000006.manifest.json").write_text("{}")
        (ckpt / "step_000012.manifest.json").write_text("{}")
        (ckpt / "step_000012.ckpt").write_text("")
        assert final_committed_step(ckpt) == 12
        assert final_committed_step(tmp_path / "nope") is None

    def test_gauges_and_markdown_render(self, tmp_path):
        _write_timeline(
            tmp_path,
            [
                _header(0, 1000.0),
                _span("host_dispatch", 1.0, 2.0, step=1),
                _footer(0, 1004.0),
            ],
        )
        ledger = compute_goodput(tmp_path)
        gauges = goodput_gauges(ledger)
        assert gauges["goodput/frac"] == ledger["goodput_frac"]
        assert gauges["goodput/wall_clock_sec"] == pytest.approx(4.0, abs=1e-3)
        for cat in CATEGORIES:
            assert f"goodput/{cat}_sec" in gauges
        md = render_goodput_md(ledger)
        assert "| category | seconds | frac |" in md
        for cat in CATEGORIES:
            assert f"| {cat} |" in md
        assert "| segment |" in md


class TestChaosConfig:
    def test_min_goodput_frac_validation(self):
        from llmtrain_tpu.config.schemas import ChaosConfig

        assert ChaosConfig().min_goodput_frac == 0.0
        assert ChaosConfig(min_goodput_frac=0.5).min_goodput_frac == 0.5
        with pytest.raises(Exception):
            ChaosConfig(min_goodput_frac=1.5)
        with pytest.raises(Exception):
            ChaosConfig(unknown_knob=1)


# ------------------------------------------------- facade + CLI (tier-1 e2e)


def _facade_cfg(tmp_path) -> RunConfig:
    return RunConfig.model_validate(
        {
            "run": {"name": "goodput-e2e"},
            "model": {
                "name": "dummy_gpt",
                "block_size": 8,
                "d_model": 16,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 32,
                "dropout": 0.0,
                "vocab_size": 32,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 12,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "log_every_steps": 5,
                "eval_every_steps": 10,
                "save_every_steps": 10,
                "warmup_steps": 0,
            },
            "output": {"root_dir": str(tmp_path / "runs")},
        }
    )


def _record_step_loop(telemetry) -> None:
    """A tiny hand-driven 'fit': the same span vocabulary the trainer
    records, with real (sleep-backed) durations."""
    tl = telemetry.timeline
    time.sleep(0.02)  # "compile"
    for step in (1, 2, 3):
        with tl.span("data_wait", cat="data", step=step):
            time.sleep(0.005)
        with tl.span("host_dispatch", step=step):
            time.sleep(0.01)
    with tl.span("interval_sync", step=3):
        time.sleep(0.005)
    with tl.span("eval", step=3):
        time.sleep(0.005)
    with tl.span("checkpoint_save", step=3):
        time.sleep(0.005)
    telemetry.flush(3)


class TestLedgerBalancesInvariant:
    """Tier-1 invariant: through the REAL facade (no Trainer fit — the
    slow drills below cover that), the ledger balances and every exposure
    surface carries the same numbers."""

    def test_finalize_report_cli_agree_and_balance(self, tmp_path, capsys):
        from llmtrain_tpu import cli
        from llmtrain_tpu.telemetry import Telemetry
        from llmtrain_tpu.tracking.base import NullTracker

        cfg = _facade_cfg(tmp_path)
        run_dir = tmp_path / "runs" / "goodput-e2e"
        run_dir.mkdir(parents=True)
        telemetry = Telemetry(cfg, run_dir, NullTracker())
        _record_step_loop(telemetry)
        report = telemetry.finalize(run_id="goodput-e2e")
        telemetry.close()

        ledger = report["goodput"]
        assert ledger is not None
        _assert_balances(ledger)
        assert ledger["num_segments"] == 1
        assert ledger["segments"][0]["clean_end"] is True
        assert ledger["segments"][0]["steps_executed"] == 3
        assert ledger["categories"]["productive_train"] > 0
        assert ledger["categories"]["compile"] > 0

        # Surface (a): the ledger persists verbatim in report.json/.md.
        on_disk = json.loads((run_dir / "report.json").read_text())
        assert on_disk["goodput"] == ledger
        assert "## Goodput" in (run_dir / "report.md").read_text()

        # Surface (c): llmtrain_goodput_* gauges in the textfile snapshot.
        prom = (run_dir / "telemetry" / "metrics.prom").read_text()
        assert "llmtrain_goodput_frac" in prom
        assert "llmtrain_goodput_productive_train_sec" in prom

        # Surface (b): the CLI reproduces the SAME numbers from artifacts
        # alone (this is the post-mortem path — nothing in memory).
        rc = cli.main(["goodput", "--run-dir", str(run_dir), "--json"])
        assert rc == EXIT_OK
        cli_ledger = json.loads(capsys.readouterr().out)
        assert cli_ledger == ledger

        rc = cli.main(["goodput", "--run-dir", str(run_dir)])
        assert rc == EXIT_OK
        assert "# Goodput" in capsys.readouterr().out

    def test_simulated_crash_no_footer_still_balances(self, tmp_path):
        """The SIGKILL shape without the process: record spans, flush,
        abandon WITHOUT finalize (no footer) — the ledger must still
        balance, with the segment marked unclean."""
        from llmtrain_tpu.telemetry import Telemetry
        from llmtrain_tpu.tracking.base import NullTracker

        cfg = _facade_cfg(tmp_path)
        run_dir = tmp_path / "runs" / "goodput-e2e"
        run_dir.mkdir(parents=True)
        telemetry = Telemetry(cfg, run_dir, NullTracker())
        _record_step_loop(telemetry)
        # no finalize(): the process "died" here
        ledger = compute_goodput(run_dir)
        assert ledger is not None
        assert ledger["segments"][0]["clean_end"] is False
        assert ledger["segments"][0]["steps_executed"] == 3
        _assert_balances(ledger)

    def test_dropped_events_surface_as_counter(self, tmp_path):
        from llmtrain_tpu.telemetry import Telemetry
        from llmtrain_tpu.tracking.base import NullTracker

        cfg = RunConfig.model_validate(
            {
                **_facade_cfg(tmp_path).model_dump(),
                "telemetry": {"max_events": 1000},
            }
        )
        run_dir = tmp_path / "runs" / "goodput-e2e"
        run_dir.mkdir(parents=True)
        telemetry = Telemetry(cfg, run_dir, NullTracker())
        for i in range(1200):
            telemetry.timeline.instant("noise", step=i)
        telemetry.flush(1)
        for i in range(1200):
            telemetry.timeline.instant("noise", step=i)
        telemetry.flush(2)
        assert telemetry.timeline.dropped > 0
        assert (
            telemetry.metrics.counters().get("telemetry/timeline_dropped", 0)
            == telemetry.timeline.dropped
        )
        prom = (run_dir / "telemetry" / "metrics.prom").read_text()
        assert "llmtrain_telemetry_timeline_dropped_total" in prom

    def test_cli_error_paths(self, tmp_path):
        from llmtrain_tpu import cli

        rc = cli.main(["goodput", "--run-dir", str(tmp_path / "missing")])
        assert rc == EXIT_CONFIG_ERROR
        empty = tmp_path / "empty-run"
        empty.mkdir()
        rc = cli.main(["goodput", "--run-dir", str(empty)])
        assert rc == EXIT_TRAIN_FAILURE


# ------------------------------------------------------------- @slow drills


@pytest.mark.slow
class TestKillDurability:
    def test_mid_interval_sigkill_timeline_still_balances(self, tmp_path):
        """Regression (satellite 1): SIGKILL a REAL training process in
        the middle of a log interval; the per-step flushes + eager header
        must leave artifacts the ledger balances from."""
        cfg = yaml.safe_load(_CHAOS_PRESET.read_text())
        cfg["run"]["name"] = "gp-kill"
        cfg["trainer"].update(
            {
                "max_steps": 5000,
                "log_every_steps": 1,  # flush every step: maximal torn-tail odds
                "save_every_steps": 50,
                "eval_every_steps": 5000,
            }
        )
        cfg["output"]["root_dir"] = str(tmp_path / "runs")
        config_path = tmp_path / "kill.yaml"
        config_path.write_text(yaml.safe_dump(cfg, sort_keys=False))

        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.Popen(
            [sys.executable, "-m", "llmtrain_tpu", "train", "--config", str(config_path)],
            env=env,
            cwd=str(tmp_path),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            deadline = time.monotonic() + 180
            timeline = None
            while time.monotonic() < deadline:
                hits = list((tmp_path / "runs").glob("**/telemetry/timeline.jsonl"))
                if hits and '"host_dispatch"' in hits[0].read_text(errors="replace"):
                    timeline = hits[0]
                    break
                if proc.poll() is not None:
                    pytest.fail(f"train process exited early: rc={proc.returncode}")
                time.sleep(0.25)
            assert timeline is not None, "no dispatched step before the deadline"
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

        run_dir = timeline.parent.parent
        ledger = compute_goodput(run_dir)
        assert ledger is not None
        assert ledger["num_segments"] == 1
        assert ledger["segments"][0]["clean_end"] is False
        assert ledger["segments"][0]["steps_executed"] > 0
        assert ledger["categories"]["productive_train"] > 0
        _assert_balances(
            ledger, tol=0.01 * ledger["wall_clock_sec"] + 0.05
        )


@pytest.mark.slow
class TestChaosDrillGoodput:
    def test_three_cycle_drill_ledger(self, tmp_path, capsys):
        """ISSUE-12 acceptance: 3 chaos cycles produce a ledger balancing
        within 1%, with recomputed_sec > 0 (the replay the kills cost),
        and the CLI reproduces the SAME numbers from artifacts alone after
        every process is dead."""
        from llmtrain_tpu import cli
        from llmtrain_tpu.resilience.chaos import run_chaos

        result = run_chaos(
            _CHAOS_PRESET,
            cycles=3,
            seed=1,
            work_dir=tmp_path / "chaos",
            timeout_sec=300.0,
        )
        ledger = result["goodput"]
        assert ledger is not None
        # 3 killed segments + the uninterrupted finishing segment.
        assert ledger["num_segments"] >= 4
        assert ledger["categories"]["recomputed"] > 0
        assert ledger["categories"]["restart_overhead"] > 0
        wall = ledger["wall_clock_sec"]
        attributed = sum(ledger["categories"].values())
        assert abs(attributed - wall) <= 0.01 * wall + 0.05

        chaos_dir = Path(result["work_dir"]) / "runs" / "chaos"
        rc = cli.main(["goodput", "--run-dir", str(chaos_dir), "--json"])
        assert rc == EXIT_OK
        cli_ledger = json.loads(capsys.readouterr().out)
        assert cli_ledger == ledger


@pytest.mark.slow
class TestFleetStormGoodput:
    def test_storm_per_tenant_ledgers_and_fleet_rollup(self, tmp_path):
        """The storm's fleet report carries a balanced per-tenant ledger
        (suspension windows attributed), the fleet-wide second-weighted
        goodput_frac, and the llmtrain_fleet_goodput_* gauges — with the
        configured min_goodput_frac floor enforced inside the storm."""
        from llmtrain_tpu.fleet.chaos import run_fleet_storm

        raw = yaml.safe_load(_FLEET_PRESET.read_text())
        raw["resilience"] = {"chaos": {"min_goodput_frac": 0.0}}
        raw["fleet"] = {
            "pool_devices": 3,
            "preempt_grace_sec": 20.0,
            "tenants": [
                {"name": "alpha", "priority": 2, "min_devices": 1, "max_devices": 1},
                {"name": "bravo", "priority": 1, "min_devices": 1, "max_devices": 1},
                {"name": "charlie", "priority": 0, "min_devices": 1, "max_devices": 1},
            ],
        }
        config_path = tmp_path / "storm3.yaml"
        config_path.write_text(yaml.safe_dump(raw, sort_keys=False))

        result = run_fleet_storm(
            config_path,
            seed=1,
            work_dir=tmp_path / "storm",
            timeout_sec=600.0,
        )
        assert result["fleet_goodput_frac"] is not None
        for name, r in result["tenants"].items():
            ledger = r["goodput"]
            assert ledger is not None, name
            wall = ledger["wall_clock_sec"]
            attributed = sum(ledger["categories"].values())
            assert abs(attributed - wall) <= 0.01 * wall + 0.05, name
            assert ledger["num_segments"] >= 2, name  # every tenant was evicted
        if result["total_suspensions"] >= 1:
            assert any(
                r["goodput"]["categories"]["suspended"] > 0
                for r in result["tenants"].values()
            ), "suspension windows never attributed to any tenant ledger"

        report = json.loads(Path(result["fleet_report_json"]).read_text())
        assert report["totals"]["goodput_frac"] == result["fleet_goodput_frac"]
        assert "goodput_sec" in report["totals"]
        prom = (Path(result["work_dir"]) / "fleet_metrics.prom").read_text()
        assert "llmtrain_fleet_goodput_frac" in prom
        md = (Path(result["work_dir"]) / "fleet_report.md").read_text()
        assert "fleet goodput" in md
