"""Docker-free validation of the k8s e2e harness (VERDICT r3 #5).

The reference's ``k8s/test_e2e.sh`` runs on a local kind cluster
(reference k8s/test_e2e.sh:107-186); docker/kind has never been present
in this environment, so the port's ASSERTION LOGIC itself was unvalidated
— a broken grep would pass an all-green e2e and nothing would notice.
Two closures here:

* The assertion functions (factored into ``k8s/assertions.sh``) run
  against a REAL run directory produced by a CLI train — the same
  artifact tree the hostPath PV surfaces in the cluster — plus negative
  fixtures proving each assertion can actually fail.
* The manifests are structurally validated: YAML parses, the names that
  must agree across files (service account, PVC claims, configmap names,
  headless-service subdomain) do agree, and the embedded train.yaml
  payloads validate against the REAL config schema — so
  ``job-tpu-v5e.yaml`` cannot rot silently.
"""

from __future__ import annotations

import json
import os
import sqlite3
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

REPO = Path(__file__).resolve().parent.parent
K8S = REPO / "k8s"


def _sh(snippet: str) -> subprocess.CompletedProcess:
    """Run a bash snippet with assertions.sh sourced."""
    return subprocess.run(
        ["bash", "-c", f'. "{K8S}/assertions.sh"\n{snippet}'],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )


@pytest.fixture(scope="module")
def trained_run(tmp_path_factory):
    """A real CLI train: run dir + native tracking DB + stdout logs."""
    workdir = tmp_path_factory.mktemp("k8s-fixture")
    cfg = {
        "run": {"name": "e2e-fixture", "seed": 0, "device": "cpu"},
        "model": {
            "name": "dummy_gpt", "block_size": 8, "d_model": 32,
            "n_layers": 1, "n_heads": 2, "d_ff": 64, "vocab_size": 32,
            "extra": {"tokenizer": "byte"},
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 2, "micro_batch_size": 2, "grad_accum_steps": 1,
            "warmup_steps": 0, "log_every_steps": 1, "eval_every_steps": 2,
            "save_every_steps": 2,
        },
        "mlflow": {
            "enabled": True, "tracking_uri": "sqlite:///track.db",
            "experiment": "e2e", "backend": "native",
        },
        "logging": {"json_output": True, "log_to_file": True},
    }
    (workdir / "cfg.yaml").write_text(yaml.safe_dump(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", "train", "--config", "cfg.yaml",
         "--json"],
        capture_output=True, text=True, cwd=workdir, env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    run_dir = next((workdir / "runs").iterdir())
    # Pod logs = the entrypoint's exec line followed by the CLI's output.
    logs = "entrypoint: exec python -m llmtrain_tpu train --config cfg.yaml\n"
    logs += proc.stdout + proc.stderr
    return {"run_dir": run_dir, "db": workdir / "track.db", "logs": logs}


class TestAssertRank0Logs:
    def test_passes_on_real_train_logs(self, trained_run, tmp_path):
        f = tmp_path / "logs.txt"
        f.write_text(trained_run["logs"])
        r = _sh(f'assert_rank0_logs "$(cat "{f}")"')
        assert r.returncode == 0, r.stdout + r.stderr
        assert r.stdout.count("PASS") == 2

    def test_fails_without_final_step(self):
        r = _sh('assert_rank0_logs "entrypoint: exec python ... but it died"')
        assert r.returncode != 0
        assert "FAIL: no final_step" in r.stderr

    def test_fails_without_entrypoint_line(self):
        r = _sh('assert_rank0_logs "final_step: 2"')
        assert r.returncode != 0
        assert "entrypoint exec line missing" in r.stderr


class TestAssertArtifactTree:
    def test_passes_on_real_run_dir(self, trained_run):
        r = _sh(f'assert_artifact_tree "{trained_run["run_dir"]}"')
        assert r.returncode == 0, r.stdout + r.stderr

    def test_fails_on_missing_dir(self):
        r = _sh('assert_artifact_tree ""')
        assert r.returncode != 0

    def test_fails_on_incomplete_tree(self, tmp_path):
        (tmp_path / "checkpoints").mkdir()
        r = _sh(f'assert_artifact_tree "{tmp_path}"')
        assert r.returncode != 0
        assert "train.log missing" in r.stderr


class TestAssertTrackingDb:
    def test_passes_on_real_db(self, trained_run):
        r = _sh(f'assert_tracking_db "{trained_run["db"]}"')
        assert r.returncode == 0, r.stdout + r.stderr

    def test_fails_on_empty_file(self, tmp_path):
        db = tmp_path / "empty.db"
        db.touch()
        r = _sh(f'assert_tracking_db "{db}"')
        assert r.returncode != 0

    def test_fails_on_schema_only_db(self, tmp_path):
        """A DB where tracking initialized but recorded nothing must FAIL
        — that silent-no-op is the bug class the assertion exists for."""
        db = tmp_path / "schema.db"
        with sqlite3.connect(db) as conn:
            conn.execute(
                "CREATE TABLE runs (run_uuid TEXT PRIMARY KEY, run_id TEXT, "
                "experiment TEXT, status TEXT)"
            )
        r = _sh(f'assert_tracking_db "{db}"')
        assert r.returncode != 0
        assert "no recorded run" in r.stderr


class TestAssertHeartbeat:
    def test_passes_on_fresh_heartbeat(self, tmp_path):
        hb = tmp_path / "heartbeat"
        hb.touch()
        r = _sh(f'assert_heartbeat "{hb}"')
        assert r.returncode == 0, r.stdout + r.stderr
        assert "heartbeat fresh" in r.stdout

    def test_fails_on_missing_file(self, tmp_path):
        r = _sh(f'assert_heartbeat "{tmp_path}/nope"')
        assert r.returncode != 0
        assert "heartbeat file missing" in r.stderr

    def test_fails_on_stale_mtime(self, tmp_path):
        """The same freshness computation the livenessProbe exec performs:
        a back-dated mtime must FAIL."""
        hb = tmp_path / "heartbeat"
        hb.touch()
        old = 10_000  # seconds in the past
        os.utime(hb, (hb.stat().st_atime - old, hb.stat().st_mtime - old))
        r = _sh(f'assert_heartbeat "{hb}" 600')
        assert r.returncode != 0
        assert "heartbeat stale" in r.stderr


class TestAssertManifest:
    """The crash-consistency assertion the e2e's mid-run pod-kill phase
    relies on: a committed checkpoint set must verify by its manifest."""

    def test_passes_on_real_checkpoints(self, trained_run):
        r = _sh(f'assert_manifest "{trained_run["run_dir"]}/checkpoints"')
        assert r.returncode == 0, r.stdout + r.stderr
        assert "commit manifest present" in r.stdout
        assert "files verify" in r.stdout

    def test_fails_without_manifests(self, tmp_path):
        d = tmp_path / "ckpts"
        d.mkdir()
        (d / "step_000001.ckpt").write_bytes(b"payload without a commit")
        r = _sh(f'assert_manifest "{d}"')
        assert r.returncode != 0
        assert "no step_" in r.stderr

    def test_fails_on_payload_not_matching_manifest(self, trained_run, tmp_path):
        """Damage the committed payload: the sha in the manifest no longer
        matches, and the assertion must notice (this is the torn-file case
        the selection logic skips)."""
        import shutil

        src = trained_run["run_dir"] / "checkpoints"
        dst = tmp_path / "ckpts"
        shutil.copytree(src, dst)
        payload = sorted(dst.glob("step_*.ckpt"))[-1]
        data = payload.read_bytes()
        payload.write_bytes(data[: len(data) // 2])
        r = _sh(f'assert_manifest "{dst}"')
        assert r.returncode != 0
        assert "failed verification" in r.stderr


# ---------------------------------------------------------------- manifests


def _load_all(name: str) -> list[dict]:
    docs = list(yaml.safe_load_all((K8S / name).read_text()))
    return [d for d in docs if d is not None]


def _by_kind(docs: list[dict], kind: str) -> list[dict]:
    return [d for d in docs if d.get("kind") == kind]


@pytest.fixture(scope="module")
def manifests():
    return {
        name: _load_all(name)
        for name in (
            "job.yaml", "job-tpu-v5e.yaml", "infra.yaml", "configmap.yaml",
            "dashboard-admin.yaml", "kind-config.yaml", "serve.yaml",
            "router.yaml",
        )
    }


class TestManifestStructure:
    def test_all_yaml_parses(self, manifests):
        for name, docs in manifests.items():
            assert docs, f"{name} parsed to nothing"

    @pytest.mark.parametrize("job_file", ["job.yaml", "job-tpu-v5e.yaml"])
    def test_jobs_are_indexed_with_matched_completions(self, manifests, job_file):
        (job,) = _by_kind(manifests[job_file], "Job")
        spec = job["spec"]
        assert spec["completionMode"] == "Indexed"
        assert spec["completions"] == spec["parallelism"]
        # Retryable failures burn a bounded backoff budget; fatal codes
        # fail the Job fast via the podFailurePolicy below.
        assert spec["backoffLimit"] > 0

    @pytest.mark.parametrize("job_file", ["job.yaml", "job-tpu-v5e.yaml"])
    def test_jobs_consume_the_exit_code_taxonomy(self, manifests, job_file):
        """podFailurePolicy must agree with resilience/exit_codes.py:
        fatal codes (1/2) FailJob, retryable ones (75/76) are retried."""
        from llmtrain_tpu.resilience.exit_codes import (
            EXIT_CONFIG_ERROR,
            EXIT_HANG_DETECTED,
            EXIT_RETRYABLE_INFRA,
            EXIT_TRAIN_FAILURE,
        )

        (job,) = _by_kind(manifests[job_file], "Job")
        rules = job["spec"]["podFailurePolicy"]["rules"]
        by_action = {r["action"]: r["onExitCodes"]["values"] for r in rules}
        assert set(by_action["FailJob"]) == {EXIT_TRAIN_FAILURE, EXIT_CONFIG_ERROR}
        retried = set(by_action["Count"])
        assert {EXIT_RETRYABLE_INFRA, EXIT_HANG_DETECTED} <= retried

    @pytest.mark.parametrize("job_file", ["job.yaml", "job-tpu-v5e.yaml"])
    def test_jobs_have_heartbeat_liveness_probe(self, manifests, job_file):
        """The probe's exec must check the same heartbeat path the
        ConfigMap points the watchdog at, and tolerate a missing file
        (startup/compile must not be probe-killed)."""
        (job,) = _by_kind(manifests[job_file], "Job")
        (ctr,) = job["spec"]["template"]["spec"]["containers"]
        probe = ctr["livenessProbe"]
        cmd = " ".join(probe["exec"]["command"])
        assert "/tmp/llmtrain-heartbeat" in cmd
        assert "! -f" in cmd  # missing-file-passes startup contract
        assert probe["periodSeconds"] >= 10

    def test_configmap_heartbeat_paths_match_the_probes(self, manifests):
        """watchdog.heartbeat_path in every embedded train.yaml must be the
        container-local path the livenessProbe execs stat. (Scoped to the
        TRAIN payloads: the serve.yaml payload feeds the Deployment, whose
        liveness is a real HTTP /healthz probe, not the heartbeat file.)"""
        for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
            for key, raw in cm.get("data", {}).items():
                if key.startswith("train") and key.endswith(".yaml"):
                    cfg = yaml.safe_load(raw)
                    wd = cfg["resilience"]["watchdog"]
                    assert wd["enabled"] is True
                    assert wd["heartbeat_path"] == "/tmp/llmtrain-heartbeat"

    def test_job_references_resolve(self, manifests):
        """Every name job.yaml references must exist in infra/configmap."""
        (job,) = _by_kind(manifests["job.yaml"], "Job")
        pod = job["spec"]["template"]["spec"]
        sa_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["infra.yaml"], "ServiceAccount")}
        assert pod["serviceAccountName"] in sa_names
        pvc_names = {
            d["metadata"]["name"]
            for d in _by_kind(manifests["infra.yaml"], "PersistentVolumeClaim")
        }
        cm_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["configmap.yaml"], "ConfigMap")}
        for vol in pod["volumes"]:
            if "persistentVolumeClaim" in vol:
                assert vol["persistentVolumeClaim"]["claimName"] in pvc_names
            if "configMap" in vol:
                assert vol["configMap"]["name"] in cm_names

    def test_tpu_job_references_and_selectors(self, manifests):
        (job,) = _by_kind(manifests["job-tpu-v5e.yaml"], "Job")
        pod = job["spec"]["template"]["spec"]
        # GKE TPU host discovery needs the headless-service subdomain.
        svc_names = {d["metadata"]["name"]
                     for d in _by_kind(manifests["infra.yaml"], "Service")}
        assert pod["subdomain"] in svc_names
        sel = pod["nodeSelector"]
        assert "cloud.google.com/gke-tpu-accelerator" in sel
        assert "cloud.google.com/gke-tpu-topology" in sel
        (ctr,) = pod["containers"]
        res = ctr["resources"]
        assert res["requests"]["google.com/tpu"] == res["limits"]["google.com/tpu"]
        cm_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["configmap.yaml"], "ConfigMap")}
        for vol in pod["volumes"]:
            if "configMap" in vol:
                assert vol["configMap"]["name"] in cm_names

    def test_headless_service_is_headless(self, manifests):
        svcs = _by_kind(manifests["infra.yaml"], "Service")
        headless = [s for s in svcs if s["metadata"]["name"].endswith("headless")]
        assert headless and all(s["spec"]["clusterIP"] == "None" for s in headless)

    def test_configmap_payloads_validate_against_real_schema(self, manifests):
        """The embedded train.yaml configs must pass the actual config
        validators — the strongest rot protection available offline."""
        from llmtrain_tpu.config.schemas import RunConfig

        payloads = 0
        for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
            for key, raw in cm.get("data", {}).items():
                if key.endswith(".yaml"):
                    RunConfig.model_validate(yaml.safe_load(raw))
                    payloads += 1
        assert payloads >= 3  # kind CPU config + v5e TPU config + serve config

    def test_entrypoint_config_path_matches_configmap_key(self, manifests):
        """entrypoint.sh defaults to /config/train.yaml; the configmap must
        publish that key and job.yaml must mount it at /config."""
        entry = (K8S / "entrypoint.sh").read_text()
        assert "/config/train.yaml" in entry
        for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
            assert "train.yaml" in cm["data"]
        (job,) = _by_kind(manifests["job.yaml"], "Job")
        pod = job["spec"]["template"]["spec"]
        (ctr,) = pod["containers"]
        config_mounts = [m for m in ctr["volumeMounts"] if m["name"] == "config"]
        assert config_mounts and config_mounts[0]["mountPath"] == "/config"

    @pytest.mark.parametrize("job_file", ["job.yaml", "job-tpu-v5e.yaml"])
    def test_jobs_carry_prometheus_scrape_annotations(self, manifests, job_file):
        """The telemetry scrape contract (docs/observability.md): pod
        templates must carry the prometheus.io discovery annotations."""
        (job,) = _by_kind(manifests[job_file], "Job")
        annotations = job["spec"]["template"]["metadata"]["annotations"]
        assert annotations["prometheus.io/scrape"] == "true"
        assert annotations["prometheus.io/path"] == "/metrics"
        assert int(annotations["prometheus.io/port"]) > 0

    def test_configmap_telemetry_matches_scrape_annotations(self, manifests):
        """Every embedded train.yaml must enable the telemetry endpoint on
        the SAME port the Job annotations advertise — a mismatch means
        scrapers poll a dead port forever."""
        ports = set()
        for job_file in ("job.yaml", "job-tpu-v5e.yaml"):
            (job,) = _by_kind(manifests[job_file], "Job")
            ports.add(
                int(job["spec"]["template"]["metadata"]["annotations"][
                    "prometheus.io/port"
                ])
            )
        for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
            for key, raw in cm.get("data", {}).items():
                if key.startswith("train") and key.endswith(".yaml"):
                    cfg = yaml.safe_load(raw)
                    tele = cfg["telemetry"]
                    assert tele["prometheus"] is True
                    assert tele["prometheus_port"] in ports


class TestServeManifest:
    """k8s/serve.yaml: the inference Deployment + Service contracts
    (docs/serving.md "Kubernetes rollout")."""

    def test_deployment_selector_and_service_agree(self, manifests):
        (dep,) = _by_kind(manifests["serve.yaml"], "Deployment")
        (svc,) = _by_kind(manifests["serve.yaml"], "Service")
        labels = dep["spec"]["template"]["metadata"]["labels"]
        assert dep["spec"]["selector"]["matchLabels"].items() <= labels.items()
        assert svc["spec"]["selector"].items() <= labels.items()
        (port,) = svc["spec"]["ports"]
        (ctr,) = dep["spec"]["template"]["spec"]["containers"]
        names = {p["name"] for p in ctr["ports"]}
        assert port["targetPort"] in names

    def test_healthz_probes_on_the_serve_port(self, manifests):
        """Readiness gates traffic on /healthz (the server binds only
        after checkpoint load + engine build); liveness restarts a wedged
        process but must not probe-kill cold-cache compiles."""
        (dep,) = _by_kind(manifests["serve.yaml"], "Deployment")
        (ctr,) = dep["spec"]["template"]["spec"]["containers"]
        for probe_name in ("readinessProbe", "livenessProbe"):
            probe = ctr[probe_name]
            assert probe["httpGet"]["path"] == "/healthz"
        assert ctr["livenessProbe"]["initialDelaySeconds"] >= 60

    def test_liveness_probe_covers_the_staleness_window(self, manifests):
        """/healthz 503s once the scheduler beacon exceeds
        serving.liveness_stale_sec (serving/http.py) — the probe budget
        (period x failureThreshold) must EXCEED that window so the
        server declares itself unhealthy before the kubelet acts, and
        the restart is attributable to the 503, not a race."""
        (dep,) = _by_kind(manifests["serve.yaml"], "Deployment")
        (ctr,) = dep["spec"]["template"]["spec"]["containers"]
        liveness = ctr["livenessProbe"]
        budget = liveness["periodSeconds"] * liveness["failureThreshold"]
        for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
            if "serve.yaml" in cm.get("data", {}):
                serving = yaml.safe_load(cm["data"]["serve.yaml"])["serving"]
                stale = serving["liveness_stale_sec"]
                assert stale < budget, (
                    f"liveness_stale_sec ({stale}) must be under the probe "
                    f"kill budget ({budget}s)"
                )

    def test_overload_control_pinned_in_serve_config(self, manifests):
        """The fleet ships with SLO-aware overload control ON
        (serving/overload.py): bounded admission, priority classes, a
        real brownout hysteresis gap, and the router's probe timeout /
        retry budget. A replica under pressure answers 429/503 WITH
        Retry-After (serving/http.py lifts it into the header), so the
        kubelet probes and the router both know when to come back —
        these knobs are the contract that behavior hangs off."""
        for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
            if "serve.yaml" not in cm.get("data", {}):
                continue
            serving = yaml.safe_load(cm["data"]["serve.yaml"])["serving"]
            ov = serving["overload"]
            assert ov["enabled"] is True
            assert ov["queue_cap"] >= 1
            assert ov["brownout_low_ms"] < ov["brownout_high_ms"]
            assert set(ov["classes"]) >= {"interactive", "batch"}
            router = serving["router"]
            # The probe timeout must undercut the liveness window: a
            # wedged replica has to fail its health sweep BEFORE the
            # kubelet's own probe budget runs out.
            assert router["probe_timeout_sec"] < serving["liveness_stale_sec"]
            assert router["retry_budget"] >= 0
            assert router["retry_window_sec"] > 0

    def test_prometheus_annotations_point_at_the_serve_port(self, manifests):
        """The inference server exposes llmtrain_serve_* on its OWN HTTP
        port (serving/http.py /metrics) — the scrape annotation must
        advertise that port, not the training telemetry port."""
        (dep,) = _by_kind(manifests["serve.yaml"], "Deployment")
        annotations = dep["spec"]["template"]["metadata"]["annotations"]
        assert annotations["prometheus.io/scrape"] == "true"
        assert annotations["prometheus.io/path"] == "/metrics"
        (ctr,) = dep["spec"]["template"]["spec"]["containers"]
        container_ports = {p["containerPort"] for p in ctr["ports"]}
        assert int(annotations["prometheus.io/port"]) in container_ports
        # The CLI is told to bind the same port.
        assert str(annotations["prometheus.io/port"]) in ctr["command"]

    def test_references_resolve_and_serve_config_is_continuous(self, manifests):
        (dep,) = _by_kind(manifests["serve.yaml"], "Deployment")
        pod = dep["spec"]["template"]["spec"]
        sa_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["infra.yaml"], "ServiceAccount")}
        assert pod["serviceAccountName"] in sa_names
        pvc_names = {
            d["metadata"]["name"]
            for d in _by_kind(manifests["infra.yaml"], "PersistentVolumeClaim")
        }
        cm_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["configmap.yaml"], "ConfigMap")}
        serve_cfgs = []
        for vol in pod["volumes"]:
            if "persistentVolumeClaim" in vol:
                assert vol["persistentVolumeClaim"]["claimName"] in pvc_names
            if "configMap" in vol:
                assert vol["configMap"]["name"] in cm_names
                for cm in _by_kind(manifests["configmap.yaml"], "ConfigMap"):
                    if cm["metadata"]["name"] == vol["configMap"]["name"]:
                        assert "serve.yaml" in cm["data"]
                        serve_cfgs.append(yaml.safe_load(cm["data"]["serve.yaml"]))
        # The mounted config must select the continuous backend and match
        # the training model shape (the checkpoint must load 1:1).
        assert serve_cfgs, "Deployment mounts no configmap with serve.yaml"
        for cfg, cm in zip(serve_cfgs, [c for c in _by_kind(
                manifests["configmap.yaml"], "ConfigMap")
                if "serve.yaml" in c.get("data", {})]):
            assert cfg["serving"]["mode"] == "continuous"
            train = yaml.safe_load(cm["data"]["train.yaml"])
            for key in ("name", "d_model", "n_layers", "n_heads", "block_size"):
                assert cfg["model"][key] == train["model"][key]


class TestRouterManifest:
    """k8s/router.yaml: the fleet tier — replica pods behind a headless
    Service, fronted by a router Deployment that discovers them over DNS
    (docs/serving.md "Fleet tier")."""

    def _deployments(self, manifests):
        deps = {d["metadata"]["name"]: d
                for d in _by_kind(manifests["router.yaml"], "Deployment")}
        return deps["llmtrain-tpu-serve-replica"], deps["llmtrain-tpu-router"]

    def test_replica_service_is_headless_and_selects_replicas(self, manifests):
        """DNS-based discovery only works through a headless Service: one
        A record per READY replica pod is what resolve_backends consumes."""
        svcs = {s["metadata"]["name"]: s
                for s in _by_kind(manifests["router.yaml"], "Service")}
        headless = svcs["llmtrain-tpu-serve-replicas"]
        assert headless["spec"]["clusterIP"] == "None"
        replica_dep, _ = self._deployments(manifests)
        labels = replica_dep["spec"]["template"]["metadata"]["labels"]
        assert headless["spec"]["selector"].items() <= labels.items()
        assert replica_dep["spec"]["replicas"] >= 2  # a fleet, not a pod

    def test_router_discovers_the_headless_service(self, manifests):
        """The router's --discover target must be the headless Service's
        name on the port the replicas actually serve."""
        _, router_dep = self._deployments(manifests)
        (ctr,) = router_dep["spec"]["template"]["spec"]["containers"]
        cmd = ctr["command"]
        assert "--discover" in cmd
        target = cmd[cmd.index("--discover") + 1]
        host, port = target.rsplit(":", 1)
        svcs = {s["metadata"]["name"]: s
                for s in _by_kind(manifests["router.yaml"], "Service")}
        assert host == "llmtrain-tpu-serve-replicas"
        (svc_port,) = svcs[host]["spec"]["ports"]
        assert int(port) == svc_port["port"]

    def test_both_deployments_probe_healthz_and_resolve_references(
        self, manifests
    ):
        sa_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["infra.yaml"], "ServiceAccount")}
        pvc_names = {
            d["metadata"]["name"]
            for d in _by_kind(manifests["infra.yaml"], "PersistentVolumeClaim")
        }
        cm_names = {d["metadata"]["name"]
                    for d in _by_kind(manifests["configmap.yaml"], "ConfigMap")}
        for dep in self._deployments(manifests):
            pod = dep["spec"]["template"]["spec"]
            assert pod["serviceAccountName"] in sa_names
            for vol in pod["volumes"]:
                if "persistentVolumeClaim" in vol:
                    assert vol["persistentVolumeClaim"]["claimName"] in pvc_names
                if "configMap" in vol:
                    assert vol["configMap"]["name"] in cm_names
            (ctr,) = pod["containers"]
            for probe_name in ("readinessProbe", "livenessProbe"):
                assert ctr[probe_name]["httpGet"]["path"] == "/healthz"
            # Cold-cache compiles must not be probe-killed.
            assert ctr["livenessProbe"]["initialDelaySeconds"] >= 60
            # /healthz is a real liveness signal (503 on dead/stale
            # scheduler loop, 503 on a fully evicted router fleet) —
            # pin the kill budget the 503 contract was sized against.
            liveness = ctr["livenessProbe"]
            assert liveness["failureThreshold"] >= 2
            assert liveness["periodSeconds"] * liveness["failureThreshold"] >= 60


class TestAssertTelemetryArtifacts:
    def test_passes_on_real_run(self, trained_run):
        r = _sh(f'assert_telemetry_artifacts "{trained_run["run_dir"]}"')
        assert r.returncode == 0, r.stdout + r.stderr
        assert "report.json + trace.json validate" in r.stdout
        assert "metrics.prom carries llmtrain_ gauges" in r.stdout

    def test_fails_on_dir_without_telemetry(self, tmp_path):
        r = _sh(f'assert_telemetry_artifacts "{tmp_path}"')
        assert r.returncode != 0
        assert "report.json missing" in r.stderr


class TestAssertPrometheusScrape:
    def test_passes_on_rendered_scrape(self, tmp_path):
        from llmtrain_tpu.telemetry import render_prometheus

        scrape = tmp_path / "scrape.prom"
        scrape.write_text(
            render_prometheus(
                {"train/loss": (1.0, 3)}, {}, info={"run_name": "e2e"}
            )
        )
        r = _sh(f'assert_prometheus_scrape "{scrape}"')
        assert r.returncode == 0, r.stdout + r.stderr

    def test_fails_on_missing_capture(self, tmp_path):
        r = _sh(f'assert_prometheus_scrape "{tmp_path}/scrape.prom"')
        assert r.returncode != 0
        assert "no captured prometheus scrape" in r.stderr

    def test_fails_without_gauges(self, tmp_path):
        scrape = tmp_path / "scrape.prom"
        scrape.write_text("# just comments\nother_metric 1\n")
        r = _sh(f'assert_prometheus_scrape "{scrape}"')
        assert r.returncode != 0


class TestAssertServingReport:
    """assert_serving_report validates the load-harness SLO block
    (k8s/test_e2e_local.sh serving phase, docs/serving.md)."""

    @staticmethod
    def _block(**overrides):
        pct = {"p50": 1.0, "p95": 2.0, "p99": 3.0, "mean": 1.5, "max": 3.0}
        block = {
            "requests": {"submitted": 4, "completed": 4, "failed": 0,
                         "timed_out": 0},
            "slo": {"ttft_ms": dict(pct), "per_token_ms": dict(pct)},
            "throughput": {"wall_sec": 1.0, "new_tokens": 16,
                           "tokens_per_sec": 16.0},
            "occupancy": {"peak": 3, "mean": 2.0, "max_batch_slots": 4},
            "compile": {"within_budget": True, "budget": 5},
        }
        block.update(overrides)
        return block

    def _write(self, tmp_path, block):
        report = tmp_path / "report.json"
        report.write_text(json.dumps({"serving": block}))
        return report

    def test_passes_on_valid_block(self, tmp_path):
        r = _sh(f'assert_serving_report "{self._write(tmp_path, self._block())}"')
        assert r.returncode == 0, r.stdout + r.stderr
        assert "occupancy>=2" in r.stdout

    def test_fails_on_missing_file(self, tmp_path):
        r = _sh(f'assert_serving_report "{tmp_path}/report.json"')
        assert r.returncode != 0
        assert "no serving report" in r.stderr

    def test_fails_when_never_batched(self, tmp_path):
        block = self._block(occupancy={"peak": 1, "mean": 1.0,
                                       "max_batch_slots": 4})
        r = _sh(f'assert_serving_report "{self._write(tmp_path, block)}"')
        assert r.returncode != 0

    def test_fails_on_compile_budget_overrun(self, tmp_path):
        block = self._block(compile={"within_budget": False, "budget": 5})
        r = _sh(f'assert_serving_report "{self._write(tmp_path, block)}"')
        assert r.returncode != 0

    def test_fails_on_missing_percentile(self, tmp_path):
        block = self._block()
        block["slo"]["ttft_ms"]["p99"] = None
        r = _sh(f'assert_serving_report "{self._write(tmp_path, block)}"')
        assert r.returncode != 0


class TestAssertServingScrape:
    def test_passes_on_real_serving_metrics(self, tmp_path):
        """Rendered through the REAL registry + renderer, not a synthetic
        string — pins the llmtrain_serve_* naming end to end."""
        from llmtrain_tpu.telemetry import render_prometheus
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        registry = MetricsRegistry(None)
        registry.publish({
            "serve/queue_depth": 0.0,
            "serve/batch_occupancy": 2.0,
            "serve/kv_pool_utilization": 0.5,
        })
        registry.inc("serve/requests", 4)
        scrape = tmp_path / "serve.prom"
        scrape.write_text(
            render_prometheus(registry.latest(), registry.counters(), {})
        )
        r = _sh(f'assert_serving_scrape "{scrape}"')
        assert r.returncode == 0, r.stdout + r.stderr

    def test_fails_on_training_only_scrape(self, tmp_path):
        scrape = tmp_path / "serve.prom"
        scrape.write_text("llmtrain_train_loss 1.0\n")
        r = _sh(f'assert_serving_scrape "{scrape}"')
        assert r.returncode != 0
        assert "llmtrain_serve_requests_total missing" in r.stderr
