"""XLA cost attribution + roofline analysis (telemetry/profiling.py).

Tier-1 keeps to pure units — cost-dict normalization, the HLO op parser
on synthetic text, roofline classification, peak-table resolution, the
perf_attribution assembly, the serve-latency percentile reservoir, and
the perf gate's comparison core. Everything that lowers or compiles a
real program (the fit-path attribution, the ``llmtrain profile`` CLI) is
``@pytest.mark.slow`` under ``make verify-profile``.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from llmtrain_tpu.telemetry.profiling import (
    DEVICE_PEAKS,
    MFU_RECONCILE_BAND,
    attribution_gauges,
    build_perf_attribution,
    classify_roofline,
    cost_summary,
    gradient_collective_bytes,
    normalize_cost,
    parse_hlo_ops,
    render_top_ops_markdown,
    resolve_peaks,
    top_ops,
)

REPO = Path(__file__).resolve().parents[1]


# --------------------------------------------------------------------------
# cost_analysis normalization
# --------------------------------------------------------------------------


class TestCostNormalization:
    def test_dict_list_and_none_shapes(self):
        assert normalize_cost(None) == {}
        assert normalize_cost({"flops": 10, "bytes accessed": 2.5}) == {
            "flops": 10.0,
            "bytes accessed": 2.5,
        }
        # Compiled.cost_analysis() returns a list of per-computation dicts;
        # the first entry is the entry computation.
        assert normalize_cost([{"flops": 7}, {"flops": 99}]) == {"flops": 7.0}
        assert normalize_cost([]) == {}

    def test_cost_summary_maps_xla_key_spelling(self):
        summary = cost_summary({"flops": 4.0, "bytes accessed": 8.0})
        assert summary == {"flops": 4.0, "bytes_accessed": 8.0, "transcendentals": 0.0}

    def test_cost_summary_garbage_degrades_to_zeros(self):
        assert cost_summary(object()) == {
            "flops": 0.0,
            "bytes_accessed": 0.0,
            "transcendentals": 0.0,
        }


# --------------------------------------------------------------------------
# peak table
# --------------------------------------------------------------------------


class TestResolvePeaks:
    def test_substring_match_prefers_longest_key(self):
        # "TPU v5 lite" must hit the v5e-class row, not a bare "v5" guess.
        peaks = resolve_peaks("TPU v5 lite")
        assert peaks["peak_flops"] == DEVICE_PEAKS["v5 lite"]["peak_flops"]
        assert peaks["device_kind"] == "tpu v5 lite"

    def test_unknown_kind_falls_back_to_cpu_row(self):
        peaks = resolve_peaks("quantum-abacus")
        assert peaks["peak_flops"] == DEVICE_PEAKS["cpu"]["peak_flops"]

    def test_config_overrides_win(self):
        peaks = resolve_peaks("TPU v4", {"peak_flops": 123.0})
        assert peaks["peak_flops"] == 123.0
        # non-overridden keys keep the table value
        assert peaks["hbm_bytes_per_sec"] == DEVICE_PEAKS["v4"]["hbm_bytes_per_sec"]


# --------------------------------------------------------------------------
# HLO op parser (synthetic post-optimization HLO)
# --------------------------------------------------------------------------

_SYNTHETIC_HLO = """\
HloModule synthetic

%helper (p: f32[64]) -> f32[64] {
  %p = f32[64]{0} parameter(0)
  %e = f32[64]{0} exponential(f32[64]{0} %p)
  ROOT %a = f32[64]{0} add(f32[64]{0} %e, f32[64]{0} %e)
}

ENTRY %main (lhs: f32[8,16], rhs: f32[16,32]) -> f32[8,32] {
  %lhs = f32[8,16]{1,0} parameter(0)
  %rhs = f32[16,32]{1,0} parameter(1)
  %d = f32[8,32]{1,0} dot(f32[8,16]{1,0} %lhs, f32[16,32]{1,0} %rhs), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ar = f32[8,32]{1,0} all-reduce(f32[8,32]{1,0} %d), replica_groups={}, to_apply=%helper
  ROOT %r = f32[8,32]{1,0} add(f32[8,32]{1,0} %ar, f32[8,32]{1,0} %ar)
}
"""


class TestParseHloOps:
    def test_dot_flops_use_contracting_dims(self):
        parsed = parse_hlo_ops(_SYNTHETIC_HLO)
        # 2 * out_elems(8*32) * contracting(16)
        assert parsed["ops"]["dot"]["flops"] == 2.0 * 8 * 32 * 16

    def test_bytes_counted_in_entry_only(self):
        parsed = parse_hlo_ops(_SYNTHETIC_HLO)
        # helper's exponential does math (flops + transcendentals) but its
        # buffers are fusion-internal: no entry-level bytes.
        exp = parsed["ops"]["exponential"]
        assert exp["flops"] == 64 and exp["transcendentals"] == 64
        assert exp["bytes_accessed"] == 0.0
        # entry dot: output 8*32*4 plus operands (8*16 + 16*32)*4
        assert parsed["ops"]["dot"]["bytes_accessed"] == (8 * 32 + 8 * 16 + 16 * 32) * 4

    def test_collective_bytes_and_parameter_cost(self):
        parsed = parse_hlo_ops(_SYNTHETIC_HLO)
        assert parsed["collective_bytes"] == 8 * 32 * 4  # all-reduce operand
        assert parsed["ops"]["parameter"]["flops"] == 0.0
        assert parsed["ops"]["parameter"]["bytes_accessed"] == 0.0

    def test_top_ops_ranks_dot_first_and_classes_collectives(self):
        parsed = parse_hlo_ops(_SYNTHETIC_HLO)
        rows = top_ops(parsed, resolve_peaks("cpu"), k=10)
        assert rows[0]["op"] == "dot"
        by_op = {r["op"]: r for r in rows}
        assert by_op["all-reduce"]["class"] == "comms"
        assert "parameter" not in by_op  # zero-cost rows are dropped

    def test_markdown_table_renders_every_row(self):
        parsed = parse_hlo_ops(_SYNTHETIC_HLO)
        rows = top_ops(parsed, resolve_peaks("cpu"), k=3)
        lines = render_top_ops_markdown(rows)
        assert lines[0].startswith("| op |")
        assert len(lines) == 2 + len(rows)


# --------------------------------------------------------------------------
# roofline classification
# --------------------------------------------------------------------------


class TestRoofline:
    _PEAKS = {
        "peak_flops": 100.0,
        "hbm_bytes_per_sec": 10.0,
        "ici_bytes_per_sec": 1.0,
    }

    def test_compute_bound(self):
        roof = classify_roofline(flops=1000.0, bytes_accessed=50.0, peaks=self._PEAKS)
        assert roof["class"] == "compute"
        assert roof["arithmetic_intensity"] == pytest.approx(20.0)
        assert roof["ridge_intensity"] == pytest.approx(10.0)

    def test_memory_bound(self):
        roof = classify_roofline(flops=10.0, bytes_accessed=50.0, peaks=self._PEAKS)
        assert roof["class"] == "memory"

    def test_comms_bound(self):
        roof = classify_roofline(
            flops=10.0, bytes_accessed=5.0, collective_bytes=100.0, peaks=self._PEAKS
        )
        assert roof["class"] == "comms"

    def test_gradient_collective_bytes_ring_formula(self):
        assert gradient_collective_bytes({}, 100.0) == 0.0
        assert gradient_collective_bytes({"model": 8}, 100.0) == 0.0
        # dp=4 ring all-reduce: 2*(4-1)/4 * grad_bytes
        assert gradient_collective_bytes({"data": 2, "fsdp": 2}, 100.0) == 150.0


# --------------------------------------------------------------------------
# perf_attribution assembly + gauges
# --------------------------------------------------------------------------


class TestPerfAttribution:
    def _block(self, **kw):
        defaults = dict(
            executables=[
                {
                    "name": "train_step",
                    "flops": 1e6,
                    "bytes_accessed": 1e5,
                    "transcendentals": 0.0,
                }
            ],
            peaks=resolve_peaks("cpu"),
            step_time_ms=10.0,
            tokens_per_step=100.0,
            palm_flops_per_token=1e4,
            measured_mfu=0.1,
            span_totals={"data_wait": {"total_ms": 4.0}, "host_dispatch": {"total_ms": 6.0}},
            steps=2,
        )
        defaults.update(kw)
        return build_perf_attribution(**defaults)

    def test_mfu_ratio_is_deterministic_and_reconciled(self):
        block = self._block()
        # 1e6 / (100 * 1e4) == 1.0: inside the documented tolerance band.
        assert block["mfu"]["ratio_analytical_over_measured"] == pytest.approx(1.0)
        assert block["mfu"]["reconciled"] is True
        assert block["mfu"]["tolerance_band"] == list(MFU_RECONCILE_BAND)

    def test_flops_model_mismatch_flags_unreconciled(self):
        block = self._block(palm_flops_per_token=1e2)
        assert block["mfu"]["ratio_analytical_over_measured"] > MFU_RECONCILE_BAND[1]
        assert block["mfu"]["reconciled"] is False

    def test_step_split_accounts_host_spans_per_step(self):
        split = self._block()["step_time_split_ms"]
        assert split["step"] == 10.0
        assert split["measured_host"] == pytest.approx((4.0 + 6.0) / 2)
        total = (
            split["analytical_compute"]
            + split["analytical_collective"]
            + split["measured_host"]
            + split["unattributed_gap"]
        )
        assert total <= split["step"] + 1e-6

    def test_gauges_flatten_the_block(self):
        gauges = attribution_gauges(self._block())
        assert gauges["perf/flops_per_step"] == 1e6
        assert gauges["perf/mfu_reconcile_ratio"] == pytest.approx(1.0)
        assert gauges["perf/roofline_class"] in (0.0, 1.0, 2.0)
        assert "perf/step_unattributed_gap_ms" in gauges


# --------------------------------------------------------------------------
# serve-latency percentile reservoir (serving/http.py satellite)
# --------------------------------------------------------------------------


class TestServerStatsPercentiles:
    def test_ttft_and_per_token_gauges(self):
        from llmtrain_tpu.serving.http import ServerStats

        stats = ServerStats()
        for i in range(100):
            stats.record(latency_ms=float(i + 1), ttft_ms=float(i) / 2, tokens=11)
        gauges = stats.prometheus_gauges()
        for stem in ("serve/latency_ms", "serve/ttft_ms", "serve/per_token_ms"):
            for tag in ("p50", "p95", "p99"):
                assert f"{stem}_{tag}" in gauges
        assert gauges["serve/latency_ms_p50"] <= gauges["serve/latency_ms_p99"]
        # per-token = (latency - ttft) / (tokens - 1): decode-rate only
        assert gauges["serve/per_token_ms_p50"] == pytest.approx(
            (51.0 - 25.0) / 10, abs=0.5
        )

    def test_empty_reservoirs_export_nothing(self):
        from llmtrain_tpu.serving.http import ServerStats

        assert ServerStats().prometheus_gauges() == {}

    def test_legacy_record_without_ttft(self):
        from llmtrain_tpu.serving.http import ServerStats

        stats = ServerStats()
        stats.record(latency_ms=100.0, tokens=4)
        gauges = stats.prometheus_gauges()
        assert "serve/ttft_ms_p50" not in gauges
        assert gauges["serve/per_token_ms_p50"] == pytest.approx(25.0)

    def test_snapshot_gains_p95_and_ttft(self):
        from llmtrain_tpu.serving.http import ServerStats

        stats = ServerStats()
        for i in range(20):
            stats.record(latency_ms=float(i), ttft_ms=1.0, tokens=2)
        snap = stats.snapshot()
        assert snap["p95_latency_ms"] >= snap["p50_latency_ms"]
        assert snap["p50_ttft_ms"] == 1.0


# --------------------------------------------------------------------------
# perf gate comparison core (tools/perf_gate.py)
# --------------------------------------------------------------------------


def _load_perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate", REPO / "tools" / "perf_gate.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPerfGate:
    def _line(self, **kw):
        base = {
            "metric": "tokens_per_sec_per_chip",
            "value": 1000.0,
            "detail": {"model": "gpt L2 d128 T128", "attention": "dense", "batch": 4},
        }
        detail_keys = {"fallback"}
        for key, val in kw.items():
            if key in detail_keys:
                base["detail"][key] = val
            else:
                base[key] = val
        return base

    def test_synthetic_regression_gates(self):
        gate = _load_perf_gate()
        verdict = gate.compare([self._line()], [self._line(value=400.0)])
        assert verdict["regressions"]

    def test_noise_wobble_passes(self):
        gate = _load_perf_gate()
        verdict = gate.compare([self._line()], [self._line(value=950.0)])
        assert verdict["compared"] and not verdict["regressions"]

    def test_degraded_lines_never_gate(self):
        gate = _load_perf_gate()
        verdict = gate.compare(
            [self._line()], [self._line(value=10.0, degraded=True, fallback="oom")]
        )
        assert not verdict["regressions"]
        assert verdict["skipped"]

    def test_real_r04_r05_pair_passes(self):
        """The acceptance pin: the repo's own consecutive rounds must not
        false-positive (different scenarios + degraded lines → skip)."""
        gate = _load_perf_gate()
        old = gate.load_results(str(REPO / "BENCH_r04.json"))
        new = gate.load_results(str(REPO / "BENCH_r05.json"))
        assert old and new
        verdict = gate.compare(old, new)
        assert not verdict["regressions"]


# --------------------------------------------------------------------------
# slow: real lowering/compiles
# --------------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
class TestFitAttribution:
    def test_fit_report_gains_perf_attribution(self, tmp_path):
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "perf-attr"},
                "model": {
                    "name": "dummy_gpt",
                    "block_size": 8,
                    "d_model": 16,
                    "n_layers": 1,
                    "n_heads": 2,
                    "d_ff": 32,
                    "dropout": 0.0,
                    "vocab_size": 32,
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 6,
                    "micro_batch_size": 2,
                    "grad_accum_steps": 1,
                    "log_every_steps": 3,
                    "eval_every_steps": 6,
                    "save_every_steps": 6,
                    "warmup_steps": 0,
                },
                "output": {"root_dir": str(tmp_path / "runs")},
            }
        )
        run_dir = tmp_path / "runs" / "perf-attr"
        (run_dir / "logs").mkdir(parents=True)
        Trainer(cfg, run_dir, NullTracker()).fit()

        report = json.loads((run_dir / "report.json").read_text())
        block = report["perf_attribution"]
        exe = block["executables"][0]
        assert exe["name"] == "train_step"
        assert exe["flops"] > 0 and exe["bytes_accessed"] > 0
        assert exe["roofline"]["class"] in ("compute", "memory", "comms")
        # The XLA flop count and the PaLM 6N model must agree within the
        # documented tolerance band on a plain dense GPT.
        assert block["mfu"]["reconciled"] is True, block["mfu"]
        assert set(block["step_time_split_ms"]) == {
            "step",
            "analytical_compute",
            "analytical_collective",
            "measured_host",
            "unattributed_gap",
        }

    def test_profile_cli_emits_report(self, tmp_path):
        """`llmtrain profile` acceptance: per-executable flops/bytes,
        roofline class, top-10 ops, and compiled memory footprint."""
        out = tmp_path / "profile_report.json"
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "llmtrain_tpu",
                "profile",
                "--config",
                "configs/presets/gpt_telemetry_smoke.yaml",
                "--steps",
                "2",
                "--output",
                str(out),
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            env=_cli_env(),
            timeout=420,
        )
        assert proc.returncode == 0, proc.stderr
        report = json.loads(out.read_text())
        assert report["schema"] == "llmtrain-profile-report/1"
        assert report["probe"]["steps"] == 2
        exes = {e["name"]: e for e in report["executables"]}
        train = exes["train_step"]
        assert train["flops"] > 0 and train["bytes_accessed"] > 0
        assert train["roofline"]["class"] in ("compute", "memory", "comms")
        assert 0 < len(train["top_ops"]) <= 10
        assert train["compile_time_s"] > 0
        assert report["memory"]["compiled_train_step"]["total_hbm_bytes"] > 0
        assert report["perf_attribution"]["mfu"]["reconciled"] is True
