"""run_dir / metadata / logging / summary tests."""

import json
import logging

import pytest
import yaml

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.utils import (
    JsonFormatter,
    configure_logging,
    create_run_directory,
    format_run_summary,
    generate_meta,
    get_logger,
    write_meta_json,
    write_resolved_config,
)

MINIMAL = {
    "run": {"name": "t"},
    "model": {"name": "dummy_gpt"},
    "data": {"name": "dummy_text"},
    "trainer": {"max_steps": 10, "warmup_steps": 0},
}


def test_create_run_directory(tmp_path):
    d = create_run_directory(tmp_path, "abc")
    assert d.is_dir() and (d / "logs").is_dir()
    with pytest.raises(FileExistsError):
        create_run_directory(tmp_path, "abc")


def test_write_resolved_config_atomic(tmp_path):
    d = create_run_directory(tmp_path, "abc")
    cfg = RunConfig.model_validate(MINIMAL)
    path = write_resolved_config(d, cfg.model_dump())
    loaded = yaml.safe_load(path.read_text())
    assert loaded["run"]["name"] == "t"
    assert not list(d.glob("*.tmp"))


def test_meta_json(tmp_path, monkeypatch):
    monkeypatch.setenv("RANK", "3")
    meta = generate_meta(
        run_id="rid", run_name="t", config_path="c.yaml", resolved_config_path=None
    )
    assert meta["meta_version"] == 1
    assert meta["distributed_env"]["RANK"] == "3"
    assert meta["hostname"]
    path = write_meta_json(tmp_path, meta)
    assert json.loads(path.read_text())["run_id"] == "rid"


def test_json_formatter_single_line():
    record = logging.LogRecord("llmtrain", logging.INFO, "f", 1, "hello %s", ("x",), None)
    line = JsonFormatter().format(record)
    parsed = json.loads(line)
    assert parsed["message"] == "hello x"
    assert "\n" not in line


def test_configure_logging_idempotent(tmp_path):
    log_file = tmp_path / "t.log"
    logger = configure_logging(level="INFO", json_output=True, log_file=log_file)
    configure_logging(level="INFO", json_output=True, log_file=log_file)
    stream_handlers = [
        h for h in logger.handlers
        if isinstance(h, logging.StreamHandler) and not isinstance(h, logging.FileHandler)
    ]
    file_handlers = [h for h in logger.handlers if isinstance(h, logging.FileHandler)]
    assert len(stream_handlers) == 1
    assert len(file_handlers) == 1
    logger.info("written")
    for h in logger.handlers:
        h.flush()
    assert "written" in log_file.read_text()
    assert get_logger().propagate is False
    configure_logging(level="INFO", json_output=True, log_file=None)


def test_summary_json_and_text():
    cfg = RunConfig.model_validate(MINIMAL)
    s = format_run_summary(cfg, run_id="rid", run_dir="/tmp/rid", dry_run=True, as_json=True)
    assert isinstance(s, dict)
    assert s["run_id"] == "rid" and s["dry_run"] is True
    assert s["model"]["name"] == "dummy_gpt"
    text = format_run_summary(cfg, run_id="rid", run_dir=None, dry_run=True, as_json=False)
    assert isinstance(text, str) and text.startswith("Planned run:")
    assert "dummy_gpt" in text


def test_hw_flops_and_mfu():
    from llmtrain_tpu.utils import hw

    # 6N dominates when L*T*d is small
    fpt = hw.transformer_flops_per_token(
        n_params=1000, n_layers=1, seq_len=2, d_model=4
    )
    assert fpt == 6 * 1000 + 12 * 1 * 2 * 4

    # mfu is linear in throughput and inverse in peak
    m = hw.mfu(
        100.0, n_params=1000, n_layers=1, seq_len=2, d_model=4, peak_flops=1e6
    )
    assert m == pytest.approx(100.0 * fpt / 1e6)

    # CPU backend in tests -> nominal placeholder peak
    assert hw.peak_flops_per_chip() == hw.CPU_NOMINAL_FLOPS


class TestHW:
    """utils/hw.py: the MFU arithmetic every reported number rests on."""

    def test_transformer_flops_formula(self):
        from llmtrain_tpu.utils.hw import transformer_flops_per_token

        # PaLM appendix B: 6N + 12*L*T*d, hand-checked.
        assert transformer_flops_per_token(
            n_params=1000, n_layers=2, seq_len=8, d_model=4
        ) == 6 * 1000 + 12 * 2 * 8 * 4

    def test_mfu_hand_computed(self):
        from llmtrain_tpu.utils.hw import mfu

        # 10 tokens/s * 600 FLOPs/token = 6000 FLOP/s on a 60000-peak chip.
        got = mfu(
            10.0,
            n_params=100,
            n_layers=0,
            seq_len=8,
            d_model=4,
            peak_flops=60000.0,
        )
        assert abs(got - 0.1) < 1e-12

    def test_headline_run_mfu_reproduces(self):
        """RESULTS.md's headline numbers cross-check: the 85.6M byte-level
        GPT at the measured 165.8k tokens/s gives the recorded 0.48 MFU on
        v5e peak."""
        from llmtrain_tpu.utils.hw import TPU_PEAK_FLOPS, mfu

        got = mfu(
            165_800,
            n_params=85_600_000,
            n_layers=12,
            seq_len=512,
            d_model=768,
            peak_flops=TPU_PEAK_FLOPS["v5e"],
        )
        assert abs(got - 0.48) < 0.01

    def test_peak_lookup_defaults_cpu(self):
        from llmtrain_tpu.utils.hw import CPU_NOMINAL_FLOPS, peak_flops_per_chip

        # conftest pins the CPU backend, so the nominal figure applies.
        assert peak_flops_per_chip() == CPU_NOMINAL_FLOPS
