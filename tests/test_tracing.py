"""Distributed request tracing (telemetry/tracing.py, trace_collect.py,
stats.py) plus the EventTimeline concurrency contract.

Tier-1 keeps to pure units: traceparent round-trips, tail-sampling
decisions, tracer flush/idempotency, the shared nearest-rank percentile
helper, exemplar-carrying histograms and their Prometheus rendering, the
cross-process trace collector over synthetic JSONL, and timeline
thread-safety. The 2-replica fleet drill with a forced failover lives in
``tests/test_trace_e2e.py`` under ``@pytest.mark.slow``
(``make verify-trace``).
"""

from __future__ import annotations

import json
import math
import threading
import time

import pytest

from llmtrain_tpu.telemetry.stats import (
    Histogram,
    percentile,
    percentiles,
)
from llmtrain_tpu.telemetry.timeline import EventTimeline
from llmtrain_tpu.telemetry.tracing import (
    TailSampler,
    TraceContext,
    Tracer,
    new_span_id,
    new_trace_id,
)

# ---------------------------------------------------------------------------
# shared percentile helper (the ONE implementation every caller uses)
# ---------------------------------------------------------------------------


class TestSharedPercentiles:
    def test_nearest_rank_known_values(self):
        xs = sorted(float(v) for v in range(1, 101))  # 1..100
        assert percentile(xs, 0.50) == 50.0
        assert percentile(xs, 0.95) == 95.0
        assert percentile(xs, 0.99) == 99.0
        assert percentile(xs, 1.0) == 100.0

    def test_small_samples_clamp_to_extremes(self):
        assert percentile([7.0], 0.5) == 7.0
        assert percentile([7.0], 0.99) == 7.0
        assert percentile([1.0, 2.0], 0.01) == 1.0

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            percentile([], 0.5)

    def test_percentiles_dict_shape(self):
        out = percentiles([1.0, 2.0, 3.0, 10.0])
        assert out == {
            "p50": 2.0,
            "p95": 10.0,
            "p99": 10.0,
            "mean": 4.0,
            "max": 10.0,
        }
        assert percentiles([]) == {}

    def test_loadgen_wrapper_keeps_explicit_none_contract(self):
        # lifecycle/controller.py indexes ["p50"] on possibly-empty
        # samples — the serving wrapper must keep the keys-with-None
        # shape rather than the {} the shared helper returns.
        from llmtrain_tpu.serving.loadgen import percentiles as lg_pct

        empty = lg_pct([])
        assert empty["p50"] is None and empty["p99"] is None
        assert lg_pct([1.0, 2.0, 3.0, 10.0])["p50"] == 2.0


class TestHistogram:
    def test_cumulative_buckets_and_inf_row(self):
        h = Histogram((10.0, 100.0))
        for v in (5.0, 50.0, 500.0):
            h.observe(v)
        rows, total, count = h.snapshot()
        assert [(le, cum) for le, cum, _ in rows] == [
            (10.0, 1),
            (100.0, 2),
            (math.inf, 3),
        ]
        assert total == 555.0 and count == 3

    def test_exemplar_lands_in_its_bucket(self):
        h = Histogram((10.0, 100.0))
        h.observe(50.0, trace_id="aa" * 16, unix_time=123.0)
        rows, _, _ = h.snapshot()
        by_le = {le: ex for le, _, ex in rows}
        assert by_le[10.0] is None
        assert by_le[100.0] is not None
        assert by_le[100.0].trace_id == "aa" * 16
        assert by_le[100.0].value == 50.0


# ---------------------------------------------------------------------------
# trace context / traceparent header
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_header_round_trip(self):
        ctx = TraceContext.root()
        parsed = TraceContext.from_traceparent(ctx.to_traceparent())
        assert parsed is not None
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id
        assert parsed.forced is False

    def test_forced_flag_survives_the_wire(self):
        ctx = TraceContext.root(forced=True)
        parsed = TraceContext.from_traceparent(ctx.to_traceparent())
        assert parsed is not None and parsed.forced is True

    @pytest.mark.parametrize(
        "header",
        [
            "",
            "garbage",
            "00-zz-bb-01",
            "01-" + "a" * 32 + "-" + "b" * 16,  # missing flags
            "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # zero trace id
            "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # zero span id
        ],
    )
    def test_malformed_headers_parse_to_none(self, header):
        assert TraceContext.from_traceparent(header) is None

    def test_child_links_to_parent(self):
        root = TraceContext.root()
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        assert child.span_id != root.span_id

    def test_ids_are_well_formed(self):
        assert len(new_trace_id()) == 32
        assert len(new_span_id()) == 16
        int(new_trace_id(), 16)  # hex


# ---------------------------------------------------------------------------
# tail sampling
# ---------------------------------------------------------------------------


class TestTailSampler:
    def test_keep_reason_priority(self):
        s = TailSampler(warmup=0)
        assert (
            s.decide(1.0, errored=True, failover=True, forced=True)
            == "forced"
        )
        assert s.decide(1.0, errored=True, failover=True) == "error"
        assert s.decide(1.0, failover=True) == "failover"

    def test_warmup_keeps_the_first_traces(self):
        s = TailSampler(warmup=3)
        assert [s.decide(1.0) for _ in range(3)] == ["warmup"] * 3

    def test_fast_requests_drop_and_slow_keep(self):
        s = TailSampler(slow_frac=0.05, reservoir=64, warmup=0)
        for _ in range(64):
            s.decide(100.0)
        assert s.decide(1.0) is None
        assert s.decide(500.0) == "slow"

    def test_slow_frac_validated(self):
        with pytest.raises(ValueError):
            TailSampler(slow_frac=0.0)


# ---------------------------------------------------------------------------
# tracer: buffer -> sample -> flush
# ---------------------------------------------------------------------------


def _trace_events(tl: EventTimeline) -> list[dict]:
    return [e for e in tl.events() if e.get("cat") == "trace"]


class TestTracer:
    def test_kept_trace_flushes_the_whole_tree(self, tmp_path):
        tl = EventTimeline(tmp_path / "timeline.jsonl")
        tracer = Tracer(tl, sampler=TailSampler(warmup=16))
        t0 = time.perf_counter()
        tr = tracer.start(root_name="serve/request")
        tr.add_span("serve/prefill", t0=t0 + 0.001, t1=t0 + 0.002, step=3)
        tr.add_event("serve/prefix_cache", t=t0 + 0.0015, hit=True)
        reason = tracer.finish(
            tr, t0=t0, t1=t0 + 0.01, request_id="r1", finish_reason="eos"
        )
        assert reason == "warmup"

        evs = _trace_events(tl)
        assert [e["name"] for e in evs] == [
            "serve/request",
            "serve/prefill",
            "serve/prefix_cache",
        ]
        root, child, mark = evs
        assert root["args"]["trace_id"] == tr.trace_id
        assert root["args"]["span_id"] == tr.root_span_id
        assert root["args"]["sampled"] == "warmup"
        assert root["args"]["request_id"] == "r1"
        assert child["args"]["parent_span_id"] == tr.root_span_id
        # A buffered `step` arg rides the record() keyword, landing as the
        # event's own step field like every other timeline span.
        assert child["step"] == 3
        assert mark["args"]["hit"] is True and mark["dur_us"] == 0
        # Flushed to JSONL too (the collector reads the file).
        lines = (tmp_path / "timeline.jsonl").read_text().splitlines()
        assert sum(1 for ln in lines if '"cat": "trace"' in ln) == 3

    def test_dropped_trace_writes_nothing(self):
        tl = EventTimeline(None)
        sampler = TailSampler(slow_frac=0.05, reservoir=64, warmup=0)
        for _ in range(64):
            sampler.decide(100.0)
        tracer = Tracer(tl, sampler=sampler)
        tr = tracer.start()
        t0 = time.perf_counter()
        assert tracer.finish(tr, t0=t0, t1=t0 + 0.0001) is None
        assert _trace_events(tl) == []
        assert tracer.stats() == {"finished": 1, "kept": {}}

    def test_finish_is_first_caller_wins(self):
        tl = EventTimeline(None)
        tracer = Tracer(tl)
        tr = tracer.start()
        t0 = time.perf_counter()
        assert tracer.finish(tr, t0=t0, t1=t0 + 0.001) == "warmup"
        assert tracer.finish(tr, t0=t0, t1=t0 + 0.001) is None
        assert len(_trace_events(tl)) == 1
        assert tracer.stats()["finished"] == 1

    def test_error_note_keeps_the_trace(self):
        tl = EventTimeline(None)
        sampler = TailSampler(slow_frac=0.05, reservoir=64, warmup=0)
        for _ in range(64):
            sampler.decide(100.0)
        tracer = Tracer(tl, sampler=sampler)
        tr = tracer.start()
        tr.note(error="boom")
        t0 = time.perf_counter()
        assert tracer.finish(tr, t0=t0, t1=t0 + 0.0001) == "error"
        root = _trace_events(tl)[0]
        assert root["args"]["error"] == "boom"

    def test_span_cap_drops_detail_not_the_trace(self):
        tl = EventTimeline(None)
        tracer = Tracer(tl, max_spans=4)
        tr = tracer.start(forced=True)
        t0 = time.perf_counter()
        for i in range(10):
            tr.add_span(f"s{i}", t0=t0, t1=t0 + 0.001)
        assert tracer.finish(tr, t0=t0, t1=t0 + 0.01) == "forced"
        evs = _trace_events(tl)
        assert len(evs) == 5  # root + max_spans
        assert evs[0]["args"]["dropped_spans"] == 6

    def test_finish_tolerates_flushless_duck_typed_timeline(self):
        # Scheduler/router auto-create a Tracer for ANY timeline-shaped
        # object (tests pass fakes with only instant/record/span) — a
        # kept trace must degrade to record() calls, not crash on the
        # missing flush().
        class RecordOnly:
            def __init__(self):
                self.records = []

            def record(self, name, **kw):
                self.records.append(name)

            def instant(self, name, **kw):
                pass

            def span(self, name, **kw):
                from contextlib import nullcontext

                return nullcontext()

        tl = RecordOnly()
        tracer = Tracer(tl)
        tr = tracer.start(forced=True)
        t0 = time.perf_counter()
        tr.add_span("serve/prefill", t0=t0, t1=t0 + 0.001)
        assert tracer.finish(tr, t0=t0, t1=t0 + 0.01) == "forced"
        assert tl.records == ["serve/request", "serve/prefill"]

    def test_remote_parent_becomes_parent_span_id(self):
        tl = EventTimeline(None)
        tracer = Tracer(tl)
        parent = TraceContext.root()
        tr = tracer.start(parent=parent, root_name="serve/request")
        assert tr.trace_id == parent.trace_id
        t0 = time.perf_counter()
        tracer.finish(tr, t0=t0, t1=t0 + 0.001)
        root = _trace_events(tl)[0]
        assert root["args"]["parent_span_id"] == parent.span_id


# ---------------------------------------------------------------------------
# Prometheus rendering: exemplars out, federation strips them
# ---------------------------------------------------------------------------


class TestPrometheusExemplars:
    def _registry(self):
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry(None)
        reg.observe(
            "serve/ttft_ms",
            42.0,
            buckets=(10.0, 100.0),
            trace_id="ab" * 16,
        )
        return reg

    def test_histogram_renders_with_exemplar_suffix(self):
        from llmtrain_tpu.telemetry.prometheus import render_prometheus

        reg = self._registry()
        text = render_prometheus(
            reg.latest(), reg.counters(), histograms=reg.histograms()
        )
        assert "# TYPE llmtrain_serve_ttft_ms histogram" in text
        assert (
            'llmtrain_serve_ttft_ms_bucket{le="100.0"} 1 '
            '# {trace_id="' + "ab" * 16 + '"} 42.0'
        ) in text
        assert 'le="+Inf"' in text
        assert "llmtrain_serve_ttft_ms_count 1" in text

    def test_federation_strips_exemplars(self):
        from llmtrain_tpu.telemetry.prometheus import (
            federate_prometheus,
            render_prometheus,
        )

        reg = self._registry()
        text = render_prometheus(
            reg.latest(), reg.counters(), histograms=reg.histograms()
        )
        fed = federate_prometheus({"replica0": text})
        assert "# {" not in fed
        # Bucket survives (tenant label injected) rather than being
        # dropped as unparseable because of the exemplar suffix.
        assert (
            'llmtrain_serve_ttft_ms_bucket{tenant="replica0",le="100.0"} 1'
            in fed
        )

    def test_exemplar_lookalike_inside_label_value_parses_whole(self):
        # The exemplar suffix is only recognized AFTER the sample value;
        # a label value that happens to contain ` # {` must not be
        # truncated mid-sample.
        from llmtrain_tpu.telemetry.prometheus import federate_prometheus

        text = (
            "# TYPE g gauge\n"
            'g{path="a # {weird} b"} 3\n'
            'g{q="esc\\" # {x"} 5\n'
        )
        fed = federate_prometheus({"t0": text})
        assert 'g{tenant="t0",path="a # {weird} b"} 3' in fed
        assert 'g{tenant="t0",q="esc\\" # {x"} 5' in fed


# ---------------------------------------------------------------------------
# EventTimeline under contention (satellite: concurrency contract)
# ---------------------------------------------------------------------------


class TestTimelineConcurrency:
    def test_producer_threads_against_flush_lose_nothing(self, tmp_path):
        # Bounded well under max_events: overflow is a separate contract
        # (oldest dropped + counted); here we pin exactly-once flushing.
        tl = EventTimeline(tmp_path / "timeline.jsonl")
        per_thread = 2000

        def produce(tag: str):
            for i in range(per_thread):
                t0 = time.perf_counter()
                tl.record(f"{tag}/span", t0=t0, t1=t0, seq=i)
                tl.instant(f"{tag}/mark", seq=i)

        threads = [
            threading.Thread(target=produce, args=(f"w{k}",)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for _ in range(20):  # flush racing the producers
            tl.flush()
        for t in threads:
            t.join()
        tl.flush()
        mem = [e for e in tl.events() if "seq" in (e.get("args") or {})]
        lines = [
            json.loads(ln)
            for ln in (tmp_path / "timeline.jsonl").read_text().splitlines()
        ]
        disk = [e for e in lines if "seq" in (e.get("args") or {})]
        # Exactly-once persistence: no event duplicated or lost.
        assert len(disk) == len(mem) > 0
        # Per-producer sequence order survives interleaving.
        for k in range(4):
            seqs = [
                e["args"]["seq"]
                for e in disk
                if e["name"] == f"w{k}/span"
            ]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_concurrent_spans_keep_thread_attribution(self):
        tl = EventTimeline(None)
        barrier = threading.Barrier(3)

        def worker():
            barrier.wait()
            with tl.span("work", cat="serve"):
                time.sleep(0.001)

        threads = [
            threading.Thread(target=worker, name=f"producer-{i}")
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        names = {
            e["thread"] for e in tl.events() if e.get("name") == "work"
        }
        assert names == {f"producer-{i}" for i in range(3)}

    def test_tag_rollback_after_flush_tags_memory_not_disk(self, tmp_path):
        tl = EventTimeline(tmp_path / "timeline.jsonl")
        t0 = time.perf_counter()
        tl.record("train/step", t0=t0, t1=t0, step=5)
        tl.flush()
        tl.tag_rollback(5, 5)
        # Already-flushed JSONL lines keep their shape (the paired
        # rollback instant gives post-processing the window)...
        line = json.loads(
            (tmp_path / "timeline.jsonl").read_text().splitlines()[-1]
        )
        assert "rolled_back" not in line
        # ...while the retained in-memory event carries the tag for
        # span_totals/report consumers.
        ev = [e for e in tl.events() if e.get("step") == 5][0]
        assert ev["rolled_back"] is True
        # A re-flush must not duplicate the line.
        tl.flush()
        stepped = [
            ln
            for ln in (tmp_path / "timeline.jsonl").read_text().splitlines()
            if '"step": 5' in ln
        ]
        assert len(stepped) == 1


# ---------------------------------------------------------------------------
# cross-process collector (trace_collect.py) over synthetic fleet JSONL
# ---------------------------------------------------------------------------

_T = "ab" * 16  # trace id
_R, _H, _S, _P, _D = ("1" * 16, "2" * 16, "3" * 16, "4" * 16, "5" * 16)


def _write_fleet(tmp_path):
    """Two-process fleet: router roots the trace, a replica continues it
    via the traceparent hop span id. Same wall-clock anchor, 100ms request."""

    def _ev(name, ts_us, dur_us, **args):
        return {
            "name": name,
            "cat": "trace",
            "ph": "X",
            "ts_us": ts_us,
            "dur_us": dur_us,
            "thread": "MainThread",
            "args": args,
        }

    router_dir = tmp_path / "router" / "telemetry"
    replica_dir = tmp_path / "replica0" / "telemetry"
    router_dir.mkdir(parents=True)
    replica_dir.mkdir(parents=True)
    seg = {
        "name": "segment_start",
        "ph": "seg",
        "segment_id": 0,
        "start_unix_time": 1000.0,
    }
    router = [
        seg,
        _ev(
            "router/request", 0, 100_000,
            trace_id=_T, span_id=_R, sampled="slow", request_id="proc/1",
        ),
        _ev(
            "router/http_dispatch", 10_000, 80_000,
            trace_id=_T, span_id=_H, parent_span_id=_R, replica="replica0",
        ),
    ]
    replica = [
        seg,
        _ev(
            "serve/request", 15_000, 70_000,
            trace_id=_T, span_id=_S, parent_span_id=_H, sampled="forced",
        ),
        _ev("serve/prefill", 20_000, 30_000,
            trace_id=_T, span_id=_P, parent_span_id=_S),
        _ev("serve/decode_phase", 50_000, 30_000,
            trace_id=_T, span_id=_D, parent_span_id=_S),
        "this line is mid-write garbage {",
    ]
    for path, evs in (
        (router_dir / "timeline.jsonl", router),
        (replica_dir / "timeline.jsonl", replica),
    ):
        path.write_text(
            "\n".join(
                e if isinstance(e, str) else json.dumps(e) for e in evs
            )
            + "\n"
        )
    return tmp_path


class TestTraceCollect:
    def _load(self, tmp_path):
        from llmtrain_tpu.telemetry.trace_collect import (
            collect_traces,
            discover_sources,
        )

        sources = discover_sources([_write_fleet(tmp_path)])
        return sources, collect_traces(sources)

    def test_discovery_and_assembly(self, tmp_path):
        sources, traces = self._load(tmp_path)
        assert sorted(s.label for s in sources) == [
            "replica0/timeline",
            "router/timeline",
        ]
        assert list(traces) == [_T]
        tr = traces[_T]
        assert len(tr.spans) == 5
        assert sorted(tr.sources) == ["replica0/timeline", "router/timeline"]

    def test_cross_process_parentage(self, tmp_path):
        _, traces = self._load(tmp_path)
        tr = traces[_T]
        root = tr.root
        assert root is not None and root.name == "router/request"
        assert [c.name for c in tr.children(root.span_id)] == [
            "router/http_dispatch"
        ]
        # The replica's root hangs off the PRE-ALLOCATED hop span id the
        # router sent in the traceparent header.
        assert [c.name for c in tr.children(_H)] == ["serve/request"]
        assert [c.name for c in tr.children(_S)] == [
            "serve/prefill",
            "serve/decode_phase",
        ]

    def test_critical_path_sums_to_end_to_end(self, tmp_path):
        from llmtrain_tpu.telemetry.trace_collect import critical_path

        _, traces = self._load(tmp_path)
        cp = critical_path(traces[_T])
        assert cp["total_ms"] == 100.0
        assert cp["root"] == "router/request"
        assert sum(cp["breakdown"].values()) == pytest.approx(100.0)
        # Leaf spans own their full windows; ancestors keep only gaps.
        assert cp["breakdown"]["serve/prefill"] == 30.0
        assert cp["breakdown"]["serve/decode_phase"] == 30.0
        assert cp["breakdown"]["router/request"] == 20.0

    def test_format_tree_shows_offsets_and_processes(self, tmp_path):
        from llmtrain_tpu.telemetry.trace_collect import format_tree

        _, traces = self._load(tmp_path)
        lines = format_tree(traces[_T])
        assert lines[0].startswith(f"trace {_T}")
        assert "2 processes" in lines[0]
        assert any(
            "serve/prefill" in ln and "(replica0/timeline)" in ln
            for ln in lines
        )
        assert any("[slow]" in ln for ln in lines)

    def test_summarize_per_span_kind(self, tmp_path):
        from llmtrain_tpu.telemetry.trace_collect import summarize

        _, traces = self._load(tmp_path)
        out = summarize(traces)
        assert out["traces"] == 1
        assert out["end_to_end_ms"]["p50"] == 100.0
        assert out["spans"]["serve/prefill"]["count"] == 1
        assert out["spans"]["serve/prefill"]["p99"] == 30.0

    def test_merge_draws_cross_process_flow_arrows(self, tmp_path):
        from llmtrain_tpu.telemetry.trace_collect import merge_perfetto

        sources, traces = self._load(tmp_path)
        out = tmp_path / "merged_trace.json"
        merge_perfetto(sources, out, traces=traces)
        doc = json.loads(out.read_text())
        evs = doc["traceEvents"]
        proc_names = {
            e["args"]["name"] for e in evs if e["name"] == "process_name"
        }
        assert proc_names == {"router/timeline", "replica0/timeline"}
        flows = [e for e in evs if e["name"] == "trace_link"]
        # Exactly one cross-source link (hop→replica-root): an s/f pair.
        assert sorted(e["ph"] for e in flows) == ["f", "s"]
        assert flows[0]["id"] == flows[1]["id"]
        # In-process parent→child links (router→hop) draw NO arrow.
        assert len(flows) == 2

    def test_merge_rebases_headerless_sources_to_the_base(self, tmp_path):
        """A timeline with no segment header carries relative stamps;
        the merge must rebase it to the fleet base (and flag it) rather
        than fling its events ~1.7e9 s before everything else."""
        from llmtrain_tpu.telemetry.trace_collect import (
            discover_sources,
            merge_perfetto,
        )

        _write_fleet(tmp_path)
        bare = tmp_path / "bare" / "telemetry"
        bare.mkdir(parents=True)
        (bare / "timeline.jsonl").write_text(
            json.dumps(
                {
                    "name": "x",
                    "ph": "X",
                    "ts_us": 2000,
                    "dur_us": 500,
                    "cat": "serve",
                }
            )
            + "\n"
        )
        sources = discover_sources([tmp_path])
        out = tmp_path / "merged_trace.json"
        merge_perfetto(sources, out)
        doc = json.loads(out.read_text())
        assert doc["otherData"]["unaligned"] == ["bare/timeline"]
        assert all(
            e["ts"] >= 0 for e in doc["traceEvents"] if e.get("ph") == "X"
        )

    def test_orphaned_subtree_surfaces_as_extra_root(self, tmp_path):
        """When only the replica kept the trace (tail sampling disagreed),
        its subtree must still show up instead of being dropped."""
        from llmtrain_tpu.telemetry.trace_collect import (
            collect_traces,
            discover_sources,
        )

        _write_fleet(tmp_path)
        (tmp_path / "router" / "telemetry" / "timeline.jsonl").unlink()
        traces = collect_traces(discover_sources([tmp_path]))
        tr = traces[_T]
        assert [r.name for r in tr.roots] == ["serve/request"]
        assert tr.duration_ms == pytest.approx(70.0)


class TestTraceCLI:
    def _ns(self, tmp_path, action, trace_id=None, **kw):
        import argparse

        return argparse.Namespace(
            action=action,
            trace_id=trace_id,
            run_dirs=[str(tmp_path)],
            k=kw.get("k", 10),
            out=kw.get("out"),
            json=kw.get("json", False),
        )

    def test_slowest_show_summary_merge(self, tmp_path, capsys):
        from llmtrain_tpu.cli import _handle_trace

        _write_fleet(tmp_path)
        assert _handle_trace(self._ns(tmp_path, "slowest")) == 0
        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["trace_id"] == _T
        assert rows[0]["total_ms"] == 100.0
        assert rows[0]["request_id"] == "proc/1"

        # Unique-prefix match is enough for `show`.
        assert _handle_trace(self._ns(tmp_path, "show", _T[:8])) == 0
        out = capsys.readouterr().out
        assert "router/request" in out and "serve/prefill" in out
        assert '"breakdown"' in out  # critical-path block follows the tree

        assert _handle_trace(self._ns(tmp_path, "summary")) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["traces"] == 1

        assert _handle_trace(self._ns(tmp_path, "merge")) == 0
        merged = json.loads(capsys.readouterr().out)
        assert (tmp_path / "merged_trace.json").exists()
        assert merged["traces"] == 1

    def test_empty_dir_is_a_config_error(self, tmp_path, capsys):
        from llmtrain_tpu.cli import EXIT_CONFIG_ERROR, _handle_trace

        assert (
            _handle_trace(self._ns(tmp_path, "slowest")) == EXIT_CONFIG_ERROR
        )
        capsys.readouterr()
