"""Torch checkpoint interop (llmtrain_tpu/interop/torch_interop.py).

The migration path in BOTH directions: export our GPT weights to a
torch-layout state dict, and rebuild our params from one. Correctness is
anchored to the parity-proven transforms of tests/test_torch_parity.py —
the exported dict must drive the torch mirror to the flax model's exact
logits, and import(export(params)) must be the identity.
"""

from __future__ import annotations

import json
import subprocess
import sys

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from llmtrain_tpu.interop import (  # noqa: E402
    params_from_torch_state_dict,
    params_to_torch_state_dict,
)

# The parity-test mirror and helpers double as the reference
# implementation here (pytest puts tests/ on sys.path).
from test_torch_parity import T, V, _flax_gpt, _TorchGPT  # noqa: E402


@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_roundtrip_is_identity(tie):
    _, params = _flax_gpt(tie)
    sd = params_to_torch_state_dict(params)
    back = params_from_torch_state_dict(sd, params)
    for (pa, va), (pb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
        strict=True,
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_exported_state_dict_drives_torch_mirror(tie):
    """load_state_dict(exported) on the torch mirror reproduces the flax
    logits — the export really is the parity transplant."""
    model, params = _flax_gpt(tie)
    sd = {k: torch.from_numpy(v) for k, v in params_to_torch_state_dict(params).items()}
    mirror = _TorchGPT(tie)
    missing, unexpected = mirror.load_state_dict(sd, strict=True)
    assert not missing and not unexpected
    ids = np.random.default_rng(3).integers(0, V, size=(2, T), dtype=np.int64)
    import jax.numpy as jnp

    flax_logits = np.asarray(
        model.apply({"params": params}, jnp.asarray(ids, jnp.int32), deterministic=True)
    )
    with torch.no_grad():
        torch_logits = mirror(torch.from_numpy(ids)).numpy()
    np.testing.assert_allclose(flax_logits, torch_logits, atol=2e-5, rtol=2e-5)


def test_import_rejects_missing_and_misshaped_keys():
    _, params = _flax_gpt(True)
    sd = params_to_torch_state_dict(params)
    incomplete = {k: v for k, v in sd.items() if k != "blocks.1.qkv.weight"}
    with pytest.raises(ValueError, match="missing 'blocks.1.qkv.weight'"):
        params_from_torch_state_dict(incomplete, params)
    bad = dict(sd)
    bad["ln_f.weight"] = np.zeros(7, np.float32)
    with pytest.raises(ValueError, match="ln_f.weight"):
        params_from_torch_state_dict(bad, params)


def test_export_rejects_non_gpt_tree():
    with pytest.raises(ValueError, match="block_0"):
        params_to_torch_state_dict({"token_embedding": {"embedding": np.zeros((4, 2))},
                                    "position_embedding": {"embedding": np.zeros((4, 2))},
                                    "ln_f": {"scale": np.ones(2), "bias": np.zeros(2)}})


class TestExportCLI:
    def test_train_then_export(self, tmp_path):
        import yaml

        cfg = {
            "run": {"name": "export", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 8,
                "d_model": 16,
                "n_layers": 1,
                "n_heads": 4,
                "d_ff": 32,
                "dropout": 0.0,
                "vocab_size": 64,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 2,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": 2,
                "save_every_steps": 2,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))

        def run(argv):
            return subprocess.run(
                [sys.executable, "-m", "llmtrain_tpu", *argv],
                capture_output=True,
                text=True,
                timeout=300,
            )

        train = run(["train", "--config", str(cfg_path), "--run-id", "x", "--json"])
        assert train.returncode == 0, train.stderr
        out_pt = tmp_path / "export" / "model.pt"
        proc = run(
            [
                "export-checkpoint", "--config", str(cfg_path),
                "--from", "x", "--output", str(out_pt), "--json",
            ]
        )
        assert proc.returncode == 0, proc.stderr
        stats = json.loads(proc.stdout)
        sd = torch.load(out_pt, weights_only=True)
        assert stats["tensors"] == len(sd)
        assert "tok.weight" in sd and sd["tok.weight"].shape == (64, 16)
        assert stats["step"] == 2

    def test_bad_checkpoint_exit_1(self, tmp_path):
        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "x", "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 8, "d_model": 16,
                        "n_layers": 1, "n_heads": 4, "d_ff": 32,
                        "vocab_size": 64, "extra": {"tokenizer": "byte"},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                },
                sort_keys=False,
            )
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "export-checkpoint",
                "--config", str(cfg_path), "--from", "no-such-run",
                "--output", str(tmp_path / "m.pt"),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "export failed" in proc.stderr


def test_import_rejects_unconsumed_state_dict_keys():
    """An sd with weights the template cannot hold (deeper model, untied
    head into a tied template) must fail, not silently drop them."""
    _, params = _flax_gpt(True)  # tied: no lm_head in template
    sd = params_to_torch_state_dict(params)
    sd["lm_head.weight"] = np.zeros((V, 16), np.float32)
    with pytest.raises(ValueError, match="cannot hold"):
        params_from_torch_state_dict(sd, params)
