"""Torch checkpoint interop (llmtrain_tpu/interop/torch_interop.py).

The migration path in BOTH directions: export our GPT weights to a
torch-layout state dict, and rebuild our params from one. Correctness is
anchored to the parity-proven transforms of tests/test_torch_parity.py —
the exported dict must drive the torch mirror to the flax model's exact
logits, and import(export(params)) must be the identity.
"""

from __future__ import annotations

import json
import subprocess
import sys

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from llmtrain_tpu.interop import (  # noqa: E402
    params_from_torch_state_dict,
    params_to_torch_state_dict,
)

# The parity-test mirror and helpers double as the reference
# implementation here (pytest puts tests/ on sys.path).
from test_torch_parity import T, V, _flax_gpt, _TorchGPT  # noqa: E402


@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_roundtrip_is_identity(tie):
    _, params = _flax_gpt(tie)
    sd = params_to_torch_state_dict(params)
    back = params_from_torch_state_dict(sd, params)
    for (pa, va), (pb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
        strict=True,
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_exported_state_dict_drives_torch_mirror(tie):
    """load_state_dict(exported) on the torch mirror reproduces the flax
    logits — the export really is the parity transplant."""
    model, params = _flax_gpt(tie)
    sd = {k: torch.from_numpy(v) for k, v in params_to_torch_state_dict(params).items()}
    mirror = _TorchGPT(tie)
    missing, unexpected = mirror.load_state_dict(sd, strict=True)
    assert not missing and not unexpected
    ids = np.random.default_rng(3).integers(0, V, size=(2, T), dtype=np.int64)
    import jax.numpy as jnp

    flax_logits = np.asarray(
        model.apply({"params": params}, jnp.asarray(ids, jnp.int32), deterministic=True)
    )
    with torch.no_grad():
        torch_logits = mirror(torch.from_numpy(ids)).numpy()
    np.testing.assert_allclose(flax_logits, torch_logits, atol=2e-5, rtol=2e-5)


def test_import_rejects_missing_and_misshaped_keys():
    _, params = _flax_gpt(True)
    sd = params_to_torch_state_dict(params)
    incomplete = {k: v for k, v in sd.items() if k != "blocks.1.attn.qkv_proj.weight"}
    with pytest.raises(ValueError, match="missing 'blocks.1.attn.qkv_proj.weight'"):
        params_from_torch_state_dict(incomplete, params)
    bad = dict(sd)
    bad["ln_f.weight"] = np.zeros(7, np.float32)
    with pytest.raises(ValueError, match="ln_f.weight"):
        params_from_torch_state_dict(bad, params)


@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_import_maps_legacy_export_format(tie):
    """Pre-alignment .pt files (tok.weight / blocks.{i}.qkv.*, no
    causal_mask buffers, no tied lm_head duplicate) still import."""
    _, params = _flax_gpt(tie)
    sd = params_to_torch_state_dict(params)
    legacy = {}
    for k, v in sd.items():
        if k.endswith(".attn.causal_mask"):
            continue  # legacy exports had no mask buffers
        if tie and k == "lm_head.weight":
            continue  # legacy tied exports omitted the duplicate
        k = k.replace("token_embedding.weight", "tok.weight")
        k = k.replace("position_embedding.weight", "pos.weight")
        k = k.replace(".attn.qkv_proj.", ".qkv.").replace(".attn.out_proj.", ".out_proj.")
        legacy[k] = v
    back = params_from_torch_state_dict(legacy, params)
    for (pa, va), (pb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
        strict=True,
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_export_rejects_non_gpt_tree():
    with pytest.raises(ValueError, match="block_0"):
        params_to_torch_state_dict({"token_embedding": {"embedding": np.zeros((4, 2))},
                                    "position_embedding": {"embedding": np.zeros((4, 2))},
                                    "ln_f": {"scale": np.ones(2), "bias": np.zeros(2)}})


class TestExportCLI:
    def test_train_then_export(self, tmp_path):
        import yaml

        cfg = {
            "run": {"name": "export", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 8,
                "d_model": 16,
                "n_layers": 1,
                "n_heads": 4,
                "d_ff": 32,
                "dropout": 0.0,
                "vocab_size": 64,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 2,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": 2,
                "save_every_steps": 2,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))

        def run(argv):
            return subprocess.run(
                [sys.executable, "-m", "llmtrain_tpu", *argv],
                capture_output=True,
                text=True,
                timeout=300,
            )

        train = run(["train", "--config", str(cfg_path), "--run-id", "x", "--json"])
        assert train.returncode == 0, train.stderr
        out_pt = tmp_path / "export" / "model.pt"
        proc = run(
            [
                "export-checkpoint", "--config", str(cfg_path),
                "--from", "x", "--output", str(out_pt), "--json",
            ]
        )
        assert proc.returncode == 0, proc.stderr
        stats = json.loads(proc.stdout)
        sd = torch.load(out_pt, weights_only=True)
        assert stats["tensors"] == len(sd)
        assert "token_embedding.weight" in sd
        assert sd["token_embedding.weight"].shape == (64, 16)
        # Reference-format invariants: tied head materialized, persistent
        # causal-mask buffer present (reference gpt.py:32-33,143-146).
        assert "lm_head.weight" in sd
        assert sd["blocks.0.attn.causal_mask"].shape == (1, 1, 8, 8)
        assert stats["step"] == 2

    def test_bad_checkpoint_exit_1(self, tmp_path):
        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "x", "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 8, "d_model": 16,
                        "n_layers": 1, "n_heads": 4, "d_ff": 32,
                        "vocab_size": 64, "extra": {"tokenizer": "byte"},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                },
                sort_keys=False,
            )
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "export-checkpoint",
                "--config", str(cfg_path), "--from", "no-such-run",
                "--output", str(tmp_path / "m.pt"),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "export failed" in proc.stderr


def test_import_rejects_untied_head_into_tied_template():
    """The reference always emits lm_head.weight; for a tied template it
    must equal token_embedding.weight — a differing head means the source
    model was untied and silently dropping it would change logits."""
    _, params = _flax_gpt(True)  # tied: no lm_head in template
    sd = params_to_torch_state_dict(params)
    assert "lm_head.weight" in sd  # tied export still materializes it
    sd["lm_head.weight"] = np.zeros_like(sd["lm_head.weight"])
    with pytest.raises(ValueError, match="untied"):
        params_from_torch_state_dict(sd, params)


def test_tied_import_accepts_bf16_template():
    """The tied-duplicate equality check must compare raw sd values, not
    the template-dtype-cast tree — a bf16 param_dtype template would
    otherwise spuriously reject a genuinely tied f32 checkpoint."""
    import jax.numpy as jnp

    _, params = _flax_gpt(True)
    sd = params_to_torch_state_dict(params)
    bf16_template = jax.tree.map(lambda a: jnp.asarray(a, jnp.bfloat16), params)
    back = params_from_torch_state_dict(sd, bf16_template)
    assert back["token_embedding"]["embedding"].dtype == jnp.bfloat16


def test_import_rejects_unconsumed_state_dict_keys():
    """An sd with weights the template cannot hold (deeper torch model)
    must fail, not silently drop them."""
    _, params = _flax_gpt(True)
    sd = params_to_torch_state_dict(params)
    sd["blocks.9.mlp_fc.weight"] = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError, match="cannot hold"):
        params_from_torch_state_dict(sd, params)


REFERENCE_SRC = __import__("os").environ.get(
    "LLMTRAIN_REFERENCE_SRC", "/root/reference/src"
)


@pytest.mark.skipif(
    not __import__("os").path.isdir(REFERENCE_SRC),
    reason="reference checkout not present (set LLMTRAIN_REFERENCE_SRC)",
)
@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_exported_state_dict_loads_into_actual_reference_gpt(tie):
    """Ground truth for the migration claim: the export strict-loads into
    the REAL reference torch GPT (not our mirror) and reproduces the flax
    logits. Runs only where a reference checkout exists."""
    import sys

    sys.path.insert(0, REFERENCE_SRC)
    try:
        from llmtrain.models.gpt import GPT as RefGPT  # type: ignore[import-not-found]
    finally:
        sys.path.remove(REFERENCE_SRC)

    model, params = _flax_gpt(tie)
    ref = RefGPT(
        vocab_size=V, block_size=T, d_model=32, n_layers=2, n_heads=4,
        d_ff=64, dropout=0.0, tie_embeddings=tie,
    )
    sd = {k: torch.from_numpy(np.asarray(v)) for k, v in params_to_torch_state_dict(params).items()}
    missing, unexpected = ref.load_state_dict(sd, strict=True)
    assert not missing and not unexpected
    # Normalize the one documented divergence (docs/parity.md): flax
    # LayerNorm eps=1e-6 vs torch default 1e-5.
    for m in ref.modules():
        if isinstance(m, torch.nn.LayerNorm):
            m.eps = 1e-6
    ref.eval()
    ids = np.random.default_rng(5).integers(0, V, size=(2, T), dtype=np.int64)
    import jax.numpy as jnp

    flax_logits = np.asarray(
        model.apply({"params": params}, jnp.asarray(ids, jnp.int32), deterministic=True)
    )
    with torch.no_grad():
        ref_logits = ref(torch.from_numpy(ids)).numpy()
    np.testing.assert_allclose(flax_logits, ref_logits, atol=2e-5, rtol=2e-5)


def test_moe_export_raises_clear_error():
    """MoE params (moe_mlp experts) have no reference counterpart; export
    must say so instead of a bare KeyError('mlp_fc')."""
    _, params = _flax_gpt(True)
    moe = dict(params)
    blk = dict(params["block_0"])
    blk["moe_mlp"] = blk.pop("mlp_fc")
    moe["block_0"] = blk
    with pytest.raises(ValueError, match="n_experts"):
        params_to_torch_state_dict(moe)


def test_gqa_export_raises_clear_error():
    """GQA params (split q_proj/kv_proj) have no reference checkpoint
    format; export must say so instead of dying with a bare KeyError."""
    _, params = _flax_gpt(True)
    gqa = dict(params)
    blk = dict(params["block_0"])
    att = dict(blk["attn"])
    att["q_proj"] = att.pop("qkv_proj")
    blk["attn"] = att
    gqa["block_0"] = blk
    with pytest.raises(ValueError, match="n_kv_heads"):
        params_to_torch_state_dict(gqa)


class TestImportCLI:
    @pytest.mark.slow  # ~20s: four CLI subprocesses end to end. The
    # export/import conversion math stays tier-1 (round-trip units above
    # and TestExportCLI's train->export run).
    def test_full_migration_loop(self, tmp_path):
        """train -> export-checkpoint -> import-checkpoint -> eval: the
        re-imported checkpoint evaluates to the original's exact val loss,
        and training can resume from it."""
        import yaml

        cfg = {
            "run": {"name": "migrate", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 8,
                "d_model": 16,
                "n_layers": 1,
                "n_heads": 4,
                "d_ff": 32,
                "dropout": 0.0,
                "vocab_size": 64,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 3,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": 3,
                "save_every_steps": 3,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))

        def run(argv):
            return subprocess.run(
                [sys.executable, "-m", "llmtrain_tpu", *argv],
                capture_output=True,
                text=True,
                timeout=300,
            )

        train = run(["train", "--config", str(cfg_path), "--run-id", "src", "--json"])
        assert train.returncode == 0, train.stderr

        pt = tmp_path / "model.pt"
        exp = run(
            ["export-checkpoint", "--config", str(cfg_path), "--from", "src",
             "--output", str(pt), "--json"]
        )
        assert exp.returncode == 0, exp.stderr

        ckpt_dir = tmp_path / "imported"
        imp = run(
            ["import-checkpoint", "--config", str(cfg_path), "--input", str(pt),
             "--output", str(ckpt_dir), "--json"]
        )
        assert imp.returncode == 0, imp.stderr
        assert (ckpt_dir / "step_000000.ckpt").exists()

        ev_src = run(["eval", "--config", str(cfg_path), "--from", "src", "--json"])
        ev_imp = run(
            ["eval", "--config", str(cfg_path), "--from", str(ckpt_dir), "--json"]
        )
        assert ev_src.returncode == 0 and ev_imp.returncode == 0, ev_imp.stderr
        src_loss = json.loads(ev_src.stdout)["metrics"]["val/loss"]
        imp_loss = json.loads(ev_imp.stdout)["metrics"]["val/loss"]
        assert abs(src_loss - imp_loss) < 1e-6

        # And training resumes from the imported step-0 checkpoint.
        cont = run(
            ["train", "--config", str(cfg_path), "--run-id", "cont", "--json",
             "--resume", str(ckpt_dir)]
        )
        assert cont.returncode == 0, cont.stderr
        result = json.loads(cont.stdout)["train_result"]
        assert result["final_step"] == 3
        assert result["resumed_from_step"] == 0

    def test_bad_input_exit_1(self, tmp_path):
        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "x", "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 8, "d_model": 16,
                        "n_layers": 1, "n_heads": 4, "d_ff": 32,
                        "vocab_size": 64, "extra": {"tokenizer": "byte"},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                },
                sort_keys=False,
            )
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "import-checkpoint",
                "--config", str(cfg_path), "--input", str(tmp_path / "nope.pt"),
                "--output", str(tmp_path / "out"),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "import failed" in proc.stderr

    def test_refuses_nonempty_output_dir(self, tmp_path):
        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "x", "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 8, "d_model": 16,
                        "n_layers": 1, "n_heads": 4, "d_ff": 32,
                        "vocab_size": 64, "extra": {"tokenizer": "byte"},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                },
                sort_keys=False,
            )
        )
        out = tmp_path / "ckpts"
        out.mkdir()
        (out / "step_000300.ckpt").write_bytes(b"x")
        pt = tmp_path / "m.pt"
        _, params = _flax_gpt(True)
        torch.save(
            {k: torch.from_numpy(v) for k, v in params_to_torch_state_dict(params).items()},
            pt,
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "import-checkpoint",
                "--config", str(cfg_path), "--input", str(pt), "--output", str(out),
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "already holds checkpoints" in proc.stderr
        assert (out / "step_000300.ckpt").exists()  # untouched

    def test_bf16_state_dict_imports(self, tmp_path):
        """torch bf16 tensors can't .numpy() directly; the importer must
        still accept bf16-saved reference checkpoints."""
        import yaml

        _, params = _flax_gpt(True)
        sd = {
            k: torch.from_numpy(v).to(torch.bfloat16)
            for k, v in params_to_torch_state_dict(params).items()
        }
        pt = tmp_path / "bf16.pt"
        torch.save(sd, pt)
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "x", "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": T, "d_model": 32,
                        "n_layers": 2, "n_heads": 4, "d_ff": 64,
                        "vocab_size": V, "extra": {"tokenizer": "byte"},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                },
                sort_keys=False,
            )
        )
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "import-checkpoint",
                "--config", str(cfg_path), "--input", str(pt),
                "--output", str(tmp_path / "out"), "--json",
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert (tmp_path / "out" / "step_000000.ckpt").exists()
