"""Crash-consistent checkpoint commits + elastic topology-change resume.

Three contracts under test (docs/robustness.md "Crash consistency and
elastic resume"):

* **Atomic commit** — a checkpoint step is only visible once its
  ``step_N.manifest.json`` landed via atomic rename; kills anywhere in the
  multi-file write leave either a previous committed step (selected) or an
  adoptable complete payload, never a torn restore. Pre-manifest dirs
  migrate in place (synthesized manifests) — the backward-compat satellite.
* **Elastic resume** — world-size changes that preserve the global
  micro-batch re-shard through parallel/sharding.py and continue the SAME
  trajectory (pinned here at 1e-4 against reduction-order noise, exactly 0
  in practice on this backend); incompatible changes (tensor degree,
  global batch, grad accum) fail fast with TopologyMismatchError → exit 2.
  "World size" is emulated by restricting the visible CPU device set —
  this container's jax cannot run real multi-process collectives.
* **Chaos** (slow marks; ``make verify-elastic`` runs them) — a seeded
  ≥5-cycle SIGKILL/resume schedule, with one kill inside the async
  checkpoint write, ends bitwise-identical to an uninterrupted run.
"""

from __future__ import annotations

import json
import shutil
from contextlib import contextmanager
from pathlib import Path

import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.resilience import (
    TopologyMismatchError,
    classify_topology_change,
    describe_topology,
    exit_code_for_exception,
    resume_batch_index,
)
from llmtrain_tpu.resilience.exit_codes import EXIT_CONFIG_ERROR
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import CheckpointManager, Trainer, resolve_resume_path
from llmtrain_tpu.training.checkpoint import manifest_path, read_manifest


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _host_state(step):
    return {
        "step": step,
        "params": {"w": np.full(4, step, np.float32)},
        "opt_state": {},
    }


# --------------------------------------------------------------------------
# atomic commit protocol
# --------------------------------------------------------------------------


class TestManifestCommit:
    def test_save_publishes_manifest_listing_all_files(self, tmp_path):
        import hashlib

        mgr = CheckpointManager(tmp_path / "c")
        target = mgr.save_host(
            1, _host_state(1), {"a": 1}, manifest_extra={"topology": {"mesh": {"data": 2}}}
        )
        manifest = read_manifest(target)
        assert manifest["step"] == 1
        names = [f["name"] for f in manifest["files"]]
        assert names == ["step_000001.ckpt", "step_000001.ckpt.sha256"]
        for entry in manifest["files"]:
            blob = (tmp_path / "c" / entry["name"]).read_bytes()
            assert entry["bytes"] == len(blob)
            assert entry["sha256"] == hashlib.sha256(blob).hexdigest()
        assert manifest["topology"] == {"mesh": {"data": 2}}
        assert mgr.verify_manifest(target)

    def test_on_commit_fires_per_published_manifest(self, tmp_path):
        commits = []
        mgr = CheckpointManager(
            tmp_path / "c", on_commit=lambda step, path: commits.append(step)
        )
        mgr.save_host(1, _host_state(1), {})
        mgr.save_host_async(2, _host_state(2), {})
        mgr.close()
        assert commits == [1, 2]

    def test_uncommitted_payload_is_invisible(self, tmp_path):
        """A complete payload whose manifest never published (kill between
        staged files and commit) must not be selected while committed
        steps exist."""
        d = tmp_path / "c"
        mgr = CheckpointManager(d)
        mgr.save_host(1, _host_state(1), {})
        newest = mgr.save_host(2, _host_state(2), {})
        staged = d / "step_000003.ckpt"
        shutil.copy(newest, staged)  # valid bytes, no sidecar, no manifest
        assert CheckpointManager(d).latest_valid_checkpoint().name == "step_000002.ckpt"
        assert resolve_resume_path(str(d), tmp_path).name == "step_000002.ckpt"

    def test_prune_collects_torn_stage_and_adopts_complete_one(self, tmp_path):
        d = tmp_path / "c"
        mgr = CheckpointManager(d, keep_last_k=10)
        mgr.save_host(1, _host_state(1), {})
        complete = d / "step_000002.ckpt"
        shutil.copy(d / "step_000001.ckpt", complete)  # adopted: verifies
        (d / "step_000003.ckpt").write_bytes(b"torn bytes")  # GC'd
        (d / "step_000004.ckpt.tmp").write_bytes(b"half a stage")  # GC'd
        mgr.save_host(5, _host_state(5), {})
        names = sorted(p.name for p in d.iterdir())
        assert "step_000003.ckpt" not in names
        assert "step_000004.ckpt.tmp" not in names
        assert read_manifest(complete)["synthesized"] is True
        assert CheckpointManager(d).verify_manifest(complete)

    def test_dangling_manifest_without_payload_is_collected(self, tmp_path):
        d = tmp_path / "c"
        mgr = CheckpointManager(d, keep_last_k=10)
        mgr.save_host(1, _host_state(1), {})
        mgr.save_host(2, _host_state(2), {})
        (d / "step_000002.ckpt").unlink()
        mgr.save_host(3, _host_state(3), {})
        assert not (d / "step_000002.manifest.json").exists()
        assert CheckpointManager(d).latest_valid_checkpoint().name == "step_000003.ckpt"

    def test_resave_replaces_commit_atomically(self, tmp_path):
        """Rollback replay re-saves a step: the old commit is withdrawn
        first, and the new manifest matches the new bytes."""
        d = tmp_path / "c"
        mgr = CheckpointManager(d)
        mgr.save_host(1, _host_state(1), {})
        first = read_manifest(d / "step_000001.ckpt")
        mgr.save_host(1, {"step": 1, "params": {"w": np.full(4, 9.0, np.float32)}, "opt_state": {}}, {})
        second = read_manifest(d / "step_000001.ckpt")
        assert first["files"][0]["sha256"] != second["files"][0]["sha256"]
        assert CheckpointManager(d).verify_manifest(d / "step_000001.ckpt")

    def test_corrupt_committed_payload_skipped_with_fallback(self, tmp_path):
        d = tmp_path / "c"
        mgr = CheckpointManager(d)
        mgr.save_host(1, _host_state(1), {})
        newest = mgr.save_host(2, _host_state(2), {})
        data = newest.read_bytes()
        newest.write_bytes(data[: len(data) // 2])
        assert CheckpointManager(d).latest_valid_checkpoint().name == "step_000001.ckpt"

    def test_prune_keeps_manifests_paired_with_survivors(self, tmp_path):
        d = tmp_path / "c"
        mgr = CheckpointManager(d, keep_last_k=1)
        for step in (1, 2, 3):
            mgr.save_host(step, _host_state(step), {})
        assert sorted(p.name for p in d.glob("*.manifest.json")) == [
            "step_000003.manifest.json"
        ]

    def test_legacy_dir_without_manifests_resolves_and_migrates(self, tmp_path):
        """Backward compat: a pre-manifest checkpoint dir (payload +
        sidecar only) resumes cleanly, and the scan synthesizes the
        manifest in place."""
        d = tmp_path / "c"
        mgr = CheckpointManager(d)
        mgr.save_host(1, _host_state(1), {})
        mgr.save_host(2, _host_state(2), {})
        for p in d.glob("*.manifest.json"):
            p.unlink()
        got = CheckpointManager(d).latest_valid_checkpoint()
        assert got.name == "step_000002.ckpt"
        manifest = read_manifest(got)
        assert manifest is not None and manifest["synthesized"] is True
        # And the payload still loads through the normal path.
        assert int(CheckpointManager.load(got)["step"]) == 2

    def test_manifest_path_naming(self, tmp_path):
        assert (
            manifest_path(tmp_path / "step_000007.ckpt").name
            == "step_000007.manifest.json"
        )


# --------------------------------------------------------------------------
# topology classification (pure)
# --------------------------------------------------------------------------


def _topo(mesh=None, *, dp=1, global_micro=4, micro=4, accum=1, procs=1):
    sizes = {"data": 1, "fsdp": 1, "tensor": 1, "sequence": 1, "pipeline": 1, "expert": 1}
    sizes.update(mesh or {})
    return describe_topology(
        sizes,
        data_parallel=dp,
        global_micro_batch=global_micro,
        micro_batch_size=micro,
        grad_accum_steps=accum,
        num_processes=procs,
    )


class TestTopologyClassification:
    def test_identical_topology_is_a_no_op(self):
        cur = _topo({"data": 2}, dp=2, micro=2)
        assert classify_topology_change(cur, cur) == {"elastic": False, "changes": []}

    def test_batch_axis_resize_with_same_global_batch_is_elastic(self):
        saved = _topo({"data": 4}, dp=4, micro=1)
        cur = _topo({"data": 2}, dp=2, micro=2)
        verdict = classify_topology_change(saved, cur)
        assert verdict["elastic"] is True
        assert verdict["changes"] == ["data: 4 -> 2"]

    def test_unknown_saved_topology_validates_nothing(self):
        assert classify_topology_change(None, _topo()) == {
            "elastic": False,
            "changes": [],
        }

    def test_tensor_degree_change_raises_exit_2(self):
        saved = _topo({"tensor": 2})
        with pytest.raises(TopologyMismatchError, match="tensor"):
            classify_topology_change(saved, _topo())
        try:
            classify_topology_change(saved, _topo())
        except TopologyMismatchError as exc:
            assert exit_code_for_exception(exc) == EXIT_CONFIG_ERROR

    def test_global_batch_change_raises_with_remediation(self):
        saved = _topo({"data": 2}, dp=2, micro=2, global_micro=4)
        with pytest.raises(TopologyMismatchError, match="micro_batch_size"):
            classify_topology_change(saved, _topo(global_micro=2, micro=2))

    def test_grad_accum_change_raises(self):
        saved = _topo(accum=2)
        with pytest.raises(TopologyMismatchError, match="grad_accum_steps"):
            classify_topology_change(saved, _topo(accum=1))

    def test_wrapped_mismatch_still_maps_to_exit_2(self):
        try:
            try:
                raise TopologyMismatchError("tp mismatch")
            except TopologyMismatchError as inner:
                raise RuntimeError("resume failed") from inner
        except RuntimeError as outer:
            assert exit_code_for_exception(outer) == EXIT_CONFIG_ERROR

    def test_resume_batch_index_prefers_manifest_progress(self):
        assert resume_batch_index(None, step=10, grad_accum_steps=2) == 20
        assert (
            resume_batch_index(
                {"consumed_micro_batches": 26}, step=10, grad_accum_steps=2
            )
            == 26
        )

    def test_sampler_progress_records_consumption(self):
        from llmtrain_tpu.data.sampler import DeterministicSampler

        s = DeterministicSampler(num_examples=16, batch_size=4, seed=3)
        prog = s.progress(9)
        assert prog["consumed_micro_batches"] == 9
        assert prog["global_micro_batch"] == 4
        assert prog["consumed_examples"] == 36
        assert prog["epoch"] == 2 and prog["position_in_epoch"] == 1


# --------------------------------------------------------------------------
# elastic resume across emulated world sizes
# --------------------------------------------------------------------------


@contextmanager
def _visible_devices(n):
    """Emulate a world-size change by restricting the devices the Trainer
    sees (this container's jax cannot form real multi-process meshes)."""
    import jax

    all_cpu = jax.devices("cpu")
    assert len(all_cpu) >= n
    real = jax.devices
    jax.devices = lambda *a, **k: all_cpu[:n]
    try:
        yield
    finally:
        jax.devices = real


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Topology-independent dataset: local_text sizes itself from the file
    contents, never from the batch topology (dummy_text does not)."""
    tmp = tmp_path_factory.mktemp("elastic_corpus")
    f = tmp / "corpus.txt"
    f.write_text("the quick brown fox jumps over the lazy dog. " * 200)
    return tmp


def _elastic_cfg(corpus_dir, root, *, micro, mesh, max_steps=6):
    return RunConfig.model_validate(
        {
            "run": {"name": "el", "seed": 7},
            "model": {
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 256,
                "dropout": 0.0,
                "d_model": 32,
                "n_heads": 2,
                "d_ff": 64,
                "n_layers": 1,
                "extra": {"tokenizer": "byte"},
            },
            "data": {
                "name": "local_text",
                "cache_dir": str(corpus_dir / "cache"),
                "extra": {"globs": [str(corpus_dir / "corpus.txt")], "val_fraction": 0.1},
            },
            "trainer": {
                "max_steps": max_steps,
                "micro_batch_size": micro,
                "grad_accum_steps": 1,
                "lr": 3e-3,
                "warmup_steps": 0,
                "log_every_steps": 3,
                "eval_every_steps": 100,
                "save_every_steps": 3,
            },
            "distributed": {"mesh": mesh},
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(root)},
        }
    )


class TestElasticResume:
    def test_ws2_to_ws1_and_back_match_at_same_global_step(
        self, tmp_path, corpus, caplog
    ):
        """Save at world-size 2 (data=2), resume at world-size 1 with the
        global micro-batch preserved (micro 2x2 -> 4x1) — and the reverse.
        Loss at the same global step matches the same-topology resume to
        reduction-order noise; the manifest records both topologies."""
        import logging

        with _visible_devices(2):
            r2 = tmp_path / "ws2"
            r2.mkdir()
            Trainer(
                _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 2}),
                r2,
                NullTracker(),
                None,
            ).fit()
        manifest = read_manifest(r2 / "checkpoints" / "step_000006.ckpt")
        assert manifest["topology"]["data_parallel"] == 2
        assert manifest["topology"]["global_micro_batch"] == 4
        # Goodput-ledger stamps (satellite of the goodput PR): segment
        # identity + process/save wall-clock times ride every manifest.
        resil = manifest["resilience"]
        assert resil["segment_id"] == 0
        assert 0 < resil["process_start_unix_time"] <= resil["saved_unix_time"]

        with _visible_devices(1):
            r1 = tmp_path / "ws1"
            r1.mkdir()
            ref = Trainer(
                _elastic_cfg(corpus, tmp_path, micro=4, mesh={"data": 1}),
                r1,
                NullTracker(),
                None,
            ).fit()
            # Elastic 2 -> 1: resume the ws2 checkpoint on one device.
            with caplog.at_level(logging.WARNING, logger="llmtrain"):
                res = Trainer(
                    _elastic_cfg(corpus, tmp_path, micro=4, mesh={"data": 1}),
                    None,
                    NullTracker(),
                    None,
                ).fit(resume_from=str(r2 / "checkpoints" / "step_000003.ckpt"))
        assert res.resumed_from_step == 3
        assert res.final_step == 6
        assert res.final_loss == pytest.approx(ref.final_loss, abs=1e-4)
        assert any("elastic resume" in r.message for r in caplog.records)

        # Elastic 1 -> 2: the ws1 run's checkpoint back onto two devices.
        with _visible_devices(2):
            res_up = Trainer(
                _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 2}),
                None,
                NullTracker(),
                None,
            ).fit(resume_from=str(r1 / "checkpoints" / "step_000003.ckpt"))
        assert res_up.resumed_from_step == 3
        assert res_up.final_loss == pytest.approx(ref.final_loss, abs=1e-4)

    def test_incompatible_global_batch_fails_fast(self, tmp_path, corpus):
        with _visible_devices(2):
            r2 = tmp_path / "ws2b"
            r2.mkdir()
            Trainer(
                _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 2}),
                r2,
                NullTracker(),
                None,
            ).fit(max_steps_override=3)
        with _visible_devices(1):
            # micro stays 2 on 1 device -> global batch halves: refuse.
            with pytest.raises(TopologyMismatchError, match="global"):
                Trainer(
                    _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 1}),
                    None,
                    NullTracker(),
                    None,
                ).fit(resume_from=str(r2 / "checkpoints"))

    def test_tensor_degree_mismatch_fails_fast_with_exit_2(self, tmp_path, corpus):
        with _visible_devices(2):
            r2 = tmp_path / "ws2c"
            r2.mkdir()
            Trainer(
                _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 2}),
                r2,
                NullTracker(),
                None,
            ).fit(max_steps_override=3)
            try:
                Trainer(
                    _elastic_cfg(corpus, tmp_path, micro=4, mesh={"data": 1, "tensor": 2}),
                    None,
                    NullTracker(),
                    None,
                ).fit(resume_from=str(r2 / "checkpoints"))
            except TopologyMismatchError as exc:
                assert "tensor" in str(exc)
                assert exit_code_for_exception(exc) == EXIT_CONFIG_ERROR
            else:
                pytest.fail("tensor-degree mismatch did not raise")

    def test_cli_maps_topology_mismatch_to_exit_2(self, tmp_path, corpus):
        """End to end through the CLI boundary: the orchestrator sees a
        deterministic config error, not a retryable failure."""
        import logging
        import yaml

        from llmtrain_tpu import cli
        from llmtrain_tpu.utils.logging import get_logger

        with _visible_devices(2):
            saved = tmp_path / "ws2d"
            saved.mkdir()
            Trainer(
                _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 2}),
                saved,
                NullTracker(),
                None,
            ).fit(max_steps_override=3)
        cfg = _elastic_cfg(corpus, tmp_path, micro=2, mesh={"data": 1})
        cfg_path = tmp_path / "bad_resume.yaml"
        cfg_path.write_text(
            yaml.safe_dump(cfg.model_dump(mode="json"), sort_keys=False)
        )
        # In-process cli.main reconfigures the llmtrain logger (propagate
        # off, handlers re-targeted) — snapshot and restore it, or every
        # later caplog-based test in the session goes blind.
        llm_logger = get_logger()
        saved_state = (
            llm_logger.propagate,
            llm_logger.level,
            list(llm_logger.handlers),
        )
        try:
            with _visible_devices(1):
                rc = cli.main(
                    [
                        "train",
                        "--config",
                        str(cfg_path),
                        "--run-id",
                        "bad-resume",
                        "--resume",
                        str(saved / "checkpoints"),
                    ]
                )
        finally:
            for handler in list(llm_logger.handlers):
                if handler not in saved_state[2]:
                    if isinstance(handler, logging.FileHandler):
                        handler.close()
                    llm_logger.removeHandler(handler)
            for handler in saved_state[2]:
                if handler not in llm_logger.handlers:
                    llm_logger.addHandler(handler)
            llm_logger.propagate = saved_state[0]
            llm_logger.setLevel(saved_state[1])
        assert rc == EXIT_CONFIG_ERROR


# --------------------------------------------------------------------------
# backward compat: pre-manifest run dirs resume cleanly
# --------------------------------------------------------------------------


def _legacy_cfg(tmp_path):
    return RunConfig.model_validate(
        {
            "run": {"name": "t", "seed": 7},
            "model": {
                "name": "dummy_gpt",
                "block_size": 8,
                "vocab_size": 32,
                "dropout": 0.0,
                "d_model": 48,
                "n_heads": 2,
                "d_ff": 96,
                "n_layers": 1,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 20,
                "micro_batch_size": 2,
                "grad_accum_steps": 2,
                "lr": 3e-3,
                "warmup_steps": 0,
                "log_every_steps": 50,
                "eval_every_steps": 50,
                "save_every_steps": 10,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path)},
        }
    )


class TestPreManifestBackwardCompat:
    def test_resume_from_pre_manifest_run_matches_continuous(self, tmp_path):
        """Regression for existing runs/ dirs: strip every manifest (what a
        pre-upgrade run left behind), resume, and land on the continuous
        run's loss. The first scan synthesizes the manifest in place."""
        cfg = _legacy_cfg(tmp_path)
        run_full = tmp_path / "full"
        run_full.mkdir()
        res_full = Trainer(cfg, run_full, NullTracker(), None).fit()

        run_old = tmp_path / "old"
        run_old.mkdir()
        Trainer(cfg, run_old, NullTracker(), None).fit(max_steps_override=10)
        for p in (run_old / "checkpoints").glob("*.manifest.json"):
            p.unlink()
        res = Trainer(cfg, None, NullTracker(), None).fit(
            resume_from=str(run_old / "checkpoints")
        )
        assert res.resumed_from_step == 10
        assert res.final_loss == pytest.approx(res_full.final_loss, abs=1e-5)
        assert read_manifest(run_old / "checkpoints" / "step_000010.ckpt") is not None


# --------------------------------------------------------------------------
# chaos harness (slow: subprocess kill/resume cycles; `make verify-elastic`)
# --------------------------------------------------------------------------


_CHAOS_PRESET = Path(__file__).resolve().parents[1] / "configs" / "presets" / (
    "gpt_chaos_smoke.yaml"
)


@pytest.mark.slow
class TestChaosHarness:
    def test_five_cycle_seeded_schedule_is_bitwise_recoverable(self, tmp_path):
        """The acceptance drill: 5 SIGKILLed segments (one inside the async
        checkpoint write, one with a corrupted committed payload), then an
        uninterrupted finish — final trajectory and checkpoint bitwise-
        identical to the reference, no cycle ever selecting a torn file
        (run_chaos raises ChaosInvariantError on any violation)."""
        from llmtrain_tpu.resilience.chaos import run_chaos

        result = run_chaos(
            _CHAOS_PRESET,
            cycles=5,
            seed=1,
            work_dir=tmp_path / "chaos",
            timeout_sec=300.0,
        )
        assert result["kills_delivered"] >= 5
        assert result["kill_during_checkpoint_cycles"] >= 1
        assert result["bitwise_match"] is True
        assert result["final_loss"] == result["reference_final_loss"]
        assert result["trajectory_points_compared"] >= 1
        modes = {r["mode"] for r in result["cycles"]}
        assert "kill_during_checkpoint" in modes

    def test_soak_schedule(self, tmp_path):
        """Long soak (more cycles, different seed): opt-in via
        LLMTRAIN_CHAOS_SOAK=1 so verify-elastic stays fast."""
        import os

        if os.environ.get("LLMTRAIN_CHAOS_SOAK") != "1":
            pytest.skip("set LLMTRAIN_CHAOS_SOAK=1 to run the soak drill")
        from llmtrain_tpu.resilience.chaos import run_chaos

        result = run_chaos(
            _CHAOS_PRESET,
            cycles=12,
            seed=23,
            max_steps=36,
            work_dir=tmp_path / "soak",
            timeout_sec=600.0,
        )
        assert result["bitwise_match"] is True

    def test_cli_rejects_zero_cycles(self):
        from llmtrain_tpu import cli

        rc = cli.main(
            ["chaos", "--config", str(_CHAOS_PRESET), "--cycles", "0"]
        )
        assert rc == EXIT_CONFIG_ERROR


class TestChaosKillFaultUnits:
    def test_take_checkpoint_kill_is_one_shot_and_step_gated(self):
        from llmtrain_tpu.config.schemas import FaultInjectionConfig
        from llmtrain_tpu.resilience import FaultPlan

        plan = FaultPlan.from_config(
            FaultInjectionConfig(kill_at_step=6, kill_during_checkpoint=True)
        )
        assert plan.take_checkpoint_kill(3) is False
        assert plan.take_checkpoint_kill(6) is True
        assert plan.take_checkpoint_kill(12) is False  # one-shot

    def test_take_checkpoint_kill_defaults_to_first_save(self):
        from llmtrain_tpu.config.schemas import FaultInjectionConfig
        from llmtrain_tpu.resilience import FaultPlan

        plan = FaultPlan.from_config(
            FaultInjectionConfig(kill_during_checkpoint=True)
        )
        assert plan.take_checkpoint_kill(2) is True

    def test_plain_kill_config_round_trips(self):
        from llmtrain_tpu.config.schemas import FaultInjectionConfig

        cfg = FaultInjectionConfig(kill_at_step=4)
        assert cfg.kill_at_step == 4 and cfg.kill_during_checkpoint is False

    def test_derive_config_pins_cadence_and_disables_trackers(self, tmp_path):
        from llmtrain_tpu.resilience.chaos import _derive_config

        derived = _derive_config(
            {"trainer": {"log_every_steps": 4}, "mlflow": {"enabled": True}},
            root_dir=str(tmp_path),
            max_steps=18,
            save_every=6,
            log_every=3,
            faults={"kill_at_step": 5},
        )
        assert derived["trainer"]["max_steps"] == 18
        assert derived["trainer"]["save_every_steps"] == 6
        assert derived["trainer"]["log_every_steps"] == 3
        assert derived["mlflow"]["enabled"] is False
        assert derived["resilience"]["faults"] == {"kill_at_step": 5}

    def test_trees_bitwise_equal_reports_first_divergence(self):
        from llmtrain_tpu.resilience.chaos import _trees_bitwise_equal

        a = {"p": {"w": np.ones(3), "b": np.zeros(2)}}
        assert _trees_bitwise_equal(a, {"p": {"w": np.ones(3), "b": np.zeros(2)}}) is None
        diff = _trees_bitwise_equal(a, {"p": {"w": np.ones(3), "b": np.full(2, 1e-9)}})
        assert diff is not None and "/p/b" in diff


# --------------------------------------------------------------------------
# recovery telemetry surfaces
# --------------------------------------------------------------------------


class TestRecoveryTelemetry:
    def test_resume_counts_commits_and_report_block(self, tmp_path):
        """resilience/resume_count round-trips through checkpoints,
        checkpoint commits are counted per published manifest, and
        report.json carries the recovery block."""
        cfg = _legacy_cfg(tmp_path)
        run_a = tmp_path / "tele_a"
        run_a.mkdir()
        Trainer(cfg, run_a, NullTracker(), None).fit(max_steps_override=10)
        rep = json.loads((run_a / "report.json").read_text())
        assert rep["resilience"]["checkpoint_commits"] == 1
        assert rep["resilience"]["resumes"] == 0

        run_b = tmp_path / "tele_b"
        run_b.mkdir()
        Trainer(cfg, run_b, NullTracker(), None).fit(
            resume_from=str(run_a / "checkpoints")
        )
        rep_b = json.loads((run_b / "report.json").read_text())
        assert rep_b["resilience"]["resumes"] == 1
        assert rep_b["resilience"]["resume_count"] == 1
        assert rep_b["resilience"]["checkpoint_commits"] == 1
        # The cumulative counter rode into the new run's checkpoint.
        payload = CheckpointManager.load(
            run_b / "checkpoints" / "step_000020.ckpt"
        )
        assert int(payload["resilience"]["resume_count"]) == 1

    def test_commit_counter_renders_in_prometheus(self, tmp_path):
        from llmtrain_tpu.telemetry.prometheus import render_prometheus

        text = render_prometheus({}, {"checkpoint/commits": 3.0})
        assert "llmtrain_checkpoint_commits_total 3.0" in text
