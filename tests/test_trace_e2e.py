"""End-to-end distributed tracing drill (``make verify-trace``).

A real 2-replica HTTP fleet — each replica a continuous-batching
scheduler behind ``make_server``, writing its own file-backed timeline —
fronted by a :class:`ReplicaRouter` with a third, dead backend so one
request provably fails over. Loadgen drives the router; afterwards the
merged trace must reconstruct the full CROSS-PROCESS span tree (router
root → pre-allocated ``router/http_dispatch`` hop → replica
``serve/request`` parented via the propagated ``traceparent`` header →
prefill/decode children), the critical path must tile the root interval
exactly, and the replicas' ``/metrics`` TTFT histogram must carry
exemplar trace ids.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.serving import (
    ContinuousBatchingScheduler,
    PagedDecodeEngine,
    ServerState,
    build_requests,
    make_server,
    run_loadgen,
)
from llmtrain_tpu.serving.router import HTTPReplica, ReplicaRouter
from llmtrain_tpu.telemetry.timeline import EventTimeline
from llmtrain_tpu.telemetry.tracing import TailSampler, Tracer

pytestmark = pytest.mark.slow


def _tiny_model():
    from llmtrain_tpu.models.gpt import GPT

    model = GPT(
        vocab_size=64,
        block_size=64,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = nn_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
            "params"
        ]
    )
    return model, params


def _keep_all() -> TailSampler:
    # Deterministic drill: warmup larger than the request count keeps
    # every trace, so assertions don't depend on the latency reservoir.
    return TailSampler(warmup=10_000)


class _Replica:
    """One serving process: scheduler + HTTP server + its own timeline."""

    def __init__(self, model, params, trace_dir, name):
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        self.timeline = EventTimeline(trace_dir / name / "timeline.jsonl")
        engine = PagedDecodeEngine(
            model,
            params,
            block_tokens=4,
            max_batch_slots=4,
            prompt_buckets=[8, 16],
            batch_buckets=[2, 4],
        )
        self.registry = MetricsRegistry(None)
        self.scheduler = ContinuousBatchingScheduler(
            engine,
            registry=self.registry,
            timeline=self.timeline,
            tracer=Tracer(self.timeline, sampler=_keep_all()),
        ).start()
        state = ServerState(
            model=model,
            params=params,
            tokenizer=None,
            step=1,
            checkpoint="mem://tiny",
            max_new_tokens_cap=16,
            default_max_new_tokens=4,
            scheduler=self.scheduler,
            registry=self.registry,
        )
        self.httpd = make_server(state, "127.0.0.1", 0)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.scheduler.close()


def _dead_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestDistributedTraceE2E:
    def test_fleet_trace_reconstructs_cross_process_tree(self, tmp_path):
        from llmtrain_tpu.telemetry.trace_collect import (
            collect_traces,
            critical_path,
            discover_sources,
            format_tree,
            merge_perfetto,
        )

        model, params = _tiny_model()
        replicas = [
            _Replica(model, params, tmp_path, f"replica{i}") for i in range(2)
        ]
        router_tl = EventTimeline(tmp_path / "router" / "timeline.jsonl")
        # Backend 0 is DEAD (nothing listens): the first request placed on
        # it fails over, forcing that trace's fleet-wide keep.
        backends = [
            HTTPReplica(f"http://127.0.0.1:{_dead_port()}", "dead",
                        timeout_sec=30.0, probe_timeout_sec=1.0),
            HTTPReplica(replicas[0].url, "replica0", timeout_sec=120.0),
            HTTPReplica(replicas[1].url, "replica1", timeout_sec=120.0),
        ]
        router = ReplicaRouter(
            backends,
            fail_threshold=1,
            revive_sec=600.0,
            block_tokens=4,
            timeline=router_tl,
            tracer=Tracer(router_tl, sampler=_keep_all()),
        )
        try:
            reqs = build_requests(
                num_requests=8,
                seed=3,
                vocab_size=64,
                prompt_tokens_min=4,
                prompt_tokens_max=8,
                max_new_tokens=4,
            )
            block = run_loadgen(
                router, reqs, rate_rps=30.0, seed=5, timeout_sec=300.0
            )
            assert block["requests"]["failed"] == 0
            assert block["requests"]["completed"] == len(reqs)
            assert router.stats()["router"]["failovers"] >= 1
            assert router.stats()["router"]["tracing"]["finished"] == len(
                reqs
            )
            # Exemplars on the live replicas' /metrics scrape.
            exemplar_seen = False
            for rep in replicas:
                with urllib.request.urlopen(
                    rep.url + "/metrics", timeout=30
                ) as resp:
                    text = resp.read().decode()
                if "llmtrain_serve_ttft_ms_bucket" in text:
                    exemplar_seen = exemplar_seen or '# {trace_id="' in text
            assert exemplar_seen
        finally:
            router.close()
            for rep in replicas:
                rep.close()

        sources = discover_sources([tmp_path])
        assert len(sources) == 3
        traces = collect_traces(sources)
        assert len(traces) == len(reqs)

        failovers = 0
        for trace in traces.values():
            root = trace.root
            assert root is not None and root.name == "router/request"
            assert "router/timeline" in root.source
            # Hop spans under the root, replica tree under the hop —
            # linked purely by the traceparent the router sent.
            hops = [
                s
                for s in trace.children(root.span_id)
                if s.name == "router/http_dispatch"
            ]
            assert hops, format_tree(trace)
            served = [
                c
                for h in hops
                for c in trace.children(h.span_id)
                if c.name == "serve/request"
            ]
            assert len(served) == 1, format_tree(trace)
            replica_root = served[0]
            assert "replica" in replica_root.source
            child_names = {
                c.name for c in trace.children(replica_root.span_id)
            }
            assert "serve/prefill" in child_names
            assert "serve/decode_phase" in child_names
            # Critical path tiles the root interval exactly.
            cp = critical_path(trace)
            assert sum(cp["breakdown"].values()) == pytest.approx(
                cp["total_ms"], abs=0.05
            )
            if any(s.args.get("error") for s in hops):
                failovers += 1
                assert any(
                    s.name == "router/failover" for s in trace.spans
                ), format_tree(trace)
        assert failovers >= 1

        # The merged Perfetto file: one track group per process, flow
        # arrows for every cross-process hop link.
        out = tmp_path / "merged_trace.json"
        merge_perfetto(sources, out, traces=traces)
        doc = json.loads(out.read_text())
        names = {
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e["name"] == "process_name"
        }
        assert len(names) == 3
        flows = [
            e for e in doc["traceEvents"] if e["name"] == "trace_link"
        ]
        assert len(flows) >= 2 * len(reqs)  # one s/f pair per hop link

    def test_force_header_keeps_a_fast_trace(self, tmp_path):
        """``X-Trace: force`` on the ingress keeps the trace even when the
        sampler would drop everything."""
        model, params = _tiny_model()
        rep = _Replica(model, params, tmp_path, "replica0")
        # Replace the keep-all drill sampler with a drop-everything one.
        rep.scheduler.tracer = Tracer(
            rep.timeline,
            sampler=TailSampler(slow_frac=0.01, reservoir=16, warmup=0),
        )
        for _ in range(20):  # saturate the reservoir with slow latencies
            rep.scheduler.tracer.sampler.decide(60_000.0)
        try:
            body = json.dumps(
                {"prompt_ids": [1, 2, 3], "max_new_tokens": 2,
                 "temperature": 0.0}
            ).encode()
            plain = urllib.request.Request(
                rep.url + "/v1/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(plain, timeout=120) as resp:
                assert resp.status == 200
            forced = urllib.request.Request(
                rep.url + "/v1/generate", data=body,
                headers={
                    "Content-Type": "application/json",
                    "X-Trace": "force",
                },
            )
            with urllib.request.urlopen(forced, timeout=120) as resp:
                out = json.loads(resp.read())
            assert resp.status == 200
            forced_trace_id = out["trace_id"]
        finally:
            rep.close()

        from llmtrain_tpu.telemetry.trace_collect import (
            collect_traces,
            discover_sources,
        )

        traces = collect_traces(discover_sources([tmp_path]))
        assert list(traces) == [forced_trace_id]
        root = traces[forced_trace_id].root
        assert root is not None and root.args.get("sampled") == "forced"
