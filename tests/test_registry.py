"""Registry tests (parity with reference tests/test_registry.py)."""

import pytest

from llmtrain_tpu.registry import (
    RegistryError,
    available_data_modules,
    available_model_adapters,
    get_data_module,
    get_model_adapter,
    initialize_registries,
    register_data_module,
    register_model,
)


def test_initialize_registers_builtins():
    initialize_registries()
    assert "gpt" in available_model_adapters()
    assert "dummy_gpt" in available_model_adapters()
    assert "hf_text" in available_data_modules()
    assert "dummy_text" in available_data_modules()


def test_initialize_is_idempotent():
    initialize_registries()
    before = available_model_adapters()
    initialize_registries()
    assert available_model_adapters() == before


def test_duplicate_model_registration_raises():
    initialize_registries()
    with pytest.raises(RegistryError, match="already registered"):

        @register_model("gpt")
        class Dup:  # pragma: no cover - registration fails before use
            pass


def test_duplicate_data_registration_raises():
    initialize_registries()
    with pytest.raises(RegistryError, match="already registered"):

        @register_data_module("dummy_text")
        class Dup:  # pragma: no cover
            pass


def test_unknown_model_lists_available():
    initialize_registries()
    with pytest.raises(RegistryError, match="gpt"):
        get_model_adapter("nope")


def test_unknown_data_lists_available():
    initialize_registries()
    with pytest.raises(RegistryError, match="dummy_text"):
        get_data_module("nope")


def test_lookup_returns_class():
    initialize_registries()
    adapter_cls = get_model_adapter("dummy_gpt")
    adapter = adapter_cls()
    assert hasattr(adapter, "build_model")
