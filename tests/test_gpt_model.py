"""GPT model correctness tests (parity with reference tests/test_gpt_model.py).

Includes the flagship causality-invariance test: perturbing tokens after
position t must leave logits at positions <= t unchanged (reference
test_gpt_model.py:144-175).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.models.gpt import GPT

VOCAB = 97
BLOCK = 16


def _tiny_gpt(**overrides):
    kwargs = dict(
        vocab_size=VOCAB,
        block_size=BLOCK,
        d_model=32,
        n_layers=2,
        n_heads=4,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    kwargs.update(overrides)
    return GPT(**kwargs)


def _init(model, batch=2, seqlen=BLOCK, seed=0):
    tokens = jnp.zeros((batch, seqlen), dtype=jnp.int32)
    return model.init({"params": jax.random.key(seed)}, tokens, deterministic=True)["params"]


def test_forward_shape():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(1), (3, 10), 0, VOCAB)
    logits = model.apply({"params": params}, tokens, deterministic=True)
    assert logits.shape == (3, 10, VOCAB)


def test_block_size_overflow_raises():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jnp.zeros((1, BLOCK + 1), dtype=jnp.int32)
    with pytest.raises(ValueError, match="exceeds block size"):
        model.apply({"params": params}, tokens, deterministic=True)


def test_weight_tying_removes_lm_head():
    tied = _tiny_gpt(tie_embeddings=True)
    untied = _tiny_gpt(tie_embeddings=False)
    tied_params = _init(tied)
    untied_params = _init(untied)
    assert "lm_head" not in tied_params
    assert "lm_head" in untied_params
    tied_count = sum(x.size for x in jax.tree.leaves(tied_params))
    untied_count = sum(x.size for x in jax.tree.leaves(untied_params))
    assert untied_count == tied_count + 32 * VOCAB


def test_causality_invariance():
    """Perturb tokens after position t; logits up to t must be unchanged."""
    model = _tiny_gpt()
    params = _init(model)
    key = jax.random.key(7)
    tokens = jax.random.randint(key, (2, BLOCK), 0, VOCAB)
    t = 9
    perturbed = tokens.at[:, t + 1 :].set((tokens[:, t + 1 :] + 13) % VOCAB)

    logits_a = model.apply({"params": params}, tokens, deterministic=True)
    logits_b = model.apply({"params": params}, perturbed, deterministic=True)

    np.testing.assert_allclose(
        np.asarray(logits_a[:, : t + 1]), np.asarray(logits_b[:, : t + 1]), atol=1e-6
    )
    assert not np.allclose(np.asarray(logits_a[:, t + 1 :]), np.asarray(logits_b[:, t + 1 :]))


def test_padding_mask_zeroes_padded_rows_and_blocks_keys():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(3), (1, 8), 0, VOCAB)
    mask = jnp.array([[1, 1, 1, 1, 1, 0, 0, 0]], dtype=jnp.int32)

    logits_masked = model.apply({"params": params}, tokens, attention_mask=mask)
    # Changing tokens in the padded region must not change unpadded logits.
    perturbed = tokens.at[:, 5:].set((tokens[:, 5:] + 1) % VOCAB)
    logits_masked2 = model.apply({"params": params}, perturbed, attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(logits_masked[:, :5]), np.asarray(logits_masked2[:, :5]), atol=1e-6
    )


def test_gradient_flow():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(5), (2, BLOCK), 0, VOCAB)

    def loss_fn(p):
        logits = model.apply({"params": p}, tokens, deterministic=True)
        return jnp.mean(logits**2)

    grads = jax.grad(loss_fn)(params)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert sum(norms) > 0.0


def test_dropout_rng_changes_output():
    model = _tiny_gpt(dropout=0.5)
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(2), (2, 8), 0, VOCAB)
    out1 = model.apply(
        {"params": params}, tokens, deterministic=False, rngs={"dropout": jax.random.key(1)}
    )
    out2 = model.apply(
        {"params": params}, tokens, deterministic=False, rngs={"dropout": jax.random.key(2)}
    )
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_bfloat16_compute_dtype():
    model = _tiny_gpt(dtype=jnp.bfloat16)
    params = _init(model)
    # Master params stay f32; activations/logits come out bf16.
    assert all(x.dtype == jnp.float32 for x in jax.tree.leaves(params))
    tokens = jnp.zeros((1, 4), dtype=jnp.int32)
    logits = model.apply({"params": params}, tokens, deterministic=True)
    assert logits.dtype == jnp.bfloat16


def test_remat_matches_no_remat():
    base = _tiny_gpt(remat=False)
    rem = _tiny_gpt(remat=True)
    params = _init(base)
    tokens = jax.random.randint(jax.random.key(11), (2, BLOCK), 0, VOCAB)
    out_a = base.apply({"params": params}, tokens, deterministic=True)
    out_b = rem.apply({"params": params}, tokens, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), atol=1e-6)
