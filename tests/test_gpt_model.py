"""GPT model correctness tests (parity with reference tests/test_gpt_model.py).

Includes the flagship causality-invariance test: perturbing tokens after
position t must leave logits at positions <= t unchanged (reference
test_gpt_model.py:144-175).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.models.gpt import GPT

VOCAB = 97
BLOCK = 16


def _tiny_gpt(**overrides):
    kwargs = dict(
        vocab_size=VOCAB,
        block_size=BLOCK,
        d_model=32,
        n_layers=2,
        n_heads=4,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    kwargs.update(overrides)
    return GPT(**kwargs)


def _init(model, batch=2, seqlen=BLOCK, seed=0):
    tokens = jnp.zeros((batch, seqlen), dtype=jnp.int32)
    return model.init({"params": jax.random.key(seed)}, tokens, deterministic=True)["params"]


def test_forward_shape():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(1), (3, 10), 0, VOCAB)
    logits = model.apply({"params": params}, tokens, deterministic=True)
    assert logits.shape == (3, 10, VOCAB)


def test_block_size_overflow_raises():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jnp.zeros((1, BLOCK + 1), dtype=jnp.int32)
    with pytest.raises(ValueError, match="exceeds block size"):
        model.apply({"params": params}, tokens, deterministic=True)


def test_weight_tying_removes_lm_head():
    tied = _tiny_gpt(tie_embeddings=True)
    untied = _tiny_gpt(tie_embeddings=False)
    tied_params = _init(tied)
    untied_params = _init(untied)
    assert "lm_head" not in tied_params
    assert "lm_head" in untied_params
    tied_count = sum(x.size for x in jax.tree.leaves(tied_params))
    untied_count = sum(x.size for x in jax.tree.leaves(untied_params))
    assert untied_count == tied_count + 32 * VOCAB


def test_causality_invariance():
    """Perturb tokens after position t; logits up to t must be unchanged."""
    model = _tiny_gpt()
    params = _init(model)
    key = jax.random.key(7)
    tokens = jax.random.randint(key, (2, BLOCK), 0, VOCAB)
    t = 9
    perturbed = tokens.at[:, t + 1 :].set((tokens[:, t + 1 :] + 13) % VOCAB)

    logits_a = model.apply({"params": params}, tokens, deterministic=True)
    logits_b = model.apply({"params": params}, perturbed, deterministic=True)

    np.testing.assert_allclose(
        np.asarray(logits_a[:, : t + 1]), np.asarray(logits_b[:, : t + 1]), atol=1e-6
    )
    assert not np.allclose(np.asarray(logits_a[:, t + 1 :]), np.asarray(logits_b[:, t + 1 :]))


def test_padding_mask_zeroes_padded_rows_and_blocks_keys():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(3), (1, 8), 0, VOCAB)
    mask = jnp.array([[1, 1, 1, 1, 1, 0, 0, 0]], dtype=jnp.int32)

    logits_masked = model.apply({"params": params}, tokens, attention_mask=mask)
    # Changing tokens in the padded region must not change unpadded logits.
    perturbed = tokens.at[:, 5:].set((tokens[:, 5:] + 1) % VOCAB)
    logits_masked2 = model.apply({"params": params}, perturbed, attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(logits_masked[:, :5]), np.asarray(logits_masked2[:, :5]), atol=1e-6
    )


def test_gradient_flow():
    model = _tiny_gpt()
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(5), (2, BLOCK), 0, VOCAB)

    def loss_fn(p):
        logits = model.apply({"params": p}, tokens, deterministic=True)
        return jnp.mean(logits**2)

    grads = jax.grad(loss_fn)(params)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert sum(norms) > 0.0


def test_dropout_rng_changes_output():
    model = _tiny_gpt(dropout=0.5)
    params = _init(model)
    tokens = jax.random.randint(jax.random.key(2), (2, 8), 0, VOCAB)
    out1 = model.apply(
        {"params": params}, tokens, deterministic=False, rngs={"dropout": jax.random.key(1)}
    )
    out2 = model.apply(
        {"params": params}, tokens, deterministic=False, rngs={"dropout": jax.random.key(2)}
    )
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_bfloat16_compute_dtype():
    model = _tiny_gpt(dtype=jnp.bfloat16)
    params = _init(model)
    # Master params stay f32; activations/logits come out bf16.
    assert all(x.dtype == jnp.float32 for x in jax.tree.leaves(params))
    tokens = jnp.zeros((1, 4), dtype=jnp.int32)
    logits = model.apply({"params": params}, tokens, deterministic=True)
    assert logits.dtype == jnp.bfloat16


def test_remat_matches_no_remat():
    base = _tiny_gpt(remat=False)
    rem = _tiny_gpt(remat=True)
    params = _init(base)
    tokens = jax.random.randint(jax.random.key(11), (2, BLOCK), 0, VOCAB)
    out_a = base.apply({"params": params}, tokens, deterministic=True)
    out_b = rem.apply({"params": params}, tokens, deterministic=True)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), atol=1e-6)


class TestRematPolicy:
    """model.extra.remat_policy: value/grad equality across policies (the
    policy only changes what gets RECOMPUTED, never the math)."""

    def _model(self, policy):
        return GPT(
            vocab_size=64, block_size=16, d_model=32, n_layers=2, n_heads=4,
            d_ff=64, dropout=0.0, remat=True, remat_policy=policy,
        )

    @pytest.mark.parametrize("policy", ["dots", "dots_no_batch"])
    def test_matches_default_policy(self, policy):
        from flax.linen import meta as nn_meta

        base = self._model("nothing")
        ids = jnp.zeros((1, 16), jnp.int32)
        params = nn_meta.unbox(
            base.init(jax.random.key(0), ids, deterministic=True)
        )["params"]
        toks = jnp.asarray(
            np.random.default_rng(3).integers(0, 64, (2, 16)), jnp.int32
        )

        def loss(model, p):
            logits = model.apply({"params": p}, toks, deterministic=True)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        v0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
        v1, g1 = jax.value_and_grad(lambda p: loss(self._model(policy), p))(params)
        assert abs(float(v0) - float(v1)) < 1e-6
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_unknown_policy_raises(self):
        ids = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="remat_policy"):
            self._model("everything").init(
                jax.random.key(0), ids, deterministic=True
            )

    def test_adapter_validates_policy_even_without_remat(self):
        """A typo'd policy fails at config time, not silently ignored
        until someone later flips remat: true."""
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.models.gpt import GPTAdapter

        cfg = RunConfig.model_validate(
            {
                "run": {"name": "x", "device": "cpu"},
                "model": {
                    "name": "gpt", "block_size": 8, "d_model": 16,
                    "n_layers": 1, "n_heads": 4, "d_ff": 32,
                    "vocab_size": 64, "remat": False,
                    "extra": {"tokenizer": "byte", "remat_policy": "dotz"},
                },
                "data": {"name": "dummy_text"},
                "trainer": {"max_steps": 1, "micro_batch_size": 2,
                            "warmup_steps": 0},
            }
        )
        with pytest.raises(ValueError, match="remat_policy"):
            GPTAdapter().build_model(cfg)


class TestGroupedQueryAttention:
    """GQA (model.extra.n_kv_heads): narrow K/V heads shared across query
    groups; the decode cache stores only n_kv_heads."""

    def _model(self, n_kv_heads, **kw):
        return GPT(
            vocab_size=64, block_size=16, d_model=32, n_layers=2, n_heads=4,
            d_ff=64, dropout=0.0, n_kv_heads=n_kv_heads, **kw,
        )

    def _params(self, model):
        from flax.linen import meta as nn_meta

        ids = jnp.zeros((1, 16), jnp.int32)
        return nn_meta.unbox(model.init(jax.random.key(0), ids, deterministic=True))[
            "params"
        ]

    def test_mha_param_tree_unchanged(self):
        """n_kv_heads=0 (and ==n_heads) keeps the fused qkv_proj tree so
        existing checkpoints still load."""
        for kvh in (0, 4):
            params = self._params(self._model(kvh))
            attn = params["block_0"]["attn"]
            assert "qkv_proj" in attn and "q_proj" not in attn

    def test_gqa_param_tree_and_shapes(self):
        params = self._params(self._model(2))
        attn = params["block_0"]["attn"]
        assert "qkv_proj" not in attn
        assert attn["q_proj"]["kernel"].shape == (32, 4, 8)
        assert attn["kv_proj"]["kernel"].shape == (32, 2, 2, 8)

    @pytest.mark.parametrize("kvh", [1, 2], ids=["mqa", "gqa2"])
    def test_causality_invariance(self, kvh):
        """Perturbing tokens after position t leaves logits <= t unchanged
        (the reference's flagship invariant, test_gpt_model.py:144-175)."""
        model = self._model(kvh)
        params = self._params(model)
        rng = np.random.default_rng(5)
        ids = rng.integers(0, 64, (2, 16))
        t = 7
        pert = ids.copy()
        pert[:, t + 1 :] = rng.integers(0, 64, (2, 16 - t - 1))
        a = model.apply({"params": params}, jnp.asarray(ids, jnp.int32), deterministic=True)
        b = model.apply({"params": params}, jnp.asarray(pert, jnp.int32), deterministic=True)
        np.testing.assert_allclose(
            np.asarray(a[:, : t + 1]), np.asarray(b[:, : t + 1]), atol=1e-6
        )

    @pytest.mark.parametrize("kvh", [1, 2], ids=["mqa", "gqa2"])
    def test_flash_route_matches_dense(self, kvh):
        """The flash path consumes narrow K/V natively (no jnp.repeat in
        the model); logits equal the dense-attention route."""
        dense = self._model(kvh)
        params = self._params(dense)
        flash = self._model(kvh, attention="flash")
        ids = jnp.asarray(np.random.default_rng(7).integers(0, 64, (2, 16)), jnp.int32)
        a = dense.apply({"params": params}, ids, deterministic=True)
        b = flash.apply({"params": params}, ids, deterministic=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    @pytest.mark.parametrize("kvh", [2, 0], ids=["gqa2", "mha"])
    def test_flash_route_applies_padding_mask(self, kvh):
        """Padded batches through attention='flash' now match dense — the
        padding mask is applied INSIDE attention on every path (closes the
        r2 'flash ignores masks' gap; reference gpt.py:60-64)."""
        dense = self._model(kvh)
        params = self._params(dense)
        flash = self._model(kvh, attention="flash")
        ids = jnp.asarray(np.random.default_rng(8).integers(0, 64, (2, 16)), jnp.int32)
        mask = jnp.asarray(
            (np.arange(16)[None, :] < np.asarray([16, 9])[:, None]).astype(np.int32)
        )
        a = dense.apply(
            {"params": params}, ids, attention_mask=mask, deterministic=True
        )
        b = flash.apply(
            {"params": params}, ids, attention_mask=mask, deterministic=True
        )
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
        # assume_packed drops the mask — valid rows must then differ from
        # the masked result only on rows that actually carry padding.
        packed = self._model(kvh, attention="flash", assume_packed=True)
        c = packed.apply(
            {"params": params}, ids, attention_mask=mask, deterministic=True
        )
        np.testing.assert_allclose(np.asarray(a[0]), np.asarray(c[0]), atol=1e-5)

    def test_decode_cache_stores_narrow_kv(self):
        model = self._model(1).for_decoding(cache_len=8)
        variables = model.init(
            jax.random.key(0), jnp.zeros((2, 1), jnp.int32), deterministic=True
        )
        cache_shape = variables["cache"]["block_0"]["attn"]["cached_key"].shape
        assert cache_shape == (2, 8, 1, 8)  # n_kv_heads=1, not n_heads=4

    @pytest.mark.parametrize("kvh", [1, 2], ids=["mqa", "gqa2"])
    def test_cached_decode_matches_windowed(self, kvh):
        """The narrow-cache decode path equals the full re-forward path —
        the GQA twin of the MHA equivalence test (test_generation.py)."""
        from llmtrain_tpu.generation import generate

        model = self._model(kvh)
        params = self._params(model)
        prompt = np.asarray([[3, 1, 4, 1, 5]], np.int32)
        cached = generate(
            model, params, prompt, max_new_tokens=8, temperature=0.0, use_cache=True
        )
        windowed = generate(
            model, params, prompt, max_new_tokens=8, temperature=0.0, use_cache=False
        )
        np.testing.assert_array_equal(cached, windowed)

    def test_training_loss_decreases(self):
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "gqa", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt",
                    "block_size": 8,
                    "d_model": 16,
                    "n_layers": 1,
                    "n_heads": 4,
                    "d_ff": 32,
                    "dropout": 0.0,
                    "vocab_size": 64,
                    "extra": {"tokenizer": "byte", "n_kv_heads": 2},
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 10,
                    "micro_batch_size": 2,
                    "grad_accum_steps": 1,
                    "warmup_steps": 2,
                    "log_every_steps": 5,
                    "eval_every_steps": 10,
                    "save_every_steps": 10,
                },
                "mlflow": {"enabled": False},
            }
        )
        trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
        result = trainer.fit()
        assert result.final_loss < result.first_step_loss

    def test_invalid_n_kv_heads_rejected(self):
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.models.gpt import GPTAdapter

        def cfg(kvh):
            return RunConfig.model_validate(
                {
                    "run": {"name": "x", "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 8, "d_model": 16,
                        "n_layers": 1, "n_heads": 4, "d_ff": 32,
                        "vocab_size": 64,
                        "extra": {"tokenizer": "byte", "n_kv_heads": kvh},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                }
            )

        with pytest.raises(ValueError, match="n_kv_heads"):
            GPTAdapter().build_model(cfg(3))  # 4 % 3 != 0
        with pytest.raises(ValueError, match="n_kv_heads"):
            GPTAdapter().build_model(cfg(-1))

    def test_tp_mesh_incompatible_kv_heads_rejected_loudly(self):
        """MQA (n_kv_heads=1) on a tensor=2 mesh must fail with a clear
        message at Trainer construction, not an opaque pjit sharding error
        at compile time; kv_heads >= tp shards fine."""
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()

        def cfg(kvh):
            return RunConfig.model_validate(
                {
                    "run": {"name": "gqa-tp", "seed": 0, "device": "cpu"},
                    "model": {
                        "name": "gpt", "block_size": 8, "d_model": 32,
                        "n_layers": 1, "n_heads": 4, "d_ff": 64,
                        "dropout": 0.0, "vocab_size": 64,
                        "extra": {"tokenizer": "byte", "n_kv_heads": kvh},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {
                        "max_steps": 1, "micro_batch_size": 2,
                        "grad_accum_steps": 1, "warmup_steps": 0,
                        "log_every_steps": 1, "eval_every_steps": 1,
                        "save_every_steps": 1,
                    },
                    "distributed": {"mesh": {"tensor": 2, "data": 4}},
                    "mlflow": {"enabled": False},
                }
            )

        with pytest.raises(ValueError, match="divisible by the mesh tensor axis"):
            Trainer(cfg(1), run_dir=None, tracker=NullTracker())
        result = Trainer(cfg(2), run_dir=None, tracker=NullTracker()).fit(
            max_steps_override=1
        )
        assert result.final_step == 1
