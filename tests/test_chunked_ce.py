"""Chunked cross-entropy (ops/chunked_ce.py) vs the dense loss path.

The op must be numerically the dense masked CE (models/base.py) in both
value and gradient — it only changes WHERE the compute happens (streamed
vocab chunks + recompute-in-backward), never the math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.models.base import masked_ce_components
from llmtrain_tpu.models.gpt import GPT, GPTAdapter
from llmtrain_tpu.ops.chunked_ce import chunked_ce_components, chunked_ce_per_token

B, T, D, V = 2, 8, 16, 203  # V deliberately not a chunk multiple


def _data(seed=0, v=V):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(v, D)) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, size=(B, T)), jnp.int32)
    return hidden, w, labels


def _dense_per_token(hidden, w, labels):
    logits = jnp.einsum("btd,vd->btv", hidden, w)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]


class TestValue:
    @pytest.mark.parametrize("chunk", [64, 128, 203, 512])
    def test_matches_dense_any_chunking(self, chunk):
        hidden, w, labels = _data()
        got = chunked_ce_per_token(hidden, w, labels, chunk)
        want = _dense_per_token(hidden, w, labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)

    def test_components_match_masked_dense(self):
        hidden, w, labels = _data(3)
        mask = jnp.asarray(np.random.default_rng(4).integers(0, 2, (B, T)), jnp.int32)
        logits = jnp.einsum("btd,vd->btv", hidden, w)
        want_sum, want_tok = masked_ce_components(logits, labels, mask)
        got_sum, got_tok = chunked_ce_components(hidden, w, labels, mask, chunk=64)
        np.testing.assert_allclose(np.asarray(got_sum), np.asarray(want_sum), atol=1e-5, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(got_tok), np.asarray(want_tok))

    def test_jit_and_single_chunk(self):
        hidden, w, labels = _data(5)
        f = jax.jit(lambda h, w, l: chunked_ce_per_token(h, w, l, 1024))
        np.testing.assert_allclose(
            np.asarray(f(hidden, w, labels)),
            np.asarray(_dense_per_token(hidden, w, labels)),
            atol=1e-5,
            rtol=1e-5,
        )


class TestGrad:
    @pytest.mark.parametrize("chunk", [64, 203])
    def test_grads_match_dense_autodiff(self, chunk):
        hidden, w, labels = _data(7)
        mask = jnp.ones((B, T), jnp.float32)

        def loss_chunked(h, w_):
            s, t = chunked_ce_components(h, w_, labels, mask, chunk=chunk)
            return jnp.sum(s) / jnp.sum(t)

        def loss_dense(h, w_):
            per = _dense_per_token(h, w_, labels)
            return jnp.mean(per)

        gc_h, gc_w = jax.grad(loss_chunked, argnums=(0, 1))(hidden, w)
        gd_h, gd_w = jax.grad(loss_dense, argnums=(0, 1))(hidden, w)
        np.testing.assert_allclose(np.asarray(gc_h), np.asarray(gd_h), atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gc_w), np.asarray(gd_w), atol=1e-5, rtol=1e-4)

    def test_masked_grads(self):
        """Masked positions contribute nothing to either gradient."""
        hidden, w, labels = _data(9)
        mask = jnp.ones((B, T), jnp.float32).at[0, T // 2 :].set(0.0)

        def loss(h, w_):
            s, t = chunked_ce_components(h, w_, labels, mask, chunk=64)
            return jnp.sum(s) / jnp.sum(t)

        g_h = jax.grad(loss)(hidden, w)
        assert np.allclose(np.asarray(g_h)[0, T // 2 :], 0.0, atol=1e-7)


def _gpt(tie: bool, loss_impl: str):
    model = GPT(
        vocab_size=V,
        block_size=T,
        d_model=D,
        n_layers=2,
        n_heads=4,
        d_ff=32,
        dropout=0.0,
        tie_embeddings=tie,
        loss_impl=loss_impl,
        ce_chunk=64,
    )
    ids = jnp.zeros((1, T), jnp.int32)
    params = nn_meta.unbox(model.init(jax.random.key(0), ids, deterministic=True))[
        "params"
    ]
    return model, params


class TestAdapterIntegration:
    @pytest.mark.parametrize(
        "tie",
        [
            pytest.param(True, id="tied"),
            # budget: untied rides test-all; the tied run keeps the
            # adapter-parity contract tier-1
            pytest.param(False, id="untied", marks=pytest.mark.slow),
        ],
    )
    def test_same_loss_and_grads_as_dense_path(self, tie):
        rng = np.random.default_rng(11)
        batch = {
            "input_ids": jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32),
            "labels": jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32),
            "attention_mask": jnp.ones((B, T), jnp.int32),
        }
        adapter = GPTAdapter()
        dense_model, params = _gpt(tie, "dense")
        chunk_model, _ = _gpt(tie, "chunked_ce")

        def loss_with(model):
            def f(p):
                s, t = adapter.compute_loss_components(model, p, batch)
                return jnp.sum(s) / jnp.sum(t)

            return f

        ld, gd = jax.value_and_grad(loss_with(dense_model))(params)
        lc, gc = jax.value_and_grad(loss_with(chunk_model))(params)
        np.testing.assert_allclose(float(lc), float(ld), atol=1e-5, rtol=1e-5)
        for (pd, vd), (pc, vc) in zip(
            jax.tree_util.tree_leaves_with_path(gd),
            jax.tree_util.tree_leaves_with_path(gc),
            strict=True,
        ):
            assert pd == pc
            np.testing.assert_allclose(
                np.asarray(vd), np.asarray(vc), atol=2e-5, rtol=1e-3,
                err_msg=jax.tree_util.keystr(pd),
            )

    def test_trains_end_to_end(self):
        """Few train steps through the real train_step with chunked CE."""
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "chunked-ce", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt",
                    "block_size": 8,
                    "d_model": 16,
                    "n_layers": 1,
                    "n_heads": 4,
                    "d_ff": 32,
                    "dropout": 0.0,
                    "vocab_size": 64,
                    "extra": {"tokenizer": "byte", "loss_impl": "chunked_ce", "ce_chunk": 32},
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 10,
                    "micro_batch_size": 2,
                    "grad_accum_steps": 1,
                    "warmup_steps": 2,
                    "log_every_steps": 5,
                    "eval_every_steps": 10,
                    "save_every_steps": 10,
                },
                "mlflow": {"enabled": False},
            }
        )
        trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
        result = trainer.fit()
        assert result.final_step == 10
        assert result.final_loss < result.first_step_loss


class TestKnobValidation:
    """Review findings: unknown loss_impl values and unsupported model
    families must fail loudly, not silently run dense."""

    def _cfg(self, model_name, extra):
        from llmtrain_tpu.config.schemas import RunConfig

        return RunConfig.model_validate(
            {
                "run": {"name": "x", "device": "cpu"},
                "model": {
                    "name": model_name,
                    "block_size": 8,
                    "d_model": 16,
                    "n_layers": 1,
                    "n_heads": 4,
                    "d_ff": 32,
                    "dropout": 0.0,
                    "vocab_size": 64,
                    "extra": {"tokenizer": "byte", **extra},
                },
                "data": {"name": "dummy_text"},
                "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                "mlflow": {"enabled": False},
            }
        )

    def test_unknown_loss_impl_rejected(self):
        with pytest.raises(ValueError, match="loss_impl"):
            GPTAdapter().build_model(self._cfg("gpt", {"loss_impl": "chunked"}))

    def test_gpt_moe_chunked_matches_dense(self):
        """MoE composes with chunked CE: same CE + router-aux loss and
        gradients as the dense path."""
        from llmtrain_tpu.models.gpt_moe import GPTMoEAdapter

        adapter = GPTMoEAdapter()
        rng = np.random.default_rng(23)
        batch = {
            "input_ids": jnp.asarray(rng.integers(0, 64, (2, 8)), jnp.int32),
            "labels": jnp.asarray(rng.integers(0, 64, (2, 8)), jnp.int32),
            "attention_mask": jnp.ones((2, 8), jnp.int32),
        }

        def build(loss_impl):
            cfg = self._cfg(
                "gpt_moe",
                {"n_experts": 4, "capacity_factor": 2.0, "loss_impl": loss_impl,
                 "ce_chunk": 32},
            )
            model = adapter.build_model(cfg)
            params = nn_meta.unbox(
                model.init(jax.random.key(0), batch["input_ids"], deterministic=True)
            )["params"]
            return model, params

        dense_model, params = build("dense")
        chunk_model, _ = build("chunked_ce")

        def loss_with(model):
            def f(p):
                s, t = adapter.compute_loss_components(model, p, batch)
                return jnp.sum(s) / jnp.sum(t)

            return f

        ld, gd = jax.value_and_grad(loss_with(dense_model))(params)
        lc, gc = jax.value_and_grad(loss_with(chunk_model))(params)
        np.testing.assert_allclose(float(lc), float(ld), atol=1e-5, rtol=1e-5)
        for (pd, vd), (pc, vc) in zip(
            jax.tree_util.tree_leaves_with_path(gd),
            jax.tree_util.tree_leaves_with_path(gc),
            strict=True,
        ):
            assert pd == pc
            np.testing.assert_allclose(
                np.asarray(vd), np.asarray(vc), atol=2e-5, rtol=1e-3,
                err_msg=jax.tree_util.keystr(pd),
            )


class TestShardedMesh:
    """chunked_ce composes with tensor/fsdp/sequence sharding: the vocab
    reshape inside the scan must not change results under a sharded mesh
    (verified bit-identical to the dense path on the virtual 8-device
    mesh)."""

    @pytest.mark.parametrize(
        "mesh",
        [
            {"tensor": 2, "data": 4},
            {"tensor": 2, "fsdp": 2, "sequence": 2, "data": 1},
        ],
        ids=["tp-dp", "tp-fsdp-sp"],
    )
    def test_matches_dense_on_mesh(self, mesh):
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()

        def run(loss_impl):
            cfg = RunConfig.model_validate(
                {
                    "run": {"name": "cce-mesh", "seed": 0, "device": "cpu"},
                    "model": {
                        "name": "gpt",
                        "block_size": 8,
                        "d_model": 32,
                        "n_layers": 2,
                        "n_heads": 4,
                        "d_ff": 64,
                        "dropout": 0.0,
                        "vocab_size": 64,
                        "extra": {
                            "tokenizer": "byte",
                            "loss_impl": loss_impl,
                            "ce_chunk": 32,
                        },
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {
                        "max_steps": 3,
                        "micro_batch_size": 2,
                        "grad_accum_steps": 2,
                        "warmup_steps": 0,
                        "log_every_steps": 1,
                        "eval_every_steps": 3,
                        "save_every_steps": 3,
                    },
                    "distributed": {"mesh": mesh},
                    "mlflow": {"enabled": False},
                }
            )
            trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
            result = trainer.fit()
            return result.final_loss, result.final_val_loss

        dense = run("dense")
        chunked = run("chunked_ce")
        assert abs(dense[0] - chunked[0]) < 1e-5
        assert abs(dense[1] - chunked[1]) < 1e-5


def test_gpt_pipeline_rejects_unknown_loss_impl():
    """Unknown values fail loudly, not silently run dense."""
    from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

    cfg = TestKnobValidation()._cfg("gpt_pipeline", {"loss_impl": "chunked"})
    with pytest.raises(ValueError, match="loss_impl"):
        PipelineGPTAdapter().build_model(cfg)


class TestPipelineChunked:
    """gpt_pipeline composes with chunked CE: the lm_head applies outside
    the stage shard_map, so the streamed loss drops in like for gpt."""

    def _run(self, loss_impl, mesh):
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "pipe-cce", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt_pipeline",
                    "block_size": 8,
                    "d_model": 32,
                    "n_layers": 4,
                    "n_heads": 4,
                    "d_ff": 64,
                    "dropout": 0.0,
                    "vocab_size": 64,
                    "extra": {
                        "tokenizer": "byte",
                        "pipeline_microbatches": 2,
                        "loss_impl": loss_impl,
                        "ce_chunk": 32,
                    },
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 3,
                    "micro_batch_size": 4,
                    "grad_accum_steps": 1,
                    "warmup_steps": 0,
                    "log_every_steps": 1,
                    "eval_every_steps": 3,
                    "save_every_steps": 3,
                },
                "distributed": {"mesh": mesh},
                "mlflow": {"enabled": False},
            }
        )
        trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
        result = trainer.fit()
        return result.final_loss

    def test_matches_dense_data_parallel_mesh(self):
        mesh = {"data": -1}  # all 8 virtual devices, no pipeline
        assert abs(self._run("dense", mesh) - self._run("chunked_ce", mesh)) < 1e-5

    @pytest.mark.slow  # budget: tier-1 sibling test_matches_dense_data_parallel_mesh; pipeline mesh rides test-all
    def test_matches_dense_on_pipeline_mesh(self):
        mesh = {"pipeline": 2, "data": -1}  # 2 stages x 4 data shards
        assert abs(self._run("dense", mesh) - self._run("chunked_ce", mesh)) < 1e-5


def test_ce_chunk_must_be_positive():
    tk = TestKnobValidation()
    with pytest.raises(ValueError, match="ce_chunk"):
        GPTAdapter().build_model(
            tk._cfg("gpt", {"loss_impl": "chunked_ce", "ce_chunk": 0})
        )
    from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

    with pytest.raises(ValueError, match="ce_chunk"):
        PipelineGPTAdapter().build_model(
            tk._cfg("gpt_pipeline", {"loss_impl": "chunked_ce", "ce_chunk": -8})
        )


class TestZLoss:
    """PaLM z-loss (z * log(Z)^2 per token) in both loss paths."""

    def test_analytic_value(self):
        """For a hand-checkable 1-token case the z-loss term is exactly
        z * logsumexp(logits)^2."""
        hidden = jnp.ones((1, 2, 2), jnp.float32)
        w = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [0.5, 0.5]], jnp.float32)
        labels = jnp.zeros((1, 2), jnp.int32)
        logits = np.asarray(hidden @ w.T)
        lse = np.log(np.exp(logits).sum(-1))
        base = np.asarray(chunked_ce_per_token(hidden, w, labels, 2, None, 0.0))
        with_z = np.asarray(chunked_ce_per_token(hidden, w, labels, 2, None, 0.1))
        np.testing.assert_allclose(with_z - base, 0.1 * lse**2, atol=1e-6)

    def test_chunked_matches_dense_value_and_grads(self):
        hidden, w, labels = _data(31)
        mask = jnp.ones((B, T), jnp.float32)
        z = 1e-2

        def loss_chunked(h, w_):
            s, t = chunked_ce_components(h, w_, labels, mask, chunk=64, z_loss=z)
            return jnp.sum(s) / jnp.sum(t)

        def loss_dense(h, w_):
            logits = jnp.einsum("btd,vd->btv", h, w_)
            s, t = masked_ce_components(logits, labels, mask, z_loss=z)
            return jnp.sum(s) / jnp.sum(t)

        lc, (gch, gcw) = jax.value_and_grad(loss_chunked, argnums=(0, 1))(hidden, w)
        ld, (gdh, gdw) = jax.value_and_grad(loss_dense, argnums=(0, 1))(hidden, w)
        np.testing.assert_allclose(float(lc), float(ld), atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gch), np.asarray(gdh), atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gcw), np.asarray(gdw), atol=1e-5, rtol=1e-4)

    def test_adapter_paths_agree_with_z(self):
        """gpt with z_loss: dense and chunked loss paths still match."""
        rng = np.random.default_rng(37)
        batch = {
            "input_ids": jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32),
            "labels": jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32),
            "attention_mask": jnp.ones((B, T), jnp.int32),
        }
        adapter = GPTAdapter()

        def build(loss_impl):
            model = GPT(
                vocab_size=V, block_size=T, d_model=D, n_layers=1, n_heads=4,
                d_ff=32, dropout=0.0, loss_impl=loss_impl, ce_chunk=64,
                z_loss=1e-3,
            )
            ids = jnp.zeros((1, T), jnp.int32)
            params = nn_meta.unbox(
                model.init(jax.random.key(0), ids, deterministic=True)
            )["params"]
            return model, params

        dense_model, params = build("dense")
        chunk_model, _ = build("chunked_ce")
        sd, td = adapter.compute_loss_components(dense_model, params, batch)
        sc, tc = adapter.compute_loss_components(chunk_model, params, batch)
        np.testing.assert_allclose(np.asarray(sc), np.asarray(sd), atol=1e-5, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(tc), np.asarray(td))

    def test_negative_z_rejected(self):
        from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

        tk = TestKnobValidation()
        with pytest.raises(ValueError, match="z_loss"):
            GPTAdapter().build_model(tk._cfg("gpt", {"z_loss": -0.1}))
        with pytest.raises(ValueError, match="z_loss"):
            PipelineGPTAdapter().build_model(tk._cfg("gpt_pipeline", {"z_loss": -0.1}))

    def test_z_zero_is_reference_behavior(self):
        """Default z=0 leaves the loss bit-identical to plain CE."""
        hidden, w, labels = _data(41)
        a = chunked_ce_per_token(hidden, w, labels, 64, None, 0.0)
        b = chunked_ce_per_token(hidden, w, labels, 64)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
