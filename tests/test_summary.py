"""Run-summary contract tests (parity with reference tests/test_summary.py,
309 lines of coverage on both render modes): every config section echoed,
``Planned run:`` vs ``Run summary:`` headers, nested ``distributed.mesh``
rendering, dry-run resolution block, train-result block, env snapshot."""

from dataclasses import dataclass


from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.utils import format_run_summary

MINIMAL = {
    "run": {"name": "sum-test", "seed": 13},
    "model": {"name": "dummy_gpt", "block_size": 8, "vocab_size": 32},
    "data": {"name": "dummy_text"},
    "trainer": {"max_steps": 10, "warmup_steps": 0},
    "distributed": {"mesh": {"data": 2, "fsdp": 2, "tensor": 1, "sequence": 1}},
}


@dataclass
class _FakeDryRunResult:
    model_adapter: str = "DummyGPTAdapter"
    data_module: str = "DummyTextDataModule"
    steps_executed: int = 5


@dataclass
class _FakeTrainResult:
    final_step: int = 10
    final_loss: float = 1.25
    final_val_loss: float | None = 1.5
    first_step_loss: float | None = 3.0
    total_tokens: int = 640
    total_time: float = 2.5
    peak_memory: float = 0.0
    parameter_count: int = 1000
    trainable_parameter_count: int = 1000
    val_metrics: dict | None = None
    resumed_from_step: int | None = None


def _cfg(overrides=None):
    base = dict(MINIMAL)
    if overrides:
        base = {**base, **overrides}
    return RunConfig.model_validate(base)


class TestJsonSummary:
    def test_every_section_echoed(self):
        s = format_run_summary(_cfg(), run_id="rid", run_dir="/r/rid", as_json=True)
        for section in (
            "run", "model", "data", "trainer", "distributed",
            "mlflow", "logging", "output", "distributed_env",
        ):
            assert section in s, f"missing section {section}"
        assert s["run_id"] == "rid"
        assert s["run_dir"] == "/r/rid"
        assert s["dry_run"] is False

    def test_mesh_round_trips_in_json(self):
        s = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=True)
        assert s["distributed"]["mesh"]["data"] == 2
        assert s["distributed"]["mesh"]["fsdp"] == 2

    def test_defaults_materialized(self):
        """Sections absent from the input YAML appear fully defaulted."""
        s = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=True)
        assert s["mlflow"]["enabled"] is True  # reference default: enabled
        assert s["output"]["root_dir"] == "runs"
        assert s["logging"]["level"] == "INFO"

    def test_dry_run_resolution_block(self):
        s = format_run_summary(
            _cfg(),
            run_id="r",
            run_dir=None,
            dry_run=True,
            dry_run_result=_FakeDryRunResult(),
            as_json=True,
        )
        assert s["dry_run"] is True
        assert s["dry_run_resolution"] == {
            "model_adapter": "DummyGPTAdapter",
            "data_module": "DummyTextDataModule",
            "steps_executed": 5,
        }

    def test_train_result_block_complete(self):
        result = _FakeTrainResult(val_metrics={"val/loss": 1.5}, resumed_from_step=5)
        s = format_run_summary(
            _cfg(), run_id="r", run_dir=None, train_result=result, as_json=True
        )
        tr = s["train_result"]
        assert tr["final_step"] == 10
        assert tr["final_loss"] == 1.25
        assert tr["final_val_loss"] == 1.5
        assert tr["first_step_loss"] == 3.0
        assert tr["total_tokens"] == 640
        assert tr["parameter_count"] == 1000
        assert tr["trainable_parameter_count"] == 1000
        assert tr["val_metrics"] == {"val/loss": 1.5}
        assert tr["resumed_from_step"] == 5

    def test_val_metrics_none_becomes_empty_dict(self):
        s = format_run_summary(
            _cfg(), run_id="r", run_dir=None, train_result=_FakeTrainResult(), as_json=True
        )
        assert s["train_result"]["val_metrics"] == {}

    def test_env_snapshot_captures_rank_vars(self, monkeypatch):
        monkeypatch.setenv("RANK", "3")
        monkeypatch.setenv("WORLD_SIZE", "8")
        s = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=True)
        assert s["distributed_env"].get("RANK") == "3"
        assert s["distributed_env"].get("WORLD_SIZE") == "8"


class TestTextSummary:
    def test_planned_run_header_for_dry_run(self):
        text = format_run_summary(
            _cfg(), run_id="r", run_dir=None, dry_run=True, as_json=False
        )
        assert text.startswith("Planned run:")

    def test_run_summary_header_otherwise(self):
        text = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=False)
        assert text.startswith("Run summary:")

    def test_all_sections_present_as_headers(self):
        text = format_run_summary(_cfg(), run_id="rid", run_dir="/r/rid", as_json=False)
        for section in (
            "run:", "model:", "data:", "trainer:", "distributed:",
            "mlflow:", "logging:", "output:",
        ):
            assert f"\n  {section}" in text, f"missing text section {section}"
        assert "  run_id: rid" in text
        assert "  run_dir: /r/rid" in text

    def test_nested_mesh_renders_indented_not_repr(self):
        """distributed.mesh is a nested dict: each axis gets its own indented
        line; no one-line Python dict repr leaks into the report."""
        text = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=False)
        assert "    mesh:\n" in text
        assert "      data: 2\n" in text
        assert "      fsdp: 2\n" in text
        assert "{'data'" not in text

    def test_indentation_hierarchy(self):
        text = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=False)
        lines = text.splitlines()
        section_lines = [ln for ln in lines if ln == "  model:"]
        assert len(section_lines) == 1
        i = lines.index("  model:")
        assert lines[i + 1].startswith("    ")

    def test_train_result_rendered(self):
        text = format_run_summary(
            _cfg(),
            run_id="r",
            run_dir=None,
            train_result=_FakeTrainResult(resumed_from_step=7),
            as_json=False,
        )
        assert "  train_result:" in text
        assert "    final_step: 10" in text
        assert "    resumed_from_step: 7" in text

    def test_dry_run_resolution_rendered(self):
        text = format_run_summary(
            _cfg(),
            run_id="r",
            run_dir=None,
            dry_run=True,
            dry_run_result=_FakeDryRunResult(),
            as_json=False,
        )
        assert "  dry_run_resolution:" in text
        assert "    model_adapter: DummyGPTAdapter" in text
        assert "    steps_executed: 5" in text

    def test_empty_env_snapshot_section_omitted(self, monkeypatch):
        for var in (
            "RANK", "WORLD_SIZE", "LOCAL_RANK", "MASTER_ADDR", "MASTER_PORT",
            "JAX_PROCESS_ID", "JAX_NUM_PROCESSES", "JAX_COORDINATOR_ADDRESS",
            "JOB_COMPLETION_INDEX",
        ):
            monkeypatch.delenv(var, raising=False)
        text = format_run_summary(_cfg(), run_id="r", run_dir=None, as_json=False)
        assert "distributed_env:" not in text
