"""Weight-only int8 quantization (ops/quant.py).

Beyond-reference capability (the reference has no quantization); the
quality bar is self-imposed: quantized logits must track full-precision
logits closely on a real (tiny) GPT, and the Trainer's quantized eval
must land within a small relative loss delta.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.ops.quant import (
    QuantizedArray,
    dequantize_tree,
    quant_stats,
    quantize_array,
    quantize_tree,
)
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import Trainer


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _tiny_gpt():
    from llmtrain_tpu.models.gpt import GPT

    model = GPT(
        vocab_size=96,
        block_size=16,
        d_model=48,
        n_layers=2,
        n_heads=4,
        d_ff=96,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    from flax.core import meta as nn_meta

    return model, nn_meta.unbox(params)


class TestQuantizeArray:
    def test_per_element_error_bound(self):
        """Symmetric rounding: |w - deq| <= scale/2 per channel."""
        w = jax.random.normal(jax.random.PRNGKey(1), (64, 32), jnp.float32)
        qa = quantize_array(w, reduce_axes=(0,))
        err = jnp.abs(w - qa.dequantize())
        assert bool(jnp.all(err <= qa.scale / 2 + 1e-7))

    def test_zero_channel_is_exact(self):
        w = jnp.zeros((32, 8), jnp.float32)
        qa = quantize_array(w, reduce_axes=(0,))
        assert bool(jnp.all(qa.dequantize() == 0.0))
        assert not bool(jnp.any(jnp.isnan(qa.scale)))

    def test_array_protocol(self):
        w = jax.random.normal(jax.random.PRNGKey(2), (16, 8), jnp.bfloat16)
        qa = quantize_array(w, reduce_axes=(0,))
        assert qa.shape == (16, 8)
        assert qa.dtype == jnp.bfloat16
        assert qa.ndim == 2
        assert qa.q.dtype == jnp.int8
        # int8 codes + f32 scales beat the bf16 original only at larger
        # shapes; here just pin the accounting.
        assert qa.nbytes == 16 * 8 + 8 * 4
        assert qa.astype(jnp.float32).dtype == jnp.float32

    def test_jnp_consumes_via_jax_array(self):
        w = jax.random.normal(jax.random.PRNGKey(3), (32, 16), jnp.float32)
        qa = quantize_array(w, reduce_axes=(0,))
        x = jnp.ones((2, 32))
        direct = x @ qa.dequantize()
        via_protocol = jnp.dot(x, qa)
        np.testing.assert_allclose(direct, via_protocol, rtol=1e-6)

    def test_pytree_roundtrip_through_jit(self):
        w = jax.random.normal(jax.random.PRNGKey(4), (32, 16), jnp.float32)
        qa = quantize_array(w, reduce_axes=(0,))

        @jax.jit
        def f(q, x):
            return jnp.dot(x, q)

        y = f(qa, jnp.ones((2, 32)))
        np.testing.assert_allclose(
            y, jnp.ones((2, 32)) @ qa.dequantize(), rtol=1e-6
        )


class TestQuantizeTree:
    def test_selection_rules(self):
        _, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=1024)
        # Norm scales/biases stay float; big kernels and the embedding
        # become containers.
        assert isinstance(
            qt["token_embedding"]["embedding"], QuantizedArray
        )
        assert isinstance(
            qt["block_0"]["attn"]["qkv_proj"]["kernel"], QuantizedArray
        )
        assert not isinstance(qt["ln_f"]["scale"], QuantizedArray)
        assert not isinstance(
            qt["block_0"]["mlp_fc"]["bias"], QuantizedArray
        )

    def test_embedding_scales_per_row(self):
        _, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=1024)
        emb = qt["token_embedding"]["embedding"]
        assert emb.scale.shape == (96, 1)
        qkv = qt["block_0"]["attn"]["qkv_proj"]["kernel"]
        # (d_model, 3, heads, head_dim) kernel: d_model is the largest
        # leading axis (the contraction dim) -> per-output-unit scales.
        assert qkv.scale.shape == (1,) + qkv.shape[1:]
        out = qt["block_0"]["attn"]["out_proj"]["kernel"]
        # (heads, head_dim, d_model): head_dim is the largest leading axis.
        assert out.scale.shape == (out.shape[0], 1, out.shape[2])

    def test_min_size_gate(self):
        _, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=10**9)
        assert not any(
            isinstance(a, QuantizedArray)
            for a in jax.tree.leaves(
                qt, is_leaf=lambda x: isinstance(x, QuantizedArray)
            )
        )

    def test_qwen2_biases_stay_float(self):
        """Multi-dim qkv biases (Qwen2) pass the ndim gate but must stay
        float — they're the family's quality-sensitive additive params."""
        from llmtrain_tpu.registry.models import get_model_adapter

        cfg = _cfg(
            model={
                "name": "qwen2",
                "block_size": 8,
                "vocab_size": 64,
                "dropout": 0.0,
                "d_model": 64,
                "n_heads": 4,
                "d_ff": 128,
                "n_layers": 1,
                "tie_embeddings": False,
            }
        )
        adapter = get_model_adapter("qwen2")()
        model = adapter.build_model(cfg)
        from flax.core import meta as nn_meta

        params = nn_meta.unbox(
            model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32),
                deterministic=True,
            )["params"]
        )
        # min_size=1 forces every gate except the bias skip.
        qt = quantize_tree(params, min_size=1)
        att = qt["block_0"]["attn"]
        assert not isinstance(att["qkv_proj"]["bias"], QuantizedArray)
        assert isinstance(att["qkv_proj"]["kernel"], QuantizedArray)

    def test_double_quantize_raises(self):
        _, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=1024)
        with pytest.raises(ValueError, match="already quantized"):
            quantize_tree(qt)

    def test_dequantize_tree_restores_plain_arrays(self):
        _, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=1024)
        back = dequantize_tree(qt)
        leaves = jax.tree.leaves(back)
        assert all(not isinstance(a, QuantizedArray) for a in leaves)
        assert (
            back["block_0"]["mlp_fc"]["kernel"].dtype
            == params["block_0"]["mlp_fc"]["kernel"].dtype
        )

    def test_stats_compression(self):
        _, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=1024)
        stats = quant_stats(qt)
        assert stats["quantized_leaves"] > 0
        assert stats["quantized_params"] > 0.8 * stats["total_params"]
        # f32 weights -> int8 + f32 per-channel scales: ~4x on the
        # quantized fraction, >2.5x overall on this tiny model.
        assert stats["compression"] > 2.5
        # Unquantized tree reports 1.0.
        assert quant_stats(params)["compression"] == 1.0


class TestModelParity:
    def test_gpt_logits_track_full_precision(self):
        model, params = _tiny_gpt()
        ids = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0, 96)
        full = model.apply({"params": params}, ids, deterministic=True)
        qt = quantize_tree(params, min_size=1024)
        quant = jax.jit(
            lambda p, i: model.apply({"params": p}, i, deterministic=True)
        )(qt, ids)
        assert quant.shape == full.shape
        # Cosine similarity per position: int8 per-channel should be
        # well above 0.99 on random-init weights.
        f = np.asarray(full, np.float64).reshape(-1, 96)
        q = np.asarray(quant, np.float64).reshape(-1, 96)
        cos = (f * q).sum(-1) / (
            np.linalg.norm(f, axis=-1) * np.linalg.norm(q, axis=-1)
        )
        assert cos.min() > 0.99

    def test_generate_runs_quantized(self):
        from llmtrain_tpu.generation import generate

        model, params = _tiny_gpt()
        qt = quantize_tree(params, min_size=1024)
        out = generate(
            model,
            qt,
            np.array([[1, 2, 3]], np.int32),
            max_new_tokens=4,
            temperature=0.0,
        )
        tokens = out[0] if isinstance(out, tuple) else out
        assert np.asarray(tokens).shape[-1] == 7


def _zero_cache(dec):
    var_shapes = jax.eval_shape(
        lambda: dec.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
            deterministic=True,
        )
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), var_shapes["cache"]
    )


class TestKVCacheInt8:
    """model.extra.kv_cache_dtype: int8 — quantized decode cache."""

    def _models(self, **kw):
        from llmtrain_tpu.models.gpt import GPT

        base = dict(
            vocab_size=96, block_size=16, d_model=48, n_layers=2,
            n_heads=4, d_ff=96, dropout=0.0, tie_embeddings=True,
        )
        full = GPT(**base, **kw)
        quant = GPT(**base, kv_cache_dtype="int8", **kw)
        params = nn_meta_unbox(
            full.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
                "params"
            ]
        )
        return full, quant, params

    def test_cache_stored_int8_with_scales(self):
        _, quant, params = self._models()
        dec = quant.for_decoding(cache_len=8)
        cache = _zero_cache(dec)
        blk = cache["block_0"]["attn"]
        assert blk["cached_key"].dtype == jnp.int8
        assert blk["cached_value"].dtype == jnp.int8
        assert blk["key_scale"].shape == (1, 8, 4, 1)
        assert blk["key_scale"].dtype == jnp.float32

    def test_decode_logits_track_full_forward(self):
        full, quant, params = self._models()
        ids = jnp.asarray([[4, 7, 11, 23, 2]], jnp.int32)
        want = full.apply({"params": params}, ids, deterministic=True)[:, -1]
        dec = quant.for_decoding(cache_len=8)
        got, _ = dec.apply(
            {"params": params, "cache": _zero_cache(dec)},
            ids,
            deterministic=True,
            mutable=["cache"],
        )
        got = got[:, -1]
        f = np.asarray(want, np.float64).ravel()
        q = np.asarray(got, np.float64).ravel()
        cos = (f * q).sum() / (np.linalg.norm(f) * np.linalg.norm(q))
        assert cos > 0.999

    def test_rolling_window_int8_generates(self):
        """The ring-buffer path quantizes per slot: generation with a
        sliding window + int8 cache runs and emits valid tokens."""
        from llmtrain_tpu.generation import generate

        _, quant, params = self._models(sliding_window=4)
        out = generate(
            quant, params, np.asarray([[1, 2, 3]], np.int32),
            max_new_tokens=8, temperature=0.0, use_cache=True,
        )
        arr = np.asarray(out)
        assert arr.shape == (1, 11)
        assert ((arr >= 0) & (arr < 96)).all()

    def test_speculative_greedy_identical_with_int8_cache(self):
        """Speculative decoding's flagship invariant survives cache
        quantization: with BOTH models on int8 caches, greedy output is
        bit-identical to the target's own greedy decode (the draft only
        proposes; the target's quantized forward decides)."""
        from llmtrain_tpu.generation import generate
        from llmtrain_tpu.speculative import speculative_generate

        _, target, params = self._models()
        from llmtrain_tpu.models.gpt import GPT

        draft = GPT(
            vocab_size=96, block_size=16, d_model=32, n_layers=1,
            n_heads=2, d_ff=64, dropout=0.0, tie_embeddings=True,
            kv_cache_dtype="int8",
        )
        draft_params = nn_meta_unbox(
            draft.init(jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32))[
                "params"
            ]
        )
        prompt = np.asarray([[1, 2, 3]], np.int32)
        plain = generate(
            target, params, prompt, max_new_tokens=6, temperature=0.0,
        )
        spec = speculative_generate(
            target, params, draft, draft_params, prompt,
            max_new_tokens=6, gamma=3, temperature=0.0,
        )
        tokens = spec[0] if isinstance(spec, tuple) else spec
        assert np.asarray(tokens).tolist() == np.asarray(plain).tolist()

    def test_bad_dtype_rejected(self):
        from llmtrain_tpu.models.gpt import GPT

        m = GPT(
            vocab_size=96, block_size=16, d_model=48, n_layers=1,
            n_heads=4, d_ff=96, dropout=0.0, kv_cache_dtype="fp4",
        ).for_decoding(cache_len=8)
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            jax.eval_shape(
                lambda: m.init(
                    jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
                    deterministic=True,
                )
            )

    def test_adapter_extra_validated(self):
        from llmtrain_tpu.registry.models import get_model_adapter

        cfg = _cfg(
            model={
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 64,
                "dropout": 0.0,
                "d_model": 32,
                "n_heads": 2,
                "d_ff": 64,
                "n_layers": 1,
                "extra": {"kv_cache_dtype": "int8"},
            }
        )
        model = get_model_adapter("gpt")().build_model(cfg)
        assert model.kv_cache_dtype == "int8"
        bad = _cfg(
            model={
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 64,
                "dropout": 0.0,
                "d_model": 32,
                "n_heads": 2,
                "d_ff": 64,
                "n_layers": 1,
                "extra": {"kv_cache_dtype": "fp4"},
            }
        )
        with pytest.raises(ValueError, match="kv_cache_dtype"):
            get_model_adapter("gpt")().build_model(bad)


def nn_meta_unbox(tree):
    from flax.core import meta as nn_meta

    return nn_meta.unbox(tree)


def _cfg(**overrides):
    base = {
        "run": {"name": "q", "seed": 3},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "vocab_size": 64,
            "dropout": 0.0,
            "d_model": 32,
            "n_heads": 2,
            "d_ff": 64,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 8,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "warmup_steps": 0,
            "lr": 1e-3,
            "log_every_steps": 4,
            "eval_every_steps": 8,
            "save_every_steps": 8,
        },
        "mlflow": {"enabled": False},
    }
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


class TestTrainerEvalQuantized:
    def test_quantized_eval_loss_close(self):
        trainer = Trainer(_cfg(), None, NullTracker(), None)
        trainer.fit()
        full = trainer.evaluate()
        quant = trainer.evaluate(quantize="int8")
        assert full is not None and quant is not None
        rel = abs(quant["val/loss"] - full["val/loss"]) / full["val/loss"]
        assert rel < 0.05
        # Override semantics: state keeps full precision — a plain eval
        # afterwards reproduces the unquantized loss exactly.
        again = trainer.evaluate()
        assert again["val/loss"] == pytest.approx(full["val/loss"])

    def test_bad_mode_rejected(self):
        trainer = Trainer(_cfg(), None, NullTracker(), None)
        with pytest.raises(ValueError, match="unsupported quantize"):
            trainer.evaluate(quantize="int4")

    def test_lora_run_quantizes_merged_weights(self):
        """LoRA + quantize must measure the serving path quant(W + sBA):
        the quantized-eval override carries zeroed factors and a merged
        quantized base, not quant(W) + sBA."""
        cfg = _cfg(
            model={
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 64,
                "dropout": 0.0,
                "d_model": 64,
                "n_heads": 2,
                "d_ff": 128,
                "n_layers": 1,
                "extra": {"lora": {"rank": 2, "alpha": 4.0}},
            },
            trainer={"lr": 1e-2},
        )
        trainer = Trainer(cfg, None, NullTracker(), None)
        trainer.fit()
        full = trainer.evaluate()
        quant = trainer.evaluate(quantize="int8")
        assert full is not None and quant is not None
        rel = abs(quant["val/loss"] - full["val/loss"]) / full["val/loss"]
        assert 0 < rel < 0.05  # quantized for real, and close
