"""Config schema + loader tests (parity with reference tests/test_config.py)."""

import pytest
import yaml

from llmtrain_tpu.config import (
    ConfigLoadError,
    MeshConfig,
    RunConfig,
    load_and_validate_config,
)

MINIMAL = {
    "run": {"name": "t"},
    "model": {"name": "dummy_gpt"},
    "data": {"name": "dummy_text"},
    "trainer": {"max_steps": 10, "warmup_steps": 0},
}


def test_minimal_config_materializes_defaults():
    cfg = RunConfig.model_validate(MINIMAL)
    assert cfg.schema_version == 1
    assert cfg.run.seed == 1337
    assert cfg.run.device == "cpu"
    assert cfg.model.block_size == 256
    assert cfg.trainer.grad_accum_steps == 4
    assert cfg.distributed.enabled is False
    assert cfg.distributed.mesh.data == -1
    assert cfg.mlflow.enabled is True
    assert cfg.output.root_dir == "runs"


def test_extra_top_level_field_rejected():
    bad = dict(MINIMAL, bogus=1)
    with pytest.raises(Exception):
        RunConfig.model_validate(bad)


def test_extra_section_field_rejected():
    bad = {**MINIMAL, "model": {"name": "gpt", "not_a_field": 3}}
    with pytest.raises(Exception):
        RunConfig.model_validate(bad)


def test_plugin_extra_escape_hatch_accepted():
    cfg = RunConfig.model_validate(
        {
            **MINIMAL,
            "model": {"name": "gpt", "extra": {"custom_knob": 7}},
            "data": {"name": "dummy_text", "extra": {"n": 1}},
            "trainer": {"max_steps": 10, "warmup_steps": 0, "extra": {"keep_last_k": 2}},
        }
    )
    assert cfg.model.extra["custom_knob"] == 7
    assert cfg.trainer.extra["keep_last_k"] == 2


def test_d_model_head_divisibility_enforced():
    bad = {**MINIMAL, "model": {"name": "gpt", "d_model": 64, "n_heads": 3}}
    with pytest.raises(Exception, match="divisible"):
        RunConfig.model_validate(bad)


def test_d_ff_must_be_at_least_d_model():
    bad = {**MINIMAL, "model": {"name": "gpt", "d_model": 64, "n_heads": 2, "d_ff": 32}}
    with pytest.raises(Exception, match="d_ff"):
        RunConfig.model_validate(bad)


def test_warmup_cannot_exceed_max_steps():
    bad = {**MINIMAL, "trainer": {"max_steps": 10, "warmup_steps": 20}}
    with pytest.raises(Exception, match="warmup"):
        RunConfig.model_validate(bad)


def test_config_is_frozen():
    cfg = RunConfig.model_validate(MINIMAL)
    with pytest.raises(Exception):
        cfg.run.seed = 7  # type: ignore[misc]


def test_mesh_single_wildcard_only():
    with pytest.raises(Exception, match="wildcard"):
        MeshConfig(data=-1, tensor=-1)


def test_mesh_rejects_zero_axis():
    with pytest.raises(Exception):
        MeshConfig(tensor=0)


def test_mesh_pipeline_and_expert_axes_accepted():
    """pipeline is wired (gpt_pipeline stacks layers on it; whether the
    SELECTED model supports it is the Trainer's check, covered by
    tests/test_pipeline.py). expert is wired by MoE."""
    assert MeshConfig(pipeline=2).axis_sizes()["pipeline"] == 2
    assert MeshConfig(pipeline=1, expert=2).axis_sizes()["expert"] == 2


def test_device_literal_is_cpu_or_tpu():
    bad = {**MINIMAL, "run": {"name": "t", "device": "mps"}}
    with pytest.raises(Exception):
        RunConfig.model_validate(bad)


def test_loader_roundtrip(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text(yaml.safe_dump(MINIMAL))
    cfg, raw, resolved = load_and_validate_config(path)
    assert cfg.run.name == "t"
    assert raw == MINIMAL
    assert resolved["trainer"]["lr"] == pytest.approx(3e-4)
    assert resolved["distributed"]["mesh"]["fsdp"] == 1


def test_loader_missing_file(tmp_path):
    with pytest.raises(ConfigLoadError, match="not found"):
        load_and_validate_config(tmp_path / "nope.yaml")


def test_loader_invalid_yaml(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text("run: [unclosed")
    with pytest.raises(ConfigLoadError, match="not valid YAML"):
        load_and_validate_config(path)


def test_loader_non_mapping_root(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text("- a\n- b\n")
    with pytest.raises(ConfigLoadError, match="mapping"):
        load_and_validate_config(path)


def test_loader_validation_errors_are_structured(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text(yaml.safe_dump({**MINIMAL, "trainer": {"max_steps": -1}}))
    with pytest.raises(ConfigLoadError) as exc_info:
        load_and_validate_config(path)
    errs = exc_info.value.errors
    assert errs and any("trainer" in e["loc"] for e in errs)
