"""Config schema + loader tests (parity with reference tests/test_config.py)."""

import pytest
import yaml

from llmtrain_tpu.config import (
    ConfigLoadError,
    MeshConfig,
    RunConfig,
    load_and_validate_config,
)

MINIMAL = {
    "run": {"name": "t"},
    "model": {"name": "dummy_gpt"},
    "data": {"name": "dummy_text"},
    "trainer": {"max_steps": 10, "warmup_steps": 0},
}


def test_minimal_config_materializes_defaults():
    cfg = RunConfig.model_validate(MINIMAL)
    assert cfg.schema_version == 1
    assert cfg.run.seed == 1337
    assert cfg.run.device == "cpu"
    assert cfg.model.block_size == 256
    assert cfg.trainer.grad_accum_steps == 4
    assert cfg.distributed.enabled is False
    assert cfg.distributed.mesh.data == -1
    assert cfg.mlflow.enabled is True
    assert cfg.output.root_dir == "runs"


def test_resilience_defaults_inject_nothing():
    cfg = RunConfig.model_validate(MINIMAL)
    assert cfg.resilience.nonfinite_guard is False
    assert cfg.resilience.spike_detection is False
    assert cfg.resilience.max_consecutive_nonfinite == 25
    assert cfg.resilience.retry_attempts == 3
    faults = cfg.resilience.faults
    assert faults.nan_loss_at_step is None
    assert faults.sigterm_at_step is None
    assert faults.corrupt_checkpoint_at_step is None
    assert faults.dataset_load_failures == 0


def test_resilience_validation_bounds():
    with pytest.raises(Exception):
        RunConfig.model_validate(
            {**MINIMAL, "resilience": {"spike_factor": 1.0}}
        )
    with pytest.raises(Exception):
        RunConfig.model_validate(
            {**MINIMAL, "resilience": {"faults": {"corrupt_mode": "evaporate"}}}
        )


def test_extra_top_level_field_rejected():
    bad = dict(MINIMAL, bogus=1)
    with pytest.raises(Exception):
        RunConfig.model_validate(bad)


def test_extra_section_field_rejected():
    bad = {**MINIMAL, "model": {"name": "gpt", "not_a_field": 3}}
    with pytest.raises(Exception):
        RunConfig.model_validate(bad)


def test_plugin_extra_escape_hatch_accepted():
    cfg = RunConfig.model_validate(
        {
            **MINIMAL,
            "model": {"name": "gpt", "extra": {"custom_knob": 7}},
            "data": {"name": "dummy_text", "extra": {"n": 1}},
            "trainer": {"max_steps": 10, "warmup_steps": 0, "extra": {"keep_last_k": 2}},
        }
    )
    assert cfg.model.extra["custom_knob"] == 7
    assert cfg.trainer.extra["keep_last_k"] == 2


def test_d_model_head_divisibility_enforced():
    bad = {**MINIMAL, "model": {"name": "gpt", "d_model": 64, "n_heads": 3}}
    with pytest.raises(Exception, match="divisible"):
        RunConfig.model_validate(bad)


def test_d_ff_must_be_at_least_d_model():
    bad = {**MINIMAL, "model": {"name": "gpt", "d_model": 64, "n_heads": 2, "d_ff": 32}}
    with pytest.raises(Exception, match="d_ff"):
        RunConfig.model_validate(bad)


def test_warmup_cannot_exceed_max_steps():
    bad = {**MINIMAL, "trainer": {"max_steps": 10, "warmup_steps": 20}}
    with pytest.raises(Exception, match="warmup"):
        RunConfig.model_validate(bad)


def test_config_is_frozen():
    cfg = RunConfig.model_validate(MINIMAL)
    with pytest.raises(Exception):
        cfg.run.seed = 7  # type: ignore[misc]


def test_mesh_single_wildcard_only():
    with pytest.raises(Exception, match="wildcard"):
        MeshConfig(data=-1, tensor=-1)


def test_mesh_rejects_zero_axis():
    with pytest.raises(Exception):
        MeshConfig(tensor=0)


def test_mesh_pipeline_and_expert_axes_accepted():
    """pipeline is wired (gpt_pipeline stacks layers on it; whether the
    SELECTED model supports it is the Trainer's check, covered by
    tests/test_pipeline.py). expert is wired by MoE."""
    assert MeshConfig(pipeline=2).axis_sizes()["pipeline"] == 2
    assert MeshConfig(pipeline=1, expert=2).axis_sizes()["expert"] == 2


def test_device_literal_is_cpu_or_tpu():
    bad = {**MINIMAL, "run": {"name": "t", "device": "mps"}}
    with pytest.raises(Exception):
        RunConfig.model_validate(bad)


def test_loader_roundtrip(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text(yaml.safe_dump(MINIMAL))
    cfg, raw, resolved = load_and_validate_config(path)
    assert cfg.run.name == "t"
    assert raw == MINIMAL
    assert resolved["trainer"]["lr"] == pytest.approx(3e-4)
    assert resolved["distributed"]["mesh"]["fsdp"] == 1


def test_loader_missing_file(tmp_path):
    with pytest.raises(ConfigLoadError, match="not found"):
        load_and_validate_config(tmp_path / "nope.yaml")


def test_loader_invalid_yaml(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text("run: [unclosed")
    with pytest.raises(ConfigLoadError, match="not valid YAML"):
        load_and_validate_config(path)


def test_loader_non_mapping_root(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text("- a\n- b\n")
    with pytest.raises(ConfigLoadError, match="mapping"):
        load_and_validate_config(path)


def test_loader_validation_errors_are_structured(tmp_path):
    path = tmp_path / "c.yaml"
    path.write_text(yaml.safe_dump({**MINIMAL, "trainer": {"max_steps": -1}}))
    with pytest.raises(ConfigLoadError) as exc_info:
        load_and_validate_config(path)
    errs = exc_info.value.errors
    assert errs and any("trainer" in e["loc"] for e in errs)


class TestUnknownExtraWarnings:
    """config/extras.py: typos in extra dicts warn (never error)."""

    def _cfg(self, **extras):
        from llmtrain_tpu.config.schemas import RunConfig

        return RunConfig.model_validate(
            {
                "run": {"name": "x", "device": "cpu"},
                "model": {
                    "name": "gpt",
                    "block_size": 8,
                    "d_model": 16,
                    "n_layers": 1,
                    "n_heads": 4,
                    "d_ff": 32,
                    "vocab_size": 64,
                    "extra": {"tokenizer": "byte", **extras.get("model", {})},
                },
                "data": {"name": "dummy_text", "extra": extras.get("data", {})},
                "trainer": {
                    "max_steps": 1,
                    "micro_batch_size": 2,
                    "warmup_steps": 0,
                    "extra": extras.get("trainer", {}),
                },
                "mlflow": {"enabled": False},
            }
        )

    def test_clean_config_has_no_unknowns(self):
        from llmtrain_tpu.config.extras import unknown_extra_keys

        assert unknown_extra_keys(self._cfg()) == {}

    def test_typos_reported_per_section(self):
        from llmtrain_tpu.config.extras import unknown_extra_keys

        found = unknown_extra_keys(
            self._cfg(
                model={"los_impl": "chunked_ce"},
                data={"globz": ["x"]},
                trainer={"keep_last": 5},
            )
        )
        assert found == {
            "model.extra": ["los_impl"],
            "data.extra": ["globz"],
            "trainer.extra": ["keep_last"],
        }

    def test_known_keys_of_each_family(self):
        from llmtrain_tpu.config.extras import unknown_extra_keys

        cfg = self._cfg(model={"loss_impl": "chunked_ce", "ce_chunk": 64, "z_loss": 0.1})
        assert unknown_extra_keys(cfg) == {}

    def test_fused_kernel_knobs_are_known_extra_keys(self):
        from llmtrain_tpu.config.extras import unknown_extra_keys

        cfg = self._cfg(
            model={
                "loss_impl": "fused_ce",
                "fused_ce_block_t": 256,
                "fused_ce_block_v": 512,
                "fused_norm": True,
                "pallas_interpret": True,
            }
        )
        assert unknown_extra_keys(cfg) == {}

    def test_validate_cli_warns_but_exits_zero(self, tmp_path):
        import subprocess
        import sys

        import yaml

        cfg = self._cfg(model={"los_impl": "chunked_ce"})
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg.model_dump(mode="json"), sort_keys=False))
        proc = subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", "validate", "--config", str(cfg_path)],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        assert "los_impl" in proc.stderr and "warning" in proc.stderr


class TestActivationTierSpecs:
    """model.extra.activation_tiers strict-validates at config time
    (config/activation_tiers.py grammar; docs/perf.md "Activation tiers
    and host offload")."""

    def _model(self, *, n_layers=4, remat=False, **extra):
        return {
            **MINIMAL,
            "model": {
                "name": "gpt",
                "block_size": 8,
                "d_model": 16,
                "n_layers": n_layers,
                "n_heads": 4,
                "d_ff": 32,
                "vocab_size": 64,
                "remat": remat,
                "extra": extra,
            },
        }

    def test_valid_spec_validates_and_round_trips(self):
        cfg = RunConfig.model_validate(
            self._model(activation_tiers="offload:0-1,full:2-3")
        )
        assert cfg.model.extra["activation_tiers"] == "offload:0-1,full:2-3"
        again = RunConfig.model_validate(cfg.model_dump(mode="json"))
        assert again.model.extra["activation_tiers"] == "offload:0-1,full:2-3"

    @pytest.mark.parametrize(
        "spec",
        [
            "turbo:*",  # unknown tier
            "full:0-9",  # out of range for 4 layers
            "full:0-1,none:1",  # overlap
            "full:3-1",  # inverted range
            "full:*,none:0",  # '*' alongside other entries
            "",  # empty
        ],
    )
    def test_bad_specs_are_config_errors(self, spec):
        with pytest.raises(ValueError, match="activation_tiers"):
            RunConfig.model_validate(self._model(activation_tiers=spec))

    def test_remat_conflict_is_a_config_error(self):
        with pytest.raises(ValueError, match="conflicts"):
            RunConfig.model_validate(
                self._model(remat=True, activation_tiers="full:*")
            )

    def test_remat_alone_still_validates(self):
        # The deprecated flag keeps working (shim maps it at build time).
        cfg = RunConfig.model_validate(self._model(remat=True))
        assert cfg.model.remat is True

    def test_offload_without_pinned_host_is_not_a_config_error(self):
        """A backend without a pinned_host memory space downgrades offload
        at RUNTIME (models/activation_policy.py) — the same YAML must
        validate everywhere, so the schema never probes the backend."""
        cfg = RunConfig.model_validate(self._model(activation_tiers="offload:*"))
        assert cfg.model.extra["activation_tiers"] == "offload:*"

    def test_activation_tiers_is_a_known_extra_key(self):
        from llmtrain_tpu.config.extras import unknown_extra_keys

        cfg = RunConfig.model_validate(
            self._model(tokenizer="byte", activation_tiers="full:*")
        )
        assert unknown_extra_keys(cfg) == {}


class TestServingConfig:
    """serving: section (llmtrain_tpu/serving/, docs/serving.md)."""

    def test_defaults(self):
        cfg = RunConfig.model_validate(MINIMAL)
        assert cfg.serving.mode == "simple"  # opt-in: serve keeps its old path
        assert cfg.serving.policy == "paged"
        assert cfg.serving.max_batch_slots == 8
        assert cfg.serving.block_tokens == 16
        assert cfg.serving.num_blocks == 0  # derived from the slot count
        assert cfg.serving.prompt_buckets == []
        assert cfg.serving.batch_buckets == []
        assert cfg.serving.max_new_tokens_cap == 256
        # Fleet-tier knobs default OFF / to sane fleet sizes.
        assert cfg.serving.prefix_cache is False
        assert cfg.serving.prefill_chunk == 0
        assert cfg.serving.router.replicas == 2
        assert cfg.serving.router.affinity_weight == 4.0
        assert cfg.serving.router.fail_threshold == 3

    def test_continuous_with_buckets(self):
        cfg = RunConfig.model_validate(
            {
                **MINIMAL,
                "serving": {
                    "mode": "continuous",
                    "max_batch_slots": 4,
                    "prompt_buckets": [8, 16, 32],
                    "batch_buckets": [2, 4],
                },
            }
        )
        assert cfg.serving.mode == "continuous"
        assert cfg.serving.batch_buckets[-1] == cfg.serving.max_batch_slots

    def test_fleet_tier_knobs(self):
        cfg = RunConfig.model_validate(
            {
                **MINIMAL,
                "serving": {
                    "mode": "continuous",
                    "prefix_cache": True,
                    "prefill_chunk": 8,
                    "prompt_buckets": [8, 16],
                    "router": {"replicas": 3, "revive_sec": 5.0},
                },
            }
        )
        assert cfg.serving.prefix_cache is True
        assert cfg.serving.prefill_chunk == 8
        assert cfg.serving.router.replicas == 3
        assert cfg.serving.router.revive_sec == 5.0

    @pytest.mark.parametrize(
        "serving",
        [
            {"mode": "warp"},
            {"policy": "draft"},
            {"max_batch_slots": 0},
            {"block_tokens": 0},
            {"num_blocks": 1},  # 0 (derived) or >= 2
            {"prompt_buckets": [16, 8]},  # must be ascending
            {"prompt_buckets": [0, 8]},  # entries >= 1
            {"max_batch_slots": 4, "batch_buckets": [2, 8]},  # last != slots
            {"request_timeout_sec": 0},
            {"bogus": 1},
            {"prefill_chunk": -1},
            # Chunks must pad into an existing bucket.
            {"prefill_chunk": 64, "prompt_buckets": [8, 16]},
            # The speculative verify slab needs the whole prompt resident.
            {"policy": "speculative", "prefill_chunk": 8},
            {"router": {"replicas": 0}},
            {"router": {"fail_threshold": 0}},
            {"router": {"revive_sec": 0}},
            {"router": {"affinity_weight": -1.0}},
            {"router": {"bogus": 1}},  # strict: typos rejected
        ],
    )
    def test_rejections(self, serving):
        with pytest.raises(Exception):
            RunConfig.model_validate({**MINIMAL, "serving": serving})


class TestOverloadConfig:
    """serving.overload: section (serving/overload.py, docs/serving.md
    "Overload and SLOs")."""

    def test_defaults_off_with_sane_knobs(self):
        cfg = RunConfig.model_validate(MINIMAL)
        ov = cfg.serving.overload
        assert ov.enabled is False  # opt-in: admission stays unbounded
        assert ov.queue_cap == 64
        assert ov.default_deadline_ms == 0.0  # 0 = no implied deadline
        assert ov.classes == {"interactive": 4, "batch": 1}
        assert ov.default_class == "interactive"
        assert ov.class_rate_rps == {} and ov.class_burst == {}
        assert ov.client_rate_rps == 0.0  # per-client gate off
        assert ov.brownout_low_ms < ov.brownout_high_ms
        assert ov.brownout_enter_ticks >= 1 and ov.brownout_exit_ticks >= 1
        # Router-side overload knobs.
        assert cfg.serving.router.probe_timeout_sec == 10.0
        assert cfg.serving.router.retry_budget == 16
        assert cfg.serving.router.retry_window_sec == 10.0

    def test_full_overload_section_round_trips(self):
        cfg = RunConfig.model_validate(
            {
                **MINIMAL,
                "serving": {
                    "mode": "continuous",
                    "overload": {
                        "enabled": True,
                        "queue_cap": 32,
                        "default_deadline_ms": 2000.0,
                        "classes": {"interactive": 8, "batch": 1},
                        "class_rate_rps": {"batch": 50.0},
                        "class_burst": {"batch": 10},
                        "client_rate_rps": 20.0,
                        "brownout_high_ms": 800.0,
                        "brownout_low_ms": 200.0,
                        "brownout_max_new_tokens": 8,
                    },
                    "router": {"probe_timeout_sec": 2.5, "retry_budget": 4},
                },
            }
        )
        ov = cfg.serving.overload
        assert ov.enabled and ov.queue_cap == 32
        assert ov.classes["interactive"] == 8
        assert ov.class_rate_rps == {"batch": 50.0}
        assert cfg.serving.router.probe_timeout_sec == 2.5
        # And the controller builds straight off the section.
        from llmtrain_tpu.serving.overload import OverloadController

        ctl = OverloadController.from_config(ov)
        assert ctl.queue_cap == 32
        assert set(ctl.buckets) == {"batch"}

    @pytest.mark.parametrize(
        "overload",
        [
            {"queue_cap": 0},
            {"ewma_beta": 0.0},
            {"ewma_beta": 1.0},
            {"prior_wait_ms": -1.0},
            {"classes": {}},  # at least one class
            {"classes": {"interactive": 0}},  # weights >= 1
            {"default_class": "platinum"},  # must be a declared class
            {"class_rate_rps": {"platinum": 1.0}},  # unknown class
            {"class_rate_rps": {"batch": 0.0}},  # rates > 0
            {"class_burst": {"platinum": 4}},  # unknown class
            {"class_burst": {"batch": 0}},  # burst >= 1
            {"client_rate_rps": -1.0},
            {"client_burst": 0},
            {"max_tracked_clients": 0},
            # Hysteresis needs a real gap: low must sit BELOW high.
            {"brownout_high_ms": 100.0, "brownout_low_ms": 100.0},
            {"brownout_high_ms": 100.0, "brownout_low_ms": 200.0},
            {"brownout_enter_ticks": 0},
            {"brownout_exit_ticks": 0},
            {"brownout_max_new_tokens": 0},
            {"bogus": 1},  # strict: typos rejected
        ],
    )
    def test_rejections(self, overload):
        with pytest.raises(Exception):
            RunConfig.model_validate(
                {**MINIMAL, "serving": {"overload": overload}}
            )

    @pytest.mark.parametrize(
        "router",
        [
            {"probe_timeout_sec": 0},
            {"retry_budget": -1},
            {"retry_window_sec": 0},
        ],
    )
    def test_router_overload_knob_rejections(self, router):
        with pytest.raises(Exception):
            RunConfig.model_validate(
                {**MINIMAL, "serving": {"router": router}}
            )


class TestZeroConfig:
    """trainer.zero: section (parallel/sharding.py:opt_state_shardings,
    docs/perf.md "Sharded optimizer state")."""

    def test_defaults_off(self):
        cfg = RunConfig.model_validate(MINIMAL)
        assert cfg.trainer.zero.enabled is False
        assert cfg.trainer.zero.stage == 1
        assert cfg.trainer.zero.host_offload is False

    def test_enabled_with_stage_2(self):
        cfg = RunConfig.model_validate(
            {**MINIMAL, "trainer": {**MINIMAL["trainer"], "zero": {"enabled": True, "stage": 2}}}
        )
        assert cfg.trainer.zero.enabled is True
        assert cfg.trainer.zero.stage == 2

    @pytest.mark.parametrize(
        "zero",
        [
            {"stage": 3},  # only ZeRO-1/2 semantics exist here
            {"stage": 0},
            {"host_offload": True},  # offload requires enabled
            {"bogus": 1},
        ],
    )
    def test_rejections(self, zero):
        with pytest.raises(Exception):
            RunConfig.model_validate(
                {**MINIMAL, "trainer": {**MINIMAL["trainer"], "zero": zero}}
            )


class TestFleetConfig:
    """fleet: section (llmtrain_tpu/fleet/, docs/robustness.md "Fleet:
    many tenants, shared capacity")."""

    def test_defaults_are_an_empty_fleet(self):
        cfg = RunConfig.model_validate(MINIMAL)
        assert cfg.fleet.pool_devices == 2
        assert cfg.fleet.tenants == []
        assert cfg.fleet.preempt_grace_sec == 20.0

    def test_tenants_with_quotas_and_overrides(self):
        cfg = RunConfig.model_validate(
            {
                **MINIMAL,
                "fleet": {
                    "pool_devices": 4,
                    "tenants": [
                        {
                            "name": "a",
                            "priority": 2,
                            "min_devices": 1,
                            "max_devices": 4,
                            "overrides": {"trainer": {"lr": 0.001}},
                        },
                        {"name": "b"},
                    ],
                },
            }
        )
        assert cfg.fleet.tenants[0].max_devices == 4
        assert cfg.fleet.tenants[0].overrides["trainer"]["lr"] == 0.001
        assert cfg.fleet.tenants[1].min_devices == 1

    @pytest.mark.parametrize(
        "fleet",
        [
            # duplicate tenant names
            {"tenants": [{"name": "x"}, {"name": "x"}]},
            # quota below the floor
            {"tenants": [{"name": "x", "min_devices": 3, "max_devices": 2}]},
            # minimum can never fit the pool
            {"pool_devices": 2, "tenants": [{"name": "x", "min_devices": 4,
                                             "max_devices": 4}]},
            # tenant names become run ids / directory names
            {"tenants": [{"name": "../escape"}]},
            {"tenants": [{"name": ""}]},
            # unknown keys stay forbidden
            {"bogus": 1},
        ],
    )
    def test_rejections(self, fleet):
        with pytest.raises(Exception):
            RunConfig.model_validate({**MINIMAL, "fleet": fleet})
