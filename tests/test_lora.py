"""LoRA fine-tuning (models/lora.py): the family-agnostic adapter wrap.

Beyond-reference capability — the reference trains full-rank only. The
invariants that matter:

* wrapping changes NOTHING at init: the base subtree is bit-identical to
  a non-LoRA init of the same seed, and the zero-initialized B factor
  makes the merged forward equal the base forward exactly;
* the base is frozen end-to-end: gradients to base leaves are structural
  zeros and a real training run leaves every base leaf bit-identical
  while the loss still decreases through the factors;
* the optimizer state holds moments ONLY for the factors (the memory
  win), and still checkpoints/resumes exactly;
* the merged weights flow to inference (``inference_params``) and the
  whole thing composes with the sharded train step on a multi-device
  mesh (frozen base sharded by its logical axes, factors replicated).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.models.lora import (
    DEFAULT_TARGETS,
    LoraAdapter,
    LoraSpec,
    build_adapter,

    lora_mask,
    merge_lora,
)
from llmtrain_tpu.registry import initialize_registries

initialize_registries()


def _cfg(family="gpt", lora=None, trainer_over=None, mesh=None, **model_over):
    extra = {"tokenizer": "byte"}
    if lora is not None:
        extra["lora"] = lora
    model = {
        "name": family,
        "block_size": 16,
        "d_model": 32,
        "n_layers": 2,
        "n_heads": 2,
        "d_ff": 64,
        "vocab_size": 64,
        "dropout": 0.0,
        "extra": extra,
        **model_over,
    }
    raw = {
        "run": {"name": "lora-test", "device": "cpu", "seed": 11},
        "model": model,
        "data": {"name": "dummy_text"},
        "trainer": {
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "max_steps": 30,
            "warmup_steps": 0,
            "lr": 1e-2,
            "log_every_steps": 10,
            "eval_every_steps": 1000,
            "save_every_steps": 1000,
            **(trainer_over or {}),
        },
        "mlflow": {"enabled": False},
    }
    if mesh is not None:
        raw["distributed"] = {"mesh": mesh}
    return RunConfig.model_validate(raw)


def _batch(cfg):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.model.vocab_size, (2, cfg.model.block_size))
    return {
        "input_ids": jnp.asarray(ids, jnp.int32),
        "labels": jnp.asarray(np.roll(ids, -1, axis=1), jnp.int32),
    }


class TestSpec:
    def test_absent_means_off(self):
        assert LoraSpec.from_extra({}) is None
        assert not isinstance(build_adapter(_cfg()), LoraAdapter)

    def test_defaults(self):
        spec = LoraSpec.from_extra({"lora": {}})
        assert spec.rank == 8
        assert spec.alpha == 16.0
        assert spec.targets == DEFAULT_TARGETS
        assert spec.scale == 2.0

    @pytest.mark.parametrize(
        "raw, match",
        [
            ({"rank": 0}, "rank"),
            ({"alpha": 0}, "alpha"),
            ({"targets": []}, "targets"),
            # a bare YAML string must not explode into characters
            ({"targets": "qkv_proj"}, "targets"),
            ({"rnk": 4}, "unknown keys"),
            ("r8", "mapping"),
        ],
    )
    def test_invalid_specs_raise(self, raw, match):
        with pytest.raises(ValueError, match=match):
            LoraSpec.from_extra({"lora": raw})

    def test_unmatched_targets_list_modules(self):
        cfg = _cfg(lora={"targets": ["nonexistent_proj"]})
        adapter = build_adapter(cfg)
        model = adapter.build_model(cfg)
        with pytest.raises(ValueError, match="mlp_fc"):
            adapter.init_params(model, cfg, jax.random.key(0))


class TestInit:
    def test_base_subtree_matches_unwrapped_init(self):
        cfg0, cfgL = _cfg(), _cfg(lora={"rank": 4})
        rng = jax.random.key(3)
        p0 = build_adapter(cfg0).init_params(
            build_adapter(cfg0).build_model(cfg0), cfg0, rng
        )
        adapter = build_adapter(cfgL)
        pL = adapter.init_params(adapter.build_model(cfgL), cfgL, rng)
        for a, b in zip(
            jax.tree.leaves(nn_meta.unbox(p0)),
            jax.tree.leaves(nn_meta.unbox(pL["base"])),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_zero_delta_at_init(self):
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        params = adapter.init_params(model, cfgL, jax.random.key(3))
        merged = merge_lora(params["base"], params["lora"], adapter.spec)
        for a, b in zip(
            jax.tree.leaves(nn_meta.unbox(params["base"])),
            jax.tree.leaves(nn_meta.unbox(merged)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_factor_shapes_default_targets(self):
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        params = adapter.init_params(
            adapter.build_model(cfgL), cfgL, jax.random.key(0)
        )
        lora = params["lora"]
        # qkv_proj kernel (32, 3, 2, 16): in=32, out=96
        assert lora["block_0"]["attn"]["qkv_proj"]["kernel"]["a"].shape == (32, 4)
        assert lora["block_0"]["attn"]["qkv_proj"]["kernel"]["b"].shape == (4, 96)
        # out_proj kernel (2, 16, 32): in=(2,16)=32, out=32
        assert lora["block_0"]["attn"]["out_proj"]["kernel"]["a"].shape == (32, 4)
        assert lora["block_0"]["attn"]["out_proj"]["kernel"]["b"].shape == (4, 32)

    def test_mlp_and_embedding_targets(self):
        cfgL = _cfg(lora={"targets": ["mlp_fc", "token_embedding"]})
        adapter = build_adapter(cfgL)
        params = adapter.init_params(
            adapter.build_model(cfgL), cfgL, jax.random.key(0)
        )
        lora = params["lora"]
        assert lora["block_0"]["mlp_fc"]["kernel"]["a"].shape == (32, 8)
        assert lora["token_embedding"]["embedding"]["a"].shape == (64, 8)

    def test_eval_shape_compatible(self):
        """_abstract_params (checkpoint restore) eval_shapes init_params."""
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        abstract = jax.eval_shape(
            lambda rng: adapter.init_params(model, cfgL, rng), jax.random.key(0)
        )
        assert "base" in abstract and "lora" in abstract


class TestFrozenBase:
    def test_base_gradients_are_zero(self):
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        params = adapter.init_params(model, cfgL, jax.random.key(3))
        batch = _batch(cfgL)

        def loss(p):
            value, _ = adapter.compute_loss(model, p, batch)
            return value

        grads = jax.grad(loss)(params)
        base_total = sum(
            float(jnp.abs(g).sum())
            for g in jax.tree.leaves(nn_meta.unbox(grads["base"]))
        )
        lora_total = sum(
            float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads["lora"])
        )
        assert base_total == 0.0
        assert lora_total > 0.0

    def test_training_moves_loss_not_base(self):
        """The strongest invariant in one run: loss decreases through the
        factors while every base leaf stays bit-identical."""
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        cfgL = _cfg(lora={"rank": 4})
        trainer = Trainer(cfgL, run_dir=None, tracker=NullTracker())
        before = jax.device_get(nn_meta.unbox(trainer.state.params)["base"])
        result = trainer.fit()
        after = jax.device_get(nn_meta.unbox(trainer.state.params)["base"])
        assert result.final_loss < result.first_step_loss
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(a, b)
        assert result.trainable_parameter_count < result.parameter_count
        # factors only: 2 blocks x (qkv a/b + out a/b)
        assert result.trainable_parameter_count == 2 * (
            32 * 4 + 4 * 96 + 32 * 4 + 4 * 32
        )

    def test_optimizer_state_holds_no_base_moments(self):
        from llmtrain_tpu.training.optimizer import build_optimizer

        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        params = adapter.init_params(model, cfgL, jax.random.key(0))
        tx = adapter.wrap_optimizer(build_optimizer(cfgL.trainer))
        opt_state = tx.init(params)
        n_lora = sum(x.size for x in jax.tree.leaves(params["lora"]))
        moment_leaves = [
            x for x in jax.tree.leaves(nn_meta.unbox(opt_state)) if x.ndim >= 1
        ]
        # AdamW mu+nu over the factor subtree only.
        assert sum(x.size for x in moment_leaves) == 2 * n_lora


class TestLifecycle:
    def test_checkpoint_resume_parity(self, tmp_path):
        """Split run (save at 15, resume to 30) == continuous 30-step run."""
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        cfg30 = _cfg(lora={"rank": 4}, trainer_over={"save_every_steps": 15})
        continuous = Trainer(cfg30, run_dir=None, tracker=NullTracker()).fit()

        run_dir = tmp_path / "run"
        run_dir.mkdir()
        Trainer(cfg30, run_dir=run_dir, tracker=NullTracker()).fit(
            max_steps_override=15
        )

        resumed_trainer = Trainer(cfg30, run_dir=None, tracker=NullTracker())
        resumed = resumed_trainer.fit(resume_from=str(run_dir / "checkpoints"))
        assert resumed.resumed_from_step == 15
        assert resumed.final_loss == pytest.approx(
            continuous.final_loss, abs=1e-5
        )

    def test_inference_params_merge(self):
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        params = adapter.init_params(model, cfgL, jax.random.key(3))
        # Give B a nonzero value so the merge is not trivially the base.
        params["lora"]["block_0"]["attn"]["qkv_proj"]["kernel"]["b"] = (
            jnp.ones_like(
                params["lora"]["block_0"]["attn"]["qkv_proj"]["kernel"]["b"]
            )
        )
        merged = adapter.inference_params(params)
        a = params["lora"]["block_0"]["attn"]["qkv_proj"]["kernel"]["a"]
        b = params["lora"]["block_0"]["attn"]["qkv_proj"]["kernel"]["b"]
        want = nn_meta.unbox(params["base"])["block_0"]["attn"]["qkv_proj"][
            "kernel"
        ] + ((a @ b) * adapter.spec.scale).reshape(32, 3, 2, 16)
        got = nn_meta.unbox(merged)["block_0"]["attn"]["qkv_proj"]["kernel"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
        # and the merged forward differs from the base forward now
        batch = _batch(cfgL)
        base_adapter = build_adapter(_cfg())
        l_base, _ = base_adapter.compute_loss(model, params["base"], batch)
        l_merged, _ = base_adapter.compute_loss(model, merged, batch)
        assert float(l_base) != float(l_merged)

    def test_plain_checkpoint_with_lora_config_fails_loudly(self):
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        base_params = build_adapter(_cfg()).init_params(
            model, _cfg(), jax.random.key(0)
        )
        with pytest.raises(ValueError, match="base/lora"):
            adapter.compute_loss(model, base_params, _batch(cfgL))

    def test_llama_family_wraps(self):
        cfgL = _cfg(family="llama", lora={"rank": 4})
        adapter = build_adapter(cfgL)
        model = adapter.build_model(cfgL)
        params = adapter.init_params(model, cfgL, jax.random.key(0))
        assert "qkv_proj" in str(jax.tree_util.tree_structure(params["lora"]))
        loss, _ = adapter.compute_loss(model, params, _batch(cfgL))
        assert np.isfinite(float(loss))

    def test_dry_run_validates_the_lora_program(self):
        """--dry-run must build the SAME adapter train will: a bad
        targets list fails at the dry run, not mid-real-run; a good
        LoRA config dry-runs the merged forward."""
        from llmtrain_tpu.training.dry_run import run_dry_run

        with pytest.raises(ValueError, match="matched no parameters"):
            run_dry_run(_cfg(lora={"targets": ["qkv_porj"]}))
        result = run_dry_run(_cfg(lora={"rank": 4}))
        assert result.steps_executed >= 1

    def test_pipeline_family_rejected(self):
        cfg = _cfg(family="gpt_pipeline", lora={"rank": 4})
        with pytest.raises(ValueError, match="pipeline"):
            build_adapter(cfg)


class TestSharded:
    def test_train_step_on_fsdp_tensor_mesh(self):
        """Frozen base shards by its logical axes; factors replicate; the
        sharded step runs and the loss is finite (8 virtual CPU devices,
        tests/conftest.py)."""
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        mesh_cfg = _cfg(
            lora={"rank": 4},
            trainer_over={"max_steps": 3},
            mesh={"data": 2, "fsdp": 2, "tensor": 2},
        )
        trainer = Trainer(mesh_cfg, run_dir=None, tracker=NullTracker())
        result = trainer.fit()
        assert np.isfinite(result.final_loss)
        assert result.trainable_parameter_count == 1536


class TestMask:
    def test_mask_aligns_with_unboxed_leaves(self):
        cfgL = _cfg(lora={"rank": 4})
        adapter = build_adapter(cfgL)
        params = adapter.init_params(
            adapter.build_model(cfgL), cfgL, jax.random.key(0)
        )
        mask = lora_mask(params)
        unboxed = nn_meta.unbox(params)
        assert len(jax.tree.leaves(mask)) == len(jax.tree.leaves(unboxed))
        flags = jax.tree.leaves(mask)
        assert any(flags) and not all(flags)


def test_cli_validate_rejects_bad_spec(tmp_path):
    import subprocess
    import sys

    cfg_file = tmp_path / "bad.yaml"
    cfg_file.write_text(
        """
run: {name: x, device: cpu}
model:
  name: gpt
  block_size: 16
  d_model: 32
  n_layers: 1
  n_heads: 2
  d_ff: 64
  vocab_size: 64
  extra: {tokenizer: byte, lora: {rank: 0}}
data: {name: dummy_text}
trainer: {max_steps: 10, warmup_steps: 0}
mlflow: {enabled: false}
"""
    )
    proc = subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", "validate", "--config", str(cfg_file)],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 2
    assert "rank" in proc.stderr
