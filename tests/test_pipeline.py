"""Pipeline parallelism: GPipe executor + gpt_pipeline model.

New capability beyond the reference (SURVEY §2.3: PP absent there). The
technique mirrors the rest of the suite: a real 8-virtual-device CPU mesh
(conftest) exercises the actual shard_map/ppermute schedule in one
process, with equivalence against the sequential application of the same
stacked params as the correctness oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.parallel.pipeline import gpipe_apply, pipeline_degree
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking.base import NullTracker
from llmtrain_tpu.training.trainer import Trainer


def _mesh(pipeline=4, data=2):
    devs = np.array(jax.devices()[: pipeline * data]).reshape(pipeline, data)
    return Mesh(devs, ("pipeline", "data"))


def _stage_fn(p, h):
    def layer(h, lp):
        return jnp.tanh(h @ lp[0] + lp[1]), None

    h, _ = jax.lax.scan(layer, h, (p["w"], p["b"]))
    return h


def _stack_params(L=8, D=16, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    return {
        "w": jax.random.normal(k1, (L, D, D)) * 0.1,
        "b": jax.random.normal(k2, (L, D)) * 0.1,
    }


class TestGPipeExecutor:
    def test_forward_matches_sequential(self):
        params = _stack_params()
        x = jax.random.normal(jax.random.key(2), (8, 4, 16))
        ref = _stage_fn(params, x)
        mesh = _mesh()
        with mesh:
            y = jax.jit(
                lambda p, x: gpipe_apply(_stage_fn, p, x, mesh, n_microbatches=4)
            )(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    @pytest.mark.parametrize("n_microbatches", [1, 2, 8])
    def test_microbatch_counts(self, n_microbatches):
        params = _stack_params(seed=3)
        x = jax.random.normal(jax.random.key(4), (16, 4, 16))
        ref = _stage_fn(params, x)
        mesh = _mesh()
        with mesh:
            y = jax.jit(
                lambda p, x: gpipe_apply(
                    _stage_fn, p, x, mesh, n_microbatches=n_microbatches
                )
            )(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    def test_gradients_match_sequential(self):
        params = _stack_params(seed=5)
        x = jax.random.normal(jax.random.key(6), (8, 4, 16))
        mesh = _mesh()

        def loss_pipe(p):
            return (gpipe_apply(_stage_fn, p, x, mesh, n_microbatches=4) ** 2).sum()

        def loss_ref(p):
            return (_stage_fn(p, x) ** 2).sum()

        with mesh:
            g_pipe = jax.jit(jax.grad(loss_pipe))(params)
        g_ref = jax.grad(loss_ref)(params)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_degree_one_is_sequential(self):
        params = _stack_params(seed=7)
        x = jax.random.normal(jax.random.key(8), (4, 4, 16))
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("pipeline", "data"))
        with mesh:
            y = gpipe_apply(_stage_fn, params, x, mesh, n_microbatches=4)
        np.testing.assert_allclose(np.asarray(y), np.asarray(_stage_fn(params, x)), atol=1e-6)

    def test_pipeline_degree_helper(self):
        assert pipeline_degree(None) == 1
        assert pipeline_degree(_mesh()) == 4


def _pp_cfg(**overrides):
    model = {
        "name": "gpt_pipeline",
        "block_size": 16,
        "d_model": 32,
        "n_layers": 4,
        "n_heads": 4,
        "d_ff": 64,
        "dropout": 0.0,
        "vocab_size": 32,
        "extra": {"tokenizer": "byte", "pipeline_microbatches": 2},
    }
    model.update(overrides.pop("model", {}))
    raw = {
        "run": {"name": "pp", "seed": 0, "device": "cpu"},
        "model": model,
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 20,
            "micro_batch_size": 8,
            "grad_accum_steps": 2,
            "warmup_steps": 5,
            "log_every_steps": 10,
            "eval_every_steps": 10,
            "save_every_steps": 100,
        },
        "distributed": {"enabled": False, "mesh": {"pipeline": 4, "data": 2}},
    }
    raw.update(overrides)
    return RunConfig.model_validate(raw)


class TestPipelineGPT:
    def setup_method(self):
        initialize_registries()

    def _build(self, cfg):
        from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

        adapter = PipelineGPTAdapter()
        model = adapter.build_model(cfg)
        params = adapter.init_params(model, cfg, jax.random.key(0))
        return adapter, model, params

    def test_pipelined_forward_matches_sequential(self):
        cfg = _pp_cfg()
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, 32)
        ref = model.apply({"params": params}, tokens)  # no mesh -> sequential
        mesh = _mesh()
        with mesh:
            out = jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_pipelined_grads_match_sequential(self):
        cfg = _pp_cfg()
        adapter, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(2), (8, 16), 0, 32)
        batch = {
            "input_ids": tokens,
            "labels": tokens,
            "attention_mask": jnp.ones_like(tokens),
        }

        def loss(p):
            ls, tk = adapter.compute_loss_components(model, p, batch)
            return jnp.sum(ls) / jnp.sum(tk)

        g_ref = jax.grad(loss)(params)
        with _mesh():
            g_pp = jax.jit(jax.grad(loss))(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_indivisible_real_batch_raises_on_pipeline_mesh(self):
        """A real batch that cannot engage the pipeline is an ERROR on a
        multi-stage mesh — 'running without pipeline parallelism' would
        materialize every stage's layers on every device (an OOM at real
        sizes, previously reached via a warning; VERDICT r2 weak #5)."""
        cfg = _pp_cfg()
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(3), (6, 16), 0, 32)
        with _mesh():
            with pytest.raises(ValueError, match="not divisible"):
                model.apply({"params": params}, tokens)

    def test_batch_one_probe_still_falls_back(self):
        """The batch-1 param-init probe (models/base.py) must keep tracing
        sequentially on a pipeline mesh."""
        cfg = _pp_cfg()
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(3), (1, 16), 0, 32)
        ref = model.apply({"params": params}, tokens)
        with _mesh():
            out = model.apply({"params": params}, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    @pytest.mark.parametrize("attention", ["dense", "flash"])
    def test_masked_pipelined_matches_sequential(self, attention):
        """Padding masks inside pipelined attention: the executor hands
        each stage tick its microbatch's mask slice, so pipelined and
        sequential execution agree on padded batches."""
        cfg = _pp_cfg(model={"attention": attention})
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, 32)
        lens = np.asarray([16, 9, 16, 3, 12, 16, 7, 16])
        mask = jnp.asarray(
            (np.arange(16)[None, :] < lens[:, None]).astype(np.int32)
        )
        ref = model.apply({"params": params}, tokens, mask)
        mesh = _mesh()
        with mesh:
            out = jax.jit(
                lambda p, t, m: model.apply({"params": p}, t, m)
            )(params, tokens, mask)
        # Compare valid rows (padded rows' logits are zeroed-garbage by
        # contract; the loss masks them).
        valid = np.asarray(mask)[:, :, None]
        np.testing.assert_allclose(
            np.asarray(out) * valid, np.asarray(ref) * valid, atol=1e-5
        )

    @pytest.mark.parametrize("attention", ["dense", "flash"])
    def test_masked_pipelined_grads_match_sequential(self, attention):
        cfg = _pp_cfg(model={"attention": attention})
        adapter, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(6), (8, 16), 0, 32)
        lens = np.asarray([16, 9, 16, 3, 12, 16, 7, 14])
        mask = jnp.asarray(
            (np.arange(16)[None, :] < lens[:, None]).astype(np.int32)
        )
        batch = {"input_ids": tokens, "labels": tokens, "attention_mask": mask}

        def loss(p):
            ls, tk = adapter.compute_loss_components(model, p, batch)
            return jnp.sum(ls) / jnp.sum(tk)

        g_ref = jax.grad(loss)(params)
        with _mesh():
            g_pp = jax.jit(jax.grad(loss))(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    @pytest.mark.parametrize("attention", ["dense", "flash"])
    def test_windowed_pipelined_matches_sequential(self, attention):
        """sliding_window flows into every stage's attention: the
        pipelined result equals the single-device stack, and the window
        actually binds (differs from full causal)."""
        cfg = _pp_cfg(
            model={
                "attention": attention,
                "extra": {
                    "tokenizer": "byte",
                    "pipeline_microbatches": 2,
                    "sliding_window": 5,
                },
            }
        )
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(7), (8, 16), 0, 32)
        ref = model.apply({"params": params}, tokens)
        with _mesh():
            out = jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        full = model.clone(sliding_window=0).apply({"params": params}, tokens)
        assert np.abs(np.asarray(full) - np.asarray(ref)).max() > 1e-4

    def test_assume_packed_drops_mask(self):
        """assume_packed ignores the mask operand entirely — identical
        output with and without one (all-ones equivalence is the packed
        contract)."""
        cfg = _pp_cfg(model={"extra": {"tokenizer": "byte",
                                       "pipeline_microbatches": 2,
                                       "assume_packed": True}})
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(7), (4, 16), 0, 32)
        half = jnp.asarray(
            (np.arange(16)[None, :] < 8).astype(np.int32)
        ) * jnp.ones((4, 1), jnp.int32)
        a = model.apply({"params": params}, tokens)
        b = model.apply({"params": params}, tokens, half)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("attention", ["dense", "flash"])
    def test_gqa_pipelined_matches_sequential(self, attention):
        """Grouped-query attention (split stacked q/kv kernels) under the
        pipeline schedule equals sequential execution; flash consumes the
        narrow K/V natively."""
        cfg = _pp_cfg(
            model={
                "attention": attention,
                "extra": {"tokenizer": "byte", "pipeline_microbatches": 2,
                          "n_kv_heads": 2},
            }
        )
        _, model, params = self._build(cfg)
        assert "q_kernel" in params and "qkv_kernel" not in params
        tokens = jax.random.randint(jax.random.key(9), (8, 16), 0, 32)
        ref = model.apply({"params": params}, tokens)
        with _mesh():
            out = jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.slow  # budget: tier-1 sibling test_pp_tp_compose_matches_sequential; GQA compose rides test-all
    def test_gqa_pp_tp_compose_matches_sequential(self):
        """GQA under pipeline x tensor: the split q/kv sharding specs
        shard K/V heads over the tensor axis; forward equals sequential
        execution of the same params."""
        cfg = _pp_cfg(
            model={
                "extra": {"tokenizer": "byte", "pipeline_microbatches": 2,
                          "n_kv_heads": 2},
            },
            distributed={"enabled": False,
                         "mesh": {"pipeline": 2, "tensor": 2, "data": 2}},
        )
        _, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(11), (8, 16), 0, 32)
        ref = model.apply({"params": params}, tokens)
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = Mesh(devs, ("pipeline", "tensor", "data"))
        with mesh:
            out = jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gqa_pp_tp_kv_heads_must_divide(self):
        """A tensor axis bigger than n_kv_heads fails at startup with a
        clear message (validate_mesh), not an opaque sharding error."""
        cfg = _pp_cfg(
            model={"extra": {"tokenizer": "byte", "pipeline_microbatches": 2,
                             "n_kv_heads": 1}},
            distributed={"enabled": False,
                         "mesh": {"pipeline": 2, "tensor": 2, "data": 2}},
        )
        with pytest.raises(ValueError, match="n_kv_heads"):
            Trainer(cfg, None, NullTracker())

    def test_batch_divisor_hook(self):
        from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

        cfg = _pp_cfg()
        adapter = PipelineGPTAdapter()
        # {pipeline: 4, data: 2} x microbatches 2 -> rows must divide 4.
        assert adapter.batch_divisor(cfg, _mesh()) == 4
        assert adapter.batch_divisor(cfg, None) == 1

    def test_validate_mesh_rejects_indivisible_training_batch(self):
        trainer_cfg = {
            "max_steps": 2,
            "micro_batch_size": 3,  # not divisible by microbatches (2)
            "grad_accum_steps": 1,
            "warmup_steps": 0,
        }
        cfg = _pp_cfg(trainer=trainer_cfg)
        with pytest.raises(ValueError, match="pipeline_microbatches"):
            Trainer(cfg, None, NullTracker())

    def test_eval_pads_to_divisor_and_matches_sequential(self):
        """Eval batches are padded up to data_shards × microbatches
        (zero-masked rows are exact under token-weighted aggregation), so
        the eval pass runs the pipeline schedule — the dummy val set (25
        examples) is NOT divisible by 4, and an unpadded batch would now
        raise (see test_indivisible_real_batch_raises_on_pipeline_mesh).
        The padded pipelined val loss equals sequential eval of the same
        (untrained, same-seed) params."""
        pp = Trainer(_pp_cfg(), None, NullTracker())
        seq = Trainer(
            _pp_cfg(distributed={"enabled": False, "mesh": {"data": 8}}),
            None,
            NullTracker(),
        )
        m_pp = pp._evaluate(step=0, max_steps=1)
        m_seq = seq._evaluate(step=0, max_steps=1)
        assert m_pp is not None and m_seq is not None
        assert abs(m_pp["val/loss"] - m_seq["val/loss"]) < 1e-5

    def test_trainer_loss_decreases_on_pipeline_mesh(self):
        trainer = Trainer(_pp_cfg(), None, NullTracker())
        result = trainer.fit()
        assert result.first_step_loss is not None
        assert result.final_loss < result.first_step_loss
        assert result.final_val_loss is not None

    def test_layer_params_sharded_over_pipeline(self):
        """Stacked block params must actually shard their leading dim."""
        trainer = Trainer(_pp_cfg(), None, NullTracker())
        from flax.core import meta as nn_meta

        params = nn_meta.unbox(trainer.state.params)
        qkv = params["qkv_kernel"]
        spec = qkv.sharding.spec
        assert spec and spec[0] == "pipeline", spec

    def test_plain_gpt_rejects_pipeline_mesh(self):
        cfg = _pp_cfg(model={"name": "gpt", "extra": {"tokenizer": "byte"}})
        with pytest.raises(ValueError, match="does not stack its layers"):
            Trainer(cfg, None, NullTracker())

    def test_layers_must_divide_stages(self):
        cfg = _pp_cfg(model={"n_layers": 3})
        with pytest.raises(ValueError, match="pipeline stages"):
            Trainer(cfg, None, NullTracker())

    def test_rejects_dropout(self):
        from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

        cfg = _pp_cfg(model={"dropout": 0.1})
        with pytest.raises(ValueError, match="dropout"):
            PipelineGPTAdapter().build_model(cfg)

    def test_rejects_fsdp_sharding(self):
        cfg = _pp_cfg(
            distributed={"enabled": False, "mesh": {"pipeline": 4, "fsdp": 2}}
        )
        with pytest.raises(ValueError, match="fsdp"):
            Trainer(cfg, None, NullTracker()).fit()

    def test_pp_tp_compose_matches_sequential(self):
        """DP x PP x TP: {pipeline: 2, tensor: 2, data: 2} — stage params
        shard whole heads / mlp width over tensor, with explicit Megatron
        row-parallel psums inside the stage. Forward and grads must match
        sequential execution of the same params."""
        cfg = _pp_cfg(
            distributed={
                "enabled": False,
                "mesh": {"pipeline": 2, "tensor": 2, "data": 2},
            }
        )
        adapter, model, params = self._build(cfg)
        tokens = jax.random.randint(jax.random.key(9), (8, 16), 0, 32)
        batch = {
            "input_ids": tokens,
            "labels": tokens,
            "attention_mask": jnp.ones_like(tokens),
        }
        ref = model.apply({"params": params}, tokens)
        mesh = Mesh(
            np.array(jax.devices()[:8]).reshape(2, 2, 2),
            ("pipeline", "tensor", "data"),
        )
        with mesh:
            out = jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

        def loss(p):
            ls, tk = adapter.compute_loss_components(model, p, batch)
            return jnp.sum(ls) / jnp.sum(tk)

        g_ref = jax.grad(loss)(params)
        with mesh:
            g_pp = jax.jit(jax.grad(loss))(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_pp_tp_trainer_loss_decreases(self):
        cfg = _pp_cfg(
            distributed={
                "enabled": False,
                "mesh": {"pipeline": 2, "tensor": 2, "data": 2},
            }
        )
        result = Trainer(cfg, None, NullTracker()).fit()
        assert result.final_loss < result.first_step_loss


class TestInterleavedSchedule:
    """virtual_chunks > 1: the Megatron-style interleaved schedule, where
    each stage holds strided layer chunks and microbatches loop the ring
    v times. Correctness oracle: sequential application of the same
    stacked params (global layer order must be preserved through the
    shard permutation and per-round chunk selection)."""

    @pytest.mark.parametrize("v,n_micro,L", [(2, 4, 8), (2, 8, 8), (4, 4, 16)])
    def test_forward_matches_sequential(self, v, n_micro, L):
        params = _stack_params(L=L, seed=11)
        x = jax.random.normal(jax.random.key(12), (16, 4, 16))
        ref = _stage_fn(params, x)
        mesh = _mesh()
        with mesh:
            y = jax.jit(
                lambda p, x: gpipe_apply(
                    _stage_fn, p, x, mesh, n_microbatches=n_micro, virtual_chunks=v
                )
            )(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    def test_gradients_match_sequential(self):
        params = _stack_params(L=8, seed=13)
        x = jax.random.normal(jax.random.key(14), (8, 4, 16))
        mesh = _mesh()

        def loss_pipe(p):
            return (
                gpipe_apply(
                    _stage_fn, p, x, mesh, n_microbatches=4, virtual_chunks=2
                )
                ** 2
            ).sum()

        def loss_ref(p):
            return (_stage_fn(p, x) ** 2).sum()

        with mesh:
            g_pipe = jax.jit(jax.grad(loss_pipe))(params)
        g_ref = jax.grad(loss_ref)(params)
        for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_too_few_microbatches_raises(self):
        params = _stack_params(L=8, seed=15)
        x = jax.random.normal(jax.random.key(16), (8, 4, 16))
        mesh = _mesh()
        with mesh, pytest.raises(ValueError, match="n_microbatches"):
            gpipe_apply(_stage_fn, params, x, mesh, n_microbatches=2, virtual_chunks=2)

    def test_layers_must_divide_stages_times_chunks(self):
        params = _stack_params(L=8, seed=17)
        x = jax.random.normal(jax.random.key(18), (8, 4, 16))
        mesh = _mesh()
        with mesh, pytest.raises(ValueError, match="divide"):
            gpipe_apply(_stage_fn, params, x, mesh, n_microbatches=4, virtual_chunks=3)

    def test_model_interleaved_matches_sequential(self):
        cfg = _pp_cfg(
            model={
                "n_layers": 8,
                "extra": {
                    "tokenizer": "byte",
                    "pipeline_microbatches": 4,
                    "pipeline_virtual_chunks": 2,
                },
            }
        )
        from llmtrain_tpu.models.gpt_pipeline import PipelineGPTAdapter

        adapter = PipelineGPTAdapter()
        model = adapter.build_model(cfg)
        params = adapter.init_params(model, cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(19), (8, 16), 0, 32)
        ref = model.apply({"params": params}, tokens)
        with _mesh():
            out = jax.jit(lambda p, t: model.apply({"params": p}, t))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_trainer_interleaved_loss_decreases(self):
        cfg = _pp_cfg(
            model={
                "n_layers": 8,
                "extra": {
                    "tokenizer": "byte",
                    "pipeline_microbatches": 4,
                    "pipeline_virtual_chunks": 2,
                },
            }
        )
        trainer = Trainer(cfg, None, NullTracker())
        result = trainer.fit()
        assert result.final_loss < result.first_step_loss
