"""Real multi-process distributed smokes (parity with reference
tests/test_distributed.py:705-784's torchrun test, then past it): CLI
subprocesses rendezvous via MASTER_ADDR/MASTER_PORT and train with
process-spanning mesh axes — 2 procs x 4 devices (dp / fsdp-ckpt /
pipeline) and 4 procs (fsdp=4; pipeline with 1 device per process).
Rank-0-only artifacts throughout."""

import json
import math
import os
import socket
import subprocess
import sys

import pytest
import yaml

CFG = {
    "schema_version": 1,
    "run": {"name": "mp-smoke", "seed": 11, "device": "cpu", "deterministic": True},
    "model": {
        "name": "dummy_gpt",
        "block_size": 8,
        "d_model": 48,
        "n_layers": 1,
        "n_heads": 2,
        "d_ff": 96,
        "dropout": 0.0,
        "vocab_size": 32,
    },
    "data": {"name": "dummy_text"},
    "trainer": {
        "max_steps": 4,
        "micro_batch_size": 2,
        "grad_accum_steps": 1,
        "lr": 0.003,
        "warmup_steps": 0,
        "log_every_steps": 2,
        "eval_every_steps": 4,
        "save_every_steps": 2,
    },
    "distributed": {"enabled": True, "timeout_sec": 60},
    "mlflow": {"enabled": False},
    "logging": {"level": "INFO", "json_output": True, "log_to_file": True},
    "output": {"root_dir": "runs"},
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_procs(
    tmp_path,
    config_name: str,
    run_id: str,
    extra_args=(),
    *,
    n_procs: int = 2,
    devices_per_proc: int = 4,
    timeout: float = 300,
):
    """Run the CLI as ``n_procs`` rendezvousing processes, each with
    ``devices_per_proc`` forced CPU devices; returns [(rc, out, err)]."""
    port = _free_port()
    procs = []
    for rank in range(n_procs):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={devices_per_proc}",
            RANK=str(rank),
            WORLD_SIZE=str(n_procs),
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "llmtrain_tpu",
                    "train",
                    "--config",
                    config_name,
                    "--json",
                    "--run-id",
                    run_id,
                    *extra_args,
                ],
                cwd=tmp_path,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for proc in procs:
            out, err = proc.communicate(timeout=timeout)
            outs.append((proc.returncode, out, err))
    finally:
        # A deadlocked collective leaves the other rank hung holding the
        # rendezvous port; kill survivors so later launches can't hang.
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    return outs


def _summary_lines(out: str) -> list[str]:
    return [ln for ln in out.splitlines() if ln.startswith("{")]


def _summary(outs) -> dict:
    """Rank 0's JSON summary (its only '{'-prefixed stdout line)."""
    lines = _summary_lines(outs[0][1])
    assert len(lines) == 1
    return json.loads(lines[0])


@pytest.mark.slow
def test_two_process_data_parallel_train(tmp_path):
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text(yaml.safe_dump(CFG))

    outs = _launch_procs(tmp_path, "config.yaml", "mp_run")

    for rc, out, err in outs:
        assert rc == 0, f"rank failed: {err[-2000:]}"

    # Rank 0 prints the JSON summary as its last stdout line; rank 1 prints
    # no summary. (XLA's CPU gloo backend chats "[Gloo] ..." on stdout — a
    # CPU-test artifact that doesn't exist on TPU.)
    summary = _summary(outs)
    assert summary["train_result"]["final_step"] == 4
    assert summary["train_result"]["final_loss"] > 0
    assert _summary_lines(outs[1][1]) == []

    # Exactly one run dir, created by rank 0 only, with the expected ckpts.
    runs = list((tmp_path / "runs").iterdir())
    assert [p.name for p in runs] == ["mp_run"]
    ckpts = sorted(
        p.name
        for p in (tmp_path / "runs" / "mp_run" / "checkpoints").glob("step_*.ckpt")
    )
    assert ckpts == ["step_000002.ckpt", "step_000004.ckpt"]


@pytest.mark.slow
def test_two_process_tensor_axis_spans_processes(tmp_path):
    """2-process run whose TENSOR axis covers all 8 devices: every
    head/mlp/vocab matmul's psum crosses the process boundary (on real
    hardware: DCN, the first pod-slice failure mode VERDICT r4 flagged
    as untested). gpt with 8 heads so heads/mlp/vocab all shard 8-way."""
    tp_cfg = {
        **CFG,
        "run": {"name": "mp-tp", "seed": 7, "device": "cpu", "deterministic": True},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "d_model": 32,
            "n_layers": 1,
            "n_heads": 8,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": 64,
        },
        "trainer": {**CFG["trainer"], "max_steps": 2, "save_every_steps": 2,
                    "eval_every_steps": 2, "log_every_steps": 2},
        "distributed": {
            "enabled": True,
            "timeout_sec": 120,
            "mesh": {"data": -1, "fsdp": 1, "tensor": 8, "sequence": 1},
        },
    }
    (tmp_path / "tp.yaml").write_text(yaml.safe_dump(tp_cfg))
    outs = _launch_procs(tmp_path, "tp.yaml", "mp_tp")
    for rc, _, err in outs:
        assert rc == 0, f"tensor-spanning run failed: {err[-2000:]}"
    result = _summary(outs)["train_result"]
    assert result["final_step"] == 2
    assert math.isfinite(result["final_loss"]) and result["final_loss"] > 0


@pytest.mark.slow
def test_two_process_sequence_axis_spans_processes(tmp_path):
    """Ring attention with sequence=8 over 2 procs x 4 devices: the
    shard-3 <-> shard-4 ppermute hop crosses the process boundary every
    ring step (DCN on real hardware) — the sequence-parallel sibling of
    the tensor-spanning case above."""
    sp_cfg = {
        **CFG,
        "run": {"name": "mp-sp", "seed": 7, "device": "cpu", "deterministic": True},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "d_model": 32,
            "n_layers": 1,
            "n_heads": 8,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": 64,
            "attention": "ring",
        },
        "trainer": {**CFG["trainer"], "max_steps": 2, "save_every_steps": 2,
                    "eval_every_steps": 2, "log_every_steps": 2},
        "distributed": {
            "enabled": True,
            "timeout_sec": 120,
            "mesh": {"data": -1, "fsdp": 1, "tensor": 1, "sequence": 8},
        },
    }
    (tmp_path / "sp.yaml").write_text(yaml.safe_dump(sp_cfg))
    outs = _launch_procs(tmp_path, "sp.yaml", "mp_sp")
    for rc, _, err in outs:
        assert rc == 0, f"sequence-spanning run failed: {err[-2000:]}"
    result = _summary(outs)["train_result"]
    assert result["final_step"] == 2
    assert math.isfinite(result["final_loss"]) and result["final_loss"] > 0


@pytest.mark.slow
def test_two_process_fsdp_sharded_checkpoint_resume(tmp_path):
    """2-process GPT run with fsdp:2 spanning the process boundary: save at
    step 2, resume in fresh processes, final loss within 1e-5 of the
    continuous run (VERDICT r1 #5). Params are NOT fully addressable from
    either process, so the save path exercises checkpoint._to_host's
    process_allgather collective and restore exercises _rebox_like +
    resharding of fsdp-sharded state (reference counterpart:
    tests/test_distributed.py:705-784 + test_checkpoint.py:301-320)."""
    fsdp_cfg = {
        **CFG,
        "run": {"name": "mp-fsdp", "seed": 23, "device": "cpu", "deterministic": True},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "d_model": 32,
            "n_layers": 1,
            "n_heads": 2,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": 64,
        },
        "distributed": {
            "enabled": True,
            "timeout_sec": 60,
            # 8 global devices: data=4 outer, fsdp=2 inner — each fsdp
            # shard-pair spans devices owned by different processes.
            "mesh": {"data": -1, "fsdp": 2, "tensor": 1, "sequence": 1},
        },
    }
    (tmp_path / "full.yaml").write_text(yaml.safe_dump(fsdp_cfg))

    # Continuous 4-step run; save_every=2 leaves a mid-run step-2 checkpoint.
    # (Resuming from the SAME config keeps the cosine-decay horizon identical
    # — a shorter-max_steps run would train steps 1-2 under different LRs.)
    full = _launch_procs(tmp_path, "full.yaml", "mp_full")
    for rc, _, err in full:
        assert rc == 0, f"continuous run failed: {err[-2000:]}"
    full_loss = _summary(full)["train_result"]["final_loss"]
    mid_ckpt = tmp_path / "runs" / "mp_full" / "checkpoints" / "step_000002.ckpt"
    assert mid_ckpt.is_file()

    resumed = _launch_procs(
        tmp_path, "full.yaml", "mp_resumed", extra_args=("--resume", str(mid_ckpt))
    )
    for rc, _, err in resumed:
        assert rc == 0, f"resumed run failed: {err[-2000:]}"
    result = _summary(resumed)["train_result"]
    assert result["resumed_from_step"] == 2
    assert result["final_step"] == 4
    assert result["final_loss"] == pytest.approx(full_loss, abs=1e-5)


@pytest.mark.slow
def test_two_process_pipeline_parallel_train(tmp_path):
    """2-process gpt_pipeline run with the pipeline axis SPANNING the
    process boundary: {pipeline: 2, data: 4} over 8 global devices, one
    pipeline stage's devices owned by each process — the GPipe ppermute
    handoff crosses processes. Asserts clean completion, a finite
    decreasing loss, and rank-0-only artifacts."""
    pp_cfg = {
        **CFG,
        "run": {"name": "mp-pp", "seed": 31, "device": "cpu", "deterministic": True},
        "model": {
            "name": "gpt_pipeline",
            "block_size": 8,
            "d_model": 32,
            "n_layers": 2,
            "n_heads": 2,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": 64,
            "extra": {"tokenizer": "byte", "pipeline_microbatches": 2},
        },
        "trainer": {
            **CFG["trainer"],
            # per-shard batch = micro*dp/dp_shards: global 8 over data=4
            # shards -> 2/shard... keep global batch divisible by
            # dp(4) x microbatches(2) = 8.
            "micro_batch_size": 2,
        },
        "distributed": {
            "enabled": True,
            "timeout_sec": 60,
            "mesh": {"pipeline": 2, "data": -1, "fsdp": 1, "tensor": 1, "sequence": 1},
        },
    }
    (tmp_path / "pp.yaml").write_text(yaml.safe_dump(pp_cfg))

    outs = _launch_procs(tmp_path, "pp.yaml", "mp_pp")
    for rc, _, err in outs:
        assert rc == 0, f"pipeline rank failed: {err[-2000:]}"
    result = _summary(outs)["train_result"]
    assert result["final_step"] == 4
    assert result["final_loss"] > 0
    assert result["final_loss"] < result["first_step_loss"]
    runs = list((tmp_path / "runs").iterdir())
    assert [p.name for p in runs] == ["mp_pp"]


@pytest.mark.slow
def test_four_process_fsdp_spanning_train(tmp_path):
    """4-process GPT run with the fsdp axis spanning ALL process
    boundaries (VERDICT r4 item 5): 4 procs x 2 local devices = 8 global,
    mesh {data: 2, fsdp: 4} — every fsdp shard-group of 4 devices mixes
    devices owned by two different processes, so the just-in-time
    all-gathers and grad reduce-scatters cross the process fabric. The
    first real v5e-16 pod slice runs exactly this topology class; nothing
    about the runtime may assume world size 2."""
    cfg = {
        **CFG,
        "run": {"name": "mp4-fsdp", "seed": 41, "device": "cpu", "deterministic": True},
        "model": {
            "name": "gpt",
            "block_size": 8,
            "d_model": 32,
            "n_layers": 1,
            "n_heads": 2,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": 64,
        },
        "trainer": {
            **CFG["trainer"],
            "micro_batch_size": 4,
            "max_steps": 2,
            "log_every_steps": 1,
            "eval_every_steps": 2,
            "save_every_steps": 2,
        },
        "distributed": {
            "enabled": True,
            "timeout_sec": 600,
            "mesh": {"data": -1, "fsdp": 4, "tensor": 1, "sequence": 1},
        },
    }
    (tmp_path / "mp4.yaml").write_text(yaml.safe_dump(cfg))

    outs = _launch_procs(
        tmp_path, "mp4.yaml", "mp4_run", n_procs=4, devices_per_proc=2, timeout=600
    )
    for rc, _, err in outs:
        assert rc == 0, f"rank failed: {err[-2000:]}"
    result = _summary(outs)["train_result"]
    assert result["final_step"] == 2
    # Loss-decrease over a real horizon is proven by the 4-step 2-process
    # tests above; at 2 steps a single update on a fresh batch is noise,
    # so the 4-process tests pin completion + a sane loss.
    assert result["final_loss"] > 0
    assert math.isfinite(result["final_loss"])
    # Only rank 0 prints a summary or creates artifacts.
    for rank in (1, 2, 3):
        assert _summary_lines(outs[rank][1]) == []
    assert [p.name for p in (tmp_path / "runs").iterdir()] == ["mp4_run"]


@pytest.mark.slow
def test_four_process_pipeline_spanning_train(tmp_path):
    """4-process gpt_pipeline run, {pipeline: 4} over 4 global devices —
    one device per process, so EVERY GPipe ppermute hop crosses a process
    boundary by construction (VERDICT r4 item 5).

    One device per process (not 2) keeps the program small: XLA's CPU
    gloo collectives have a hardcoded ~30 s context-rendezvous deadline
    per communicator, and on an oversubscribed 1-core CI host the bigger
    {pipeline:4, data:2} variant's compile/execution skew between ranks
    exceeded it (GetKeyValue DEADLINE_EXCEEDED) — a host artifact, not a
    framework bug; the cross-process-hop property under test is identical.
    """
    cfg = {
        **CFG,
        "run": {"name": "mp4-pp", "seed": 43, "device": "cpu", "deterministic": True},
        "model": {
            "name": "gpt_pipeline",
            "block_size": 8,
            "d_model": 32,
            "n_layers": 4,
            "n_heads": 2,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": 64,
            "extra": {"tokenizer": "byte", "pipeline_microbatches": 2},
        },
        "trainer": {
            **CFG["trainer"],
            "micro_batch_size": 2,
            "max_steps": 2,
            "log_every_steps": 1,
            "eval_every_steps": 2,
            "save_every_steps": 2,
        },
        "distributed": {
            "enabled": True,
            "timeout_sec": 600,
            "mesh": {"pipeline": 4, "data": -1, "fsdp": 1, "tensor": 1, "sequence": 1},
        },
    }
    (tmp_path / "mp4pp.yaml").write_text(yaml.safe_dump(cfg))

    outs = _launch_procs(
        tmp_path, "mp4pp.yaml", "mp4_pp", n_procs=4, devices_per_proc=1, timeout=600
    )
    for rc, _, err in outs:
        assert rc == 0, f"rank failed: {err[-2000:]}"
    result = _summary(outs)["train_result"]
    assert result["final_step"] == 2
    # Loss-decrease over a real horizon is proven by the 4-step 2-process
    # tests above; at 2 steps a single update on a fresh batch is noise,
    # so the 4-process tests pin completion + a sane loss.
    assert result["final_loss"] > 0
    assert math.isfinite(result["final_loss"])
