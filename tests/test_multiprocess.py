"""Real 2-process distributed smoke (parity with reference
tests/test_distributed.py:705-784's torchrun test): two CLI subprocesses
rendezvous via MASTER_ADDR/MASTER_PORT, train data-parallel over a global
8-device mesh (4 forced CPU devices per process), rank-0-only artifacts."""

import json
import os
import socket
import subprocess
import sys

import pytest
import yaml

CFG = {
    "schema_version": 1,
    "run": {"name": "mp-smoke", "seed": 11, "device": "cpu", "deterministic": True},
    "model": {
        "name": "dummy_gpt",
        "block_size": 8,
        "d_model": 48,
        "n_layers": 1,
        "n_heads": 2,
        "d_ff": 96,
        "dropout": 0.0,
        "vocab_size": 32,
    },
    "data": {"name": "dummy_text"},
    "trainer": {
        "max_steps": 4,
        "micro_batch_size": 2,
        "grad_accum_steps": 1,
        "lr": 0.003,
        "warmup_steps": 0,
        "log_every_steps": 2,
        "eval_every_steps": 4,
        "save_every_steps": 2,
    },
    "distributed": {"enabled": True, "timeout_sec": 60},
    "mlflow": {"enabled": False},
    "logging": {"level": "INFO", "json_output": True, "log_to_file": True},
    "output": {"root_dir": "runs"},
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_data_parallel_train(tmp_path):
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text(yaml.safe_dump(CFG))
    port = _free_port()

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            RANK=str(rank),
            WORLD_SIZE="2",
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "llmtrain_tpu",
                    "train",
                    "--config",
                    "config.yaml",
                    "--json",
                    "--run-id",
                    "mp_run",
                ],
                cwd=tmp_path,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )

    outs = []
    for proc in procs:
        out, err = proc.communicate(timeout=300)
        outs.append((proc.returncode, out, err))

    for rc, out, err in outs:
        assert rc == 0, f"rank failed: {err[-2000:]}"

    # Rank 0 prints the JSON summary as its last stdout line; rank 1 prints
    # no summary. (XLA's CPU gloo backend chats "[Gloo] ..." on stdout — a
    # CPU-test artifact that doesn't exist on TPU.)
    def summary_lines(out):
        return [ln for ln in out.splitlines() if ln.startswith("{")]

    rank0_json = summary_lines(outs[0][1])
    assert len(rank0_json) == 1
    summary = json.loads(rank0_json[0])
    assert summary["train_result"]["final_step"] == 4
    assert summary["train_result"]["final_loss"] > 0
    assert summary_lines(outs[1][1]) == []

    # Exactly one run dir, created by rank 0 only, with the expected ckpts.
    runs = list((tmp_path / "runs").iterdir())
    assert [p.name for p in runs] == ["mp_run"]
    ckpts = sorted(p.name for p in (tmp_path / "runs" / "mp_run" / "checkpoints").iterdir())
    assert ckpts == ["step_000002.ckpt", "step_000004.ckpt"]
