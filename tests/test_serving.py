"""HTTP inference server (serving.py + the ``serve`` CLI subcommand).

Beyond-reference serving surface. Unit tests drive the request logic
and a live in-process server over a tiny model; one CLI test boots the
real subprocess on an ephemeral port and round-trips a request.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.serving import ServerState, _handle_generate_request, make_server


def _tiny_state(**kw):
    from llmtrain_tpu.models.gpt import GPT

    model = GPT(
        vocab_size=64,
        block_size=16,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = nn_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
    )
    defaults = dict(
        model=model,
        params=params,
        tokenizer=None,
        step=7,
        checkpoint="mem://tiny",
        max_new_tokens_cap=8,
        default_max_new_tokens=4,
    )
    return ServerState(**{**defaults, **kw})


class TestRequestLogic:
    def test_greedy_is_deterministic(self):
        state = _tiny_state()
        req = {"prompt_ids": [1, 2, 3], "max_new_tokens": 4, "temperature": 0.0}
        code1, out1 = _handle_generate_request(state, req)
        code2, out2 = _handle_generate_request(state, req)
        assert code1 == code2 == 200
        assert out1["completion_ids"] == out2["completion_ids"]
        assert len(out1["completion_ids"]) == 4
        assert out1["prompt_tokens"] == 3
        assert out1["latency_ms"] > 0
        assert state.requests_served == 2

    def test_default_max_new_tokens(self):
        state = _tiny_state()
        code, out = _handle_generate_request(
            state, {"prompt_ids": [5], "temperature": 0.0}
        )
        assert code == 200
        assert len(out["completion_ids"]) == state.default_max_new_tokens

    @pytest.mark.parametrize(
        "body, msg",
        [
            ({}, "exactly one"),
            ({"prompt": "x", "prompt_ids": [1]}, "exactly one"),
            ({"prompt": "hi"}, "no tokenizer"),
            ({"prompt_ids": []}, "non-empty list"),
            ({"prompt_ids": [1, "a"]}, "non-empty list"),
            ({"prompt_ids": [1], "max_new_tokens": 0}, "positive int"),
            ({"prompt_ids": [1], "max_new_tokens": 9}, "server cap"),
            ({"prompt_ids": [1], "nope": 1}, "unknown fields"),
            ({"prompt_ids": [1], "seed": "x"}, "'seed' must be an int"),
            ({"prompt_ids": list(range(14)), "max_new_tokens": 8}, "block_size"),
        ],
    )
    def test_rejections(self, body, msg):
        code, out = _handle_generate_request(_tiny_state(), body)
        assert code == 400
        assert msg in out["error"]

    def test_eos_truncates_completion(self):
        state = _tiny_state()
        code, out = _handle_generate_request(
            state, {"prompt_ids": [1, 2], "max_new_tokens": 6, "temperature": 0.0}
        )
        assert code == 200
        # Greedy on random weights repeats a token quickly; use the first
        # emitted token as a forced EOS and check truncation.
        eos = out["completion_ids"][0]
        code, out2 = _handle_generate_request(
            state,
            {
                "prompt_ids": [1, 2],
                "max_new_tokens": 6,
                "temperature": 0.0,
                "eos_token_id": eos,
            },
        )
        assert code == 200
        assert out2["completion_ids"][-1] == eos
        assert len(out2["completion_ids"]) <= 6


class TestLiveServer:
    @pytest.fixture()
    def server(self):
        state = _tiny_state()
        httpd = make_server(state, "127.0.0.1", 0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()
        httpd.server_close()

    def _post(self, url, body):
        req = urllib.request.Request(
            url + "/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())

    def test_healthz(self, server):
        with urllib.request.urlopen(server + "/healthz", timeout=30) as resp:
            payload = json.loads(resp.read())
        assert resp.status == 200
        assert payload["status"] == "ok"
        assert payload["step"] == 7

    def test_generate_roundtrip(self, server):
        status, out = self._post(
            server, {"prompt_ids": [1, 2, 3], "max_new_tokens": 3,
                     "temperature": 0.0}
        )
        assert status == 200
        assert len(out["completion_ids"]) == 3

    def test_concurrent_requests_serialize(self, server):
        """Two simultaneous posts both succeed: the device lock queues
        them instead of interleaving decodes."""
        results = []

        def post():
            results.append(
                self._post(
                    server,
                    {"prompt_ids": [1, 2], "max_new_tokens": 2,
                     "temperature": 0.0},
                )
            )

        threads = [threading.Thread(target=post) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert len(results) == 2
        assert all(status == 200 for status, _ in results)
        # Identical greedy requests: identical outputs.
        assert results[0][1]["completion_ids"] == results[1][1]["completion_ids"]

    def test_bad_json_is_400(self, server):
        req = urllib.request.Request(
            server + "/v1/generate", data=b"{not json", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400

    def test_unknown_route_is_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(server + "/nope", timeout=30)
        assert err.value.code == 404


class TestServeCLI:
    def test_serve_subprocess_roundtrip(self, tmp_path):
        """Real CLI: train a checkpoint, boot `serve --port 0`, read the
        ready line for the bound port, round-trip a request."""
        import yaml

        cfg = {
            "run": {"name": "srv", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 16,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "dropout": 0.0,
                # Derived from the byte tokenizer (>= 256): "ab" encodes
                # to ids 97/98, which a small explicit vocab would reject.
                "vocab_size": None,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 4,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 2,
                "eval_every_steps": 4,
                "save_every_steps": 4,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))
        train = subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", "train", "--config",
             str(cfg_path), "--run-id", "srv"],
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert train.returncode == 0, train.stderr

        proc = subprocess.Popen(
            [sys.executable, "-m", "llmtrain_tpu", "serve", "--config",
             str(cfg_path), "--from", "srv", "--port", "0",
             "--max-new-tokens-cap", "8"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            # readline() has no timeout: read the ready line through a
            # watchdog thread so a wedged server fails the test instead
            # of hanging the suite.
            lines: list[str] = []
            reader = threading.Thread(
                target=lambda: lines.append(proc.stdout.readline()), daemon=True
            )
            reader.start()
            reader.join(timeout=300)
            assert lines and lines[0], "serve never printed its ready line"
            ready = json.loads(lines[0])
            url = f"http://127.0.0.1:{ready['port']}"
            req = urllib.request.Request(
                url + "/v1/generate",
                data=json.dumps(
                    {"prompt": "ab", "max_new_tokens": 3, "temperature": 0.0}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                out = json.loads(resp.read())
            assert resp.status == 200
            assert len(out["completion_ids"]) == 3
            assert out["text"] is not None  # byte tokenizer decodes
        finally:
            proc.terminate()
            proc.wait(timeout=30)
