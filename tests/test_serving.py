"""HTTP inference server (serving/ package + the ``serve`` CLI subcommand).

Beyond-reference serving surface. Unit tests drive the request logic
and a live in-process server over a tiny model — in BOTH backends (the
legacy one-decode-at-a-time lock and the continuous-batching
scheduler); one CLI test boots the real subprocess on an ephemeral port
and round-trips a request. The engine/scheduler internals live in
tests/test_serving_engine.py.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.serving import (
    ContinuousBatchingScheduler,
    PagedDecodeEngine,
    ServerState,
    ServerStats,
    _handle_generate_request,
    make_server,
)


def _tiny_model():
    from llmtrain_tpu.models.gpt import GPT

    model = GPT(
        vocab_size=64,
        block_size=16,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = nn_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
    )
    return model, params


def _tiny_state(**kw):
    model, params = _tiny_model()
    defaults = dict(
        model=model,
        params=params,
        tokenizer=None,
        step=7,
        checkpoint="mem://tiny",
        max_new_tokens_cap=8,
        default_max_new_tokens=4,
    )
    return ServerState(**{**defaults, **kw})


def _continuous_state(**kw):
    """ServerState over a real continuous-batching scheduler (started).

    Callers must close ``state.scheduler``."""
    from llmtrain_tpu.telemetry.registry import MetricsRegistry

    model, params = _tiny_model()
    engine = PagedDecodeEngine(
        model,
        params,
        block_tokens=4,
        max_batch_slots=2,
        prompt_buckets=[4, 8],
        batch_buckets=[1, 2],
    )
    registry = MetricsRegistry(None)
    scheduler = ContinuousBatchingScheduler(engine, registry=registry).start()
    defaults = dict(
        model=model,
        params=params,
        tokenizer=None,
        step=7,
        checkpoint="mem://tiny",
        max_new_tokens_cap=8,
        default_max_new_tokens=4,
        scheduler=scheduler,
        registry=registry,
    )
    return ServerState(**{**defaults, **kw})


class TestServerStats:
    def test_concurrent_record_hammer(self):
        """The satellite regression: ``requests_served += 1`` from N
        ThreadingHTTPServer handler threads was a read-modify-write race;
        every mutation now lands under the lock, so the totals are exact."""
        stats = ServerStats()
        threads_n, per_thread = 8, 250
        barrier = threading.Barrier(threads_n)

        def hammer():
            barrier.wait()  # maximize interleaving
            for _ in range(per_thread):
                stats.record(latency_ms=1.0, tokens=3)
                stats.record_error()

        threads = [threading.Thread(target=hammer) for _ in range(threads_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        expected = threads_n * per_thread
        assert stats.requests_served == expected
        snap = stats.snapshot()
        assert snap["requests_served"] == expected
        assert snap["errors"] == expected
        assert snap["tokens_out"] == 3 * expected
        assert snap["mean_latency_ms"] == 1.0

    def test_latency_reservoir_is_bounded(self):
        stats = ServerStats()
        for i in range(ServerStats._RESERVOIR + 100):
            stats.record(latency_ms=float(i), tokens=1)
        snap = stats.snapshot()
        assert snap["requests_served"] == ServerStats._RESERVOIR + 100
        assert len(stats._latencies_ms) == ServerStats._RESERVOIR
        assert snap["p50_latency_ms"] is not None


class TestRequestLogic:
    def test_greedy_is_deterministic(self):
        state = _tiny_state()
        req = {"prompt_ids": [1, 2, 3], "max_new_tokens": 4, "temperature": 0.0}
        code1, out1 = _handle_generate_request(state, req)
        code2, out2 = _handle_generate_request(state, req)
        assert code1 == code2 == 200
        assert out1["completion_ids"] == out2["completion_ids"]
        assert len(out1["completion_ids"]) == 4
        assert out1["prompt_tokens"] == 3
        assert out1["latency_ms"] > 0
        assert state.requests_served == 2

    def test_default_max_new_tokens(self):
        state = _tiny_state()
        code, out = _handle_generate_request(
            state, {"prompt_ids": [5], "temperature": 0.0}
        )
        assert code == 200
        assert len(out["completion_ids"]) == state.default_max_new_tokens

    @pytest.mark.parametrize(
        "body, msg",
        [
            ({}, "exactly one"),
            ({"prompt": "x", "prompt_ids": [1]}, "exactly one"),
            ({"prompt": "hi"}, "no tokenizer"),
            ({"prompt_ids": []}, "non-empty list"),
            ({"prompt_ids": [1, "a"]}, "non-empty list"),
            ({"prompt_ids": [1], "max_new_tokens": 0}, "positive int"),
            ({"prompt_ids": [1], "max_new_tokens": 9}, "server cap"),
            ({"prompt_ids": [1], "nope": 1}, "unknown fields"),
            ({"prompt_ids": [1], "seed": "x"}, "'seed' must be an int"),
            ({"prompt_ids": list(range(14)), "max_new_tokens": 8}, "block_size"),
        ],
    )
    def test_rejections(self, body, msg):
        code, out = _handle_generate_request(_tiny_state(), body)
        assert code == 400
        assert msg in out["error"]

    def test_eos_truncates_completion(self):
        state = _tiny_state()
        code, out = _handle_generate_request(
            state, {"prompt_ids": [1, 2], "max_new_tokens": 6, "temperature": 0.0}
        )
        assert code == 200
        # Greedy on random weights repeats a token quickly; use the first
        # emitted token as a forced EOS and check truncation.
        eos = out["completion_ids"][0]
        code, out2 = _handle_generate_request(
            state,
            {
                "prompt_ids": [1, 2],
                "max_new_tokens": 6,
                "temperature": 0.0,
                "eos_token_id": eos,
            },
        )
        assert code == 200
        assert out2["completion_ids"][-1] == eos
        assert len(out2["completion_ids"]) <= 6


class TestContinuousBackend:
    """The scheduler-backed request path (serving.mode: continuous)."""

    @pytest.fixture()
    def cstate(self):
        state = _continuous_state()
        yield state
        state.scheduler.close()

    def test_greedy_matches_legacy_lock_path(self, cstate):
        """Same weights, same request: the continuous backend emits the
        same tokens the legacy one-decode-at-a-time path does, plus the
        serving extras (ttft_ms, finish_reason)."""
        body = {"prompt_ids": [1, 2, 3], "max_new_tokens": 4, "temperature": 0.0}
        code, out = _handle_generate_request(cstate, body)
        assert code == 200
        assert out["finish_reason"] == "length"
        assert out["ttft_ms"] > 0
        code2, out2 = _handle_generate_request(_tiny_state(), body)
        assert code2 == 200
        assert out["completion_ids"] == out2["completion_ids"]
        assert cstate.stats.requests_served == 1

    def test_request_error_is_500_not_a_dead_scheduler(self, cstate):
        """A request the scheduler fails (oversized for the engine,
        submitted past HTTP validation) answers 500; the NEXT request
        still succeeds — errors are per-request."""
        cstate.max_new_tokens_cap = 64  # let the bad request through
        code, out = _handle_generate_request(
            cstate,
            {"prompt_ids": [1, 2], "max_new_tokens": 14, "temperature": 0.0},
        )
        assert code == 200  # 2 + 14 fits block_size 16: sanity
        code, out = _handle_generate_request(
            cstate,
            {"prompt_ids": list(range(1, 10)), "max_new_tokens": 10,
             "temperature": 0.0},
        )
        assert code == 400  # http bound still applies
        # Paged-backend bound: a prompt past the largest prompt bucket is
        # a 400 at the boundary, not a late 500 from inside prefill.
        code, out = _handle_generate_request(
            cstate,
            {"prompt_ids": list(range(1, 11)), "max_new_tokens": 2,
             "temperature": 0.0},
        )
        assert code == 400
        assert "prompt bucket" in out["error"]
        # Bypass HTTP validation: submit an oversized ServeRequest directly.
        import numpy as np

        from llmtrain_tpu.serving import ServeRequest

        bad = ServeRequest(
            prompt_ids=np.asarray([1, 2, 3], np.int32), max_new_tokens=20
        )
        cstate.scheduler.submit(bad)
        assert bad.done.wait(timeout=60)
        assert bad.finish_reason == "error"
        code, out = _handle_generate_request(
            cstate, {"prompt_ids": [5], "max_new_tokens": 2, "temperature": 0.0}
        )
        assert code == 200  # scheduler survived

    def test_healthz_and_metrics_surfaces(self, cstate):
        """/healthz carries scheduler/KV-pool/compile stats; /metrics
        exposes llmtrain_serve_* in Prometheus text format."""
        from llmtrain_tpu.serving.http import _handle_health, _handle_metrics

        _handle_generate_request(
            cstate, {"prompt_ids": [1, 2], "max_new_tokens": 3,
                     "temperature": 0.0}
        )
        code, payload = _handle_health(cstate)
        assert code == 200
        sched = payload["scheduler"]
        assert sched["policy"] == "paged"
        assert sched["requests_finished"] == 1
        assert sched["kv_pool"]["active_sequences"] == 0
        assert sched["compile"]["within_budget"]
        code, text = _handle_metrics(cstate)
        assert code == 200
        assert "llmtrain_serve_requests_total 1" in text
        assert "llmtrain_serve_queue_depth" in text
        assert "llmtrain_serve_kv_pool_utilization" in text

    def test_metrics_404_without_registry(self):
        from llmtrain_tpu.serving.http import _handle_metrics

        code, _ = _handle_metrics(_tiny_state())
        assert code == 404


class TestLiveServer:
    @pytest.fixture()
    def server(self):
        state = _tiny_state()
        httpd = make_server(state, "127.0.0.1", 0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()
        httpd.server_close()

    def _post(self, url, body):
        req = urllib.request.Request(
            url + "/v1/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())

    def test_healthz(self, server):
        with urllib.request.urlopen(server + "/healthz", timeout=30) as resp:
            payload = json.loads(resp.read())
        assert resp.status == 200
        assert payload["status"] == "ok"
        assert payload["step"] == 7

    def test_generate_roundtrip(self, server):
        status, out = self._post(
            server, {"prompt_ids": [1, 2, 3], "max_new_tokens": 3,
                     "temperature": 0.0}
        )
        assert status == 200
        assert len(out["completion_ids"]) == 3

    def test_concurrent_requests_serialize(self, server):
        """Two simultaneous posts both succeed: the device lock queues
        them instead of interleaving decodes."""
        results = []

        def post():
            results.append(
                self._post(
                    server,
                    {"prompt_ids": [1, 2], "max_new_tokens": 2,
                     "temperature": 0.0},
                )
            )

        threads = [threading.Thread(target=post) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert len(results) == 2
        assert all(status == 200 for status, _ in results)
        # Identical greedy requests: identical outputs.
        assert results[0][1]["completion_ids"] == results[1][1]["completion_ids"]

    def test_bad_json_is_400(self, server):
        req = urllib.request.Request(
            server + "/v1/generate", data=b"{not json", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400

    def test_unknown_route_is_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(server + "/nope", timeout=30)
        assert err.value.code == 404


class TestServeBenchCLI:
    def test_nonpositive_max_new_tokens_is_a_config_error(self, tmp_path):
        """--max-new-tokens 0 used to sail past validation, emit one
        unavoidable prefill token per request, and then fail --verify-parity
        against generate()'s empty continuation — a misleading train-failure
        exit. It must be rejected up front as a config error."""
        import yaml

        from llmtrain_tpu.cli import main
        from llmtrain_tpu.resilience.exit_codes import EXIT_CONFIG_ERROR

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "mnt0", "seed": 0, "device": "cpu"},
                    "model": {"name": "dummy_gpt"},
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1},
                    "mlflow": {"enabled": False},
                    "output": {"root_dir": str(tmp_path / "runs")},
                }
            )
        )
        rc = main(
            ["serve-bench", "--config", str(cfg_path), "--from", "nope",
             "--max-new-tokens", "0"]
        )
        assert rc == EXIT_CONFIG_ERROR

    @pytest.mark.slow
    def test_serve_bench_and_continuous_serve_subprocess(self, tmp_path):
        """Real CLI, one tiny checkpoint, both serving entrypoints:

        1. ``serve-bench --verify-parity`` — seeded open-loop load run;
           report.json gains the serving block with p50/p95/p99, >= 2
           sequences were concurrently in flight, the compile count is
           within the bucket budget, and batched output matched
           sequential generate() bitwise (the flag exits nonzero else).
        2. ``serve --mode continuous`` — live HTTP server; concurrent
           posts succeed and /metrics exposes llmtrain_serve_*.
        """
        import yaml

        cfg = {
            "run": {"name": "sbench", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 32,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "dropout": 0.0,
                "vocab_size": 64,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 4,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 2,
                "eval_every_steps": 4,
                "save_every_steps": 4,
            },
            "serving": {
                "mode": "continuous",
                "max_batch_slots": 4,
                "block_tokens": 8,
                "prompt_buckets": [8, 16],
                "batch_buckets": [2, 4],
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))
        train = subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", "train", "--config",
             str(cfg_path), "--run-id", "sbench"],
            capture_output=True, text=True, timeout=600,
        )
        assert train.returncode == 0, train.stderr

        out_dir = tmp_path / "bench_report"
        bench = subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", "serve-bench",
             "--config", str(cfg_path), "--from", "sbench",
             "--requests", "6", "--rate-rps", "64", "--max-new-tokens", "6",
             "--prompt-tokens-max", "12", "--verify-parity",
             "--out", str(out_dir)],
            capture_output=True, text=True, timeout=600,
        )
        assert bench.returncode == 0, bench.stderr
        report = json.loads((out_dir / "report.json").read_text())
        serving = report["serving"]
        assert serving["requests"]["completed"] == 6
        assert serving["occupancy"]["peak"] >= 2
        for q in ("p50", "p95", "p99"):
            assert serving["slo"]["ttft_ms"][q] is not None
            assert serving["slo"]["per_token_ms"][q] is not None
        assert serving["compile"]["within_budget"] is True
        assert serving["parity"]["bitwise_identical"] is True
        assert "## Serving" in (out_dir / "report.md").read_text()

        proc = subprocess.Popen(
            [sys.executable, "-m", "llmtrain_tpu", "serve", "--config",
             str(cfg_path), "--from", "sbench", "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            lines: list[str] = []
            reader = threading.Thread(
                target=lambda: lines.append(proc.stdout.readline()), daemon=True
            )
            reader.start()
            reader.join(timeout=300)
            assert lines and lines[0], "serve never printed its ready line"
            ready = json.loads(lines[0])
            assert ready["mode"] == "continuous"  # from the config
            assert ready["policy"] == "paged"
            url = f"http://127.0.0.1:{ready['port']}"
            results = []

            def post():
                req = urllib.request.Request(
                    url + "/v1/generate",
                    data=json.dumps(
                        {"prompt_ids": [1, 2, 3], "max_new_tokens": 4,
                         "temperature": 0.0}
                    ).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=300) as resp:
                    results.append(json.loads(resp.read()))

            threads = [threading.Thread(target=post) for _ in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert len(results) == 2
            assert results[0]["completion_ids"] == results[1]["completion_ids"]
            assert all("ttft_ms" in r for r in results)
            with urllib.request.urlopen(url + "/metrics", timeout=60) as resp:
                metrics = resp.read().decode()
            assert "llmtrain_serve_requests_total 2" in metrics
            assert "llmtrain_serve_kv_pool_utilization" in metrics
        finally:
            proc.terminate()
            proc.wait(timeout=30)


class TestServeCLI:
    def test_serve_subprocess_roundtrip(self, tmp_path):
        """Real CLI: train a checkpoint, boot `serve --port 0`, read the
        ready line for the bound port, round-trip a request."""
        import yaml

        cfg = {
            "run": {"name": "srv", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 16,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "dropout": 0.0,
                # Derived from the byte tokenizer (>= 256): "ab" encodes
                # to ids 97/98, which a small explicit vocab would reject.
                "vocab_size": None,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 4,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 2,
                "eval_every_steps": 4,
                "save_every_steps": 4,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))
        train = subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", "train", "--config",
             str(cfg_path), "--run-id", "srv"],
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert train.returncode == 0, train.stderr

        proc = subprocess.Popen(
            [sys.executable, "-m", "llmtrain_tpu", "serve", "--config",
             str(cfg_path), "--from", "srv", "--port", "0",
             "--max-new-tokens-cap", "8"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        try:
            # readline() has no timeout: read the ready line through a
            # watchdog thread so a wedged server fails the test instead
            # of hanging the suite.
            lines: list[str] = []
            reader = threading.Thread(
                target=lambda: lines.append(proc.stdout.readline()), daemon=True
            )
            reader.start()
            reader.join(timeout=300)
            assert lines and lines[0], "serve never printed its ready line"
            ready = json.loads(lines[0])
            url = f"http://127.0.0.1:{ready['port']}"
            req = urllib.request.Request(
                url + "/v1/generate",
                data=json.dumps(
                    {"prompt": "ab", "max_new_tokens": 3, "temperature": 0.0}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                out = json.loads(resp.read())
            assert resp.status == 200
            assert len(out["completion_ids"]) == 3
            assert out["text"] is not None  # byte tokenizer decodes
        finally:
            proc.terminate()
            proc.wait(timeout=30)
