"""Llama-family model tests (models/llama.py, ops/rope.py).

Beyond-reference model family (the reference ships GPT only); the test
strategy mirrors tests/test_gpt_model.py — architecture invariants,
attention-impl agreement, decode parity — plus numerical parity against
HF transformers' torch Llama, the family's ground truth (the analogue of
tests/test_torch_parity.py pinning the optimizer against torch).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.models.llama import Llama, LlamaAdapter, RMSNorm
from llmtrain_tpu.ops.rope import apply_rope
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training.trainer import Trainer

V, T, D, H, F = 64, 16, 32, 4, 88


def _model(**kw):
    defaults = dict(
        vocab_size=V, block_size=T, d_model=D, n_layers=2, n_heads=H,
        d_ff=F, dropout=0.0,
    )
    return Llama(**{**defaults, **kw})


def _params(model, seed=0):
    p = model.init(
        jax.random.key(seed), jnp.zeros((1, 4), jnp.int32), deterministic=True
    )["params"]
    return nn_meta.unbox(p)


def _cfg(_mesh=None, _max_steps=25, **model_extra):
    return RunConfig.model_validate(
        {
            **(
                {"distributed": {"enabled": False, "mesh": _mesh}}
                if _mesh
                else {}
            ),
            "run": {"name": "llama-t", "seed": 0, "device": "cpu"},
            "model": {
                "name": "llama",
                "block_size": T,
                "d_model": D,
                "n_layers": 2,
                "n_heads": H,
                "d_ff": F,
                "dropout": 0.0,
                "vocab_size": V,
                "tie_embeddings": False,
                "extra": model_extra,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": _max_steps,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "lr": 5e-3,
                "warmup_steps": 0,
                "log_every_steps": 10,
                "eval_every_steps": 100,
                "save_every_steps": 100,
            },
            "mlflow": {"enabled": False},
        }
    )


class TestRope:
    def test_matches_manual_formula(self):
        d = 8
        x = jax.random.normal(jax.random.key(0), (1, 3, 1, d))
        pos = jnp.asarray([0, 1, 5])
        q, _ = apply_rope(x, x, pos)
        inv = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
        ang = np.asarray(pos)[:, None] * inv[None, :]  # (T, d/2)
        cos, sin = np.cos(ang), np.sin(ang)
        xn = np.asarray(x)[0, :, 0, :]
        want_lo = xn[:, : d // 2] * cos - xn[:, d // 2 :] * sin
        want_hi = xn[:, d // 2 :] * cos + xn[:, : d // 2] * sin
        np.testing.assert_allclose(
            np.asarray(q)[0, :, 0, :],
            np.concatenate([want_lo, want_hi], -1),
            atol=1e-5,
        )

    def test_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.key(1), (2, 1, 3, 16))
        q, k = apply_rope(x, x, jnp.zeros((1,), jnp.int32))
        np.testing.assert_allclose(np.asarray(q), np.asarray(x), atol=1e-6)
        np.testing.assert_allclose(np.asarray(k), np.asarray(x), atol=1e-6)

    def test_relative_position_invariance(self):
        """<rot(q, i), rot(k, j)> depends only on i - j."""
        d = 16
        qv = jax.random.normal(jax.random.key(2), (1, 1, 1, d))
        kv = jax.random.normal(jax.random.key(3), (1, 1, 1, d))

        def score(i, j):
            q, _ = apply_rope(qv, qv, jnp.asarray([i]))
            k, _ = apply_rope(kv, kv, jnp.asarray([j]))
            return float(jnp.sum(q * k))

        assert score(5, 3) == pytest.approx(score(9, 7), abs=1e-4)
        assert score(5, 3) != pytest.approx(score(5, 4), abs=1e-4)

    def test_odd_head_dim_rejected(self):
        x = jnp.zeros((1, 2, 1, 6))
        with pytest.raises(ValueError, match="even head_dim"):
            apply_rope(x[..., :5], x[..., :5], jnp.arange(2))


class TestRMSNorm:
    def test_unit_rms_and_scale(self):
        x = jax.random.normal(jax.random.key(0), (4, 8)) * 3.0
        m = RMSNorm()
        y, _ = m.init_with_output(jax.random.key(1), x)
        rms = np.sqrt(np.mean(np.asarray(y) ** 2, -1))
        np.testing.assert_allclose(rms, 1.0, atol=1e-4)


class TestLlamaArchitecture:
    def test_param_tree_llama_shaped(self):
        p = _params(_model(n_kv_heads=2))
        assert "position_embedding" not in p  # RoPE, not learned positions
        blk = p["block_0"]
        assert set(blk) == {
            "attn_norm", "mlp_norm", "attn", "mlp_gate", "mlp_up", "mlp_down",
        }
        assert "bias" not in blk["mlp_gate"]  # bias-free everywhere
        assert "bias" not in blk["attn"]["q_proj"]
        assert blk["attn"]["kv_proj"]["kernel"].shape == (D, 2, 2, D // H)
        assert "scale" in blk["attn_norm"] and "bias" not in blk["attn_norm"]
        assert p["lm_head"]["kernel"].shape == (D, V)  # untied default

    def test_tied_embeddings_drop_head(self):
        p = _params(_model(tie_embeddings=True))
        assert "lm_head" not in p

    def test_loss_decreases_under_trainer(self):
        initialize_registries()
        res = Trainer(_cfg(n_kv_heads=2), None, NullTracker(), None).fit()
        assert res.final_loss < res.first_step_loss

    def test_flash_matches_dense(self):
        ids = jax.random.randint(jax.random.key(5), (2, T), 0, V)
        dense = _model(attention="dense")
        p = _params(dense)
        out_d = dense.apply({"params": p}, ids, deterministic=True)
        out_f = _model(attention="flash").apply({"params": p}, ids, deterministic=True)
        np.testing.assert_allclose(
            np.asarray(out_d), np.asarray(out_f), atol=2e-4
        )

    def test_padding_mask_blocks_padded_keys(self):
        """Changing a padded position's token must not change unpadded
        logits (in-attention masking, reference gpt.py:60-74 semantics)."""
        m = _model()
        p = _params(m)
        mask = jnp.asarray([[1] * 10 + [0] * 6])
        a = jnp.concatenate(
            [jnp.arange(10), jnp.zeros(6, jnp.int32)]
        )[None, :]
        b = jnp.concatenate(
            [jnp.arange(10), jnp.full((6,), 7, jnp.int32)]
        )[None, :]
        la = m.apply({"params": p}, a, attention_mask=mask, deterministic=True)
        lb = m.apply({"params": p}, b, attention_mask=mask, deterministic=True)
        np.testing.assert_allclose(
            np.asarray(la)[:, :10], np.asarray(lb)[:, :10], atol=1e-5
        )

    def test_chunked_ce_matches_dense_loss(self):
        initialize_registries()
        ad = LlamaAdapter()
        ids = jax.random.randint(jax.random.key(6), (2, T), 0, V)
        batch = {
            "input_ids": ids, "labels": ids,
            "attention_mask": jnp.ones_like(ids),
        }
        dense = ad.build_model(_cfg())
        p = _params(dense)
        l_d, n_d = ad.compute_loss_components(dense, p, batch)
        chunked = ad.build_model(_cfg(loss_impl="chunked_ce", ce_chunk=16))
        l_c, n_c = ad.compute_loss_components(chunked, p, batch)
        np.testing.assert_allclose(
            np.asarray(l_d).sum() / np.asarray(n_d).sum(),
            np.asarray(l_c).sum() / np.asarray(n_c).sum(),
            atol=1e-4,
        )

    def test_cached_decode_matches_nocache(self):
        from llmtrain_tpu.generation import generate

        m = _model(n_kv_heads=2)
        p = _params(m)
        prompt = np.asarray([[1, 2, 3]], np.int32)
        with_cache = generate(
            m, p, prompt, max_new_tokens=6, temperature=0.0, use_cache=True
        )
        without = generate(
            m, p, prompt, max_new_tokens=6, temperature=0.0, use_cache=False
        )
        assert with_cache.tolist() == without.tolist()

    def test_gqa_cache_is_narrow(self):
        m = _model(n_kv_heads=1).for_decoding(cache_len=8)
        state = m.init(
            jax.random.key(0), jnp.zeros((1, 2), jnp.int32), deterministic=True
        )
        cache = nn_meta.unbox(state["cache"])["block_0"]["attn"]
        assert cache["cached_key"].shape == (1, 8, 1, D // H)

    def test_unset_tie_embeddings_defaults_untied(self):
        """A config that omits tie_embeddings gets the Llama convention
        (untied head), not the schema's GPT-convention default of True;
        an explicit true still ties."""
        base = _cfg().model_dump()
        del base["model"]["tie_embeddings"]
        omitted = LlamaAdapter().build_model(RunConfig.model_validate(base))
        assert omitted.tie_embeddings is False
        base["model"]["tie_embeddings"] = True
        explicit = LlamaAdapter().build_model(RunConfig.model_validate(base))
        assert explicit.tie_embeddings is True

    def test_adapter_validates_rope_extras(self):
        with pytest.raises(ValueError, match="rope_theta"):
            LlamaAdapter().build_model(_cfg(rope_theta=-1.0))
        with pytest.raises(ValueError, match="rms_norm_eps"):
            LlamaAdapter().build_model(_cfg(rms_norm_eps=0.0))


class TestLlamaSequenceParallel:
    """RoPE composes with ring/Ulysses SP: the rotation happens on the
    global view BEFORE the sequence-sharded attention, so positions are
    absolute regardless of the shard layout."""

    @pytest.mark.parametrize("attention", ["ring", "ulysses"])
    def test_sp_matches_dense(self, attention, caplog):
        import logging

        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        dense = _model(attention="dense", n_kv_heads=2)
        p = _params(dense)
        sp = _model(attention=attention, n_kv_heads=2)
        ids = jax.random.randint(jax.random.key(70), (2, T), 0, V)

        want = dense.apply({"params": p}, ids, deterministic=True)
        mesh = Mesh(
            np.array(jax.devices("cpu")[:4]).reshape(1, 4),
            ("data", "sequence"),
        )
        with caplog.at_level(logging.WARNING, logger="llmtrain"), mesh:
            ids_sharded = jax.device_put(
                ids, NamedSharding(mesh, P("data", "sequence"))
            )
            got = jax.jit(
                lambda pp, xx: sp.apply({"params": pp}, xx, deterministic=True)
            )(p, ids_sharded)
            np.asarray(got)
        # Vacuity guard: a silent fallback to blockwise would also match
        # dense — the SP path must actually have been routed
        # (ops/ring_attention.py logs "falling back" when it is not).
        assert not any(
            "falling back" in r.getMessage() for r in caplog.records
        ), "sequence-parallel routing fell back to blockwise"
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-4
        )


class TestLlamaSharded:
    def test_train_step_on_fsdp_tp_mesh(self):
        """One Trainer step under {data:2, fsdp:2, tensor:2} — the logical
        axis rules must shard the llama tree without pjit errors."""
        initialize_registries()
        cfg = _cfg(
            _mesh={"data": 2, "fsdp": 2, "tensor": 2},
            _max_steps=2,
            n_kv_heads=2,
        )
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert np.isfinite(res.final_loss)


class TestHFParity:
    """Numerics pinned against transformers' torch Llama (fwd logits)."""

    @pytest.fixture(scope="class")
    def pair(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.LlamaConfig(
            vocab_size=V,
            hidden_size=D,
            intermediate_size=F,
            num_hidden_layers=2,
            num_attention_heads=H,
            num_key_value_heads=2,
            max_position_embeddings=T,
            rms_norm_eps=1e-6,
            rope_theta=10000.0,
            attention_bias=False,
            tie_word_embeddings=False,
        )
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(hf_cfg).eval()

        ours = _model(n_kv_heads=2)
        p = _params(ours)

        # Port through the LIBRARY converter (interop/llama_hf.py) — these
        # parity tests are what pin its layout transforms numerically.
        from llmtrain_tpu.interop import llama_params_from_hf_state_dict

        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        new = llama_params_from_hf_state_dict(sd, p)
        assert jax.tree.map(jnp.shape, p) == jax.tree.map(jnp.shape, new)
        return hf, ours, new

    def test_logits_match(self, pair):
        torch = pytest.importorskip("torch")
        hf, ours, params = pair
        ids = np.asarray([[1, 5, 9, 2, 40, 3, 0, 63]], np.int32)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids).long()).logits.numpy()
        got = np.asarray(
            ours.apply({"params": params}, jnp.asarray(ids), deterministic=True)
        )
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_logits_match_with_cache_decode(self, pair):
        """The KV-cache path reproduces HF numerics too: prefill + steps."""
        torch = pytest.importorskip("torch")
        hf, ours, params = pair
        ids = np.asarray([[4, 7, 11, 23]], np.int32)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids).long()).logits.numpy()[:, -1]

        dec = ours.for_decoding(cache_len=8)
        # Zero cache (cursor 0) from an eval_shape trace, exactly as
        # generation.py:250-258 does — a real init() would RUN the model
        # and advance the cursor past the prefill positions.
        var_shapes = jax.eval_shape(
            lambda: dec.init(
                jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                deterministic=True,
            )
        )
        cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), var_shapes["cache"]
        )
        logits, _ = dec.apply(
            {"params": params, "cache": cache},
            jnp.asarray(ids),
            deterministic=True,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(logits)[:, -1], want, atol=2e-4
        )


class TestSlidingWindowModel:
    """model.extra.sliding_window end to end (the Mistral architecture:
    llama + window)."""

    def test_cached_decode_matches_nocache(self):
        from llmtrain_tpu.generation import generate

        m = _model(n_kv_heads=2, sliding_window=4)
        p = _params(m)
        prompt = np.asarray([[1, 2, 3]], np.int32)
        a = generate(m, p, prompt, max_new_tokens=8, temperature=0.0,
                     use_cache=True)
        b = generate(m, p, prompt, max_new_tokens=8, temperature=0.0,
                     use_cache=False)
        assert a.tolist() == b.tolist()

    def test_window_changes_logits_beyond_window(self):
        """Token 0 is outside position 6's window of 4 — with ONE layer
        (stacked windows compound the receptive field by W-1 per layer),
        perturbing it must not change position 6's logits, and must
        change them under full attention."""
        win = _model(n_layers=1, sliding_window=4)
        p = _params(win)
        a = jnp.asarray([[5, 1, 2, 3, 4, 5, 6, 7]])
        b = jnp.asarray([[9, 1, 2, 3, 4, 5, 6, 7]])
        la = win.apply({"params": p}, a, deterministic=True)
        lb = win.apply({"params": p}, b, deterministic=True)
        np.testing.assert_allclose(
            np.asarray(la)[:, 6:], np.asarray(lb)[:, 6:], atol=1e-5
        )
        full = _model(n_layers=1)
        fa = full.apply({"params": p}, a, deterministic=True)
        fb = full.apply({"params": p}, b, deterministic=True)
        assert np.abs(np.asarray(fa)[:, 6:] - np.asarray(fb)[:, 6:]).max() > 1e-4

    def test_rolling_cache_is_window_sized(self):
        """window < cache_len → the KV cache is a min(cache_len, W)-slot
        ring with a per-slot position buffer: O(W) serving memory."""
        m = _model(n_kv_heads=2, sliding_window=4).for_decoding(cache_len=16)
        state = m.init(
            jax.random.key(0), jnp.zeros((1, 2), jnp.int32), deterministic=True
        )
        cache = nn_meta.unbox(state["cache"])["block_0"]["attn"]
        assert cache["cached_key"].shape == (1, 4, 2, D // H)
        assert cache["cached_pos1"].shape == (4,)

    def test_rolling_decode_matches_nocache_through_wraps(self):
        """Generations several times longer than the ring: every wrap
        must keep greedy decode identical to the uncached path."""
        from llmtrain_tpu.generation import generate

        m = _model(n_kv_heads=2, sliding_window=4)
        p = _params(m)
        prompt = np.asarray([[1, 2, 3]], np.int32)
        a = generate(m, p, prompt, max_new_tokens=12, temperature=0.0,
                     use_cache=True)
        b = generate(m, p, prompt, max_new_tokens=12, temperature=0.0,
                     use_cache=False)
        assert a.tolist() == b.tolist()

    def test_rolling_prefill_longer_than_window_matches_nocache(self):
        """Prompt (10) > window (4): the ring keeps only the last 4
        prefill keys — the sampled continuation must still match the
        uncached path exactly (only final-position logits are sampled)."""
        from llmtrain_tpu.generation import generate

        m = _model(sliding_window=4)
        p = _params(m)
        prompt = np.arange(1, 11, dtype=np.int32)[None, :]
        a = generate(m, p, prompt, max_new_tokens=5, temperature=0.0,
                     use_cache=True)
        b = generate(m, p, prompt, max_new_tokens=5, temperature=0.0,
                     use_cache=False)
        assert a.tolist() == b.tolist()

    def test_adapter_rejects_window_with_ring(self):
        with pytest.raises(ValueError, match="sliding_window"):
            base = _cfg(sliding_window=4).model_dump()
            base["model"]["attention"] = "ring"
            LlamaAdapter().build_model(RunConfig.model_validate(base))

    def test_hf_mistral_parity(self):
        """The sliding-window model IS Mistral: logits match HF
        transformers' torch MistralForCausalLM (same state-dict naming as
        llama, so the interop converter ports it unchanged)."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from llmtrain_tpu.interop import llama_params_from_hf_state_dict

        hf_cfg = transformers.MistralConfig(
            vocab_size=V,
            hidden_size=D,
            intermediate_size=F,
            num_hidden_layers=2,
            num_attention_heads=H,
            num_key_value_heads=2,
            max_position_embeddings=T,
            rms_norm_eps=1e-6,
            rope_theta=10000.0,
            sliding_window=4,
            tie_word_embeddings=False,
            attn_implementation="eager",
        )
        torch.manual_seed(1)
        hf = transformers.MistralForCausalLM(hf_cfg).eval()

        ours = _model(n_kv_heads=2, sliding_window=4)
        p = _params(ours)
        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        params = llama_params_from_hf_state_dict(sd, p)

        ids = np.asarray([[1, 5, 9, 2, 40, 3, 0, 63, 12, 7, 30, 11]], np.int32)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids).long()).logits.numpy()
        got = np.asarray(
            ours.apply({"params": params}, jnp.asarray(ids), deterministic=True)
        )
        np.testing.assert_allclose(got, want, atol=2e-4)


class TestLlamaMoE:
    """llama_moe (Mixtral-class): SwiGLU experts on the llama trunk."""

    def _cfg(self, **extra):
        base = _cfg(
            n_experts=4, router_top_k=2, n_kv_heads=2, **extra
        ).model_dump()
        base["model"]["name"] = "llama_moe"
        return RunConfig.model_validate(base)

    def test_requires_n_experts(self):
        from llmtrain_tpu.models.llama import LlamaMoEAdapter

        cfg = _cfg().model_dump()
        cfg["model"]["name"] = "llama_moe"
        with pytest.raises(ValueError, match="llama_moe requires"):
            LlamaMoEAdapter().build_model(RunConfig.model_validate(cfg))

    def test_builds_llama_with_swiglu_experts(self):
        from llmtrain_tpu.models.llama import LlamaMoEAdapter

        m = LlamaMoEAdapter().build_model(self._cfg(sliding_window=8))
        assert type(m).__name__ == "Llama"
        assert m.n_experts == 4 and m.sliding_window == 8
        p = _params(m)
        moe = p["block_0"]["moe_mlp"]
        assert set(moe) == {"router", "wg", "wu", "wo"}
        assert "mlp_gate" not in p["block_0"]

    def test_objective_includes_aux_and_loss_decreases(self):
        initialize_registries()
        res = Trainer(self._cfg(), None, NullTracker(), None).fit()
        assert res.final_loss < res.first_step_loss

    def test_aux_loss_is_in_the_objective(self):
        """Zero aux weight → strictly smaller objective with the same
        params/routing: the MRO must resolve compute_loss_components to
        the MoE adapter's aux-folding path, not the dense one."""
        from llmtrain_tpu.models.llama import LlamaMoEAdapter

        ad = LlamaMoEAdapter()
        cfg = self._cfg()
        m = ad.build_model(cfg)
        p = _params(m)
        ids = jax.random.randint(jax.random.key(61), (2, T), 0, V)
        batch = {"input_ids": ids, "labels": ids}
        with_aux, _ = ad.compute_loss_components(m, p, batch)
        without, _ = ad.compute_loss_components(
            m.clone(moe_aux_weight=0.0), p, batch
        )
        assert float(jnp.sum(with_aux)) > float(jnp.sum(without))

    def test_expert_parallel_mesh_runs(self):
        initialize_registries()
        cfg = self._cfg(_mesh={"expert": 2, "data": 4}, _max_steps=2)
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert np.isfinite(res.final_loss)

    def test_chunked_ce_composes(self):
        from llmtrain_tpu.models.llama import LlamaMoEAdapter

        ad = LlamaMoEAdapter()
        cfg = self._cfg(loss_impl="chunked_ce", ce_chunk=16)
        m = ad.build_model(cfg)
        p = _params(m)
        ids = jax.random.randint(jax.random.key(60), (2, T), 0, V)
        batch = {
            "input_ids": ids, "labels": ids,
            "attention_mask": jnp.ones_like(ids),
        }
        ls, nt = ad.compute_loss_components(m, p, batch)
        assert np.isfinite(np.asarray(ls)).all()


class TestHFInterop:
    """interop/llama_hf.py structural contract (numerics pinned by
    TestHFParity, which routes through the same converter)."""

    def _roundtrip(self, **kw):
        from llmtrain_tpu.interop import (
            llama_params_from_hf_state_dict,
            llama_params_to_hf_state_dict,
        )

        m = _model(**kw)
        p = _params(m)
        sd = llama_params_to_hf_state_dict(p)
        back = llama_params_from_hf_state_dict(sd, p)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6
            ),
            p,
            back,
        )
        return sd

    def test_roundtrip_gqa_untied(self):
        sd = self._roundtrip(n_kv_heads=2)
        assert sd["model.layers.0.self_attn.k_proj.weight"].shape == (
            2 * (D // H), D,
        )
        assert "lm_head.weight" in sd

    def test_roundtrip_mha_fused(self):
        sd = self._roundtrip()  # n_kv_heads == n_heads → fused qkv tree
        assert sd["model.layers.0.self_attn.q_proj.weight"].shape == (D, D)

    def test_roundtrip_tied(self):
        sd = self._roundtrip(tie_embeddings=True)
        np.testing.assert_array_equal(
            sd["lm_head.weight"], sd["model.embed_tokens.weight"]
        )

    def test_tied_import_tolerates_missing_head(self):
        """HF safetensors drops shared tensors; a tied template accepts
        the absence and rejects a DIFFERENT head."""
        from llmtrain_tpu.interop import llama_params_from_hf_state_dict

        m = _model(tie_embeddings=True)
        p = _params(m)
        from llmtrain_tpu.interop import llama_params_to_hf_state_dict

        sd = llama_params_to_hf_state_dict(p)
        del sd["lm_head.weight"]
        llama_params_from_hf_state_dict(sd, p)  # must not raise
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"] + 1.0
        with pytest.raises(ValueError, match="untied"):
            llama_params_from_hf_state_dict(sd, p)

    def test_unconsumed_keys_rejected(self):
        from llmtrain_tpu.interop import (
            llama_params_from_hf_state_dict,
            llama_params_to_hf_state_dict,
        )

        p = _params(_model())
        sd = llama_params_to_hf_state_dict(p)
        sd["model.layers.9.mlp.gate_proj.weight"] = sd[
            "model.layers.0.mlp.gate_proj.weight"
        ]
        with pytest.raises(ValueError, match="cannot hold"):
            llama_params_from_hf_state_dict(sd, p)

    def test_rotary_buffers_ignored(self):
        from llmtrain_tpu.interop import (
            llama_params_from_hf_state_dict,
            llama_params_to_hf_state_dict,
        )

        p = _params(_model())
        sd = llama_params_to_hf_state_dict(p)
        sd["model.layers.0.self_attn.rotary_emb.inv_freq"] = np.ones(4)
        llama_params_from_hf_state_dict(sd, p)  # must not raise

    def test_moe_tree_dispatches_here_and_rejects_cleanly(self):
        """llama_moe trees are llama trees (is_llama_tree keys on
        attn_norm), and the converter names the real limitation."""
        from llmtrain_tpu.interop import (
            is_llama_tree,
            llama_params_to_hf_state_dict,
        )
        from llmtrain_tpu.models.llama import LlamaMoEAdapter

        base = _cfg(n_experts=4, n_kv_heads=2).model_dump()
        base["model"]["name"] = "llama_moe"
        m = LlamaMoEAdapter().build_model(RunConfig.model_validate(base))
        p = _params(m)
        assert is_llama_tree(p)
        with pytest.raises(ValueError, match="llama_moe"):
            llama_params_to_hf_state_dict(p)
        from llmtrain_tpu.interop import llama_params_from_hf_state_dict

        dense_sd = llama_params_to_hf_state_dict(_params(_model(n_kv_heads=2)))
        with pytest.raises(ValueError, match="llama_moe"):
            llama_params_from_hf_state_dict(dense_sd, p)

    def test_gpt_tree_rejected(self):
        from llmtrain_tpu.interop import llama_params_to_hf_state_dict
        from llmtrain_tpu.models.gpt import GPT

        g = GPT(
            vocab_size=V, block_size=T, d_model=D, n_layers=1, n_heads=H,
            d_ff=F, dropout=0.0,
        )
        gp = _params(g)
        with pytest.raises(ValueError, match="llama"):
            llama_params_to_hf_state_dict(gp)

    @pytest.mark.slow  # ~15s: CLI subprocess round-trip. The HF
    # state-dict conversion itself stays tier-1 via the in-process
    # parity/round-trip tests in this class.
    def test_cli_export_import_roundtrip(self, tmp_path):
        """llama checkpoints export as HF state dicts and re-import to a
        resumable step-0 checkpoint through the real CLI."""
        import subprocess
        import sys

        import yaml

        torch = pytest.importorskip("torch")
        cfg = _cfg(_max_steps=4).model_dump()
        cfg["trainer"]["save_every_steps"] = 4
        (tmp_path / "llama.yaml").write_text(yaml.safe_dump(cfg))

        def run(*args):
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            return subprocess.run(
                [sys.executable, "-m", "llmtrain_tpu", *args],
                capture_output=True, text=True, cwd=tmp_path, env=env,
                timeout=420,
            )

        first = run("train", "--config", "llama.yaml", "--json",
                    "--run-id", "rl1")
        assert first.returncode == 0, first.stderr
        exp = run("export-checkpoint", "--config", "llama.yaml", "--from",
                  "rl1", "--output", "out.pt", "--json")
        assert exp.returncode == 0, exp.stderr
        sd = torch.load(tmp_path / "out.pt", weights_only=True)
        assert "model.embed_tokens.weight" in sd
        imp = run("import-checkpoint", "--config", "llama.yaml", "--input",
                  "out.pt", "--output", "imported", "--json")
        assert imp.returncode == 0, imp.stderr
        assert (tmp_path / "imported" / "step_000000.ckpt").exists()
