"""Fleet serving tier (serving/router.py + the prefix cache, chunked
prefill, and hot-swap scheduler policies).

Tier-1 keeps to pure units — content-addressed prefix-cache bookkeeping
(refcounts, COW, eviction, the reservation invariant), router placement/
affinity/eviction/failover over fake replicas, and config validation —
so the suite stays inside the fast-gate budget. Everything that compiles
a model (the 2-replica drill with a mid-drill rolling hot swap, the
chunked long/short mix, batched speculative parity) runs under
``@pytest.mark.slow`` via ``make verify-router``.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from llmtrain_tpu.serving import chain_hashes
from llmtrain_tpu.serving.paged_kv import PagedKVPool, hash_token_block
from llmtrain_tpu.serving.router import ReplicaRouter, resolve_backends
from llmtrain_tpu.serving.scheduler import ServeRequest

# ---------------------------------------------------------------------------
# content-addressed prefix hashing
# ---------------------------------------------------------------------------


class TestChainHashes:
    def test_deterministic_and_prefix_stable(self):
        toks = list(range(32))
        h1 = chain_hashes(toks, 8)
        h2 = chain_hashes(toks, 8)
        assert h1 == h2 and len(h1) == 4
        # The chain property: a longer prompt extends, never rewrites,
        # the hashes of its prefix — what makes the cache content-addressed.
        assert chain_hashes(toks[:16], 8) == h1[:2]

    def test_hash_depends_on_parent_and_tokens(self):
        a = hash_token_block("", [1, 2, 3])
        assert a != hash_token_block("", [1, 2, 4])
        assert a != hash_token_block(a, [1, 2, 3])

    def test_partial_trailing_block_is_not_hashed(self):
        assert len(chain_hashes(list(range(10)), 8)) == 1


# ---------------------------------------------------------------------------
# paged KV pool: refcounts, COW, eviction, reservation invariant
# ---------------------------------------------------------------------------


def _register(pool: PagedKVPool, prompt: list[int]) -> None:
    """Simulate one admitted request writing `prompt` then retiring."""
    t = pool.try_reserve(len(prompt))
    assert t is not None
    m = pool.match_prefix(prompt)
    pool.bind_prefix(t, m)
    if t.shared and m.partial_block is not None:
        pool.cow_last_shared(t)
    pool.grow(t, len(prompt))
    pool.register_prefix(t, prompt)
    pool.release(t)


class TestPrefixCachePool:
    def test_register_then_match_and_bind(self):
        pool = PagedKVPool(16, 4, prefix_cache=True)
        prompt = list(range(12))
        _register(pool, prompt)
        # Blocks parked (refs drained), not freed: reclaimable supply.
        assert pool.cached_blocks == 3

        t = pool.try_reserve(13)
        m = pool.match_prefix(prompt + [99])
        assert len(m.full_blocks) == 3 and m.matched_tokens == 12
        assert pool.bind_prefix(t, m) == 12
        assert t.shared == 3
        # Binding pins the blocks again: no longer evictable.
        assert pool.cached_blocks == 0
        assert pool.prefix_hits == 3 and pool.prefix_hit_queries == 1
        pool.release(t)

    def test_match_capped_below_full_prompt(self):
        """At least one token must remain for prefill: a FULLY cached
        prompt still computes its last token (the first output's logits)."""
        pool = PagedKVPool(16, 4, prefix_cache=True)
        prompt = list(range(8))
        _register(pool, prompt)
        m = pool.match_prefix(prompt)
        assert m.matched_tokens < len(prompt)
        assert len(m.full_blocks) == 1

    def test_partial_block_match_and_cow(self):
        pool = PagedKVPool(16, 4, prefix_cache=True)
        _register(pool, [0, 1, 2, 3, 4, 5, 6, 7])
        # Diverges inside the second block: full match on block 0,
        # partial on block 1 (tokens 4,5 shared, 6 diverges).
        prompt = [0, 1, 2, 3, 4, 5, 9, 9, 9]
        m = pool.match_prefix(prompt)
        assert len(m.full_blocks) == 1 and m.partial_tokens == 2
        t = pool.try_reserve(len(prompt) + 4)
        pool.bind_prefix(t, m)
        assert t.shared == 2
        src, dst = pool.cow_last_shared(t)
        assert src != dst and t.shared == 1 and t.blocks[1] == dst
        assert pool.cow_copies == 1
        pool.grow(t, len(prompt))
        pool.release(t)

    def test_hit_rate_counts_queries_not_blocks(self):
        """One query can reuse many BLOCKS; the rate must stay <= 1."""
        pool = PagedKVPool(32, 4, prefix_cache=True)
        prompt = list(range(20))
        _register(pool, prompt)
        for _ in range(2):
            t = pool.try_reserve(21)
            pool.bind_prefix(t, pool.match_prefix(prompt + [7]))
            pool.release(t)
        s = pool.stats()
        assert s["prefix_hits"] == 10  # 2 queries x 5 blocks
        assert s["prefix_hit_queries"] == 2
        assert s["prefix_queries"] == 3  # incl. the registering miss
        assert s["prefix_hit_rate"] == round(2 / 3, 4)

    def test_double_release_raises(self):
        pool = PagedKVPool(8, 4, prefix_cache=True)
        t = pool.try_reserve(8)
        pool.grow(t, 8)
        pool.release(t)
        with pytest.raises(ValueError, match="released or foreign"):
            pool.release(t)

    def test_shared_blocks_survive_one_owners_retirement(self):
        """Refcounting: releasing one reader must not free blocks another
        reader still decodes against."""
        pool = PagedKVPool(16, 4, prefix_cache=True)
        prompt = list(range(8))
        _register(pool, prompt)
        t1 = pool.try_reserve(10)
        pool.bind_prefix(t1, pool.match_prefix(prompt + [1]))
        t2 = pool.try_reserve(10)
        pool.bind_prefix(t2, pool.match_prefix(prompt + [2]))
        shared_blk = t1.blocks[0]
        assert t2.blocks[0] == shared_blk  # literally the same physical block
        pool.release(t1)
        # Still pinned by t2: not evictable, not free.
        assert shared_blk not in pool._free
        assert shared_blk not in pool._evictable
        pool.release(t2)
        assert shared_blk in pool._evictable

    def test_lru_eviction_under_pressure(self):
        """A reserved sequence may consume parked cached blocks — oldest
        first — and grow() can never fail inside its reservation."""
        pool = PagedKVPool(9, 4, prefix_cache=True)  # 8 usable blocks
        _register(pool, list(range(8)))    # parks 2 blocks
        _register(pool, list(range(100, 108)))  # parks 2 more
        assert pool.cached_blocks == 4
        t = pool.try_reserve(32)  # needs all 8
        assert t is not None
        pool.grow(t, 32)
        assert pool.prefix_evictions == 4 and pool.cached_blocks == 0
        # The evicted entries are gone from the content index too.
        assert not pool.match_prefix(list(range(8)) + [1]).hit
        pool.release(t)

    def test_reservation_counts_cached_supply(self):
        """Admission control may promise parked blocks (they are
        reclaimable), but never blocks pinned by live tables."""
        pool = PagedKVPool(9, 4, prefix_cache=True)
        prompt = list(range(8))
        _register(pool, prompt)  # 2 parked
        assert pool.available_blocks == 8
        t = pool.try_reserve(8 * 4)
        assert t is not None and pool.available_blocks == 0
        assert pool.try_reserve(1) is None
        pool.release(t)

    def test_invalidate_frees_parked_and_stales_live(self):
        pool = PagedKVPool(16, 4, prefix_cache=True)
        prompt = list(range(8))
        _register(pool, prompt)
        t = pool.try_reserve(10)
        pool.bind_prefix(t, pool.match_prefix(prompt + [1]))
        flushed = pool.invalidate_prefix_cache()
        assert flushed == 2  # 1 parked + 1 pinned-now-stale
        # Stale K/V must not serve new admissions...
        assert not pool.match_prefix(prompt + [2]).hit
        # ...but the in-flight reader finishes fine; on drain the stale
        # block frees instead of parking.
        pool.release(t)
        assert pool.cached_blocks == 0

    def test_disabled_cache_is_inert(self):
        pool = PagedKVPool(8, 4, prefix_cache=False)
        t = pool.try_reserve(8)
        pool.grow(t, 8)
        assert pool.register_prefix(t, list(range(8))) == 0
        pool.release(t)
        assert not pool.match_prefix(list(range(8))).hit
        # Disabled: no prefix telemetry keys leak into the stats block.
        assert "prefix_queries" not in pool.stats()


# ---------------------------------------------------------------------------
# router placement / eviction / failover over fake replicas
# ---------------------------------------------------------------------------


class FakeReplica:
    """Duck-typed in-process replica: records placements, fails on demand."""

    engine = None

    def __init__(self, name: str, load: float = 0.0, fail: bool = False):
        self.name = name
        self._load = load
        self.fail = fail
        self.served: list[ServeRequest] = []
        self.reloads: list[int | None] = []
        self.probe_ok = True
        self.reload_error: str | None = None

    def load(self) -> float:
        return self._load

    def submit(self, req: ServeRequest) -> ServeRequest:
        if self.fail:
            raise RuntimeError(f"{self.name} down")
        self.served.append(req)
        req.tokens = [1]
        req.finish_reason = "length"
        req.done.set()
        return req

    def stats(self) -> dict:
        return {
            "policy": "paged",
            "peak_batch_occupancy": 1,
            "mean_batch_occupancy": 0.5,
            "max_batch_slots": 4,
            "queue_depth": 0,
            "active_sequences": 0,
            "requests_finished": len(self.served),
            "tokens_generated": len(self.served),
            "kv_pool": {
                "prefix_hits": 4,
                "prefix_queries": 2,
                "prefix_hit_queries": 1,
                "prefix_tokens_reused": 16,
                "utilization": 0.0,
            },
        }

    def healthcheck(self) -> bool:
        return self.probe_ok

    def reload(self, *, params=None, step=None, checkpoint=None) -> dict:
        if self.reload_error:
            raise RuntimeError(self.reload_error)
        self.reloads.append(step)
        return {"replica": self.name, "step": step}

    def close(self) -> None:
        pass


def _req(prompt: list[int]) -> ServeRequest:
    return ServeRequest(
        prompt_ids=np.asarray(prompt, dtype=np.int32), max_new_tokens=4
    )


def _router(*replicas: FakeReplica, **kw) -> ReplicaRouter:
    kw.setdefault("block_tokens", 4)
    return ReplicaRouter(list(replicas), **kw)


class TestRouterPlacement:
    def test_requires_a_replica(self):
        with pytest.raises(ValueError, match="at least one replica"):
            ReplicaRouter([])

    def test_least_loaded_wins_without_affinity(self):
        a, b = FakeReplica("a", load=3.0), FakeReplica("b", load=1.0)
        router = _router(a, b)
        assert router.select(np.arange(8, dtype=np.int32)) == 1

    def test_affinity_sticks_until_the_load_gap_outweighs_it(self):
        a, b = FakeReplica("a", load=0.0), FakeReplica("b", load=0.0)
        router = _router(a, b, affinity_weight=4.0)
        prompt = np.arange(8, dtype=np.int32)  # 2 affinity blocks
        first = router.select(prompt)
        # Preferred replica moderately busier: affinity still wins.
        [a, b][first]._load = 5.0
        assert router.select(prompt) == first
        # Score 4.0*2 - 5.0 = 3.0 vs 0.0 elsewhere; past the break-even
        # point the router sheds the affinity.
        [a, b][first]._load = 9.0
        assert router.select(prompt) != first
        assert router.stats()["router"]["affinity_routed"] == 1

    def test_distinct_prefixes_spread_across_replicas(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = _router(a, b)
        p1, p2 = list(range(8)), list(range(100, 108))
        i1 = router.submit(_req(p1))
        # Queue-depth feedback: routing p1 raised nothing here (fake load
        # static), so nudge the first pick to model its new queue.
        first = router.select(np.asarray(p1, np.int32))
        [a, b][first]._load = 1.0
        second = router.select(np.asarray(p2, np.int32))
        assert second != first
        assert router.requests_routed == 3
        del i1

    def test_affinity_index_is_lru_capped(self):
        a = FakeReplica("a")
        router = _router(a, max_affinity_entries=4)
        for base in range(0, 80, 8):
            router.select(np.arange(base, base + 8, dtype=np.int32))
        assert router.stats()["router"]["affinity_entries"] <= 4

    def test_failover_then_eviction_after_threshold(self):
        a = FakeReplica("a", load=0.0, fail=True)
        b = FakeReplica("b", load=10.0)
        router = _router(a, b, fail_threshold=2, revive_sec=60.0)
        r1 = router.submit(_req(list(range(4))))
        assert r1.finish_reason == "length"
        assert any(x is r1 for x in b.served)
        assert router.failovers == 1
        r2 = router.submit(_req(list(range(4))))
        assert any(x is r2 for x in b.served) and router.failovers == 2
        # Two consecutive failures: a is out of rotation.
        assert router.stats()["router"]["replicas_healthy"] == 1
        router.submit(_req(list(range(4))))
        assert router.failovers == 2  # routed straight to b, no failover

    def test_all_replicas_down_fails_the_request_loudly(self):
        a = FakeReplica("a", fail=True)
        router = _router(a, fail_threshold=1, revive_sec=60.0)
        with pytest.raises(RuntimeError, match="no healthy replica"):
            router.submit(_req(list(range(4))))

    def test_evicted_replica_revives_on_probe(self):
        a, b = FakeReplica("a", fail=True), FakeReplica("b", load=5.0)
        router = _router(a, b, fail_threshold=1, revive_sec=0.05)
        router.submit(_req(list(range(4))))
        assert router.stats()["router"]["replicas_healthy"] == 1
        a.fail = False
        time.sleep(0.06)
        r = router.submit(_req(list(range(200, 204))))
        assert router.stats()["router"]["replicas_healthy"] == 2
        assert any(x is r for x in a.served)  # back in rotation, least loaded

    def test_rolling_reload_skips_evicted_and_reports_errors(self):
        a = FakeReplica("a", fail=True)
        b, c = FakeReplica("b"), FakeReplica("c")
        router = _router(a, b, c, fail_threshold=1, revive_sec=60.0)
        router.submit(_req(list(range(4))))  # evicts a
        c.reload_error = "disk full"
        results = router.rolling_reload(params=object(), step=42)
        assert results[0] == {"replica": "a", "skipped": "evicted"}
        assert results[1] == {"replica": "b", "step": 42}
        assert "disk full" in results[2]["error"]
        assert b.reloads == [42] and c.reloads == []

    def test_stats_aggregate_and_fleet_hit_rate_uses_queries(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = _router(a, b)
        router.submit(_req(list(range(8))))
        s = router.stats()
        fp = s["router"]["fleet_prefix"]
        # Per fake: hits=4 blocks over queries=2, hit_queries=1. Summed
        # hits (8) > queries (4): the BLOCK count must not be the rate.
        assert fp["hits"] == 8 and fp["queries"] == 4
        assert fp["hit_rate"] == 0.5
        assert s["max_batch_slots"] == 8  # summed across the fleet
        assert s["policy"] == "paged"

    def test_prometheus_gauges_published(self):
        from llmtrain_tpu.telemetry.prometheus import render_prometheus
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        registry = MetricsRegistry(None)
        router = _router(FakeReplica("a"), FakeReplica("b"), registry=registry)
        router.submit(_req(list(range(8))))
        router.stats()
        text = render_prometheus(dict(registry.latest()), registry.counters(), {})
        for want in (
            "llmtrain_router_replicas_healthy",
            "llmtrain_router_fleet_prefix_hit_rate",
            "llmtrain_router_replica0_routed",
            "llmtrain_router_replica1_healthy",
        ):
            assert want in text, want

    def test_resolve_backends_literal_host(self):
        assert resolve_backends("127.0.0.1:9123") == ["http://127.0.0.1:9123"]
        # Port defaults to 8000.
        assert resolve_backends("127.0.0.1") == ["http://127.0.0.1:8000"]


class TestRevivalBackoff:
    def test_failed_probes_back_off_exponentially(self):
        """A dead replica is probed with real healthchecks at widening
        intervals (x2 per failure, capped) — elapsed time alone never
        reinstates it, and a permanently dead replica doesn't cost one
        probe per placement call."""
        a = FakeReplica("a", fail=True)
        b = FakeReplica("b")
        router = _router(a, b, fail_threshold=1, revive_sec=10.0)
        router.submit(_req(list(range(4))))  # evicts a
        s = router._states[0]
        a.probe_ok = False
        router._healthy_indices()  # not due yet: no probe
        assert s.revive_probes == 0
        s.evicted_at = time.monotonic() - 10.0
        router._healthy_indices()
        assert s.revive_probes == 1 and not s.healthy
        assert s.revive_backoff == 2.0
        # One base interval elapsed again — but the backoff demands two.
        s.evicted_at = time.monotonic() - 10.0
        router._healthy_indices()
        assert s.revive_probes == 1
        s.evicted_at = time.monotonic() - 20.0
        router._healthy_indices()
        assert s.revive_probes == 2 and s.revive_backoff == 4.0
        # A succeeding probe revives AND resets the backoff.
        a.probe_ok = True
        a.fail = False
        s.evicted_at = time.monotonic() - 40.0
        router._healthy_indices()
        assert s.healthy and s.revive_backoff == 1.0
        assert router.stats()["router"]["replicas"][0]["revive_probes"] == 3

    def test_backoff_is_capped(self):
        a = FakeReplica("a", fail=True)
        b = FakeReplica("b")
        router = _router(a, b, fail_threshold=1, revive_sec=1.0)
        router.submit(_req(list(range(4))))
        s = router._states[0]
        a.probe_ok = False
        for _ in range(8):
            s.evicted_at = time.monotonic() - 1e6  # always due
            router._healthy_indices()
        assert s.revive_backoff == ReplicaRouter._REVIVE_BACKOFF_CAP


class TestCanarySplit:
    def test_canary_excluded_from_placement_at_zero_split(self):
        a, b = FakeReplica("a", load=9.0), FakeReplica("b", load=0.0)
        router = _router(a, b)
        router.set_canary(1)  # b would otherwise win every placement
        for base in range(0, 40, 8):
            assert router.select(np.arange(base, base + 8, dtype=np.int32)) == 0
        assert router.stats()["router"]["canary"] == {
            "index": 1, "traffic_frac": 0.0, "routed": 0,
        }
        router.clear_canary()
        assert router.canary_index is None
        assert router.select(np.arange(8, dtype=np.int32)) == 1

    def test_full_split_steers_all_traffic_to_the_canary(self):
        a, b = FakeReplica("a"), FakeReplica("b", load=50.0)
        router = _router(a, b)
        router.set_canary(1, traffic_frac=1.0, seed=3)
        for _ in range(5):
            assert router.select(np.arange(8, dtype=np.int32)) == 1
        assert router.canary_routed == 5
        assert router.stats()["router"]["canary"]["routed"] == 5

    def test_canary_validation(self):
        router = _router(FakeReplica("a"), FakeReplica("b"))
        with pytest.raises(ValueError, match="no replica index"):
            router.set_canary(7)
        with pytest.raises(ValueError, match="traffic_frac"):
            router.set_canary(0, traffic_frac=1.5)

    def test_sole_remaining_replica_serves_even_as_canary(self):
        """With every proven replica gone the canary is the fleet —
        refusing it would fail requests for placement hygiene."""
        a, b = FakeReplica("a", load=0.0, fail=True), FakeReplica("b")
        router = _router(a, b, fail_threshold=3, revive_sec=60.0)
        router.set_canary(1)
        r = router.submit(_req(list(range(4))))  # a fails -> failover
        assert r.finish_reason == "length"
        assert any(x is r for x in b.served)
        assert router.failovers == 1

    def test_failover_prefers_proven_replicas_over_the_canary(self):
        a = FakeReplica("a", load=0.0, fail=True)
        b, c = FakeReplica("b", load=5.0), FakeReplica("c", load=0.0)
        router = _router(a, b, c, fail_threshold=3, revive_sec=60.0)
        router.set_canary(2)  # c is cheapest but unproven
        r = router.submit(_req(list(range(4))))
        assert any(x is r for x in b.served)
        assert not c.served


class SteppedReplica(FakeReplica):
    """FakeReplica whose stats carry the hot-swap params block."""

    def __init__(self, name, step=None, epoch=None, **kw):
        super().__init__(name, **kw)
        self.step = step
        self.epoch = epoch

    def stats(self):
        s = super().stats()
        s["params"] = {"step": self.step, "epoch": self.epoch}
        return s


class TestEpochDivergence:
    def test_converged_fleet_reports_zero(self):
        router = _router(
            SteppedReplica("a", step=100, epoch=1),
            SteppedReplica("b", step=100, epoch=2),  # epochs local, steps global
        )
        s = router.stats()["router"]
        assert s["epoch_divergence"] == 0
        assert s["replicas"][0]["param_step"] == 100
        assert s["replicas"][1]["param_epoch"] == 2

    def test_mixed_steps_diverge(self):
        router = _router(
            SteppedReplica("a", step=100, epoch=1),
            SteppedReplica("b", step=200, epoch=1),
        )
        assert router.stats()["router"]["epoch_divergence"] == 1

    def test_evicted_replicas_do_not_count(self):
        a = SteppedReplica("a", step=100, fail=True)
        b = SteppedReplica("b", step=200)
        router = _router(a, b, fail_threshold=1, revive_sec=60.0)
        router.submit(_req(list(range(4))))  # evicts a
        assert router.stats()["router"]["epoch_divergence"] == 0

    def test_divergence_gauge_published(self):
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        registry = MetricsRegistry(None)
        router = _router(
            SteppedReplica("a", step=1),
            SteppedReplica("b", step=2),
            registry=registry,
        )
        router.stats()
        latest = dict(registry.latest())
        assert latest["router/epoch_divergence"][0] == 1.0
        assert latest["router/replica1_param_step"][0] == 2.0
        assert latest["router/canary_routed"][0] == 0.0


class TestReloadReplica:
    def test_reloads_exactly_one_replica(self):
        a, b = FakeReplica("a"), FakeReplica("b")
        router = _router(a, b)
        out = router.reload_replica(1, params=object(), step=9)
        assert out == {"replica": "b", "step": 9}
        assert b.reloads == [9] and a.reloads == []

    def test_invalid_index_and_failure_surface(self):
        a = FakeReplica("a")
        router = _router(a)
        with pytest.raises(ValueError, match="no replica index"):
            router.reload_replica(3, params=object())
        a.reload_error = "bad payload"
        with pytest.raises(RuntimeError, match="bad payload"):
            router.reload_replica(0, params=object())


# ---------------------------------------------------------------------------
# slow: real engines — drills that compile the tiny model
# ---------------------------------------------------------------------------


def _tiny_stack(vocab=32, block=64):
    import jax
    import jax.numpy as jnp
    from flax.linen import meta as nn_meta

    from llmtrain_tpu.models.gpt import GPT

    model = GPT(
        vocab_size=vocab,
        block_size=block,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = nn_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
            "params"
        ]
    )
    params2 = nn_meta.unbox(
        model.init(jax.random.PRNGKey(7), jnp.zeros((1, 4), jnp.int32))[
            "params"
        ]
    )
    return model, params, params2


def _reference(model, params, req: ServeRequest) -> list[int]:
    import jax

    from llmtrain_tpu.generation import generate

    out = generate(
        model,
        params,
        req.prompt_ids[None, :],
        max_new_tokens=req.max_new_tokens,
        temperature=req.temperature,
        eos_token_id=req.eos_token_id,
        rng=jax.random.key(req.seed),
    )
    toks = [int(t) for t in np.asarray(out)[0, req.prompt_ids.shape[0]:]]
    if req.eos_token_id is not None and req.eos_token_id in toks:
        toks = toks[: toks.index(req.eos_token_id) + 1]
    return toks


@pytest.mark.slow
class TestFleetDrills:
    def test_two_replica_drill_hot_swap_zero_failures(self):
        """The acceptance drill: 2 replicas, shared-prefix + long/short
        mix under chunked prefill, a mid-drill rolling hot swap — zero
        failed requests, prefix hits on both replicas, and bitwise
        parity against generate() on the params each request was
        ADMITTED under."""
        from llmtrain_tpu.serving import (
            ContinuousBatchingScheduler,
            InProcessReplica,
            PagedDecodeEngine,
            build_requests,
            run_loadgen,
        )

        model, params, params2 = _tiny_stack()

        def mk(i):
            eng = PagedDecodeEngine(
                model,
                params,
                block_tokens=4,
                max_batch_slots=4,
                prompt_buckets=[8, 16, 32],
                batch_buckets=[2, 4],
                prefix_cache=True,
                prefill_chunk=8,
            )
            sched = ContinuousBatchingScheduler(eng).start()
            return InProcessReplica(sched, f"replica{i}")

        router = ReplicaRouter([mk(0), mk(1)])
        try:
            reqs = build_requests(
                num_requests=20,
                seed=11,
                vocab_size=32,
                prompt_tokens_min=4,
                prompt_tokens_max=9,
                max_new_tokens=6,
                shared_prefix_tokens=12,
                shared_prefix_count=2,
                long_fraction=0.25,
                long_prompt_tokens=26,
            )
            swap_results: list[dict] = []

            def swapper():
                time.sleep(0.3)
                swap_results.extend(
                    router.rolling_reload(
                        params=params2, step=777, checkpoint="ckpt-777"
                    )
                )

            t = threading.Thread(target=swapper)
            t.start()
            block = run_loadgen(
                router, reqs, rate_rps=60.0, seed=5, timeout_sec=300.0
            )
            t.join()

            assert block["requests"]["failed"] == 0
            assert block["requests"]["timed_out"] == 0
            assert block["requests"]["completed"] == len(reqs)
            assert all("error" not in r for r in swap_results), swap_results
            # Bitwise parity on ADMITTED params (hot-swap audit trail).
            for r in reqs:
                p = params2 if r.params_step == 777 else params
                assert r.tokens == _reference(model, p, r), r.params_step
            rb = block["router"]
            assert rb["replicas_healthy"] == 2
            assert rb["requests_routed"] == len(reqs)
            assert rb["fleet_prefix"]["hits"] > 0
            assert 0 < block["prefix_cache"]["hit_rate"] <= 1.0
            # Chunked prefill keeps decode interleaved: the long cohort
            # must not blow up the short cohort's inter-token gap.
            p99 = block["slo"]["per_token_ms"]["p99"]
            assert p99 is not None and p99 < 2000.0
        finally:
            router.close()

    def test_chunked_prefill_matches_whole_prompt_prefill(self):
        """A prompt beyond the largest bucket streams in by chunks and
        still decodes bit-identically to generate()."""
        from llmtrain_tpu.serving import (
            ContinuousBatchingScheduler,
            PagedDecodeEngine,
        )

        model, params, _ = _tiny_stack()
        eng = PagedDecodeEngine(
            model,
            params,
            block_tokens=4,
            max_batch_slots=2,
            prompt_buckets=[8, 16],
            batch_buckets=[1, 2],
            prefill_chunk=8,
        )
        sched = ContinuousBatchingScheduler(eng).start()
        try:
            rng = np.random.default_rng(3)
            long = _req(list(rng.integers(0, 32, size=40)))  # > bucket 16
            short = _req(list(rng.integers(0, 32, size=5)))
            sched.submit(long)
            sched.submit(short)
            assert long.done.wait(120) and short.done.wait(120)
            for r in (long, short):
                assert r.finish_reason == "length", r.error
                assert r.tokens == _reference(model, params, r)
            # The chunk pads into bucket 8: no new prefill programs
            # beyond the bucketed budget.
            assert eng.compile_stats()["within_budget"]
        finally:
            sched.close()

    def test_hot_swap_pins_in_flight_requests_to_their_epoch(self):
        from llmtrain_tpu.serving import (
            ContinuousBatchingScheduler,
            PagedDecodeEngine,
        )

        model, params, params2 = _tiny_stack()
        eng = PagedDecodeEngine(
            model,
            params,
            block_tokens=4,
            max_batch_slots=2,
            prompt_buckets=[8],
            batch_buckets=[1, 2],
            prefix_cache=True,
        )
        sched = ContinuousBatchingScheduler(eng)
        try:
            old = _req(list(range(6)))
            old.max_new_tokens = 8
            sched.submit(old)
            # Admit on epoch 0 with a manual step, then swap mid-flight.
            sched.step()
            sched.hot_swap(params2, step=5, checkpoint="ckpt-5")
            new = _req(list(range(10, 16)))
            new.max_new_tokens = 8
            sched.submit(new)
            for _ in range(200):
                if old.done.is_set() and new.done.is_set():
                    break
                sched.step()
            assert old.finish_reason == "length"
            assert new.finish_reason == "length"
            assert old.params_step is None  # admitted before the swap
            assert new.params_step == 5
            assert old.tokens == _reference(model, params, old)
            assert new.tokens == _reference(model, params2, new)
            assert sched.stats()["params"]["hot_swaps"] == 1
            # Old epoch params GC'd once their last reader retired.
            assert sched.stats()["params"]["live_epochs"] == [1]
        finally:
            sched.close()

    def test_batched_speculative_greedy_parity(self):
        from llmtrain_tpu.serving import (
            ContinuousBatchingScheduler,
            PagedDecodeEngine,
        )

        model, params, draft_params = _tiny_stack()
        kw = dict(
            block_tokens=4,
            max_batch_slots=2,
            prompt_buckets=[8],
            batch_buckets=[1, 2],
        )
        sched = ContinuousBatchingScheduler(
            PagedDecodeEngine(model, params, **kw),
            policy="speculative",
            model=model,
            params=params,
            draft_model=model,
            draft_params=draft_params,
            draft_engine=PagedDecodeEngine(model, draft_params, **kw),
            gamma=3,
        ).start()
        try:
            reqs = [_req(list(range(i, i + 5))) for i in range(4)]
            for r in reqs:
                r.max_new_tokens = 8
                sched.submit(r)
            for r in reqs:
                assert r.done.wait(120)
                assert r.finish_reason == "length", r.error
                assert r.tokens == _reference(model, params, r)
            s = sched.stats()["speculative"]
            assert s["mode"] == "batched"
            assert s["rounds"] > 0 and 0 < s["acceptance_rate"] <= 1.0
        finally:
            sched.close()
