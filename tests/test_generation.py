"""Sampling-loop behavior: greedy determinism, shapes, window sliding, eos."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.generation import generate, generate_text, top_next_tokens
from llmtrain_tpu.models.gpt import GPT


@pytest.fixture(scope="module")
def tiny_model():
    model = GPT(
        vocab_size=64,
        block_size=16,
        d_model=32,
        n_layers=1,
        n_heads=4,
        d_ff=64,
        dropout=0.0,
    )
    tokens = np.zeros((1, 4), np.int32)
    params = model.init({"params": jax.random.key(0)}, tokens, deterministic=True)[
        "params"
    ]
    return model, params


class _ByteTokenizer:
    def encode(self, text):
        return [b % 64 for b in text.encode()]

    def decode(self, ids):
        return "".join(chr(97 + (i % 26)) for i in ids)


class TestGenerate:
    def test_shapes_and_prompt_preserved(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[1, 2, 3]], np.int32)
        out = generate(model, params, prompt, max_new_tokens=5, temperature=0.0)
        assert out.shape == (1, 8)
        np.testing.assert_array_equal(out[:, :3], prompt)
        assert ((out >= 0) & (out < 64)).all()

    def test_greedy_deterministic(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[5, 9]], np.int32)
        a = generate(model, params, prompt, max_new_tokens=6, temperature=0.0)
        b = generate(model, params, prompt, max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(a, b)

    def test_sampling_seed_reproducible(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[5, 9]], np.int32)
        kw = dict(max_new_tokens=6, temperature=0.8, top_k=10)
        a = generate(model, params, prompt, rng=jax.random.key(3), **kw)
        b = generate(model, params, prompt, rng=jax.random.key(3), **kw)
        c = generate(model, params, prompt, rng=jax.random.key(4), **kw)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)  # overwhelmingly likely for 6 tokens

    def test_top_k_larger_than_vocab_is_clamped(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[5, 9]], np.int32)
        out = generate(
            model,
            params,
            prompt,
            max_new_tokens=4,
            temperature=0.8,
            top_k=1000,  # vocab is 64; must clamp, not raise
            rng=jax.random.key(0),
        )
        assert out.shape == (1, 6)
        assert ((out >= 0) & (out < 64)).all()

    def test_top_k_zero_disables_filtering(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[5, 9]], np.int32)
        a = generate(
            model, params, prompt, max_new_tokens=4, temperature=0.8,
            top_k=0, rng=jax.random.key(1),
        )
        b = generate(
            model, params, prompt, max_new_tokens=4, temperature=0.8,
            top_k=None, rng=jax.random.key(1),
        )
        np.testing.assert_array_equal(a, b)

    def test_out_of_vocab_prompt_rejected(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(ValueError, match=r"\[0, 64\)"):
            generate(model, params, np.array([[5, 99]], np.int32), max_new_tokens=2)
        with pytest.raises(ValueError, match=r"\[0, 64\)"):
            generate(model, params, np.array([[-1]], np.int32), max_new_tokens=2)

    def test_cached_matches_windowed_greedy(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[3, 1, 4, 1, 5]], np.int32)
        cached = generate(
            model, params, prompt, max_new_tokens=8, temperature=0.0, use_cache=True
        )
        windowed = generate(
            model, params, prompt, max_new_tokens=8, temperature=0.0, use_cache=False
        )
        np.testing.assert_array_equal(cached, windowed)

    def test_cached_matches_windowed_sampled(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[3, 1, 4]], np.int32)
        kw = dict(max_new_tokens=6, temperature=0.7, top_k=8, rng=jax.random.key(11))
        cached = generate(model, params, prompt, use_cache=True, **kw)
        windowed = generate(model, params, prompt, use_cache=False, **kw)
        np.testing.assert_array_equal(cached, windowed)

    def test_cached_batch_and_eos(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[1, 2], [3, 4], [5, 6]], np.int32)
        out = generate(
            model,
            params,
            prompt,
            max_new_tokens=6,
            temperature=0.0,
            eos_token_id=7,
            use_cache=True,
        )
        assert out.shape == (3, 8)
        for row in out:
            hits = np.where(row[2:] == 7)[0]
            if hits.size:  # everything after first eos stays eos
                assert (row[2 + hits[0] :] == 7).all()

    def test_use_cache_true_rejected_past_block_size(self, tiny_model):
        model, params = tiny_model  # block_size 16
        prompt = np.array([[1] * 10], np.int32)
        with pytest.raises(ValueError, match="block_size"):
            generate(
                model, params, prompt, max_new_tokens=10, temperature=0.0, use_cache=True
            )
        # auto mode silently falls back to the windowed path
        out = generate(model, params, prompt, max_new_tokens=10, temperature=0.0)
        assert out.shape == (1, 20)

    def test_greedy_matches_stepwise_argmax(self, tiny_model):
        """The fused loop must equal naive one-token-at-a-time decoding."""
        model, params = tiny_model
        prompt = np.array([[7, 3, 11]], np.int32)
        out = generate(model, params, prompt, max_new_tokens=4, temperature=0.0)

        ids = prompt.copy()
        for _ in range(4):
            logits = model.apply({"params": params}, ids, deterministic=True)
            nxt = np.argmax(np.asarray(logits[:, -1]), axis=-1)
            ids = np.concatenate([ids, nxt[:, None].astype(np.int32)], axis=1)
        np.testing.assert_array_equal(out, ids)

    def test_window_slides_past_block_size(self, tiny_model):
        model, params = tiny_model  # block_size 16
        prompt = np.arange(12, dtype=np.int32)[None, :] % 64
        out = generate(model, params, prompt, max_new_tokens=10, temperature=0.0)
        assert out.shape == (1, 22)  # > block_size: window slid, no raise

    def test_eos_freezes_row(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[1, 2]], np.int32)
        greedy = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
        eos = int(greedy[0, 2])  # first generated token becomes "eos"
        out = generate(
            model, params, prompt, max_new_tokens=8, temperature=0.0, eos_token_id=eos
        )
        np.testing.assert_array_equal(out[0, 2:], np.full(8, eos))

    def test_batch_decode(self, tiny_model):
        model, params = tiny_model
        prompt = np.array([[1, 2, 3], [9, 8, 7]], np.int32)
        out = generate(model, params, prompt, max_new_tokens=4, temperature=0.0)
        assert out.shape == (2, 7)
        single = generate(model, params, prompt[1:], max_new_tokens=4, temperature=0.0)
        np.testing.assert_array_equal(out[1], single[0])

    def test_empty_prompt_rejected(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(ValueError, match="at least one token"):
            generate(model, params, np.zeros((1, 0), np.int32), max_new_tokens=2)


class TestLogprobs:
    def _model(self):
        from flax.linen import meta as nn_meta

        from llmtrain_tpu.models.gpt import GPT

        m = GPT(vocab_size=32, block_size=32, d_model=32, n_layers=1,
                n_heads=2, d_ff=64, dropout=0.0)
        p = nn_meta.unbox(
            m.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32),
                   deterministic=True)["params"]
        )
        return m, p

    @pytest.mark.parametrize("use_cache", [True, False])
    def test_greedy_logprobs_match_manual_forward(self, use_cache):
        """Per-token logprob == log_softmax of a fresh forward at each
        prefix, at the emitted token — both decode paths."""
        m, p = self._model()
        prompt = np.asarray([[3, 1, 4]], np.int32)
        out, lps = generate(
            m, p, prompt, max_new_tokens=4, temperature=0.0,
            use_cache=use_cache, return_logprobs=True,
        )
        assert lps.shape == (1, 4)
        for j in range(4):
            prefix = jnp.asarray(out[:, : prompt.shape[1] + j])
            logits = m.apply({"params": p}, prefix, deterministic=True)
            want = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1
            )[0, out[0, prompt.shape[1] + j]]
            np.testing.assert_allclose(lps[0, j], float(want), atol=1e-4)

    @pytest.mark.parametrize("use_cache", [True, False])
    def test_post_eos_logprobs_are_zero(self, use_cache):
        """Forced post-eos padding reports 0.0, so sum(logprobs) scores
        exactly the real emissions (the first eos keeps its logprob)."""
        m, p = self._model()
        prompt = np.asarray([[3, 1, 4]], np.int32)
        free = generate(m, p, prompt, max_new_tokens=6, temperature=0.0,
                        use_cache=use_cache)
        eos = int(free[0, prompt.shape[1]])  # first generated token = eos
        out, lps = generate(
            m, p, prompt, max_new_tokens=6, temperature=0.0,
            use_cache=use_cache, eos_token_id=eos, return_logprobs=True,
        )
        assert (out[0, prompt.shape[1] :] == eos).all()
        assert lps[0, 0] < 0.0  # the real first emission
        np.testing.assert_allclose(lps[0, 1:], 0.0)

    def test_default_return_unchanged(self):
        m, p = self._model()
        prompt = np.asarray([[3, 1, 4]], np.int32)
        out = generate(m, p, prompt, max_new_tokens=3, temperature=0.0)
        assert isinstance(out, np.ndarray) and out.shape == (1, 6)

    def test_zero_new_tokens(self):
        m, p = self._model()
        prompt = np.asarray([[3, 1]], np.int32)
        out, lps = generate(m, p, prompt, max_new_tokens=0,
                            return_logprobs=True)
        assert out.tolist() == prompt.tolist() and lps.shape == (1, 0)


class TestTextHelpers:
    def test_generate_text_roundtrip(self, tiny_model):
        model, params = tiny_model
        text = generate_text(
            model,
            params,
            _ByteTokenizer(),
            "hello",
            max_new_tokens=4,
            temperature=0.0,
        )
        assert isinstance(text, str) and len(text) == 9

    def test_top_next_tokens(self, tiny_model):
        model, params = tiny_model
        top = top_next_tokens(model, params, _ByteTokenizer(), "abc", k=5)
        assert len(top) == 5
        probs = [p for _, p in top]
        assert probs == sorted(probs, reverse=True)
        assert all(0.0 <= p <= 1.0 for p in probs)


class TestGenerateNewModelFamilies:
    """generate() works for every registered LM family, not just gpt."""

    @pytest.mark.slow  # budget: tier-1 siblings test_moe_gpt_cached_matches_windowed + test_pipeline forward parity
    def test_pipeline_gpt_windowed_path(self):
        from llmtrain_tpu.models.gpt_pipeline import PipelineGPT

        model = PipelineGPT(
            vocab_size=64, block_size=16, d_model=32, n_layers=2, n_heads=4, d_ff=64
        )
        params = model.init(
            {"params": jax.random.key(0)}, np.zeros((1, 4), np.int32)
        )["params"]
        prompt = np.array([[1, 2, 3]], np.int32)
        # No for_decoding() on the stacked model -> sliding-window path.
        out = generate(model, params, prompt, max_new_tokens=5, temperature=0.0)
        assert out.shape == (1, 8)
        np.testing.assert_array_equal(out[:, :3], prompt)
        out2 = generate(model, params, prompt, max_new_tokens=5, temperature=0.0)
        np.testing.assert_array_equal(out, out2)

    def test_moe_gpt_cached_matches_windowed(self):
        from llmtrain_tpu.models.gpt import GPT

        # capacity_factor=8 makes per-expert capacity >= the window length,
        # so no token is ever capacity-dropped: the two decode paths are
        # only guaranteed numerically identical when routing drops nothing
        # (the windowed path routes all window positions jointly; the
        # cached path routes one token at a time).
        model = GPT(
            vocab_size=64, block_size=16, d_model=32, n_layers=1, n_heads=4,
            d_ff=64, dropout=0.0, n_experts=2, capacity_factor=8.0,
        )
        params = model.init(
            {"params": jax.random.key(1)}, np.zeros((1, 4), np.int32)
        )["params"]
        prompt = np.array([[4, 5]], np.int32)
        cached = generate(
            model, params, prompt, max_new_tokens=6, temperature=0.0, use_cache=True
        )
        windowed = generate(
            model, params, prompt, max_new_tokens=6, temperature=0.0, use_cache=False
        )
        np.testing.assert_array_equal(cached, windowed)


class TestPromptsFileCLI:
    """--prompts-file: batched generation, one prompt per line, grouped by
    token length into rectangular decode batches (cli.py)."""

    def _train_and_generate(self, tmp_path, gen_args):
        import subprocess
        import sys

        import yaml

        cfg = {
            "run": {"name": "gen-batch", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt",
                "block_size": 32,
                "d_model": 16,
                "n_layers": 1,
                "n_heads": 4,
                "d_ff": 32,
                "dropout": 0.0,
                "vocab_size": 256,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 2,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": 2,
                "save_every_steps": 2,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))

        def run(argv):
            return subprocess.run(
                [sys.executable, "-m", "llmtrain_tpu", *argv],
                capture_output=True,
                text=True,
                timeout=300,
            )

        train = run(["train", "--config", str(cfg_path), "--run-id", "g", "--json"])
        assert train.returncode == 0, train.stderr
        return run(["generate", "--config", str(cfg_path), "--from", "g", *gen_args])

    def test_mixed_length_prompts_keep_order(self, tmp_path):
        import json as _json

        prompts = ["alpha", "be", "gamma", "xy"]  # lengths 5, 2, 5, 2
        pfile = tmp_path / "prompts.txt"
        pfile.write_text("\n".join(prompts) + "\n\n")
        proc = self._train_and_generate(
            tmp_path,
            ["--prompts-file", str(pfile), "--max-new-tokens", "4", "--json"],
        )
        assert proc.returncode == 0, proc.stderr
        payload = _json.loads(proc.stdout)
        results = payload["results"]
        assert [r["prompt"] for r in results] == prompts  # input order kept
        for p, r in zip(prompts, results):
            assert r["prompt_ids"] == list(p.encode("utf-8"))
            assert len(r["completion_ids"]) == 4
            assert r["output_ids"][: len(p)] == r["prompt_ids"]

    def _generate_only(self, tmp_path, gen_args):
        """Bad-input paths fail before any checkpoint is needed, so no
        training subprocess — just the generate call with a bogus --from."""
        import subprocess
        import sys

        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(
            yaml.safe_dump(
                {
                    "run": {"name": "gen-err", "device": "cpu"},
                    "model": {
                        "name": "gpt",
                        "block_size": 8,
                        "d_model": 16,
                        "n_layers": 1,
                        "n_heads": 4,
                        "d_ff": 32,
                        "vocab_size": 256,
                        "extra": {"tokenizer": "byte"},
                    },
                    "data": {"name": "dummy_text"},
                    "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                    "mlflow": {"enabled": False},
                },
                sort_keys=False,
            )
        )
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "llmtrain_tpu",
                "generate",
                "--config",
                str(cfg_path),
                "--from",
                "never-resolved",
                *gen_args,
            ],
            capture_output=True,
            text=True,
            timeout=120,
        )

    def test_empty_prompts_file_exit_1(self, tmp_path):
        pfile = tmp_path / "prompts.txt"
        pfile.write_text("\n  \n")
        proc = self._generate_only(tmp_path, ["--prompts-file", str(pfile), "--json"])
        assert proc.returncode == 1
        assert "no non-empty prompt lines" in proc.stderr

    def test_missing_prompts_file_clean_error(self, tmp_path):
        proc = self._generate_only(
            tmp_path, ["--prompts-file", str(tmp_path / "nope.txt")]
        )
        assert proc.returncode == 1
        assert "cannot read --prompts-file" in proc.stderr

    @pytest.mark.slow  # budget: tier-1 sibling test_mixed_length_prompts_keep_order covers the prompts-file contract
    def test_single_line_file_still_emits_results_array(self, tmp_path):
        import json as _json

        pfile = tmp_path / "prompts.txt"
        pfile.write_text("solo\n")
        proc = self._train_and_generate(
            tmp_path,
            ["--prompts-file", str(pfile), "--max-new-tokens", "2", "--json"],
        )
        assert proc.returncode == 0, proc.stderr
        payload = _json.loads(proc.stdout)
        assert len(payload["results"]) == 1  # stable schema per input mode


class TestTopP:
    """Nucleus (top-p) sampling in the shared sampler."""

    def _logits(self):
        # probs ~ [0.5, 0.3, 0.1, 0.06, 0.04]: the 0.75-nucleus (exclusive
        # cumulative < 0.75) is exactly tokens {0, 1}.
        p = np.array([0.5, 0.3, 0.1, 0.06, 0.04])
        return jnp.asarray(np.log(p)[None, :], jnp.float32)

    def test_samples_stay_in_nucleus(self):
        from llmtrain_tpu.generation import _sample_next

        logits = self._logits()
        seen = set()
        for i in range(200):
            tok = int(
                _sample_next(
                    logits, jax.random.key(3), i, temperature=1.0, top_k=None,
                    top_p=0.75,
                )[0]
            )
            seen.add(tok)
        assert seen <= {0, 1}
        assert seen == {0, 1}  # both nucleus members actually drawn

    def test_top_p_one_is_unfiltered(self):
        from llmtrain_tpu.generation import _sample_next

        logits = self._logits()
        a = [
            int(_sample_next(logits, jax.random.key(5), i, temperature=1.0,
                             top_k=None, top_p=None)[0])
            for i in range(50)
        ]
        b = [
            int(_sample_next(logits, jax.random.key(5), i, temperature=1.0,
                             top_k=None, top_p=1.0)[0])
            for i in range(50)
        ]
        assert a == b

    def test_always_keeps_argmax(self):
        """A tiny top_p still keeps the most likely token (never all -inf)."""
        from llmtrain_tpu.generation import _sample_next

        logits = self._logits()
        for i in range(20):
            assert int(
                _sample_next(logits, jax.random.key(7), i, temperature=1.0,
                             top_k=None, top_p=1e-6)[0]
            ) == 0

    def test_generate_accepts_top_p(self, tiny_model):
        from llmtrain_tpu.generation import generate

        model, params = tiny_model
        out = generate(
            model, params, np.asarray([1, 2, 3], np.int32), max_new_tokens=4,
            temperature=0.9, top_p=0.9, rng=jax.random.key(0),
        )
        assert out.shape == (1, 7)

    def test_out_of_band_top_p_disables(self, tiny_model):
        """0 and >=1 disable the filter (mirrors the --top-k 0 convention)."""
        from llmtrain_tpu.generation import generate

        model, params = tiny_model
        prompt = np.asarray([[1, 2]], np.int32)
        kw = dict(max_new_tokens=4, temperature=0.8, rng=jax.random.key(2))
        base = generate(model, params, prompt, top_p=None, **kw)
        for p in (0.0, 1.0, 1.5):
            np.testing.assert_array_equal(
                generate(model, params, prompt, top_p=p, **kw), base
            )
