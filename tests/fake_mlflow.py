"""A faithful fake of the mlflow API surface MLflowTracker uses.

The image ships without mlflow (the [mlflow] extra is only installed in
the k8s images), so tests/test_mlflow_roundtrip.py skips here and
``tracking/mlflow.py`` would otherwise never execute anywhere the fast
suite runs. This module lets tests inject a behaviorally-accurate stand-in
via ``sys.modules["mlflow"]`` — the tracker's lazy ``import mlflow``
(tracking/mlflow.py:53) then resolves to this module and every line of the
tracker runs for real.

Faithfulness notes (matched to mlflow 2.x semantics the tracker relies on):

* ``log_params`` stores every value as ``str(value)`` — mlflow params are
  strings on read-back, which is exactly what the parity test asserts
  against the native backend's TEXT column.
* ``start_run(run_id=...)`` reattaches to a known run (raises for an
  unknown id, as mlflow does); ``start_run(run_name=...)`` creates one in
  the CURRENT experiment set by ``set_experiment``.
* ``search_runs(..., filter_string='tags."k" = \'v\'', output_format=
  "list")`` supports the one filter shape the tracker emits
  (tracking/mlflow.py:100) and returns Run-shaped objects with
  ``.info.run_id``.
* ``log_metrics`` records (key, value, step, timestamp) rows per call —
  history, not last-write-wins — like mlflow's metric store.
* State persists in a module-global store keyed by tracking URI for the
  lifetime of the process, so a second ``MLflowTracker`` (the
  auto-resume relaunch path) sees the first one's runs. Call ``reset()``
  between tests.
"""

from __future__ import annotations

import re
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

_FILTER_RE = re.compile(r'^tags\."([^"]+)"\s*=\s*\'([^\']*)\'$')


@dataclass
class _Run:
    run_id: str
    experiment_id: str
    run_name: str
    status: str = "RUNNING"
    start_time: float = field(default_factory=time.time)
    end_time: float | None = None
    tags: dict[str, str] = field(default_factory=dict)
    params: dict[str, str] = field(default_factory=dict)
    metrics: list[dict[str, Any]] = field(default_factory=list)
    artifacts: list[tuple[str, str | None]] = field(default_factory=list)

    @property
    def info(self) -> "_Run":  # mlflow Run.info.run_id shape
        return self


class _Experiment:
    def __init__(self, experiment_id: str, name: str) -> None:
        self.experiment_id = experiment_id
        self.name = name


class _Store:
    def __init__(self) -> None:
        self.experiments: dict[str, _Experiment] = {}
        self.runs: dict[str, _Run] = {}

    def experiment(self, name: str) -> _Experiment:
        if name not in self.experiments:
            self.experiments[name] = _Experiment(str(len(self.experiments)), name)
        return self.experiments[name]


_stores: dict[str, _Store] = {}
_tracking_uri: str = "file:./mlruns"
_current_experiment: str = "Default"
_active: _Run | None = None


def reset() -> None:
    global _tracking_uri, _current_experiment, _active
    _stores.clear()
    _tracking_uri = "file:./mlruns"
    _current_experiment = "Default"
    _active = None


def _store() -> _Store:
    return _stores.setdefault(_tracking_uri, _Store())


def set_tracking_uri(uri: str) -> None:
    global _tracking_uri
    _tracking_uri = uri


def get_tracking_uri() -> str:
    return _tracking_uri


def set_experiment(name: str) -> _Experiment:
    global _current_experiment
    _current_experiment = name
    return _store().experiment(name)


def get_experiment_by_name(name: str) -> _Experiment | None:
    return _store().experiments.get(name)


def start_run(run_id: str | None = None, run_name: str | None = None) -> _Run:
    global _active
    store = _store()
    if run_id is not None:
        if run_id not in store.runs:
            raise Exception(f"Run with id={run_id} not found")  # mlflow-like
        run = store.runs[run_id]
        run.status = "RUNNING"
        run.end_time = None
    else:
        exp = store.experiment(_current_experiment)
        run = _Run(
            run_id=uuid.uuid4().hex,
            experiment_id=exp.experiment_id,
            run_name=run_name or f"run-{len(store.runs)}",
        )
        store.runs[run.run_id] = run
    _active = run
    return run


def active_run() -> _Run | None:
    return _active


def _require_active() -> _Run:
    if _active is None:
        raise Exception("no active run; call start_run first")
    return _active


def set_tag(key: str, value: Any) -> None:
    _require_active().tags[key] = str(value)


def log_params(params: dict[str, Any]) -> None:
    run = _require_active()
    for k, v in params.items():
        run.params[k] = str(v)


def log_metrics(metrics: dict[str, float], step: int | None = None) -> None:
    run = _require_active()
    now = time.time()
    for k, v in metrics.items():
        run.metrics.append(
            {"key": k, "value": float(v), "step": step, "timestamp": now}
        )


def log_artifact(local_path: str, artifact_path: str | None = None) -> None:
    _require_active().artifacts.append((local_path, artifact_path))


def end_run(status: str = "FINISHED") -> None:
    global _active
    if _active is not None:
        _active.status = status
        _active.end_time = time.time()
        _active = None


def search_runs(
    experiment_ids: list[str] | None = None,
    filter_string: str = "",
    max_results: int = 1000,
    output_format: str = "pandas",
) -> list[_Run]:
    if output_format != "list":
        raise NotImplementedError("fake_mlflow only supports output_format='list'")
    m = _FILTER_RE.match(filter_string.strip())
    if filter_string and not m:
        raise Exception(f"unsupported filter: {filter_string!r}")
    out = []
    for run in _store().runs.values():
        if experiment_ids is not None and run.experiment_id not in experiment_ids:
            continue
        if m and run.tags.get(m.group(1)) != m.group(2):
            continue
        out.append(run)
        if len(out) >= max_results:
            break
    return out
