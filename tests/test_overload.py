"""SLO-aware overload control (serving/overload.py + its wiring).

Tier-1 keeps to pure units — token buckets, the EWMA wait estimator, the
weighted-class queue, brownout hysteresis, the retry budget, the
per-client gate, admission verdicts — plus scheduler integration over a
FakeEngine (real PagedKVPool accounting, no jax compiles) and the HTTP /
router rejection surfaces. The seeded 10x-burst acceptance drill
(parity, shedding, brownout entry AND exit, exact pool accounting)
compiles a model and runs under ``@pytest.mark.slow`` via
``make verify-overload``.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from llmtrain_tpu.serving.overload import (
    REASON_DEADLINE_EXCEEDED,
    REASON_DEADLINE_UNMEETABLE,
    REASON_QUEUE_FULL,
    REASON_RATE_LIMITED,
    REASON_RETRY_BUDGET,
    REJECT_REASONS,
    Brownout,
    ClientRateGate,
    EwmaWaitEstimator,
    OverloadController,
    RetryBudget,
    TokenBucket,
    WeightedClassQueue,
    rejected_counter,
)
from llmtrain_tpu.serving.paged_kv import PagedKVPool
from llmtrain_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    ServeRequest,
)


class FakeClock:
    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _req(prompt: int = 4, max_new: int = 4, **kw) -> ServeRequest:
    return ServeRequest(
        prompt_ids=(np.arange(prompt, dtype=np.int32) % 32),
        max_new_tokens=max_new,
        **kw,
    )


# ---------------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_deny_then_refill(self):
        clock = FakeClock()
        b = TokenBucket(2.0, 3, clock=clock)
        assert all(b.try_acquire() for _ in range(3))
        assert not b.try_acquire()
        clock.advance(0.5)  # 2/s * 0.5s = 1 token back
        assert b.try_acquire()
        assert not b.try_acquire()

    def test_refill_caps_at_burst(self):
        clock = FakeClock()
        b = TokenBucket(100.0, 2, clock=clock)
        clock.advance(60.0)
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()

    def test_retry_after_hint(self):
        clock = FakeClock()
        b = TokenBucket(2.0, 1, clock=clock)
        assert b.retry_after() == 0.0
        assert b.try_acquire()
        # 1 token at 2/s = 0.5s away.
        assert b.retry_after() == pytest.approx(0.5)

    def test_validation(self):
        with pytest.raises(ValueError, match="rate"):
            TokenBucket(0.0, 1)
        with pytest.raises(ValueError, match="burst"):
            TokenBucket(1.0, 0)


# ---------------------------------------------------------------------------
# EWMA wait estimator
# ---------------------------------------------------------------------------


class TestEwmaWaitEstimator:
    def test_prior_seeds_prediction(self):
        est = EwmaWaitEstimator(beta=0.8, prior_ms=40.0)
        assert est.predicted_wait_ms(0) == pytest.approx(40.0)
        assert est.predicted_wait_ms(3) == pytest.approx(160.0)

    def test_observation_moves_per_slot(self):
        est = EwmaWaitEstimator(beta=0.5, prior_ms=0.0)
        # wait 100ms at depth 1 -> per-slot sample 50ms, EWMA 25ms.
        est.observe(100.0, 1)
        assert est.per_slot_ms == pytest.approx(25.0)
        assert est.samples == 1

    def test_converges_to_steady_state(self):
        est = EwmaWaitEstimator(beta=0.5, prior_ms=1000.0)
        for _ in range(30):
            est.observe(10.0, 0)
        assert est.per_slot_ms == pytest.approx(10.0, rel=1e-3)

    def test_bad_beta_rejected(self):
        for beta in (0.0, 1.0, -1.0):
            with pytest.raises(ValueError, match="beta"):
                EwmaWaitEstimator(beta=beta)


# ---------------------------------------------------------------------------
# weighted-class queue
# ---------------------------------------------------------------------------


def _wcq() -> WeightedClassQueue:
    return WeightedClassQueue({"interactive": 4, "batch": 1}, "interactive")


class TestWeightedClassQueue:
    def test_wrr_drains_four_to_one(self):
        q = _wcq()
        for i in range(8):
            q.append(_req(priority="interactive", seed=i))
        for i in range(8):
            q.append(_req(priority="batch", seed=100 + i))
        first_five = [q.popleft().priority for _ in range(5)]
        assert first_five.count("interactive") == 4
        assert first_five.count("batch") == 1

    def test_no_class_starves(self):
        # Batch-only backlog: every WRR cycle visits every class, so the
        # weight-1 class drains even with zero interactive traffic.
        q = _wcq()
        for i in range(3):
            q.append(_req(priority="batch", seed=i))
        assert [q.popleft().seed for _ in range(3)] == [0, 1, 2]
        with pytest.raises(IndexError):
            q.popleft()

    def test_appendleft_goes_to_own_class_head(self):
        q = _wcq()
        a, b = _req(priority="batch", seed=1), _req(priority="batch", seed=2)
        q.append(a)
        q.appendleft(b)  # the pool-full retry path
        assert q.popleft() is b

    def test_unknown_priority_falls_back_to_default(self):
        q = _wcq()
        q.append(_req(priority="platinum"))
        assert q.depths() == {"interactive": 1, "batch": 0}

    def test_sweep_removes_matches_keeps_order(self):
        q = _wcq()
        reqs = [_req(priority="interactive", seed=i) for i in range(4)]
        for r in reqs:
            q.append(r)
        out = q.sweep(lambda r: r.seed % 2 == 0)
        assert [r.seed for r in out] == [0, 2]
        assert [q.popleft().seed for _ in range(2)] == [1, 3]

    def test_len_bool_iter(self):
        q = _wcq()
        assert not q and len(q) == 0
        q.append(_req(priority="batch"))
        q.append(_req(priority="interactive"))
        assert q and len(q) == 2
        assert len(list(iter(q))) == 2

    def test_validation(self):
        with pytest.raises(ValueError, match="at least one class"):
            WeightedClassQueue({}, "interactive")
        with pytest.raises(ValueError, match="default class"):
            WeightedClassQueue({"a": 1}, "b")
        with pytest.raises(ValueError, match="weight"):
            WeightedClassQueue({"a": 0}, "a")


# ---------------------------------------------------------------------------
# brownout hysteresis
# ---------------------------------------------------------------------------


class TestBrownout:
    def test_enters_after_consecutive_high_ticks_only(self):
        b = Brownout(high_ms=100.0, low_ms=20.0, enter_ticks=3, exit_ticks=2)
        assert b.tick(150.0) is None
        assert b.tick(150.0) is None
        assert b.tick(50.0) is None  # dip resets the streak
        assert b.tick(150.0) is None
        assert b.tick(150.0) is None
        assert b.tick(150.0) == "entered"
        assert b.active and b.entries == 1

    def test_no_flap_between_watermarks(self):
        b = Brownout(high_ms=100.0, low_ms=20.0, enter_ticks=1, exit_ticks=1)
        assert b.tick(100.0) == "entered"
        # Pressure fell below HIGH but not below LOW: still browned out.
        for _ in range(10):
            assert b.tick(50.0) is None
        assert b.active

    def test_exits_after_consecutive_low_ticks(self):
        b = Brownout(high_ms=100.0, low_ms=20.0, enter_ticks=1, exit_ticks=2)
        assert b.tick(200.0) == "entered"
        assert b.tick(10.0) is None
        assert b.tick(30.0) is None  # bounce resets the exit streak
        assert b.tick(10.0) is None
        assert b.tick(10.0) == "exited"
        assert not b.active and b.exits == 1

    def test_watermark_validation(self):
        with pytest.raises(ValueError, match="watermark"):
            Brownout(high_ms=100.0, low_ms=100.0)


# ---------------------------------------------------------------------------
# retry budget + per-client gate
# ---------------------------------------------------------------------------


class TestRetryBudget:
    def test_spends_then_denies_then_window_resets(self):
        clock = FakeClock()
        rb = RetryBudget(2, 10.0, clock=clock)
        assert rb.try_spend() and rb.try_spend()
        assert not rb.try_spend()
        assert rb.remaining() == 0
        clock.advance(10.0)
        assert rb.remaining() == 2
        assert rb.try_spend()

    def test_validation(self):
        with pytest.raises(ValueError, match="budget"):
            RetryBudget(-1, 1.0)
        with pytest.raises(ValueError, match="window"):
            RetryBudget(1, 0.0)


class TestClientRateGate:
    def test_clients_are_isolated(self):
        clock = FakeClock()
        gate = ClientRateGate(1.0, 1, clock=clock)
        assert gate.check("alice") is None
        assert gate.check("alice") is not None  # burst spent
        assert gate.check("bob") is None  # own bucket

    def test_retry_after_hint_positive(self):
        clock = FakeClock()
        gate = ClientRateGate(2.0, 1, clock=clock)
        assert gate.check("c") is None
        assert gate.check("c") == pytest.approx(0.5)

    def test_lru_cap_bounds_cardinality(self):
        clock = FakeClock()
        gate = ClientRateGate(0.001, 1, max_clients=2, clock=clock)
        assert gate.check("a") is None
        assert gate.check("b") is None
        assert gate.check("c") is None  # evicts "a"
        # "a" comes back with a FRESH burst: its old spent bucket is gone.
        assert gate.check("a") is None


# ---------------------------------------------------------------------------
# controller: admission verdicts, shedding, brownout plumbing
# ---------------------------------------------------------------------------


class TestOverloadController:
    def test_admits_in_calm_seas(self):
        ov = OverloadController(queue_cap=4)
        assert ov.admission_check(_req(), depth=0) is None

    def test_queue_full_rejects_with_retry_after(self):
        ov = OverloadController(queue_cap=4, prior_wait_ms=100.0)
        verdict = ov.admission_check(_req(), depth=4)
        assert verdict is not None
        reason, retry_after = verdict
        assert reason == REASON_QUEUE_FULL
        assert retry_after > 0

    def test_class_bucket_rate_limits(self):
        clock = FakeClock()
        ov = OverloadController(
            queue_cap=64,
            class_rate_rps={"batch": 1.0},
            class_burst={"batch": 1},
            clock=clock,
        )
        assert ov.admission_check(_req(priority="batch"), depth=0) is None
        verdict = ov.admission_check(_req(priority="batch"), depth=0)
        assert verdict is not None and verdict[0] == REASON_RATE_LIMITED
        # The interactive class has no bucket: never rate-limited.
        assert ov.admission_check(_req(priority="interactive"), depth=0) is None

    def test_deadline_unmeetable_rejects_at_submit(self):
        ov = OverloadController(queue_cap=64, prior_wait_ms=1000.0)
        verdict = ov.admission_check(_req(deadline_ms=10.0), depth=0)
        assert verdict is not None
        assert verdict[0] == REASON_DEADLINE_UNMEETABLE
        # No deadline = no deadline check, whatever the predicted wait.
        assert ov.admission_check(_req(), depth=0) is None

    def test_unknown_rate_class_rejected_at_build(self):
        with pytest.raises(ValueError, match="unknown class"):
            OverloadController(class_rate_rps={"platinum": 1.0})

    def test_shedding_requires_sustained_pressure(self):
        ov = OverloadController(
            prior_wait_ms=5.0, brownout_high_ms=100.0, brownout_low_ms=10.0
        )
        ov.tick(0)
        assert not ov.shedding_active  # calm seas: late requests still serve
        ov.tick(50)  # predicted 5 * 51 = 255ms >= high watermark
        assert ov.shedding_active

    def test_past_deadline(self):
        clock = FakeClock()
        ov = OverloadController(clock=clock)
        req = _req(deadline_ms=100.0)
        req.submitted_t = clock()
        assert not ov.past_deadline(req)
        clock.advance(0.2)
        assert ov.past_deadline(req)
        assert not ov.past_deadline(_req())  # deadline-less never expires

    def test_brownout_clamp_only_while_active(self):
        ov = OverloadController(
            prior_wait_ms=500.0,
            brownout_high_ms=100.0,
            brownout_low_ms=10.0,
            brownout_enter_ticks=1,
            brownout_max_new_tokens=8,
        )
        assert ov.clamp_new_tokens(64) == 64
        assert ov.tick(0) == "entered"
        assert ov.clamp_new_tokens(64) == 8
        assert ov.clamp_new_tokens(4) == 4

    def test_from_config_and_overrides(self):
        from llmtrain_tpu.config.schemas import OverloadConfig

        cfg = OverloadConfig(
            queue_cap=7,
            default_deadline_ms=1234.0,
            classes={"interactive": 3, "batch": 2},
            class_rate_rps={"batch": 5.0},
            brownout_high_ms=300.0,
            brownout_low_ms=30.0,
        )
        clock = FakeClock()
        ov = OverloadController.from_config(cfg, clock=clock)
        assert ov.queue_cap == 7
        assert ov.default_deadline_ms == 1234.0
        assert ov.class_weights == {"interactive": 3, "batch": 2}
        assert set(ov.buckets) == {"batch"}
        assert ov.brownout.high_ms == 300.0
        assert ov._clock is clock

    def test_stats_shape(self):
        ov = OverloadController(queue_cap=9)
        ov.note_rejection(REASON_QUEUE_FULL)
        ov.note_rejection(REASON_DEADLINE_EXCEEDED, shed=True)
        s = ov.stats()
        assert s["queue_cap"] == 9
        assert s["rejected"] == {
            REASON_QUEUE_FULL: 1,
            REASON_DEADLINE_EXCEEDED: 1,
        }
        assert s["rejected_total"] == 2
        assert s["shed"] == 1
        assert s["in_brownout"] is False
        assert set(s["queue_depths"]) == {"interactive", "batch"}


# ---------------------------------------------------------------------------
# labeled rejection counters -> one Prometheus family
# ---------------------------------------------------------------------------


class TestRejectedCounterRendering:
    def test_reasons_share_one_counter_family(self):
        from llmtrain_tpu.telemetry.prometheus import render_prometheus
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry(None)
        reg.inc(rejected_counter(REASON_QUEUE_FULL), 3)
        reg.inc(rejected_counter(REASON_RATE_LIMITED))
        text = render_prometheus(reg.latest(), reg.counters())
        assert 'llmtrain_serve_rejected_total{reason="queue_full"} 3.0' in text
        assert 'llmtrain_serve_rejected_total{reason="rate_limited"} 1.0' in text
        assert (
            text.count("# TYPE llmtrain_serve_rejected_total counter") == 1
        )


# ---------------------------------------------------------------------------
# scheduler integration: FakeEngine over a REAL PagedKVPool
# ---------------------------------------------------------------------------


class FakeEngine:
    """Duck-types PagedDecodeEngine's scheduler surface with real pool
    accounting and deterministic token emission — overload-control paths
    (admission, shedding, clamping, chunked-prefill teardown) exercise
    without compiling anything."""

    def __init__(
        self,
        *,
        num_blocks: int = 64,
        block_tokens: int = 4,
        max_batch_slots: int = 4,
        prefill_chunk: int = 0,
        prefix_cache: bool = False,
    ) -> None:
        self.pool = PagedKVPool(
            num_blocks, block_tokens, prefix_cache=prefix_cache
        )
        self.prefill_chunk = prefill_chunk
        self.max_batch_slots = max_batch_slots
        self.max_blocks_per_seq = num_blocks
        self.cache_epoch = 0
        self.params = {"epoch": 0}

    def set_params(self, params) -> None:
        self.params = params

    def validate_request(self, prompt_len: int, max_new: int) -> str | None:
        return None

    def prefill(self, slab, table, *, seed, temperature, top_k, top_p,
                offset, params):
        return int(slab[-1])

    def decode(self, rows, *, params):
        return [(int(r["token"]) + 1) % 97 for r in rows]

    def cow_copy(self, src: int, dst: int) -> None:
        pass

    def compile_stats(self) -> dict:
        return {"within_budget": True}


class FakeTimeline:
    def __init__(self) -> None:
        self.instants: list[tuple[str, dict]] = []

    def instant(self, name: str, **kw) -> None:
        self.instants.append((name, kw))

    def record(self, name: str, **kw) -> None:
        pass

    def span(self, name: str, **kw):
        from contextlib import nullcontext

        return nullcontext()


def _drain(sched: ContinuousBatchingScheduler, steps: int = 50) -> None:
    for _ in range(steps):
        if not sched.step():
            break


class TestSchedulerOverloadIntegration:
    def test_submit_rejects_synchronously_when_queue_full(self):
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry(None)
        tl = FakeTimeline()
        ov = OverloadController(queue_cap=2)
        sched = ContinuousBatchingScheduler(
            FakeEngine(), overload=ov, registry=reg, timeline=tl
        )
        a, b = sched.submit(_req()), sched.submit(_req())
        c = sched.submit(_req(rid="req-c"))
        assert not a.done.is_set() and not b.done.is_set()
        assert c.done.is_set()
        assert c.finish_reason == "rejected"
        assert c.reject_reason == REASON_QUEUE_FULL
        assert c.retry_after_sec and c.retry_after_sec > 0
        assert reg.counters()[rejected_counter(REASON_QUEUE_FULL)] == 1.0
        name, kw = tl.instants[-1]
        assert name == "serve/rejected"
        assert kw["reason"] == REASON_QUEUE_FULL and kw["rid"] == "req-c"
        # The queued pair still completes: rejection never wedges admission.
        _drain(sched)
        assert a.finish_reason == "length" and b.finish_reason == "length"

    def test_tracer_failure_is_best_effort_not_a_hang(self):
        # _finish_trace runs on the completion path BEFORE req.done.set();
        # a tracer/timeline failure (full disk, broken adapter) must be
        # swallowed, never leaving the waiter hanging or killing the loop.
        from llmtrain_tpu.telemetry.tracing import TailSampler, Tracer

        class BoomTimeline(FakeTimeline):
            def record(self, name: str, **kw) -> None:
                if kw.get("cat") == "trace":  # the tracer's flush records
                    raise OSError("disk full")

            def flush(self) -> None:
                raise OSError("disk full")

        tl = BoomTimeline()
        sched = ContinuousBatchingScheduler(
            FakeEngine(),
            timeline=tl,
            tracer=Tracer(tl, sampler=TailSampler(warmup=16)),
        )
        r = sched.submit(_req(prompt=5, max_new=3))
        _drain(sched)
        assert r.done.is_set()
        assert r.finish_reason == "length" and len(r.tokens) == 3

    def test_submit_rejects_unmeetable_deadline(self):
        ov = OverloadController(queue_cap=64, prior_wait_ms=1000.0)
        sched = ContinuousBatchingScheduler(FakeEngine(), overload=ov)
        r = sched.submit(_req(deadline_ms=5.0))
        assert r.finish_reason == "rejected"
        assert r.reject_reason == REASON_DEADLINE_UNMEETABLE

    def test_default_deadline_is_stamped_at_submit(self):
        ov = OverloadController(queue_cap=64, default_deadline_ms=9000.0)
        sched = ContinuousBatchingScheduler(FakeEngine(), overload=ov)
        r = sched.submit(_req())
        assert r.deadline_ms == 9000.0

    def test_end_to_end_completion_and_exact_pool_release(self):
        ov = OverloadController(queue_cap=8)
        eng = FakeEngine()
        sched = ContinuousBatchingScheduler(eng, overload=ov)
        reqs = [sched.submit(_req(prompt=5, max_new=3)) for _ in range(3)]
        _drain(sched)
        for r in reqs:
            assert r.finish_reason == "length" and len(r.tokens) == 3
        stats = eng.pool.stats()
        assert stats["allocated_blocks"] == 0
        assert stats["reserved_blocks"] == 0
        assert stats["active_sequences"] == 0
        assert sched.stats()["overload"]["rejected_total"] == 0

    def test_eager_shed_past_deadline_under_pressure(self):
        # prior 50ms/slot -> pressure >= high watermark from the first
        # tick at any depth: shedding is ACTIVE.
        ov = OverloadController(
            queue_cap=8, prior_wait_ms=50.0, brownout_high_ms=40.0,
            brownout_low_ms=4.0,
        )
        sched = ContinuousBatchingScheduler(FakeEngine(), overload=ov)
        r = sched.submit(_req(deadline_ms=60.0))
        assert not r.done.is_set()
        time.sleep(0.09)  # now past its deadline while still queued
        sched.step()
        assert r.finish_reason == "shed"
        assert r.reject_reason == REASON_DEADLINE_EXCEEDED
        assert sched.stats()["overload"]["shed"] == 1

    def test_calm_seas_late_request_still_served(self):
        # Same expired deadline, but pressure far below the watermark:
        # no shedding, the request serves.
        ov = OverloadController(
            queue_cap=8, prior_wait_ms=1.0, brownout_high_ms=5000.0,
            brownout_low_ms=500.0,
        )
        sched = ContinuousBatchingScheduler(FakeEngine(), overload=ov)
        r = sched.submit(_req(max_new=2, deadline_ms=20.0))
        time.sleep(0.05)
        _drain(sched)
        assert r.finish_reason == "length" and len(r.tokens) == 2

    def test_brownout_clamps_admissions_then_exits(self):
        tl = FakeTimeline()
        ov = OverloadController(
            queue_cap=8,
            prior_wait_ms=50.0,
            brownout_high_ms=40.0,
            brownout_low_ms=4.0,
            brownout_enter_ticks=1,
            brownout_exit_ticks=1,
            brownout_max_new_tokens=2,
        )
        sched = ContinuousBatchingScheduler(
            FakeEngine(), overload=ov, timeline=tl
        )
        sched.step()  # pressure 50ms >= 40ms for 1 tick -> entered
        assert ov.in_brownout
        assert any(n == "serve/brownout_entered" for n, _ in tl.instants)
        r = sched.submit(_req(max_new=16))
        _drain(sched)
        assert r.finish_reason == "length"
        assert len(r.tokens) == 2  # clamped BEFORE reservation/decode
        # Observed waits collapse -> EWMA decays below the low watermark
        # -> hysteresis exits.
        for _ in range(40):
            ov.observe_queue_wait(0.0, 0)
        sched.step()
        assert not ov.in_brownout
        assert any(n == "serve/brownout_exited" for n, _ in tl.instants)
        s = sched.stats()["overload"]
        assert s["brownout_entries"] == 1 and s["brownout_exits"] == 1

    def test_pool_full_requeues_instead_of_wedging(self):
        # Capacity 4 usable blocks; each request reserves 2 (4+4 tokens,
        # block 4): two admit, the third re-queues and admits as the
        # earlier ones retire. Nothing wedges, nothing leaks.
        ov = OverloadController(queue_cap=8)
        eng = FakeEngine(num_blocks=5, block_tokens=4, max_batch_slots=8)
        sched = ContinuousBatchingScheduler(eng, overload=ov)
        reqs = [sched.submit(_req(prompt=4, max_new=4)) for _ in range(3)]
        _drain(sched)
        assert [r.finish_reason for r in reqs] == ["length"] * 3
        assert eng.pool.stats()["allocated_blocks"] == 0

    def test_shed_mid_chunked_prefill_releases_blocks_and_no_prefix(self):
        # The satellite property: a request shed PART WAY through chunked
        # prefill returns the pool to its pre-admission state and never
        # publishes its partial prefix to the cache.
        ov = OverloadController(queue_cap=8)
        eng = FakeEngine(
            num_blocks=32, block_tokens=4, prefill_chunk=2, prefix_cache=True
        )
        sched = ContinuousBatchingScheduler(eng, overload=ov)
        before = eng.pool.stats()
        assert before["allocated_blocks"] == 0 and before["reserved_blocks"] == 0
        r = sched.submit(_req(prompt=8, max_new=2))
        sched.step()  # admit + stream FIRST chunk only (2 of 8 tokens)
        mid = eng.pool.stats()
        assert mid["active_sequences"] == 1
        assert mid["reserved_blocks"] > 0 and mid["allocated_blocks"] > 0
        assert sched._prefilling and sched._prefilling[0].prefilled < 8
        r.abandon()  # the waiter gave up mid-prefill
        sched.step()
        after = eng.pool.stats()
        assert after["allocated_blocks"] == before["allocated_blocks"]
        assert after["reserved_blocks"] == before["reserved_blocks"]
        assert after["active_sequences"] == 0
        # The partial prompt was NEVER registered: no cached blocks, and
        # a fresh lookup of the same prompt misses outright.
        assert after["prefix_cached_blocks"] == 0
        assert not eng.pool.match_prefix(r.prompt_ids).hit

    def test_predicted_wait_and_brownout_gauges_published(self):
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry(None)
        ov = OverloadController(queue_cap=8)
        sched = ContinuousBatchingScheduler(
            FakeEngine(), overload=ov, registry=reg
        )
        sched.submit(_req(max_new=1))
        _drain(sched)
        latest = reg.latest()
        assert "serve/predicted_wait_ms" in latest
        assert latest["serve/brownout"][0] == 0.0


# ---------------------------------------------------------------------------
# router: retry budget + backpressure rejection
# ---------------------------------------------------------------------------


class _SinkReplica:
    """Always-succeeds fake replica (router-surface duck type)."""

    def __init__(self, name: str = "sink") -> None:
        self.name = name
        self.submitted: list[ServeRequest] = []

    def submit(self, req: ServeRequest) -> None:
        self.submitted.append(req)
        req.finish_reason = "length"
        req.finished_t = time.monotonic()
        req.done.set()

    def load(self) -> float:
        return 0.0

    def stats(self) -> dict:
        return {}

    def close(self) -> None:
        pass


class TestRouterRetryBudget:
    def test_budget_spends_then_rejects_fast(self):
        from llmtrain_tpu.serving.router import ReplicaRouter
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry(None)
        sink = _SinkReplica()
        router = ReplicaRouter(
            [sink], registry=reg, retry_budget=1, retry_window_sec=60.0
        )
        ok = _req()
        router._failover(ok, exclude=set(), cause=RuntimeError("transport"))
        assert ok.finish_reason == "length" and sink.submitted == [ok]
        # Budget (1) spent: the next failover is rejected honestly
        # instead of re-hammering the fleet.
        r2 = _req()
        router._failover(r2, exclude=set(), cause=RuntimeError("transport"))
        assert r2.done.is_set()
        assert r2.finish_reason == "rejected"
        assert r2.reject_reason == REASON_RETRY_BUDGET
        assert r2.retry_after_sec == pytest.approx(60.0)
        assert router.retries_rejected == 1
        assert reg.counters()[rejected_counter(REASON_RETRY_BUDGET)] == 1.0
        s = router.stats()["router"]["overload"]
        assert s["retries_rejected"] == 1
        assert s["retry_budget_remaining"] == 0

    def test_zero_budget_means_unlimited(self):
        from llmtrain_tpu.serving.router import ReplicaRouter

        sink = _SinkReplica()
        router = ReplicaRouter([sink], retry_budget=0)
        for _ in range(5):
            router._failover(_req(), exclude=set(), cause=RuntimeError("x"))
        assert len(sink.submitted) == 5
        assert router.retries_rejected == 0

    def test_backpressure_parse_and_window(self):
        from llmtrain_tpu.serving.router import ReplicaBackpressure

        exc = ReplicaBackpressure("replica0", "queue_full", 2.5)
        assert exc.replica_name == "replica0"
        assert exc.reason == "queue_full"
        assert exc.retry_after == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# HTTP boundary: deadline header, client gate, SLO headers, rid echo
# ---------------------------------------------------------------------------


class _StubModel:
    vocab_size = 64
    block_size = 128


class _RejectingScheduler:
    """Scheduler stub whose admission always says 429."""

    engine = None

    def __init__(self) -> None:
        self.seen: list[ServeRequest] = []

    def submit(self, req: ServeRequest) -> ServeRequest:
        self.seen.append(req)
        req.finish_reason = "rejected"
        req.reject_reason = REASON_QUEUE_FULL
        req.retry_after_sec = 0.25
        req.finished_t = time.monotonic()
        req.done.set()
        return req


def _state(**kw):
    from llmtrain_tpu.serving.http import ServerState

    defaults = dict(
        model=_StubModel(), params=None, tokenizer=None, step=0,
        checkpoint="ckpt",
    )
    defaults.update(kw)
    return ServerState(**defaults)


class TestHTTPOverloadSurface:
    def test_bad_deadline_header_is_400(self):
        from llmtrain_tpu.serving.http import _handle_generate_request

        for bad in ("nope", "-5", "0"):
            code, payload = _handle_generate_request(
                _state(), {"prompt_ids": [1, 2]}, {"X-Deadline-Ms": bad}
            )
            assert code == 400
            assert "X-Deadline-Ms" in payload["error"]

    def test_request_id_echoes_on_errors(self):
        from llmtrain_tpu.serving.http import _handle_generate_request

        code, payload = _handle_generate_request(
            _state(), {}, {"X-Request-Id": "trace-1"}
        )
        assert code == 400
        assert payload["request_id"] == "trace-1"

    def test_client_gate_429_with_retry_after(self):
        from llmtrain_tpu.serving.http import _handle_generate_request
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        clock = FakeClock()
        reg = MetricsRegistry(None)
        state = _state(
            client_gate=ClientRateGate(0.5, 1, clock=clock), registry=reg
        )
        headers = {"X-Client-Id": "tenant-a", "X-Request-Id": "r-9"}
        code, _ = _handle_generate_request(state, {}, headers)
        assert code == 400  # gate admitted; body validation said no
        code, payload = _handle_generate_request(state, {}, headers)
        assert code == 429
        assert payload["reason"] == REASON_RATE_LIMITED
        assert payload["retry_after"] > 0
        assert payload["request_id"] == "r-9"
        assert reg.counters()[rejected_counter(REASON_RATE_LIMITED)] == 1.0
        # A different tenant is untouched by tenant-a's bucket.
        code, _ = _handle_generate_request(
            state, {}, {"X-Client-Id": "tenant-b"}
        )
        assert code == 400

    def test_scheduler_rejection_maps_to_429_payload(self):
        from llmtrain_tpu.serving.http import _handle_generate_request

        sched = _RejectingScheduler()
        state = _state(scheduler=sched)
        headers = {
            "X-Request-Id": "abc",
            "X-Deadline-Ms": "150",
            "X-Priority": "batch",
        }
        code, payload = _handle_generate_request(
            state, {"prompt_ids": [1, 2, 3]}, headers
        )
        assert code == 429
        assert payload["reason"] == REASON_QUEUE_FULL
        assert payload["finish_reason"] == "rejected"
        assert payload["retry_after"] == pytest.approx(0.25)
        assert payload["request_id"] == "abc"
        # The SLO envelope rode the headers into the ServeRequest.
        req = sched.seen[0]
        assert req.deadline_ms == 150.0
        assert req.priority == "batch"
        assert req.rid == "abc"

    def test_slo_headers_lift(self):
        from llmtrain_tpu.serving.http import _Handler

        out = _Handler._slo_headers(
            429, {"retry_after": 0.2, "request_id": "r1"}
        )
        assert out == {"Retry-After": "1", "X-Request-Id": "r1"}
        assert _Handler._slo_headers(429, {"retry_after": 3.2}) == {
            "Retry-After": "4"
        }
        assert _Handler._slo_headers(503, {"retry_after": 2}) == {
            "Retry-After": "2"
        }
        # 200s never carry Retry-After, whatever the payload says.
        assert _Handler._slo_headers(200, {"retry_after": 9}) == {}


# ---------------------------------------------------------------------------
# the seeded overload acceptance drill (compiles a model)
# ---------------------------------------------------------------------------


def _tiny_stack(vocab=32, block=64):
    import jax
    import jax.numpy as jnp
    from flax.linen import meta as nn_meta

    from llmtrain_tpu.models.gpt import GPT

    model = GPT(
        vocab_size=vocab,
        block_size=block,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = nn_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))[
            "params"
        ]
    )
    return model, params


def _reference(model, params, req: ServeRequest) -> list[int]:
    import jax

    from llmtrain_tpu.generation import generate

    out = generate(
        model,
        params,
        req.prompt_ids[None, :],
        max_new_tokens=req.max_new_tokens,
        temperature=req.temperature,
        eos_token_id=req.eos_token_id,
        rng=jax.random.key(req.seed),
    )
    toks = [int(t) for t in np.asarray(out)[0, req.prompt_ids.shape[0]:]]
    if req.eos_token_id is not None and req.eos_token_id in toks:
        toks = toks[: toks.index(req.eos_token_id) + 1]
    return toks


@pytest.mark.slow
class TestOverloadDrills:
    def test_burst_drill_parity_shedding_and_brownout_hysteresis(self):
        """The acceptance drill: a seeded 10x burst against a 2-replica
        router with bounded admission. Accepted greedy requests stay
        bitwise generate()-exact, rejections are fast and carry the
        documented taxonomy, the scheduler never wedges, brownout enters
        AND exits, and the KV pools account to exactly zero."""
        from llmtrain_tpu.serving import (
            ContinuousBatchingScheduler,
            InProcessReplica,
            PagedDecodeEngine,
            ReplicaRouter,
            build_requests,
            run_loadgen,
        )
        from llmtrain_tpu.telemetry.prometheus import render_prometheus
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        model, params = _tiny_stack()
        registry = MetricsRegistry(None)
        controllers: list[OverloadController] = []

        def mk(i):
            eng = PagedDecodeEngine(
                model,
                params,
                block_tokens=4,
                max_batch_slots=4,
                prompt_buckets=[8, 16],
                batch_buckets=[2, 4],
                prefix_cache=False,
            )
            ov = OverloadController(
                queue_cap=6,
                prior_wait_ms=5.0,
                brownout_high_ms=40.0,
                brownout_low_ms=8.0,
                brownout_enter_ticks=2,
                brownout_exit_ticks=2,
                brownout_max_new_tokens=4,
            )
            controllers.append(ov)
            sched = ContinuousBatchingScheduler(
                eng, registry=registry, overload=ov
            ).start()
            return InProcessReplica(sched, f"replica{i}")

        router = ReplicaRouter(
            [mk(0), mk(1)],
            registry=registry,
            retry_budget=8,
            retry_window_sec=5.0,
        )
        try:
            reqs = build_requests(
                num_requests=80,
                seed=13,
                vocab_size=32,
                prompt_tokens_min=4,
                prompt_tokens_max=8,
                max_new_tokens=6,
                deadline_ms=2000.0,
                batch_fraction=0.3,
            )
            block = run_loadgen(
                router,
                reqs,
                rate_rps=60.0,
                seed=7,
                timeout_sec=120.0,
                arrival="burst",
                burst_factor=10.0,
            )

            # -- no wedge: every request reached a terminal state.
            rq = block["requests"]
            assert rq["timed_out"] == 0 and rq["failed"] == 0
            assert (
                rq["completed"] + rq["rejected"] + rq["shed"] == len(reqs)
            )
            # -- the burst actually overloaded: fast rejections happened,
            #    every reason is from the documented taxonomy.
            assert rq["rejected"] + rq["shed"] > 0
            ob = block["overload"]
            assert set(ob["rejected_by_reason"]) <= set(REJECT_REASONS)
            assert ob["rejected"] == rq["rejected"]
            assert ob["shed"] == rq["shed"]
            assert ob["controller"] is not None
            # -- submit-time rejections are FAST (the whole point of
            #    admission control); queue-sheds are bounded by deadline
            #    plus one sweep interval.
            for r in reqs:
                if r.finish_reason == "rejected":
                    assert (r.finished_t - r.submitted_t) < 0.5
                elif r.finish_reason == "shed":
                    assert (r.finished_t - r.submitted_t) < 2.0 + 5.0
            # -- accepted requests hold the latency SLO (loose bound:
            #    the drill must bound the tail, not win a benchmark).
            done = [r for r in reqs if r.finish_reason in ("eos", "length")]
            assert done, "the drill must complete some requests"
            lat = sorted(r.latency_ms for r in done)
            assert lat[int(len(lat) * 0.99) - 1] < 30_000.0
            # -- bitwise parity on every ACCEPTED greedy request, on the
            #    post-clamp token budget it actually decoded under.
            for r in done:
                assert r.tokens == _reference(model, params, r), r.request_id
            # -- brownout hysteresis: entered under the burst...
            assert sum(ov.brownout.entries for ov in controllers) >= 1
            # ... and exits once calm traffic drains the EWMA back down.
            # Submit the calm trickle to each replica DIRECTLY: the
            # router's placement penalty steers traffic away from a
            # browned-out replica, which is exactly right in production
            # but would starve it of the small-wait observations its
            # EWMA needs to decay below the exit watermark here.
            calm_deadline = time.monotonic() + 60.0
            while (
                any(ov.brownout.active for ov in controllers)
                and time.monotonic() < calm_deadline
            ):
                for rep, ov in zip(router.replicas, controllers):
                    if not ov.brownout.active:
                        continue
                    trickle = _req(prompt=4, max_new=2)
                    rep.scheduler.submit(trickle)
                    trickle.done.wait(10.0)
            assert not any(ov.brownout.active for ov in controllers)
            assert sum(ov.brownout.exits for ov in controllers) >= 1
            # -- pool accounting is EXACT at drill end: every accepted,
            #    shed, and trickle request returned its blocks.
            for rep in router.replicas:
                pool = rep.scheduler.engine.pool.stats()
                assert pool["allocated_blocks"] == 0
                assert pool["reserved_blocks"] == 0
                assert pool["active_sequences"] == 0
            # -- the decisions are all visible as labeled counters and
            #    gauges on the shared registry.
            text = render_prometheus(registry.latest(), registry.counters())
            assert "llmtrain_serve_rejected_total{reason=" in text
            assert "llmtrain_serve_brownout" in text
            assert "llmtrain_serve_predicted_wait_ms" in text
            assert block["arrival"]["process"] == "burst-open-loop"
            assert block["arrival"]["burst_factor"] == 10.0
        finally:
            router.close()
