"""EMA shadow weights (trainer.extra.ema_decay, training/optimizer.py).

The shadow rides the optimizer state, so the properties to pin are:

* the recurrence is exactly ``ema ← d·ema + (1-d)·params_post_update``;
* checkpoints carry it and resume reproduces it bit-exactly;
* ``load_ema_params`` digs the shadow out of a saved payload (and fails
  loudly on checkpoints that have none);
* it composes with LoRA (the shadow then mirrors the factor subtree).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking.base import NullTracker
from llmtrain_tpu.training.checkpoint import load_ema_params
from llmtrain_tpu.training.optimizer import EMA_STATE_KEY, build_optimizer
from llmtrain_tpu.training.trainer import Trainer

initialize_registries()


def _cfg(extra=None, model_extra=None):
    return RunConfig.model_validate(
        {
            "run": {"name": "ema-test", "device": "cpu", "seed": 5},
            "model": {
                "name": "gpt",
                "block_size": 16,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "vocab_size": 64,
                "dropout": 0.0,
                "extra": {"tokenizer": "byte", **(model_extra or {})},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "max_steps": 20,
                "warmup_steps": 0,
                "lr": 1e-2,
                "log_every_steps": 10,
                "eval_every_steps": 1000,
                "save_every_steps": 10,
                "extra": {"ema_decay": 0.9, **(extra or {})},
            },
            "mlflow": {"enabled": False},
        }
    )


def _find_ema(opt_state):
    hit = []

    def walk(node):
        if isinstance(node, dict) and EMA_STATE_KEY in node:
            hit.append(node[EMA_STATE_KEY])
            return
        for child in node if isinstance(node, (tuple, list)) else (
            node.values() if isinstance(node, dict) else ()
        ):
            walk(child)

    walk(opt_state)
    assert len(hit) == 1
    return hit[0]


class TestTransform:
    def test_recurrence_matches_manual(self):
        """Drive the raw transform on a toy tree against the recurrence."""
        cfg = _cfg()
        tx = build_optimizer(cfg.trainer)
        params = {"w": jnp.ones((4,), jnp.float32)}
        state = tx.init(params)
        manual = params["w"]
        for step in range(3):
            grads = {"w": jnp.full((4,), 0.1 * (step + 1), jnp.float32)}
            updates, state = tx.update(grads, state, params)
            params = {"w": params["w"] + updates["w"]}
            manual = 0.9 * manual + 0.1 * params["w"]
            np.testing.assert_allclose(
                np.asarray(_find_ema(state)["w"]),
                np.asarray(manual),
                rtol=1e-6,
            )

    def test_shadow_accumulates_in_f32_under_bf16_params(self):
        """(1-d)~0.1% increments underflow bf16's ~0.4% resolution — the
        shadow must be f32 regardless of param dtype or it freezes."""
        cfg = _cfg()
        tx = build_optimizer(cfg.trainer)
        params = {"w": jnp.ones((4,), jnp.bfloat16)}
        state = tx.init(params)
        assert _find_ema(state)["w"].dtype == jnp.float32
        # 20 tiny steps: a bf16 shadow would stay pinned at 1.0
        for _ in range(20):
            grads = {"w": jnp.full((4,), 1e-3, jnp.bfloat16)}
            updates, state = tx.update(grads, state, params)
            params = {"w": params["w"] + updates["w"]}
        assert float(jnp.abs(_find_ema(state)["w"] - 1.0).max()) > 1e-4

    def test_invalid_decay_raises(self):
        with pytest.raises(ValueError, match="ema_decay"):
            build_optimizer(_cfg(extra={"ema_decay": 1.0}).trainer)
        with pytest.raises(ValueError, match="ema_decay"):
            build_optimizer(_cfg(extra={"ema_decay": 0}).trainer)


class TestTrainerIntegration:
    def test_shadow_tracks_and_checkpoints(self, tmp_path):
        cfg = _cfg()
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        trainer = Trainer(cfg, run_dir=run_dir, tracker=NullTracker())
        trainer.fit()
        shadow = nn_meta.unbox(_find_ema(trainer.state.opt_state))
        raw = nn_meta.unbox(trainer.state.params)
        # After 20 hot-LR steps the shadow lags the raw weights...
        diffs = [
            float(jnp.abs(a - b).max())
            for a, b in zip(jax.tree.leaves(shadow), jax.tree.leaves(raw))
        ]
        assert max(diffs) > 0.0
        # ...and load_ema_params recovers it bit-exactly from the payload.
        abstract = jax.eval_shape(lambda: raw)
        loaded, step = load_ema_params(
            run_dir / "checkpoints" / "step_000020.ckpt", abstract
        )
        assert step == 20
        for a, b in zip(jax.tree.leaves(shadow), jax.tree.leaves(loaded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resume_reproduces_shadow_exactly(self, tmp_path):
        cfg = _cfg()
        continuous = Trainer(cfg, run_dir=None, tracker=NullTracker())
        continuous.fit()

        run_dir = tmp_path / "run"
        run_dir.mkdir()
        Trainer(cfg, run_dir=run_dir, tracker=NullTracker()).fit(
            max_steps_override=10
        )
        resumed = Trainer(cfg, run_dir=None, tracker=NullTracker())
        resumed.fit(resume_from=str(run_dir / "checkpoints"))

        want = nn_meta.unbox(_find_ema(continuous.state.opt_state))
        got = nn_meta.unbox(_find_ema(resumed.state.opt_state))
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
            )

    def test_missing_ema_fails_loudly(self, tmp_path):
        cfg = _cfg(extra={"ema_decay": None})
        # ema_decay None -> off; checkpoint then holds no shadow.
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        trainer = Trainer(cfg, run_dir=run_dir, tracker=NullTracker())
        trainer.fit()
        abstract = jax.eval_shape(lambda: nn_meta.unbox(trainer.state.params))
        with pytest.raises(ValueError, match="no EMA state"):
            load_ema_params(run_dir / "checkpoints" / "step_000020.ckpt", abstract)


class TestEvalEma:
    def test_evaluate_use_ema_swaps_weights(self, tmp_path):
        cfg = _cfg()
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        Trainer(cfg, run_dir=run_dir, tracker=NullTracker()).fit()
        raw = Trainer(cfg, run_dir=None, tracker=NullTracker()).evaluate(
            resume_from=str(run_dir / "checkpoints")
        )
        ema = Trainer(cfg, run_dir=None, tracker=NullTracker()).evaluate(
            resume_from=str(run_dir / "checkpoints"), use_ema=True
        )
        # Hot LR + decay 0.9 over 20 steps: the shadow lags, losses differ.
        assert raw["val/loss"] != ema["val/loss"]

    def test_evaluate_use_ema_does_not_mutate_trainer(self, tmp_path):
        """use_ema passes an override — a later raw evaluate on the SAME
        trainer must still see the real weights."""
        cfg = _cfg()
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        Trainer(cfg, run_dir=run_dir, tracker=NullTracker()).fit()
        trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
        ema = trainer.evaluate(
            resume_from=str(run_dir / "checkpoints"), use_ema=True
        )
        raw_after = trainer.evaluate()
        fresh_raw = Trainer(cfg, run_dir=None, tracker=NullTracker()).evaluate(
            resume_from=str(run_dir / "checkpoints")
        )
        assert raw_after["val/loss"] == fresh_raw["val/loss"]
        assert raw_after["val/loss"] != ema["val/loss"]

    def test_evaluate_use_ema_without_state_raises(self):
        cfg = _cfg(extra={"ema_decay": None})
        trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
        with pytest.raises(ValueError, match="no EMA state"):
            trainer.evaluate(use_ema=True)


class TestLoraComposition:
    def test_shadow_mirrors_factor_subtree(self, tmp_path):
        cfg = _cfg(model_extra={"lora": {"rank": 4}})
        run_dir = tmp_path / "run"
        run_dir.mkdir()
        trainer = Trainer(cfg, run_dir=run_dir, tracker=NullTracker())
        trainer.fit()
        shadow = _find_ema(trainer.state.opt_state)
        lora = trainer.state.params["lora"]
        assert jax.tree_util.tree_structure(shadow) == (
            jax.tree_util.tree_structure(lora)
        )
        # and it restores against the factor subtree abstract
        abstract = jax.eval_shape(lambda: lora)
        loaded, _ = load_ema_params(
            run_dir / "checkpoints" / "step_000020.ckpt", abstract
        )
        for a, b in zip(jax.tree.leaves(shadow), jax.tree.leaves(loaded)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(b)
            )
