"""Attention ops: blockwise + pallas (interpret mode) vs dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.models.gpt import GPT, dense_attention
from llmtrain_tpu.ops.blockwise_attention import blockwise_attention
from llmtrain_tpu.ops.flash_attention import flash_attention
from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention


def _qkv(b=2, t=32, h=2, d=8, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype=jnp.float32) for k in keys)


def _dense_ref(q, k, v, causal=True):
    return dense_attention(q, k, v, attention_mask=None)


class TestBlockwise:
    def test_matches_dense(self):
        q, k, v = _qkv()
        out = blockwise_attention(q, k, v, causal=True, q_chunk=8, kv_chunk=8)
        ref = _dense_ref(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_single_chunk_matches(self):
        q, k, v = _qkv(t=16)
        out = blockwise_attention(q, k, v, causal=True, q_chunk=16, kv_chunk=16)
        ref = _dense_ref(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_non_causal(self):
        q, k, v = _qkv(t=16)
        out = blockwise_attention(q, k, v, causal=False, q_chunk=4, kv_chunk=4)
        import math

        scale = 1.0 / math.sqrt(q.shape[-1])
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        probs = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gradients_match_dense(self):
        q, k, v = _qkv(t=16)

        def loss_block(q, k, v):
            return blockwise_attention(q, k, v, causal=True, q_chunk=4, kv_chunk=4).sum()

        def loss_dense(q, k, v):
            return _dense_ref(q, k, v).sum()

        g_block = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gb, gd in zip(g_block, g_dense):
            np.testing.assert_allclose(np.asarray(gb), np.asarray(gd), atol=1e-4)

    def test_kv_offset_for_ring(self):
        """Chunked causal mask with offsets == global causal attention."""
        q, k, v = _qkv(t=16)
        full = blockwise_attention(q, k, v, causal=True, q_chunk=16, kv_chunk=16)
        # Query block [8:16] attending to keys [0:16] with the right offsets.
        out = blockwise_attention(
            q[:, 8:], k, v, causal=True, q_chunk=8, kv_chunk=8, q_offset=8, kv_offset=0
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, 8:]), atol=1e-5)


class TestPallasInterpret:
    def test_matches_dense(self):
        q, k, v = _qkv(t=32)
        out = pallas_flash_attention(q, k, v, block_q=8, block_k=8, interpret=True)
        ref = _dense_ref(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_bf16(self):
        q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(t=16))
        out = pallas_flash_attention(q, k, v, block_q=8, block_k=8, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = _dense_ref(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32), atol=2e-2
        )

    def test_ragged_seq_raises(self):
        q, k, v = _qkv(t=24)
        with pytest.raises(ValueError, match="divisible"):
            pallas_flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)

    def test_lse_matches_reference(self):
        """Forward's logsumexp residual == logsumexp of scaled masked logits."""
        import math

        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention_fwd

        q, k, v = _qkv(b=1, t=16, h=1, d=8)
        _, lse = pallas_flash_attention_fwd(q, k, v, block_q=8, block_k=8, interpret=True)
        scale = 1.0 / math.sqrt(q.shape[-1])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((16, 16), bool))
        s = jnp.where(mask, s, -jnp.inf)
        ref = jax.scipy.special.logsumexp(s, axis=-1).reshape(1, 16)  # b*h=1
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize(
        "block_q,block_k",
        [
            pytest.param(8, 8, marks=pytest.mark.slow),
            (8, 16),
            pytest.param(16, 8, marks=pytest.mark.slow),
            (32, 32),
        ],
    )
    def test_fused_backward_matches_dense_grads(self, block_q, block_k):
        """The Pallas dq/dk/dv kernels against jax.grad of the dense
        reference, over a block-shape sweep (VERDICT r1 #4)."""
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(b=2, t=32, h=2, d=8, seed=3)
        g = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)

        out, lse = pallas_flash_attention_fwd(
            q, k, v, block_q=block_q, block_k=block_k, interpret=True
        )
        dq, dk, dv = pallas_flash_attention_bwd(
            q, k, v, out, lse, g, block_q=block_q, block_k=block_k, interpret=True
        )

        def loss(q, k, v):
            return jnp.sum(_dense_ref(q, k, v) * g)

        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=1e-4)

    def test_fused_backward_bf16_mha(self):
        """bf16 MHA backward — the default training dtype on TPU. Guards
        the group==1 narrow-dtype output store (a float32 value stored
        into a bfloat16 ref raises in Pallas); grads are checked at bf16
        tolerance against the dense reference."""
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(b=2, t=32, h=2, d=8, seed=4))
        g = jax.random.normal(jax.random.key(10), q.shape, jnp.bfloat16)

        out, lse = pallas_flash_attention_fwd(q, k, v, block_q=8, block_k=8, interpret=True)
        dq, dk, dv = pallas_flash_attention_bwd(
            q, k, v, out, lse, g, block_q=8, block_k=8, interpret=True
        )
        assert dk.dtype == jnp.bfloat16 and dv.dtype == jnp.bfloat16

        qf, kf, vf, gf = (x.astype(jnp.float32) for x in (q, k, v, g))

        def loss(q, k, v):
            return jnp.sum(_dense_ref(q, k, v) * gf)

        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(qf, kf, vf)
        for got, want in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(
                np.asarray(got, dtype=np.float32), np.asarray(want), atol=0.1, rtol=0.1
            )


class TestFlashDispatch:
    def test_cpu_dispatch_and_grads(self):
        q, k, v = _qkv(t=16)
        out = flash_attention(q, k, v)
        ref = _dense_ref(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        g = jax.grad(lambda q: flash_attention(q, k, v).sum())(q)
        g_ref = jax.grad(lambda q: _dense_ref(q, k, v).sum())(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)

    def test_all_ones_mask_matches_unmasked(self):
        q, k, v = _qkv(t=16)
        out = flash_attention(q, k, v, attention_mask=jnp.ones((2, 16), jnp.int32))
        ref = flash_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def _suffix_mask(b, t, seed=1):
    """Per-row valid prefix lengths in [1, t] — reference padding shape."""
    rng = np.random.default_rng(seed)
    lens = rng.integers(1, t + 1, size=(b,))
    lens[0] = t  # keep one fully-packed row in the mix
    return jnp.asarray((np.arange(t)[None, :] < lens[:, None]).astype(np.int32))


def _valid(x, mask):
    """Zero padded query rows: comparisons follow the model contract,
    which multiplies attention output by the mask (models/gpt.py)."""
    return np.asarray(x) * np.asarray(mask)[:, :, None, None].astype(np.float32)


class TestMaskedFlash:
    """Key-padding masks applied INSIDE attention (reference gpt.py:60-64),
    on every flash path: Pallas kernels, blockwise fallback, dispatch."""

    def test_pallas_fwd_matches_masked_dense(self):
        q, k, v = _qkv(b=3, t=32, h=2, d=8, seed=5)
        mask = _suffix_mask(3, 32)
        out = pallas_flash_attention(q, k, v, mask, block_q=8, block_k=8, interpret=True)
        ref = dense_attention(q, k, v, attention_mask=mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

    def test_pallas_bwd_matches_masked_dense_grads(self):
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(b=3, t=32, h=2, d=8, seed=7)
        mask = _suffix_mask(3, 32, seed=2)
        # Cotangent zeroed on padded rows — exactly what the model's
        # output-mask multiply feeds back into attention.
        g = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)
        g = g * mask[:, :, None, None].astype(jnp.float32)

        out, lse = pallas_flash_attention_fwd(
            q, k, v, mask, block_q=8, block_k=8, interpret=True
        )
        dq, dk, dv = pallas_flash_attention_bwd(
            q, k, v, out, lse, g, mask, block_q=8, block_k=8, interpret=True
        )

        def loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, attention_mask=mask) * g)

        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=1e-4)

    def test_blockwise_key_mask_matches_masked_dense(self):
        q, k, v = _qkv(b=3, t=16, h=2, d=8, seed=11)
        mask = _suffix_mask(3, 16, seed=3)
        out = blockwise_attention(q, k, v, causal=True, q_chunk=4, kv_chunk=4, key_mask=mask)
        ref = dense_attention(q, k, v, attention_mask=mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

    def test_dispatch_masked_fwd_and_grads(self):
        """flash_attention(attention_mask=...) on the CPU fallback path."""
        q, k, v = _qkv(b=2, t=16, h=2, d=8, seed=13)
        mask = _suffix_mask(2, 16, seed=4)
        gmask = mask[:, :, None, None].astype(jnp.float32)
        out = flash_attention(q, k, v, attention_mask=mask)
        ref = dense_attention(q, k, v, attention_mask=mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

        g = jax.grad(
            lambda q: (flash_attention(q, k, v, attention_mask=mask) * gmask).sum()
        )(q)
        g_ref = jax.grad(
            lambda q: (dense_attention(q, k, v, attention_mask=mask) * gmask).sum()
        )(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)


class TestSequenceParallelMasks:
    """Padding masks inside ring/Ulysses attention: the mask shard rotates
    with its K/V shard (ring) or is all-gathered after the head exchange
    (ulysses); both equal masked dense on valid rows."""

    def _mesh(self):
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.distributed import build_mesh

        return build_mesh(
            MeshConfig(data=2, fsdp=1, tensor=2, sequence=2), jax.devices()[:8]
        )

    @pytest.mark.parametrize("scheme", ["ring", "ulysses"])
    def test_sharded_masked_matches_dense(self, scheme):
        if scheme == "ring":
            from llmtrain_tpu.ops.ring_attention import ring_attention_sharded as fn
        else:
            from llmtrain_tpu.ops.ulysses_attention import (
                ulysses_attention_sharded as fn,
            )

        q, k, v = _qkv(b=4, t=16, h=4, d=8, seed=41)
        mask = _suffix_mask(4, 16, seed=7)
        ref = dense_attention(q, k, v, attention_mask=mask)
        mesh = self._mesh()
        out = jax.jit(
            lambda q, k, v, m: fn(q, k, v, mesh, key_mask=m)
        )(q, k, v, mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

    @pytest.mark.parametrize("scheme", ["ring", "ulysses"])
    def test_sharded_masked_grads_match_dense(self, scheme):
        if scheme == "ring":
            from llmtrain_tpu.ops.ring_attention import ring_attention_sharded as fn
        else:
            from llmtrain_tpu.ops.ulysses_attention import (
                ulysses_attention_sharded as fn,
            )

        q, k, v = _qkv(b=4, t=16, h=4, d=8, seed=43)
        mask = _suffix_mask(4, 16, seed=8)
        gmask = mask[:, :, None, None].astype(jnp.float32)
        mesh = self._mesh()

        g_sp = jax.jit(
            jax.grad(lambda q: (fn(q, k, v, mesh, key_mask=mask) * gmask).sum())
        )(q)
        g_ref = jax.grad(
            lambda q: (dense_attention(q, k, v, attention_mask=mask) * gmask).sum()
        )(q)
        np.testing.assert_allclose(np.asarray(g_sp), np.asarray(g_ref), atol=1e-4)

    @pytest.mark.parametrize("scheme", ["ring", "ulysses"])
    def test_sharded_segment_mask_matches_dense(self, scheme):
        """SEGMENT masks (packed cross-document) ride the SP schemes: the
        query-side segments come from the unrotated local shard (ring) or
        the full replicated mask (ulysses)."""
        if scheme == "ring":
            from llmtrain_tpu.ops.ring_attention import ring_attention_sharded as fn
        else:
            from llmtrain_tpu.ops.ulysses_attention import (
                ulysses_attention_sharded as fn,
            )

        q, k, v = _qkv(b=4, t=16, h=4, d=8, seed=51)
        seg = np.ones((4, 16), np.int32)
        seg[:, 6:13] = 2  # doc boundary NOT on the shard boundary (t/2=8)
        seg[:, 13:] = 0
        seg = jnp.asarray(seg)
        ref = dense_attention(q, k, v, attention_mask=seg)
        mesh = self._mesh()
        out = jax.jit(
            lambda q, k, v, m: fn(q, k, v, mesh, key_mask=m)
        )(q, k, v, seg)
        np.testing.assert_allclose(_valid(out, seg), _valid(ref, seg), atol=1e-5)

    @pytest.mark.parametrize("scheme", ["ring", "ulysses"])
    def test_sharded_segment_grads_match_dense(self, scheme):
        if scheme == "ring":
            from llmtrain_tpu.ops.ring_attention import ring_attention_sharded as fn
        else:
            from llmtrain_tpu.ops.ulysses_attention import (
                ulysses_attention_sharded as fn,
            )

        q, k, v = _qkv(b=4, t=16, h=4, d=8, seed=53)
        seg = np.ones((4, 16), np.int32)
        seg[:, 5:11] = 2
        seg[:, 11:] = 3
        seg = jnp.asarray(seg)
        gmask = (seg != 0)[:, :, None, None].astype(jnp.float32)
        mesh = self._mesh()
        g_sp = jax.jit(
            jax.grad(lambda q: (fn(q, k, v, mesh, key_mask=seg) * gmask).sum())
        )(q)
        g_ref = jax.grad(
            lambda q: (dense_attention(q, k, v, attention_mask=seg) * gmask).sum()
        )(q)
        np.testing.assert_allclose(np.asarray(g_sp), np.asarray(g_ref), atol=1e-4)

    def test_fallback_keeps_segment_semantics(self):
        """No mesh → blockwise fallback: a split_documents segment mask
        must STILL block cross-document attention (degrading to key-only
        padding here silently re-opened the leak the feature closes)."""
        from llmtrain_tpu.ops.ring_attention import ring_or_blockwise
        from llmtrain_tpu.ops.ulysses_attention import ulysses_or_blockwise

        q, k, v = _qkv(b=2, t=16, h=2, d=8, seed=55)
        seg = np.ones((2, 16), np.int32)
        seg[:, 7:] = 2
        seg = jnp.asarray(seg)
        ref = dense_attention(q, k, v, attention_mask=seg)
        for fn in (ring_or_blockwise, ulysses_or_blockwise):
            out = fn(q, k, v, key_mask=seg)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=1e-5
            )

    def test_fallback_masked_matches_dense(self):
        """No mesh: the route-or-fallback path passes the mask to
        blockwise."""
        from llmtrain_tpu.ops.ring_attention import ring_or_blockwise
        from llmtrain_tpu.ops.ulysses_attention import ulysses_or_blockwise

        q, k, v = _qkv(b=2, t=16, h=2, d=8, seed=47)
        mask = _suffix_mask(2, 16, seed=9)
        ref = dense_attention(q, k, v, attention_mask=mask)
        for fn in (ring_or_blockwise, ulysses_or_blockwise):
            out = fn(q, k, v, key_mask=mask)
            np.testing.assert_allclose(
                _valid(out, mask), _valid(ref, mask), atol=1e-5
            )


class TestSlidingWindow:
    """Mistral-style sliding-window masking across the stack: dense
    (full-matrix reference), blockwise (mask-only), Pallas interpret
    (skip-block), and the flash dispatch fallback — all must agree."""

    def _naive_window_ref(self, q, k, v, window):
        import math

        scale = 1.0 / math.sqrt(q.shape[-1])
        t = q.shape[1]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        pos = jnp.arange(t)
        live = (pos[:, None] >= pos[None, :]) & (
            pos[:, None] - pos[None, :] < window
        )
        s = jnp.where(live[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def test_dense_matches_naive(self):
        q, k, v = _qkv(t=32)
        out = dense_attention(q, k, v, attention_mask=None, window=5)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._naive_window_ref(q, k, v, 5)),
            atol=1e-5,
        )

    @pytest.mark.parametrize(
        "window",
        [
            1,
            pytest.param(7, marks=pytest.mark.slow),
            8,
            pytest.param(13, marks=pytest.mark.slow),
            pytest.param(32, marks=pytest.mark.slow),
            100,
        ],
    )
    def test_blockwise_matches_dense(self, window):
        """Window edges off/on chunk boundaries, window == 1 (self only),
        window >= T (== full causal)."""
        q, k, v = _qkv(t=32, seed=41)
        out = blockwise_attention(
            q, k, v, causal=True, q_chunk=8, kv_chunk=8, window=window
        )
        ref = dense_attention(q, k, v, attention_mask=None, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize(
        "window",
        [
            1,
            pytest.param(7, marks=pytest.mark.slow),
            8,
            pytest.param(13, marks=pytest.mark.slow),
            pytest.param(32, marks=pytest.mark.slow),
            100,
        ],
    )
    def test_pallas_fwd_matches_dense(self, window):
        q, k, v = _qkv(t=32, seed=42)
        out = pallas_flash_attention(
            q, k, v, block_q=8, block_k=8, interpret=True, window=window
        )
        ref = dense_attention(q, k, v, attention_mask=None, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize("window", [7, 16])
    def test_pallas_bwd_matches_autodiff(self, window):
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(t=32, seed=43)
        g = jax.random.normal(jax.random.key(44), q.shape, jnp.float32)
        out, lse = pallas_flash_attention_fwd(
            q, k, v, block_q=8, block_k=8, interpret=True, window=window
        )
        dq, dk, dv = pallas_flash_attention_bwd(
            q, k, v, out, lse, g, block_q=8, block_k=8, interpret=True,
            window=window,
        )

        def loss(q, k, v):
            return jnp.sum(
                dense_attention(q, k, v, attention_mask=None, window=window) * g
            )

        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=1e-4)

    def test_window_with_padding_mask(self):
        """Sliding window and key-padding combine in one kernel."""
        q, k, v = _qkv(b=3, t=32, seed=45)
        mask = _suffix_mask(3, 32, seed=46)
        out = pallas_flash_attention(
            q, k, v, mask, block_q=8, block_k=8, interpret=True, window=9
        )
        ref = dense_attention(q, k, v, attention_mask=mask, window=9)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

    def test_window_with_gqa(self):
        """Sliding window over narrow grouped-query K/V."""
        ks = jax.random.split(jax.random.key(47), 3)
        q = jax.random.normal(ks[0], (2, 32, 4, 8), jnp.float32)
        kn = jax.random.normal(ks[1], (2, 32, 2, 8), jnp.float32)
        vn = jax.random.normal(ks[2], (2, 32, 2, 8), jnp.float32)
        out = pallas_flash_attention(
            q, kn, vn, block_q=8, block_k=8, interpret=True, window=11
        )
        kw, vw = jnp.repeat(kn, 2, axis=2), jnp.repeat(vn, 2, axis=2)
        ref = dense_attention(q, kw, vw, attention_mask=None, window=11)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_dispatch_fallback_grads(self):
        """flash_attention(window=...) differentiates through the
        blockwise fallback and matches dense-window autodiff."""
        q, k, v = _qkv(t=16, seed=48)

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, window=6).sum()

        def loss_dense(q, k, v):
            return dense_attention(q, k, v, attention_mask=None, window=6).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_window_requires_causal(self):
        q, k, v = _qkv(t=16)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=4)

    def test_negative_window_rejected(self):
        """A negative window would silently mask EVERY key (uniform-
        average garbage) — the ops layer rejects it."""
        q, k, v = _qkv(t=16)
        with pytest.raises(ValueError, match=">= 0"):
            flash_attention(q, k, v, window=-1)
        with pytest.raises(ValueError, match=">= 0"):
            blockwise_attention(q, k, v, causal=True, window=-1)
        with pytest.raises(ValueError, match=">= 0"):
            pallas_flash_attention(q, k, v, interpret=True, window=-1)


class TestGQAKernels:
    """Native grouped-query attention: narrow (B, T, Hkv, D) K/V through
    the Pallas kernels with in-kernel group mapping — no jnp.repeat."""

    def _gqa_qkv(self, b=2, t=32, h=4, hkv=2, d=8, seed=21):
        ks = jax.random.split(jax.random.key(seed), 3)
        q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
        k = jax.random.normal(ks[1], (b, t, hkv, d), jnp.float32)
        v = jax.random.normal(ks[2], (b, t, hkv, d), jnp.float32)
        return q, k, v

    @pytest.mark.parametrize("hkv", [1, 2, 4], ids=["mqa", "gqa2", "mha"])
    def test_fwd_matches_widened_dense(self, hkv):
        q, kn, vn = self._gqa_qkv(hkv=hkv)
        reps = q.shape[2] // hkv
        kw, vw = jnp.repeat(kn, reps, axis=2), jnp.repeat(vn, reps, axis=2)
        out = pallas_flash_attention(q, kn, vn, block_q=8, block_k=8, interpret=True)
        ref = dense_attention(q, kw, vw, attention_mask=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize("hkv", [1, 2], ids=["mqa", "gqa2"])
    def test_bwd_matches_widened_autodiff(self, hkv):
        """dk/dv come back at the NARROW width, equal to autodiff through
        widen-then-dense (which group-sums the cotangents)."""
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, kn, vn = self._gqa_qkv(hkv=hkv, seed=23)
        reps = q.shape[2] // hkv
        g = jax.random.normal(jax.random.key(29), q.shape, jnp.float32)

        out, lse = pallas_flash_attention_fwd(
            q, kn, vn, block_q=8, block_k=8, interpret=True
        )
        dq, dk, dv = pallas_flash_attention_bwd(
            q, kn, vn, out, lse, g, block_q=8, block_k=8, interpret=True
        )
        assert dk.shape == kn.shape and dv.shape == vn.shape

        def loss(q, kn, vn):
            kw = jnp.repeat(kn, reps, axis=2)
            vw = jnp.repeat(vn, reps, axis=2)
            return jnp.sum(dense_attention(q, kw, vw, attention_mask=None) * g)

        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, kn, vn)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=1e-4)

    def test_gqa_with_mask(self):
        """GQA and key-padding combine in one kernel invocation."""
        q, kn, vn = self._gqa_qkv(b=3, hkv=2, seed=31)
        mask = _suffix_mask(3, 32, seed=6)
        reps = q.shape[2] // 2
        kw, vw = jnp.repeat(kn, reps, axis=2), jnp.repeat(vn, reps, axis=2)
        out = pallas_flash_attention(q, kn, vn, mask, block_q=8, block_k=8, interpret=True)
        ref = dense_attention(q, kw, vw, attention_mask=mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

    def test_dispatch_gqa_fallback(self):
        """flash_attention with narrow K/V on the CPU fallback path."""
        q, kn, vn = self._gqa_qkv(t=16, hkv=2, seed=37)
        reps = q.shape[2] // 2
        kw, vw = jnp.repeat(kn, reps, axis=2), jnp.repeat(vn, reps, axis=2)
        out = flash_attention(q, kn, vn)
        ref = dense_attention(q, kw, vw, attention_mask=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize("hkv", [1, 2], ids=["mqa", "gqa2"])
    def test_blockwise_narrow_kv_fwd_and_grads(self, hkv):
        """Blockwise consumes narrow K/V natively (grouped queries) —
        forward and grads equal the widened dense reference."""
        q, kn, vn = self._gqa_qkv(t=16, hkv=hkv, seed=41)
        reps = q.shape[2] // hkv
        g = jax.random.normal(jax.random.key(43), q.shape, jnp.float32)

        def loss_narrow(q, kn, vn):
            return jnp.sum(
                blockwise_attention(q, kn, vn, causal=True, q_chunk=4, kv_chunk=4) * g
            )

        def loss_wide(q, kn, vn):
            kw = jnp.repeat(kn, reps, axis=2)
            vw = jnp.repeat(vn, reps, axis=2)
            return jnp.sum(dense_attention(q, kw, vw, attention_mask=None) * g)

        np.testing.assert_allclose(
            float(loss_narrow(q, kn, vn)), float(loss_wide(q, kn, vn)), rtol=1e-5
        )
        gn = jax.grad(loss_narrow, argnums=(0, 1, 2))(q, kn, vn)
        gw = jax.grad(loss_wide, argnums=(0, 1, 2))(q, kn, vn)
        for a, b in zip(gn, gw):
            assert a.shape == b.shape  # dk/dv born narrow
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_ring_mqa_widens_minimally_instead_of_losing_sp(self):
        """MQA (hkv=1) with tensor=2 head shards: the router widens K/V
        just enough (1 -> 2 heads) and KEEPS the ring path — previously
        this would silently fall back to single-device blockwise."""
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.distributed import build_mesh
        from llmtrain_tpu.ops.ring_attention import ring_or_blockwise

        q, kn, vn = self._gqa_qkv(b=4, t=16, h=4, hkv=1, seed=53)
        kw, vw = jnp.repeat(kn, 4, axis=2), jnp.repeat(vn, 4, axis=2)
        ref = dense_attention(q, kw, vw, attention_mask=None)
        mesh = build_mesh(
            MeshConfig(data=2, fsdp=1, tensor=2, sequence=2), jax.devices()[:8]
        )
        with mesh:
            out = jax.jit(lambda q, k, v: ring_or_blockwise(q, k, v))(q, kn, vn)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    @pytest.mark.parametrize("hkv", [1, 2], ids=["mqa", "gqa2"])
    def test_ulysses_narrow_kv_matches_widened_dense(self, hkv):
        """Ulysses exchanges narrow K/V (separate q and kv all-to-alls,
        minimal widening when Hkv doesn't split the axis) and matches the
        widened dense reference, masks included."""
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.distributed import build_mesh
        from llmtrain_tpu.ops.ulysses_attention import ulysses_attention_sharded

        q, kn, vn = self._gqa_qkv(b=4, t=16, h=8, hkv=hkv, seed=59)
        reps = 8 // hkv
        mask = _suffix_mask(4, 16, seed=13)
        kw, vw = jnp.repeat(kn, reps, axis=2), jnp.repeat(vn, reps, axis=2)
        ref = dense_attention(q, kw, vw, attention_mask=mask)
        mesh = build_mesh(
            MeshConfig(data=2, fsdp=1, tensor=2, sequence=2), jax.devices()[:8]
        )
        out = jax.jit(
            lambda q, k, v, m: ulysses_attention_sharded(q, k, v, mesh, key_mask=m)
        )(q, kn, vn, mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)

    def test_ring_rotates_narrow_kv(self):
        """Ring attention with grouped-query K/V: narrow shards rotate
        (G x less ICI traffic) and results match the widened dense
        reference, masks included."""
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.distributed import build_mesh
        from llmtrain_tpu.ops.ring_attention import ring_attention_sharded

        q, kn, vn = self._gqa_qkv(b=4, t=16, h=4, hkv=2, seed=47)
        reps = 2
        mask = _suffix_mask(4, 16, seed=11)
        kw, vw = jnp.repeat(kn, reps, axis=2), jnp.repeat(vn, reps, axis=2)
        ref = dense_attention(q, kw, vw, attention_mask=mask)
        mesh = build_mesh(
            MeshConfig(data=2, fsdp=1, tensor=2, sequence=2), jax.devices()[:8]
        )
        out = jax.jit(
            lambda q, k, v, m: ring_attention_sharded(q, k, v, mesh, key_mask=m)
        )(q, kn, vn, mask)
        np.testing.assert_allclose(_valid(out, mask), _valid(ref, mask), atol=1e-5)


class TestRingAttention:
    def _mesh(self, sequence=2, data=2, tensor=2):
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.distributed import build_mesh

        return build_mesh(
            MeshConfig(data=data, fsdp=1, tensor=tensor, sequence=sequence),
            jax.devices()[: data * tensor * sequence],
        )

    def test_matches_dense_on_sequence_mesh(self):
        from llmtrain_tpu.ops.ring_attention import ring_attention_sharded

        q, k, v = _qkv(b=4, t=16, h=2, d=8)
        ref = _dense_ref(q, k, v)
        mesh = self._mesh()
        out = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gradients_match_dense(self):
        from llmtrain_tpu.ops.ring_attention import ring_attention_sharded

        q, k, v = _qkv(b=4, t=16, h=2, d=8)
        mesh = self._mesh()

        g_ring = jax.jit(
            jax.grad(lambda q: ring_attention_sharded(q, k, v, mesh).sum())
        )(q)
        g_ref = jax.grad(lambda q: _dense_ref(q, k, v).sum())(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4)

    def test_fallback_without_mesh(self):
        from llmtrain_tpu.ops.ring_attention import ring_or_blockwise

        q, k, v = _qkv(t=16)
        out = ring_or_blockwise(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(_dense_ref(q, k, v)), atol=1e-5)

    def test_external_mesh_with_only_sequence_axis(self):
        """An externally built mesh carrying a sequence axis but none of
        data/fsdp/tensor must still route through ring attention (missing
        axes count as unsharded), not KeyError at trace time (ADVICE r1)."""
        from llmtrain_tpu.ops.ring_attention import ring_or_blockwise

        q, k, v = _qkv(b=4, t=16, h=2, d=8)
        ref = _dense_ref(q, k, v)
        mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:2]), ("sequence",))
        with mesh:
            out = jax.jit(ring_or_blockwise)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_ring_gpt_matches_dense_gpt_under_mesh(self):
        kwargs = dict(
            vocab_size=64,
            block_size=16,
            d_model=32,
            n_layers=1,
            n_heads=4,
            d_ff=64,
            dropout=0.0,
        )
        dense = GPT(**kwargs, attention="dense")
        ring = GPT(**kwargs, attention="ring")
        tokens = jax.random.randint(jax.random.key(0), (4, 16), 0, 64)
        params = dense.init({"params": jax.random.key(1)}, tokens, deterministic=True)["params"]
        out_d = dense.apply({"params": params}, tokens, deterministic=True)
        mesh = self._mesh()
        with mesh:
            out_r = jax.jit(
                lambda p, t: ring.apply({"params": p}, t, deterministic=True)
            )(params, tokens)
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_r), atol=1e-5)


class TestGPTIntegration:
    def test_flash_gpt_matches_dense_gpt(self):
        kwargs = dict(
            vocab_size=64,
            block_size=16,
            d_model=32,
            n_layers=1,
            n_heads=4,
            d_ff=64,
            dropout=0.0,
        )
        dense = GPT(**kwargs, attention="dense")
        flash = GPT(**kwargs, attention="flash")
        tokens = jax.random.randint(jax.random.key(0), (2, 16), 0, 64)
        params = dense.init({"params": jax.random.key(1)}, tokens, deterministic=True)["params"]
        out_d = dense.apply({"params": params}, tokens, deterministic=True)
        out_f = flash.apply({"params": params}, tokens, deterministic=True)
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f), atol=1e-5)

    def test_remat_with_dropout_trains(self):
        """Regression: remat + dropout>0 must trace (static deterministic)."""
        model = GPT(
            vocab_size=32,
            block_size=8,
            d_model=16,
            n_layers=1,
            n_heads=2,
            d_ff=32,
            dropout=0.1,
            remat=True,
        )
        tokens = jnp.zeros((2, 8), jnp.int32)
        params = model.init({"params": jax.random.key(0)}, tokens, deterministic=True)["params"]
        out = model.apply(
            {"params": params},
            tokens,
            deterministic=False,
            rngs={"dropout": jax.random.key(1)},
        )
        assert np.isfinite(np.asarray(out)).all()


class TestUlyssesAttention:
    """All-to-all sequence parallelism (ops/ulysses_attention.py) — the
    ring alternative; exact attention, so it must match dense."""

    def _mesh(self, sequence=2, data=2, tensor=2):
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.distributed import build_mesh

        return build_mesh(
            MeshConfig(data=data, fsdp=1, tensor=tensor, sequence=sequence),
            jax.devices()[: data * tensor * sequence],
        )

    def test_matches_dense_on_sequence_mesh(self):
        from llmtrain_tpu.ops.ulysses_attention import ulysses_attention_sharded

        # tensor=2 leaves 2 local heads per shard; sequence=2 divides them.
        q, k, v = _qkv(b=4, t=16, h=4, d=8)
        ref = _dense_ref(q, k, v)
        mesh = self._mesh()
        out = jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_matches_ring(self):
        """Both SP schemes compute the same exact attention."""
        from llmtrain_tpu.ops.ring_attention import ring_attention_sharded
        from llmtrain_tpu.ops.ulysses_attention import ulysses_attention_sharded

        q, k, v = _qkv(b=4, t=16, h=4, d=8, seed=9)
        mesh = self._mesh()
        a = jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh))(q, k, v)
        b = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_gradients_match_dense(self):
        from llmtrain_tpu.ops.ulysses_attention import ulysses_attention_sharded

        q, k, v = _qkv(b=4, t=16, h=4, d=8)
        mesh = self._mesh()
        g_uly = jax.jit(
            jax.grad(lambda q: ulysses_attention_sharded(q, k, v, mesh).sum())
        )(q)
        g_ref = jax.grad(lambda q: _dense_ref(q, k, v).sum())(q)
        np.testing.assert_allclose(np.asarray(g_uly), np.asarray(g_ref), atol=1e-4)

    def test_fallback_without_mesh(self):
        from llmtrain_tpu.ops.ulysses_attention import ulysses_or_blockwise

        q, k, v = _qkv(t=16)
        out = ulysses_or_blockwise(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense_ref(q, k, v)), atol=1e-5
        )

    def test_fallback_when_heads_not_divisible(self):
        """sequence=4 but only 2 local heads: falls back to blockwise (with
        a warning) instead of crashing inside shard_map."""
        from llmtrain_tpu.ops.ulysses_attention import ulysses_or_blockwise

        q, k, v = _qkv(b=4, t=16, h=2, d=8)
        mesh = self._mesh(sequence=4, data=2, tensor=1)
        with mesh:
            out = ulysses_or_blockwise(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense_ref(q, k, v)), atol=1e-5
        )

    def test_gpt_model_route(self):
        """attention='ulysses' through the real GPT forward on a sequence
        mesh matches the dense model's logits."""
        from flax.linen import meta as nn_meta

        from llmtrain_tpu.models.gpt import GPT
        from llmtrain_tpu.parallel.sharding import DEFAULT_LOGICAL_AXIS_RULES

        def build(attention):
            return GPT(
                vocab_size=64, block_size=16, d_model=32, n_layers=2,
                n_heads=4, d_ff=64, dropout=0.0, attention=attention,
            )

        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (4, 16)), jnp.int32
        )
        dense = build("dense")
        params = nn_meta.unbox(
            dense.init(jax.random.key(0), ids, deterministic=True)
        )["params"]
        ref = dense.apply({"params": params}, ids, deterministic=True)

        import flax.linen as nn

        mesh = self._mesh(sequence=2, data=2, tensor=2)
        with mesh, nn.logical_axis_rules(DEFAULT_LOGICAL_AXIS_RULES):
            out = build("ulysses").apply({"params": params}, ids, deterministic=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
