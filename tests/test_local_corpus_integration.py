"""Offline real-corpus integration: full CLI train on actual text.

The egress-dependent counterpart lives in tests/test_real_data.py
(WikiText-2 + tiktoken; skipped when the hub doesn't resolve). This one
exercises the same end-to-end contract — CLI subprocess, real text through
the tokenize→window pipeline, decreasing loss, artifacts on disk — with
the offline stack (byte tokenizer + local_text over this repo's own
source files), so the slow tier always has a real-text run regardless of
network. Marked slow for runtime, not for downloads."""

import json
import os
import subprocess
import sys

import pytest
import yaml

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_local_corpus_cli_train_improves(tmp_path):
    cfg = {
        "schema_version": 1,
        "run": {"name": "pycorpus-it", "seed": 7, "device": "cpu"},
        "model": {
            "name": "gpt",
            "block_size": 64,
            "d_model": 64,
            "n_layers": 2,
            "n_heads": 4,
            "d_ff": 128,
            "dropout": 0.0,
            "extra": {"tokenizer": "byte"},
        },
        "data": {
            "name": "local_text",
            "cache_dir": str(tmp_path / "cache"),
            "extra": {
                "globs": [os.path.join(REPO_ROOT, "llmtrain_tpu", "**", "*.py")],
                "val_fraction": 0.05,
            },
        },
        "trainer": {
            "max_steps": 30,
            "micro_batch_size": 4,
            "grad_accum_steps": 1,
            "lr": 0.001,
            "warmup_steps": 5,
            "log_every_steps": 10,
            "eval_every_steps": 30,
            "save_every_steps": 30,
        },
        "mlflow": {"enabled": False},
        "output": {"root_dir": "runs"},
    }
    (tmp_path / "config.yaml").write_text(yaml.safe_dump(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", "train", "--config", "config.yaml", "--json"],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        env=env,
        timeout=1800,
    )
    assert proc.returncode == 0, proc.stderr
    tr = json.loads(proc.stdout)["train_result"]
    assert tr["final_step"] == 30
    assert tr["final_loss"] < tr["first_step_loss"]
    assert tr["final_val_loss"] is not None
    run_dirs = list((tmp_path / "runs").iterdir())
    assert len(run_dirs) == 1
    assert (run_dirs[0] / "checkpoints").exists()
    assert (run_dirs[0] / "config.yaml").exists()
