"""Offline real-corpus integration: full CLI train on actual text.

The egress-dependent counterpart lives in tests/test_real_data.py
(WikiText-2 + tiktoken; skipped when the hub doesn't resolve). This one
exercises the same end-to-end contract — CLI subprocess, real text through
the tokenize→window pipeline, decreasing loss, artifacts on disk — with
the offline stack (byte tokenizer + local_text over this repo's own
source files), so the slow tier always has a real-text run regardless of
network. Marked slow for runtime, not for downloads."""

import json
import os
import subprocess
import sys

import pytest
import yaml

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_local_corpus_cli_train_improves(tmp_path):
    cfg = {
        "schema_version": 1,
        "run": {"name": "pycorpus-it", "seed": 7, "device": "cpu"},
        "model": {
            "name": "gpt",
            "block_size": 64,
            "d_model": 64,
            "n_layers": 2,
            "n_heads": 4,
            "d_ff": 128,
            "dropout": 0.0,
            "extra": {"tokenizer": "byte"},
        },
        "data": {
            "name": "local_text",
            "cache_dir": str(tmp_path / "cache"),
            "extra": {
                "globs": [os.path.join(REPO_ROOT, "llmtrain_tpu", "**", "*.py")],
                "val_fraction": 0.05,
            },
        },
        "trainer": {
            "max_steps": 30,
            "micro_batch_size": 4,
            "grad_accum_steps": 1,
            "lr": 0.001,
            "warmup_steps": 5,
            "log_every_steps": 10,
            "eval_every_steps": 30,
            "save_every_steps": 30,
        },
        "mlflow": {"enabled": False},
        "output": {"root_dir": "runs"},
    }
    (tmp_path / "config.yaml").write_text(yaml.safe_dump(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", "train", "--config", "config.yaml", "--json"],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        env=env,
        timeout=1800,
    )
    assert proc.returncode == 0, proc.stderr
    tr = json.loads(proc.stdout)["train_result"]
    assert tr["final_step"] == 30
    assert tr["final_loss"] < tr["first_step_loss"]
    assert tr["final_val_loss"] is not None
    run_dirs = list((tmp_path / "runs").iterdir())
    assert len(run_dirs) == 1
    assert (run_dirs[0] / "checkpoints").exists()
    assert (run_dirs[0] / "config.yaml").exists()


def test_preemption_kill_and_auto_resume(tmp_path):
    """Fault injection for the elastic-recovery story: SIGKILL a training
    process mid-run, relaunch the identical command with --auto-resume,
    and the run completes from the last durable checkpoint. (The
    reference's only recovery is manual --resume — SURVEY §5.)"""
    import signal
    import time

    cfg = {
        "schema_version": 1,
        "run": {"name": "preempt-it", "seed": 3, "device": "cpu", "deterministic": True},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "d_model": 48,
            "n_layers": 1,
            "n_heads": 2,
            "d_ff": 96,
            "dropout": 0.0,
            "vocab_size": 32,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            # Effectively unfinishable: the run must still be mid-flight
            # when the kill lands, however fast the machine is.
            "max_steps": 1_000_000,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "lr": 0.003,
            "warmup_steps": 0,
            "log_every_steps": 50,
            "eval_every_steps": 4_000_000,
            "save_every_steps": 50,
        },
        "mlflow": {"enabled": False},
        "output": {"root_dir": "runs"},
    }
    (tmp_path / "config.yaml").write_text(yaml.safe_dump(cfg))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    argv = [
        sys.executable, "-m", "llmtrain_tpu", "train",
        "--config", "config.yaml", "--json",
        "--run-id", "preempt_run", "--auto-resume",
    ]

    # Launch, wait until at least one checkpoint is durable, then SIGKILL.
    # Output goes to files, not PIPEs: an undrained pipe can block a chatty
    # child before its first checkpoint and mask the real error.
    out_path = tmp_path / "first.out"
    err_path = tmp_path / "first.err"
    with out_path.open("w") as out_f, err_path.open("w") as err_f:
        proc = subprocess.Popen(
            argv, cwd=tmp_path, env=env, stdout=out_f, stderr=err_f, text=True
        )
        ckpt_dir = tmp_path / "runs" / "preempt_run" / "checkpoints"
        deadline = time.time() + 240
        try:
            while time.time() < deadline:
                if ckpt_dir.is_dir() and any(ckpt_dir.glob("step_*.ckpt")):
                    break
                if proc.poll() is not None:
                    raise AssertionError(
                        "run ended before first checkpoint: "
                        + err_path.read_text()[-2000:]
                    )
                time.sleep(0.5)
            else:
                raise AssertionError(
                    "no checkpoint appeared within 240s: "
                    + err_path.read_text()[-2000:]
                )
            proc.send_signal(signal.SIGKILL)
        finally:
            if proc.poll() is None:
                proc.kill()
            proc.wait()

    steps = [
        int(p.name[len("step_") : -len(".ckpt")])
        for p in ckpt_dir.glob("step_*.ckpt")
    ]
    assert steps, "kill happened before any checkpoint"
    last_durable = max(steps)

    # Same command with a horizon RELATIVE to the durable checkpoint: the
    # relaunch must resume there and train real post-resume steps (no
    # resume-past-end escape hatch). Config beats the snapshot on resume.
    cfg["trainer"]["max_steps"] = last_durable + 100
    (tmp_path / "config.yaml").write_text(yaml.safe_dump(cfg))
    second = subprocess.run(
        argv, cwd=tmp_path, env=env, capture_output=True, text=True, timeout=600,
    )
    assert second.returncode == 0, second.stderr[-2000:]
    summary = json.loads(
        [ln for ln in second.stdout.splitlines() if ln.startswith("{")][-1]
    )
    tr = summary["train_result"]
    assert tr["resumed_from_step"] == last_durable
    assert tr["final_step"] == last_durable + 100
    assert tr["final_loss"] > 0
