"""Sampler + dummy_text + hf_text tests (parity with reference
tests/test_dummy_text_data.py and tests/test_hf_text_data.py)."""

import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.data.dummy_text import DummyTextDataModule
from llmtrain_tpu.data.hf_text import HFTextDataModule, TokenWindowDataset
from llmtrain_tpu.data.sampler import DeterministicSampler

CFG = {
    "run": {"name": "t", "seed": 11},
    "model": {"name": "dummy_gpt", "block_size": 8, "vocab_size": 32},
    "data": {"name": "dummy_text"},
    "trainer": {"max_steps": 10, "micro_batch_size": 4, "warmup_steps": 0},
}


class TestSampler:
    def test_deterministic_and_epoch_varies(self):
        s = DeterministicSampler(num_examples=100, batch_size=10, seed=3)
        assert np.array_equal(s.batch_indices(4), s.batch_indices(4))
        # Different epochs shuffle differently.
        a = s.batch_indices(0)
        b = s.batch_indices(s.batches_per_epoch)  # same position, next epoch
        assert not np.array_equal(a, b)

    def test_epoch_covers_all_examples_once(self):
        s = DeterministicSampler(num_examples=40, batch_size=10, seed=0)
        seen = np.concatenate([s.batch_indices(i) for i in range(s.batches_per_epoch)])
        assert sorted(seen.tolist()) == list(range(40))

    def test_drop_last(self):
        s = DeterministicSampler(num_examples=47, batch_size=10, seed=0)
        assert s.batches_per_epoch == 4

    def test_shard_slicing(self):
        s = DeterministicSampler(num_examples=64, batch_size=8, seed=0)
        full = s.batch_indices(2)
        parts = [s.shard_indices(2, r, 4) for r in range(4)]
        assert np.array_equal(np.concatenate(parts), full)

    def test_shard_indivisible_raises(self):
        s = DeterministicSampler(num_examples=64, batch_size=8, seed=0)
        with pytest.raises(ValueError, match="divisible"):
            s.shard_indices(0, 0, 3)

    def test_no_shuffle_is_sequential(self):
        s = DeterministicSampler(num_examples=20, batch_size=5, seed=0, shuffle=False)
        assert np.array_equal(s.batch_indices(0), np.arange(5))

    def test_small_dataset_wraps_deterministically(self):
        s = DeterministicSampler(num_examples=3, batch_size=8, seed=0)
        a = s.batch_indices(0)
        assert len(a) == 8
        assert set(a.tolist()) == {0, 1, 2}
        assert np.array_equal(a, s.batch_indices(0))

    def test_empty_dataset_raises(self):
        with pytest.raises(ValueError, match="no examples"):
            DeterministicSampler(num_examples=0, batch_size=8, seed=0)


class TestDummyText:
    def test_shapes_and_determinism(self):
        cfg = RunConfig.model_validate(CFG)
        dm = DummyTextDataModule()
        dm.setup(cfg, None)
        train = dm.train_dataset()
        assert len(train) == 40  # max_steps * micro_batch
        batch = train.get_examples(np.array([0, 1, 2]))
        assert batch["input_ids"].shape == (3, 8)
        assert np.array_equal(batch["labels"], batch["input_ids"])
        assert batch["attention_mask"].all()
        again = train.get_examples(np.array([0, 1, 2]))
        assert np.array_equal(batch["input_ids"], again["input_ids"])

    def test_val_split_sizing_and_seed(self):
        cfg = RunConfig.model_validate(CFG)
        dm = DummyTextDataModule()
        dm.setup(cfg, None)
        val = dm.val_dataset()
        assert len(val) == 8  # 40 // 5
        tb = dm.train_dataset().get_examples(np.array([0]))
        vb = val.get_examples(np.array([0]))
        assert not np.array_equal(tb["input_ids"], vb["input_ids"])

    def test_seq_len_capped_at_8(self):
        cfg = RunConfig.model_validate(
            {**CFG, "model": {"name": "dummy_gpt", "block_size": 256, "vocab_size": 32}}
        )
        dm = DummyTextDataModule()
        dm.setup(cfg, None)
        assert dm.train_dataset().get_examples(np.array([0]))["input_ids"].shape[1] == 8

    def test_setup_required(self):
        with pytest.raises(RuntimeError, match="setup"):
            DummyTextDataModule().train_dataset()


class TestTokenWindowDataset:
    def test_windows(self):
        tokens = np.arange(25, dtype=np.int32)
        ds = TokenWindowDataset(tokens, block_size=4)  # chunk=5 -> 5 windows
        assert len(ds) == 5
        b = ds.get_examples(np.array([0, 2]))
        assert np.array_equal(b["input_ids"][0], [0, 1, 2, 3])
        assert np.array_equal(b["labels"][0], [1, 2, 3, 4])
        assert np.array_equal(b["input_ids"][1], [10, 11, 12, 13])
        assert b["attention_mask"].all()


class _ToyTokenizer:
    n_vocab = 128

    def encode(self, text):
        return [ord(c) % 128 for c in text]


def _hf_cfg(tmp_path, block_size=8):
    return RunConfig.model_validate(
        {
            "run": {"name": "t"},
            "model": {"name": "gpt", "block_size": block_size, "vocab_size": 128},
            "data": {
                "name": "hf_text",
                "dataset_name": "toy",
                "cache_dir": str(tmp_path),
                "text_column": "text",
            },
            "trainer": {"max_steps": 2, "warmup_steps": 0},
        }
    )


class TestHFText:
    def _patch_load(self, monkeypatch, rows):
        calls = {"n": 0}

        class _FakeDS:
            def __getitem__(self, col):
                assert col == "text"
                return rows

        def fake_load_dataset(name, config, split, cache_dir):
            calls["n"] += 1
            return _FakeDS()

        import datasets

        monkeypatch.setattr(datasets, "load_dataset", fake_load_dataset)
        return calls

    def test_pipeline_and_cache_reuse(self, tmp_path, monkeypatch):
        calls = self._patch_load(monkeypatch, ["abcdefghijklmnopqr", None, "stuvwxyz"])
        cfg = _hf_cfg(tmp_path)
        dm = HFTextDataModule()
        dm.setup(cfg, _ToyTokenizer())
        train = dm.train_dataset()
        # 26 tokens total, chunk=9 -> 2 windows
        assert len(train) == 2
        batch = train.get_examples(np.array([0]))
        assert batch["input_ids"][0].tolist() == [ord(c) for c in "abcdefgh"]
        assert batch["labels"][0].tolist() == [ord(c) for c in "bcdefghi"]
        first_calls = calls["n"]

        dm2 = HFTextDataModule()
        dm2.setup(cfg, _ToyTokenizer())
        assert calls["n"] == first_calls  # served from .npy cache
        assert len(dm2.train_dataset()) == 2

    def test_requires_tokenizer_and_dataset_name(self, tmp_path):
        cfg = _hf_cfg(tmp_path)
        with pytest.raises(ValueError, match="tokenizer"):
            HFTextDataModule().setup(cfg, None)

    def test_empty_val_split_gives_none(self, tmp_path, monkeypatch):
        self._patch_load(monkeypatch, ["ab"])  # 2 tokens -> 0 windows
        cfg = _hf_cfg(tmp_path)
        dm = HFTextDataModule()
        dm.setup(cfg, _ToyTokenizer())
        assert dm.val_dataset() is None


class TestByteTokenizer:
    def test_roundtrip_and_vocab(self):
        from llmtrain_tpu.data.tokenizers import ByteTokenizer, build_tokenizer

        tok = ByteTokenizer()
        assert tok.n_vocab == 256
        text = "def f(x):\n    return x  # ünïcode"
        ids = tok.encode(text)
        assert all(0 <= i <= 255 for i in ids)
        assert tok.decode(ids) == text
        np.testing.assert_array_equal(tok.encode_np(text), np.asarray(ids, np.int32))
        assert isinstance(build_tokenizer("byte"), ByteTokenizer)
        with pytest.raises(ValueError, match="unknown tokenizer"):
            build_tokenizer("nope")

    def test_decode_rejects_out_of_range(self):
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        with pytest.raises(ValueError, match="255"):
            ByteTokenizer().decode([300])


def _local_cfg(tmp_path, globs, **extra):
    raw = {
        "run": {"name": "t", "seed": 11},
        "model": {"name": "gpt", "block_size": 8, "vocab_size": 256},
        "data": {
            "name": "local_text",
            "cache_dir": str(tmp_path / "cache"),
            "extra": {"globs": globs, **extra},
        },
        "trainer": {"max_steps": 10, "micro_batch_size": 4, "warmup_steps": 0},
    }
    return RunConfig.model_validate(raw)


class TestLocalText:
    def _corpus(self, tmp_path):
        d = tmp_path / "corpus"
        d.mkdir()
        (d / "a.py").write_text("a" * 100)
        (d / "b.py").write_text("b" * 100)
        (d / "ignored.txt").write_text("x" * 500)
        return str(d / "*.py")

    def test_windows_split_and_cache(self, tmp_path):
        from llmtrain_tpu.data.local_text import LocalTextDataModule
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        pattern = self._corpus(tmp_path)
        cfg = _local_cfg(tmp_path, [pattern], val_fraction=0.25)
        dm = LocalTextDataModule()
        dm.setup(cfg, ByteTokenizer())
        # 204 tokens (2x100 + 2x2 separators); val=51 -> 5 windows of 9,
        # train=153 -> 17 windows.
        assert len(dm.train_dataset()) == 17
        assert len(dm.val_dataset()) == 5
        batch = dm.train_dataset().get_examples(np.array([0]))
        assert batch["input_ids"][0].tolist() == [ord("a")] * 8

        cache_dir = tmp_path / "cache" / "processed"
        # Tokens + the document-offsets sidecar (split_documents support).
        def token_caches():
            return [p for p in cache_dir.glob("*.npy") if ".docs" not in p.name]

        assert len(token_caches()) == 1

        # Unchanged corpus -> same cache file reused.
        dm2 = LocalTextDataModule()
        dm2.setup(cfg, ByteTokenizer())
        assert len(dm2.train_dataset()) == 17
        assert len(token_caches()) == 1

        # Same-length edit -> mtime changes -> cache rebuilt, not reused.
        (tmp_path / "corpus" / "a.py").write_text("c" * 100)
        dm3 = LocalTextDataModule()
        dm3.setup(cfg, ByteTokenizer())
        assert len(token_caches()) == 2
        batch3 = dm3.train_dataset().get_examples(np.array([0]))
        assert batch3["input_ids"][0].tolist() == [ord("c")] * 8

    def test_requires_globs_and_matches(self, tmp_path):
        from llmtrain_tpu.data.local_text import LocalTextDataModule
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        with pytest.raises(ValueError, match="tokenizer"):
            LocalTextDataModule().setup(_local_cfg(tmp_path, ["x"]), None)
        with pytest.raises(ValueError, match="globs"):
            LocalTextDataModule().setup(
                RunConfig.model_validate(
                    {
                        "run": {"name": "t"},
                        "model": {"name": "gpt", "block_size": 8, "vocab_size": 256},
                        "data": {"name": "local_text"},
                        "trainer": {"max_steps": 1, "micro_batch_size": 1, "warmup_steps": 0},
                    }
                ),
                ByteTokenizer(),
            )
        with pytest.raises(ValueError, match="matched no files"):
            LocalTextDataModule().setup(
                _local_cfg(tmp_path, [str(tmp_path / "nothing-*.py")]), ByteTokenizer()
            )

    def test_corpus_too_small_raises(self, tmp_path):
        from llmtrain_tpu.data.local_text import LocalTextDataModule
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        d = tmp_path / "tiny"
        d.mkdir()
        (d / "t.py").write_text("ab")
        with pytest.raises(ValueError, match="corpus too small"):
            LocalTextDataModule().setup(
                _local_cfg(tmp_path, [str(d / "*.py")]), ByteTokenizer()
            )

    def test_registered(self):
        from llmtrain_tpu.registry import get_data_module, initialize_registries

        initialize_registries()
        from llmtrain_tpu.data.local_text import LocalTextDataModule

        assert get_data_module("local_text") is LocalTextDataModule


class TestLocalTextJsonl:
    """local_text format: jsonl — one JSON object per line, text under
    data.extra.text_key (new capability; text mode is the default)."""

    def _cfg(self, tmp_path, corpus, block_size=8, **extra):
        from llmtrain_tpu.config.schemas import RunConfig

        return RunConfig.model_validate(
            {
                "run": {"name": "jsonl", "seed": 0, "device": "cpu"},
                "model": {
                    "name": "gpt",
                    "block_size": block_size,
                    "d_model": 16,
                    "n_layers": 1,
                    "n_heads": 4,
                    "d_ff": 32,
                    "vocab_size": 256,
                    "extra": {"tokenizer": "byte"},
                },
                "data": {
                    "name": "local_text",
                    "cache_dir": str(tmp_path / "cache"),
                    "extra": {
                        "globs": [str(corpus)],
                        "format": "jsonl",
                        "val_fraction": 0.0,
                        **extra,
                    },
                },
                "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                "mlflow": {"enabled": False},
            }
        )

    def _setup(self, cfg):
        from llmtrain_tpu.data.local_text import LocalTextDataModule
        from llmtrain_tpu.data.tokenizers import ByteTokenizer

        dm = LocalTextDataModule()
        dm.setup(cfg, ByteTokenizer())
        return dm

    def test_jsonl_tokens_match_joined_documents(self, tmp_path):
        import json as _json

        corpus = tmp_path / "c.jsonl"
        docs = ["first document " * 14, "second one " * 20, "third " * 35]
        corpus.write_text(
            "\n".join(_json.dumps({"text": d, "meta": 1}) for d in docs) + "\n"
        )
        # block_size 256 makes window 0 span the doc0/doc1 boundary, so the
        # comparison pins the blank-line join convention, not just doc0.
        dm = self._setup(self._cfg(tmp_path, corpus, block_size=256))
        ds = dm.train_dataset()
        assert len(ds) >= 2
        # The stream must be exactly the byte-encoding of the
        # blank-line-joined field values (JSON braces/quotes/meta stripped).
        expected = np.frombuffer(
            "\n\n".join(docs).encode("utf-8"), dtype=np.uint8
        ).astype(np.int32)
        for w in range(len(ds)):
            got = ds.get_examples(np.asarray([w]))["input_ids"][0]
            np.testing.assert_array_equal(got, expected[w * 257 : w * 257 + 256])

    def test_text_key_override(self, tmp_path):
        import json as _json

        corpus = tmp_path / "c.jsonl"
        corpus.write_text(_json.dumps({"content": "hello world " * 20}) + "\n")
        dm = self._setup(self._cfg(tmp_path, corpus, text_key="content"))
        assert len(dm.train_dataset()) > 0

    def test_invalid_json_line_errors_with_location(self, tmp_path):
        corpus = tmp_path / "c.jsonl"
        corpus.write_text('{"text": "ok"}\nnot json\n')
        with pytest.raises(ValueError, match=r"c\.jsonl:2: invalid JSON"):
            self._setup(self._cfg(tmp_path, corpus))

    def test_missing_text_key_errors(self, tmp_path):
        import json as _json

        corpus = tmp_path / "c.jsonl"
        corpus.write_text(_json.dumps({"other": "x"}) + "\n")
        with pytest.raises(ValueError, match="expected a string field 'text'"):
            self._setup(self._cfg(tmp_path, corpus))

    def test_unknown_format_rejected(self, tmp_path):
        corpus = tmp_path / "c.jsonl"
        corpus.write_text("{}\n")
        with pytest.raises(ValueError, match="format must be"):
            self._setup(self._cfg(tmp_path, corpus, format="csv"))

    def test_cache_distinguishes_format(self, tmp_path):
        """A .jsonl file previously cached as plain text must not be served
        from that cache when re-read as jsonl (and vice versa)."""
        import json as _json

        corpus = tmp_path / "c.jsonl"
        corpus.write_text(_json.dumps({"text": "abcdef " * 30}) + "\n")
        text_cfg = self._cfg(tmp_path, corpus, format="text")
        jsonl_cfg = self._cfg(tmp_path, corpus)
        t1 = self._setup(text_cfg).train_dataset()
        t2 = self._setup(jsonl_cfg).train_dataset()
        # text mode tokenizes the raw JSON (with braces/quotes); jsonl mode
        # tokenizes only the field value — different streams.
        a = t1.get_examples(np.arange(1))["input_ids"]
        b = t2.get_examples(np.arange(1))["input_ids"]
        assert not np.array_equal(a, b)
