"""Fault-tolerance subsystem tests (llmtrain_tpu/resilience/).

Every recovery path is exercised END TO END through the config-driven
fault-injection harness, not just claimed:

* non-finite guard — NaN injected INSIDE the jitted step is survived with a
  skipped update; persistent NaN aborts after the consecutive-skip cap; the
  guard counter round-trips through the checkpoint.
* loss-spike rollback — an injected spike restores the newest verified
  checkpoint saved before the spike and the run completes; the rollback
  budget bounds repeated spikes.
* checkpoint integrity — a corrupted newest checkpoint is skipped by
  resume, which restores the previous valid one.
* SIGTERM injection — a durable preemption save that resumes to loss
  parity with a continuous run, guard enabled on both sides.
* retry — flaky dataset loading and distributed init recover under the
  exponential-backoff helper.
"""

from __future__ import annotations

import logging

import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.resilience import (
    FaultPlan,
    InjectedFault,
    LossSpikeDetector,
    NonFiniteLossError,
    RollbackBudgetExceededError,
    retry,
)
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import CheckpointManager, Trainer

pytestmark = []  # deliberately unmarked: tier-1 must exercise recovery paths


def _cfg(tmp_path=None, **overrides):
    base = {
        "run": {"name": "resil", "seed": 11},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 48,
            "n_heads": 2,
            "d_ff": 96,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 8,
            "micro_batch_size": 2,
            "grad_accum_steps": 2,
            "lr": 3e-3,
            "warmup_steps": 0,
            "log_every_steps": 2,
            "eval_every_steps": 100,
            "save_every_steps": 100,
        },
        "resilience": {"nonfinite_guard": True},
        "mlflow": {"enabled": False},
    }
    if tmp_path is not None:
        base["output"] = {"root_dir": str(tmp_path)}
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _run_dir(tmp_path, name):
    d = tmp_path / name
    d.mkdir(parents=True, exist_ok=True)
    return d


# --------------------------------------------------------------------------
# pillar 1: non-finite guard
# --------------------------------------------------------------------------


class TestNonFiniteGuard:
    def test_injected_nan_is_survived_with_skipped_update(self, tmp_path, caplog):
        """NaN at step 3 inside the compiled step: the run trains through,
        the guard warns, and the final loss is finite."""
        cfg = _cfg(
            tmp_path,
            resilience={
                "nonfinite_guard": True,
                "faults": {"nan_loss_at_step": 3, "nan_loss_steps": 1},
            },
        )
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.final_step == cfg.trainer.max_steps
        assert np.isfinite(res.final_loss)
        assert any("skipped by the guard" in r.message for r in caplog.records)

    def test_unguarded_nan_poisons_the_run(self, tmp_path):
        """Control: the same injection WITHOUT the guard destroys the
        params — this is exactly the failure mode the guard removes."""
        cfg = _cfg(
            tmp_path,
            resilience={
                "nonfinite_guard": False,
                "faults": {"nan_loss_at_step": 3, "nan_loss_steps": 1},
            },
        )
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert not np.isfinite(res.final_loss)

    def test_persistent_nan_aborts_after_cap(self, tmp_path):
        cfg = _cfg(
            tmp_path,
            trainer={"max_steps": 30, "log_every_steps": 1},
            resilience={
                "nonfinite_guard": True,
                "max_consecutive_nonfinite": 3,
                "faults": {"nan_loss_at_step": 2, "nan_loss_steps": 100},
            },
        )
        with pytest.raises(NonFiniteLossError, match="3 consecutive"):
            Trainer(cfg, None, NullTracker(), None).fit()

    def test_guard_counter_round_trips_through_checkpoint(self, tmp_path):
        """Persistent NaN from step 3 on; save at 6 must record 4 consecutive
        skips, and the resumed run must CONTINUE the count (8 by step 8),
        not restart it from zero."""
        overrides = {
            "trainer": {"max_steps": 6, "save_every_steps": 3},
            "resilience": {
                "nonfinite_guard": True,
                "max_consecutive_nonfinite": 1000,
                "faults": {"nan_loss_at_step": 3, "nan_loss_steps": 100},
            },
        }
        cfg = _cfg(tmp_path, **overrides)
        run_dir = _run_dir(tmp_path, "guard_rt")
        Trainer(cfg, run_dir, NullTracker(), None).fit()
        ckpt_dir = run_dir / "checkpoints"
        payload = CheckpointManager.load(ckpt_dir / "step_000006.ckpt")
        assert int(payload["resilience"]["nonfinite_count"]) == 4

        resumed_cfg = _cfg(
            tmp_path,
            **{**overrides, "trainer": {"max_steps": 8, "save_every_steps": 8}},
        )
        Trainer(resumed_cfg, run_dir, NullTracker(), None).fit(
            resume_from=str(ckpt_dir)
        )
        payload = CheckpointManager.load(ckpt_dir / "step_000008.ckpt")
        assert int(payload["resilience"]["nonfinite_count"]) == 6


# --------------------------------------------------------------------------
# pillar 2: loss-spike rollback
# --------------------------------------------------------------------------


class TestSpikeRollback:
    def _spike_cfg(self, tmp_path, **extra_resilience):
        return _cfg(
            tmp_path,
            trainer={
                "max_steps": 12,
                "log_every_steps": 2,
                "save_every_steps": 5,
            },
            resilience={
                "nonfinite_guard": False,
                "spike_detection": True,
                "spike_factor": 4.0,
                "spike_min_history": 4,
                "max_rollbacks": 2,
                "faults": {"spike_loss_at_step": 8, "spike_loss_scale": 100.0},
                **extra_resilience,
            },
        )

    def test_injected_spike_rolls_back_and_completes(self, tmp_path, caplog):
        cfg = self._spike_cfg(tmp_path)
        run_dir = _run_dir(tmp_path, "spike")
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, run_dir, NullTracker(), None).fit()
        assert res.rollbacks == 1
        assert res.final_step == 12
        assert np.isfinite(res.final_loss)
        assert any("rolled back to checkpoint step 5" in r.message for r in caplog.records)
        # The rollback bookkeeping round-tripped into the final checkpoint.
        payload = CheckpointManager.load(run_dir / "checkpoints" / "step_000012.ckpt")
        assert int(payload["resilience"]["rollback_count"]) == 1
        assert int(payload["resilience"]["data_offset"]) > 0

    def test_rollback_budget_exhaustion_raises(self, tmp_path):
        cfg = self._spike_cfg(tmp_path, max_rollbacks=0)
        run_dir = _run_dir(tmp_path, "spike_budget")
        with pytest.raises(RollbackBudgetExceededError, match="budget"):
            Trainer(cfg, run_dir, NullTracker(), None).fit()

    def test_early_spike_before_first_save_continues(self, tmp_path, caplog):
        """A spike with no verified checkpoint predating it (detector armed
        before the first periodic save) must warn and train through, not
        kill a run that would otherwise continue."""
        cfg = _cfg(
            tmp_path,
            trainer={
                "max_steps": 10,
                "log_every_steps": 2,
                "save_every_steps": 100,
            },
            resilience={
                "nonfinite_guard": False,
                "spike_detection": True,
                "spike_factor": 4.0,
                "spike_min_history": 3,
                "faults": {"spike_loss_at_step": 6, "spike_loss_scale": 100.0},
            },
        )
        run_dir = _run_dir(tmp_path, "early_spike")
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, run_dir, NullTracker(), None).fit()
        assert res.rollbacks == 0
        assert res.final_step == 10
        assert any(
            "continuing without rollback" in r.message for r in caplog.records
        )

    def test_spike_without_checkpoint_manager_disables_detector(
        self, tmp_path, caplog
    ):
        """No run dir → nothing to roll back to: log an error and finish the
        run rather than dying."""
        cfg = self._spike_cfg(tmp_path)
        with caplog.at_level(logging.ERROR, logger="llmtrain"):
            res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.rollbacks == 0
        assert res.final_step == 12
        assert any("rollback disabled" in r.message for r in caplog.records)

    def test_detector_unit_behavior(self):
        det = LossSpikeDetector(factor=4.0, beta=0.9, min_history=5)
        for _ in range(10):
            assert det.observe(1.0) is False
        assert det.armed
        assert det.observe(float("nan")) is False  # guard's failure mode
        assert det.observe(1.3) is False  # noise, not a spike
        assert det.observe(10.0) is True  # 10 > 4 x trend(~1.0)
        # The spike was not folded into the trend: a second spike still fires.
        assert det.observe(10.0) is True
        state = det.state()
        clone = LossSpikeDetector(factor=4.0, beta=0.9, min_history=5)
        clone.load_state(state)
        assert clone.trend == pytest.approx(det.trend)
        assert clone.armed


# --------------------------------------------------------------------------
# pillar 3: checkpoint integrity (e2e; unit coverage in test_checkpoint.py)
# --------------------------------------------------------------------------


class TestCorruptCheckpointRecovery:
    def test_resume_skips_injected_corruption(self, tmp_path, caplog):
        """The newest checkpoint is truncated after its save; resume must
        warn, fall back to the previous verified one, and continue."""
        cfg = _cfg(
            tmp_path,
            trainer={"max_steps": 10, "save_every_steps": 5},
            resilience={
                "faults": {
                    "corrupt_checkpoint_at_step": 10,
                    "corrupt_mode": "truncate",
                }
            },
        )
        run_dir = _run_dir(tmp_path, "corrupt")
        Trainer(cfg, run_dir, NullTracker(), None).fit()

        clean = _cfg(tmp_path, trainer={"max_steps": 12, "save_every_steps": 5})
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(clean, None, NullTracker(), None).fit(
                resume_from=str(run_dir / "checkpoints")
            )
        assert res.resumed_from_step == 5
        assert res.final_step == 12
        assert any(
            "failed integrity verification" in r.message for r in caplog.records
        )


# --------------------------------------------------------------------------
# pillar 4 (+ satellite): SIGTERM injection, guard-enabled resume parity
# --------------------------------------------------------------------------


class TestSigtermInjection:
    def test_injected_sigterm_saves_and_resumes_to_parity(self, tmp_path):
        """Guard enabled on both sides: the preempted-and-resumed run must
        reach the continuous run's final loss to 1e-5, proving the guard
        state (and everything else) round-trips through the preemption
        checkpoint."""
        base = {
            "trainer": {"max_steps": 14, "save_every_steps": 100},
            "resilience": {"nonfinite_guard": True},
        }
        continuous = _cfg(tmp_path, **base)
        run_a = _run_dir(tmp_path, "cont")
        res_full = Trainer(continuous, run_a, NullTracker(), None).fit()
        assert res_full.preempted is False

        preempt = _cfg(
            tmp_path,
            **{
                **base,
                "resilience": {
                    "nonfinite_guard": True,
                    "faults": {"sigterm_at_step": 7},
                },
            },
        )
        run_b = _run_dir(tmp_path, "pre")
        res_pre = Trainer(preempt, run_b, NullTracker(), None).fit()
        assert res_pre.preempted is True
        assert res_pre.final_step == 7
        ckpt = run_b / "checkpoints" / "step_000007.ckpt"
        assert ckpt.exists()
        # The preemption save carries the guard payload.
        assert "resilience" in CheckpointManager.load(ckpt)

        resumed = Trainer(_cfg(tmp_path, **base), None, NullTracker(), None).fit(
            resume_from=str(run_b / "checkpoints")
        )
        assert resumed.resumed_from_step == 7
        assert resumed.final_loss == pytest.approx(res_full.final_loss, abs=1e-5)


# --------------------------------------------------------------------------
# retry + flaky-init injection
# --------------------------------------------------------------------------


class TestRetry:
    def test_exponential_backoff_delays_without_jitter(self):
        sleeps: list[float] = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 4:
                raise RuntimeError("boom")
            return "ok"

        assert (
            retry(
                flaky,
                attempts=4,
                base_delay=0.1,
                description="unit op",
                sleep=sleeps.append,
                jitter=False,
            )
            == "ok"
        )
        assert sleeps == pytest.approx([0.1, 0.2, 0.4])

    def test_full_jitter_bounded_by_exponential_caps_and_seeded(self):
        """Default backoff is FULL jitter: each delay is uniform in
        (0, base·2^k], and a seeded RNG reproduces the exact schedule —
        deterministic per rank, different across ranks (no thundering
        herd when a fleet retries a shared dependency together)."""
        import random

        from llmtrain_tpu.resilience import retry_rng

        def run(rng):
            sleeps: list[float] = []
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] < 4:
                    raise RuntimeError("boom")
                return "ok"

            assert (
                retry(
                    flaky,
                    attempts=4,
                    base_delay=0.1,
                    sleep=sleeps.append,
                    rng=rng,
                )
                == "ok"
            )
            return sleeps

        a = run(random.Random(7))
        b = run(random.Random(7))
        assert a == b  # seeded => deterministic
        for delay, cap in zip(a, [0.1, 0.2, 0.4]):
            assert 0.0 <= delay <= cap
        # Different ranks draw different schedules from the same run seed.
        r0 = run(retry_rng(1337, 0))
        r1 = run(retry_rng(1337, 1))
        assert r0 != r1
        assert run(retry_rng(1337, 0)) == r0

    def test_max_delay_caps_jitter_window(self):
        import random

        sleeps: list[float] = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 6:
                raise RuntimeError("boom")
            return "ok"

        retry(
            flaky,
            attempts=6,
            base_delay=1.0,
            max_delay=2.0,
            sleep=sleeps.append,
            rng=random.Random(3),
        )
        assert all(d <= 2.0 for d in sleeps)

    def test_final_failure_reraises_original(self):
        def always():
            raise ValueError("real cause")

        with pytest.raises(ValueError, match="real cause"):
            retry(always, attempts=2, base_delay=0.0, sleep=lambda _t: None)

    def test_flaky_distributed_init_recovers_under_retry(self):
        from llmtrain_tpu.config import FaultInjectionConfig

        plan = FaultPlan.from_config(
            FaultInjectionConfig(distributed_init_failures=2)
        )
        wrapped = plan.flaky("distributed_init", lambda: "rendezvous")
        with pytest.raises(InjectedFault):
            wrapped()
        assert (
            retry(wrapped, attempts=3, base_delay=0.0, sleep=lambda _t: None)
            == "rendezvous"
        )

    def test_trainer_dataset_setup_retries_injected_failures(self, tmp_path, caplog):
        cfg = _cfg(
            tmp_path,
            resilience={
                "retry_attempts": 3,
                "retry_base_delay": 0.0,
                "faults": {"dataset_load_failures": 2},
            },
        )
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, None, NullTracker(), None).fit()
        assert res.final_step == cfg.trainer.max_steps
        assert any(
            "dataset setup failed (attempt 1/3" in r.message for r in caplog.records
        )

    def test_trainer_dataset_setup_fails_past_budget(self, tmp_path):
        cfg = _cfg(
            tmp_path,
            resilience={
                "retry_attempts": 2,
                "retry_base_delay": 0.0,
                "faults": {"dataset_load_failures": 5},
            },
        )
        with pytest.raises(InjectedFault):
            Trainer(cfg, None, NullTracker(), None)


# --------------------------------------------------------------------------
# async-save failure path through the trainer (satellite)
# --------------------------------------------------------------------------


class _StepRecorder(NullTracker):
    def __init__(self):
        self.steps: list[int] = []

    def log_metrics(self, metrics, step=None):
        if step is not None:
            self.steps.append(step)


class TestAsyncSaveFailureSurfaces:
    def test_background_write_error_fails_the_run_promptly(self, tmp_path):
        """A failing async checkpoint write must abort training within a log
        interval or two — not silently train to max_steps and die at
        close()."""
        cfg = _cfg(
            tmp_path,
            trainer={
                "max_steps": 200,
                "save_every_steps": 5,
                "log_every_steps": 5,
            },
        )
        run_dir = _run_dir(tmp_path, "async_fail")
        # A FILE where the checkpoints dir should be: every write fails.
        (run_dir / "checkpoints").write_text("not a directory")
        tracker = _StepRecorder()
        with pytest.raises(OSError):
            Trainer(cfg, run_dir, tracker, None).fit()
        assert not tracker.steps or max(tracker.steps) <= 50
