"""Mixture-of-Experts tests: routing math, capacity drops, aux loss,
adapter objective, expert-parallel mesh execution, and training smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.models.moe import MoEMLP
from llmtrain_tpu.registry import get_model_adapter, initialize_registries


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _moe(n_experts=4, capacity_factor=2.0, d_model=16, d_ff=32):
    return MoEMLP(
        d_model=d_model,
        d_ff=d_ff,
        n_experts=n_experts,
        n_layers=1,
        capacity_factor=capacity_factor,
        dtype=jnp.float32,
        param_dtype=jnp.float32,
    )


class TestMoEMLP:
    def test_output_shape_and_finite(self):
        m = _moe()
        x = jax.random.normal(jax.random.key(0), (2, 8, 16))
        params = m.init(jax.random.key(1), x)["params"]
        out = m.apply({"params": params}, x)
        assert out.shape == x.shape
        assert np.isfinite(np.asarray(out)).all()

    def test_matches_per_token_expert_computation(self):
        """With capacity >= T no token drops: the dispatch/combine einsums
        must equal routing each token through its argmax expert scaled by
        the router probability."""
        m = _moe(n_experts=4, capacity_factor=8.0)
        x = jax.random.normal(jax.random.key(2), (2, 8, 16))
        params = m.init(jax.random.key(3), x)["params"]
        out = np.asarray(m.apply({"params": params}, x))

        from flax.linen import meta as nn_meta

        p = nn_meta.unbox(params)
        logits = np.asarray(x) @ np.asarray(p["router"]["kernel"])
        gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        wi, bi = np.asarray(p["wi"]), np.asarray(p["bi"])
        wo, bo = np.asarray(p["wo"]), np.asarray(p["bo"])

        expected = np.zeros_like(out)
        for b in range(x.shape[0]):
            for t in range(x.shape[1]):
                e = int(gates[b, t].argmax())
                h = np.asarray(x)[b, t] @ wi[e] + bi[e]
                h = np.asarray(jax.nn.gelu(jnp.asarray(h), approximate=False))
                expected[b, t] = gates[b, t, e] * (h @ wo[e] + bo[e])
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_swiglu_matches_per_token_expert_computation(self):
        """mlp_type='swiglu' (Mixtral experts): silu(x·wg)*(x·wu)·wo,
        bias-free — per-token equivalence like the gelu test above."""
        m = _moe(n_experts=4, capacity_factor=8.0).clone(mlp_type="swiglu")
        x = jax.random.normal(jax.random.key(12), (2, 8, 16))
        params = m.init(jax.random.key(13), x)["params"]
        out = np.asarray(m.apply({"params": params}, x))

        from flax.linen import meta as nn_meta

        p = nn_meta.unbox(params)
        assert set(p) == {"router", "wg", "wu", "wo"}  # no biases
        logits = np.asarray(x) @ np.asarray(p["router"]["kernel"])
        gates = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        wg, wu, wo = np.asarray(p["wg"]), np.asarray(p["wu"]), np.asarray(p["wo"])

        expected = np.zeros_like(out)
        for b in range(x.shape[0]):
            for t in range(x.shape[1]):
                e = int(gates[b, t].argmax())
                xe = np.asarray(x)[b, t]
                h = np.asarray(jax.nn.silu(jnp.asarray(xe @ wg[e]))) * (xe @ wu[e])
                expected[b, t] = gates[b, t, e] * (h @ wo[e])
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_unknown_mlp_type_raises(self):
        m = _moe().clone(mlp_type="relu")
        x = jax.random.normal(jax.random.key(14), (1, 8, 16))
        with pytest.raises(ValueError, match="mlp_type"):
            m.init(jax.random.key(15), x)

    def test_capacity_drops_tokens_to_zero(self):
        """capacity_factor small enough that an oversubscribed expert drops
        tokens: dropped positions produce exactly 0 (residual carries them)."""
        m = _moe(n_experts=2, capacity_factor=0.25)  # capacity = 1 per expert
        x = jax.random.normal(jax.random.key(4), (1, 8, 16))
        params = m.init(jax.random.key(5), x)["params"]
        out = np.asarray(m.apply({"params": params}, x))
        # 8 tokens, 2 experts, capacity 1 -> at most 2 nonzero outputs.
        nonzero_rows = (np.abs(out).sum(-1) > 1e-9).sum()
        assert nonzero_rows <= 2

    def test_aux_loss_sown_when_mutable(self):
        m = _moe()
        x = jax.random.normal(jax.random.key(6), (2, 8, 16))
        params = m.init(jax.random.key(7), x)["params"]
        _, mutated = m.apply({"params": params}, x, mutable=["losses"])
        leaves = jax.tree.leaves(mutated["losses"])
        assert len(leaves) == 1
        aux = float(leaves[0])
        # Uniform routing gives aux_weight * 1.0; any routing is >= that.
        assert aux >= m.aux_loss_weight * 0.99
        # Immutable apply: sow is a silent no-op.
        out2 = m.apply({"params": params}, x)
        assert out2.shape == x.shape


def _moe_cfg(**trainer_overrides):
    trainer = {
        "max_steps": 20,
        "micro_batch_size": 2,
        "grad_accum_steps": 1,
        "lr": 3e-3,
        "warmup_steps": 0,
        "log_every_steps": 50,
        "eval_every_steps": 50,
        "save_every_steps": 50,
        **trainer_overrides,
    }
    return RunConfig.model_validate(
        {
            "run": {"name": "moe-t", "seed": 5},
            "model": {
                "name": "gpt_moe",
                "block_size": 8,
                "vocab_size": 64,
                "d_model": 32,
                "n_heads": 2,
                "d_ff": 64,
                "n_layers": 2,
                "dropout": 0.0,
                "extra": {"n_experts": 4, "capacity_factor": 2.0},
            },
            "data": {"name": "dummy_text"},
            "trainer": trainer,
            "mlflow": {"enabled": False},
        }
    )


class TestGPTMoEAdapter:
    def test_requires_n_experts(self):
        cfg = _moe_cfg()
        bad = cfg.model_copy(
            update={"model": cfg.model.model_copy(update={"extra": {}})}
        )
        adapter = get_model_adapter("gpt_moe")()
        with pytest.raises(ValueError, match="n_experts"):
            adapter.build_model(bad)

    def test_composes_with_gqa_and_flash(self):
        """The adapter inherits GPT's extras: n_kv_heads + flash +
        chunked CE build and take a loss step together with MoE MLPs."""
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "moe-gqa", "seed": 5, "device": "cpu"},
                "model": {
                    "name": "gpt_moe",
                    "block_size": 8,
                    "vocab_size": 64,
                    "d_model": 32,
                    "n_heads": 4,
                    "d_ff": 64,
                    "n_layers": 2,
                    "dropout": 0.0,
                    "attention": "flash",
                    "extra": {
                        "n_experts": 4,
                        "capacity_factor": 2.0,
                        "n_kv_heads": 2,
                        "loss_impl": "chunked_ce",
                        "ce_chunk": 32,
                    },
                },
                "data": {"name": "dummy_text"},
                "trainer": {"max_steps": 1, "micro_batch_size": 2, "warmup_steps": 0},
                "mlflow": {"enabled": False},
            }
        )
        adapter = get_model_adapter("gpt_moe")()
        model = adapter.build_model(cfg)
        assert model.n_kv_heads == 2 and model.attention == "flash"
        params = adapter.init_params(model, cfg, jax.random.key(0))
        batch = {
            "input_ids": jnp.zeros((2, 8), jnp.int32),
            "labels": jnp.zeros((2, 8), jnp.int32),
            "attention_mask": jnp.ones((2, 8), jnp.int32),
        }
        loss_sum, tokens = adapter.compute_loss_components(model, params, batch)
        assert np.isfinite(float(jnp.sum(loss_sum) / jnp.sum(tokens)))

    def test_objective_includes_aux_loss(self):
        cfg = _moe_cfg()
        adapter = get_model_adapter("gpt_moe")()
        model = adapter.build_model(cfg)
        params = adapter.init_params(model, cfg, jax.random.key(0))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 8), dtype=np.int32)
        )
        batch = {"input_ids": tokens, "labels": tokens}
        loss_sum, tok = adapter.compute_loss_components(model, params, batch)
        assert loss_sum.shape == (2,) and tok.shape == (2,)

        # Zero aux weight -> strictly smaller objective (same routing/CE).
        no_aux = model.clone(moe_aux_weight=0.0)
        loss_sum0, _ = adapter.compute_loss_components(no_aux, params, batch)
        assert float(jnp.sum(loss_sum)) > float(jnp.sum(loss_sum0))

    def test_loss_decreases_in_training(self, tmp_path):
        from llmtrain_tpu.tracking import NullTracker
        from llmtrain_tpu.training import Trainer

        cfg = _moe_cfg()
        result = Trainer(cfg, None, NullTracker(), None).fit()
        assert result.first_step_loss is not None
        assert result.final_loss < result.first_step_loss

    def test_expert_parallel_mesh_runs(self):
        """Full train step on a {data:2, fsdp:1, expert:2, sequence:2} mesh:
        expert weights shard over the expert axis, the batch shards over
        data x expert — XLA inserts the dispatch all-to-alls."""
        from flax import linen as nn
        from flax.linen import meta as nn_meta

        from llmtrain_tpu.distributed import build_mesh
        from llmtrain_tpu.config.schemas import MeshConfig
        from llmtrain_tpu.parallel.sharding import (
            DEFAULT_LOGICAL_AXIS_RULES,
            state_shardings,
        )
        from llmtrain_tpu.training.optimizer import build_optimizer
        from llmtrain_tpu.training.train_step import create_train_state, make_train_step

        cfg = _moe_cfg(micro_batch_size=2)
        adapter = get_model_adapter("gpt_moe")()
        model = adapter.build_model(cfg)
        tx = build_optimizer(cfg.trainer)
        mesh = build_mesh(
            MeshConfig(data=2, fsdp=1, tensor=1, sequence=2, expert=2),
            jax.devices()[:8],
        )
        rules = list(DEFAULT_LOGICAL_AXIS_RULES)

        with mesh, nn.logical_axis_rules(rules):
            params = adapter.init_params(model, cfg, jax.random.key(0))
            state = create_train_state(params, tx)
            abstract = jax.eval_shape(lambda: state)
            shardings = state_shardings(mesh, abstract, rules)
            state = jax.jit(lambda s: s, out_shardings=shardings)(state)

            # Expert FFN weights actually shard over the expert axis.
            wi = nn_meta.unbox(state.params)["block_0"]["moe_mlp"]["wi"]
            spec = wi.sharding.spec
            assert "expert" in jax.tree.leaves(tuple(spec))

            step_fn = jax.jit(
                make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False),
                out_shardings=(shardings, None),
            )
            tokens = jnp.asarray(
                np.random.default_rng(1).integers(0, 64, (1, 8, 8), dtype=np.int32)
            )
            batch = {"input_ids": tokens, "labels": tokens}
            new_state, metrics = step_fn(state, batch, jax.random.key(1))
            assert np.isfinite(float(jax.device_get(metrics["loss"])))


class TestTop2Routing:
    """router_top_k=2: GShard-style second-choice routing."""

    def _mlp(self, **kw):
        from llmtrain_tpu.models.moe import MoEMLP

        defaults = dict(
            d_model=16, d_ff=32, n_experts=4, n_layers=2, router_top_k=2
        )
        defaults.update(kw)
        return MoEMLP(**defaults)

    def test_two_experts_ample_capacity_is_exact_soft_mixture(self):
        """With E=2 and k=2 and capacity >= T, every token reaches BOTH
        experts and the renormalized gates sum to 1 — the layer must equal
        the dense mixture p0*expert0(x) + p1*expert1(x) computed by hand."""
        mlp = self._mlp(n_experts=2, capacity_factor=4.0)
        x = jax.random.normal(jax.random.key(0), (2, 6, 16))
        boxed = mlp.init({"params": jax.random.key(1)}, x)["params"]
        out = mlp.apply({"params": boxed}, x)

        import numpy as np
        from flax.linen import meta as nn_meta

        params = nn_meta.unbox(boxed)

        logits = x.astype(jnp.float32) @ params["router"]["kernel"]
        gates = jax.nn.softmax(logits, axis=-1)  # (B,T,2), sums to 1

        def expert(e, xin):
            h = jnp.einsum("btd,df->btf", xin, params["wi"][e]) + params["bi"][e]
            h = jax.nn.gelu(h, approximate=False)
            return jnp.einsum("btf,fd->btd", h, params["wo"][e]) + params["bo"][e]

        ref = gates[..., 0:1] * expert(0, x) + gates[..., 1:2] * expert(1, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5
        )

    def test_top2_blends_two_experts_top1_uses_one(self):
        """Behavioral check with constant-output experts: surgically set
        expert e to output (e+1)*ones regardless of input, so the layer
        output reveals exactly which experts each token reached and with
        what weights. k=1 must equal raw_prob(first)*(first+1); k=2 must
        equal the renormalized two-expert blend."""
        import numpy as np
        from flax.linen import meta as nn_meta

        x = jax.random.normal(jax.random.key(2), (2, 8, 16))
        outs = {}
        for k in (1, 2):
            mlp = self._mlp(router_top_k=k, capacity_factor=8.0)
            params = nn_meta.unbox(
                mlp.init({"params": jax.random.key(3)}, x)["params"]
            )
            # Constant experts: wi=0, bi=0 -> gelu(0)=0; wo=0; bo[e]=(e+1).
            n_exp = params["wi"].shape[0]
            params["wi"] = np.zeros_like(params["wi"])
            params["bi"] = np.zeros_like(params["bi"])
            params["wo"] = np.zeros_like(params["wo"])
            params["bo"] = np.tile(
                np.arange(1, n_exp + 1, dtype=np.float32)[:, None],
                (1, params["bo"].shape[1]),
            )
            outs[k] = np.asarray(mlp.apply({"params": params}, x))

            logits = np.asarray(x, np.float32) @ np.asarray(params["router"]["kernel"])
            gates = np.asarray(jax.nn.softmax(logits, axis=-1))
            order = np.argsort(-gates, axis=-1)
            e1, e2 = order[..., 0], order[..., 1]
            g1 = np.take_along_axis(gates, e1[..., None], -1)[..., 0]
            g2 = np.take_along_axis(gates, e2[..., None], -1)[..., 0]
            if k == 1:
                expect = g1 * (e1 + 1)  # raw Switch probability
            else:
                expect = (g1 * (e1 + 1) + g2 * (e2 + 1)) / (g1 + g2)
            np.testing.assert_allclose(outs[k][..., 0], expect, atol=1e-5)
        assert not np.allclose(outs[1], outs[2])

    def test_invalid_top_k_raises(self):
        x = jax.random.normal(jax.random.key(4), (1, 4, 16))
        for bad in (0, 3):
            mlp = self._mlp(router_top_k=bad)
            with pytest.raises(ValueError, match="router_top_k"):
                mlp.init({"params": jax.random.key(5)}, x)
        mlp = self._mlp(n_experts=1, router_top_k=2)
        with pytest.raises(ValueError, match="exceeds"):
            mlp.init({"params": jax.random.key(6)}, x)

    def test_adapter_knob_and_training(self, tmp_path):
        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.registry import get_model_adapter, initialize_registries
        from llmtrain_tpu.tracking.base import NullTracker
        from llmtrain_tpu.training.trainer import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "moe2", "seed": 5, "device": "cpu"},
                "model": {
                    "name": "gpt_moe",
                    "block_size": 8,
                    "d_model": 32,
                    "n_layers": 1,
                    "n_heads": 2,
                    "d_ff": 64,
                    "dropout": 0.0,
                    "vocab_size": 32,
                    "extra": {"n_experts": 2, "router_top_k": 2},
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 20,
                    "micro_batch_size": 4,
                    "warmup_steps": 0,
                    "log_every_steps": 10,
                    "eval_every_steps": 100,
                    "save_every_steps": 100,
                },
            }
        )
        model = get_model_adapter("gpt_moe")().build_model(cfg)
        assert model.router_top_k == 2
        result = Trainer(cfg, None, NullTracker()).fit()
        assert result.final_loss < result.first_step_loss
