"""Mesh planner + auto-tuner tests (docs/perf.md "Mesh planning and
auto-tuning"): wildcard/divisibility resolution tables, capability
feasibility rules, seeded candidate enumeration, the analytical pruning
pass (every discard carries a reason — no silent caps), the `llmtrain
plan` exit-code contract, and the @slow probe-fit tune -> train
round-trip on the smoke preset."""

import json
import os
import pathlib
import subprocess
import sys

import pytest
import yaml

from llmtrain_tpu.autotune.plan import (
    MESH_AXES,
    MeshPlanError,
    ModelCaps,
    caps_from_config,
    config_loss_impl,
    plan_from_config,
    predict_hbm_bytes,
    resolve_axis_sizes,
    resolve_plan,
)
from llmtrain_tpu.autotune.search import (
    DEVICE_HBM_BYTES,
    Candidate,
    enumerate_candidates,
    prune_candidates,
    resolve_hbm_limit,
)
from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.resilience.harness import deep_merge
from llmtrain_tpu.telemetry.profiling import resolve_peaks

REPO = pathlib.Path(__file__).resolve().parent.parent
SMOKE_PRESET = REPO / "configs" / "presets" / "gpt_tune_smoke.yaml"


def _cfg(**overrides):
    base = {
        "run": {"name": "tune-t", "seed": 3},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 64,
            "n_heads": 2,
            "d_ff": 128,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 6,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "lr": 3e-3,
            "warmup_steps": 0,
        },
        "mlflow": {"enabled": False},
    }
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


CAPS = ModelCaps(n_heads=4, block_size=16)


class TestResolveAxisSizes:
    @pytest.mark.parametrize(
        "sizes,devices,expected",
        [
            ({"data": -1}, 8, {"data": 8}),
            ({"data": 2, "fsdp": -1}, 8, {"data": 2, "fsdp": 4}),
            ({"tensor": 2, "data": -1}, 8, {"data": 4, "tensor": 2}),
            (
                {"data": 2, "fsdp": 2, "tensor": 2},
                8,
                {"data": 2, "fsdp": 2, "tensor": 2},
            ),
            ({}, 1, {}),
        ],
    )
    def test_wildcard_table(self, sizes, devices, expected):
        out = resolve_axis_sizes(sizes, devices)
        want = {axis: expected.get(axis, 1) for axis in MESH_AXES}
        assert out == want

    def test_two_wildcards_rejected(self):
        with pytest.raises(MeshPlanError, match="at most one"):
            resolve_axis_sizes({"data": -1, "fsdp": -1}, 8)

    def test_wildcard_unfillable(self):
        # Messages keep the words the pre-refactor tests pinned:
        # "divisible" for wildcard failures, "devices" for tiling ones.
        with pytest.raises(MeshPlanError, match="divisible"):
            resolve_axis_sizes({"data": 3, "fsdp": -1}, 8)

    def test_product_must_tile_devices(self):
        with pytest.raises(MeshPlanError, match="devices"):
            resolve_axis_sizes({"data": 3}, 8)

    def test_zero_axis_rejected(self):
        with pytest.raises(MeshPlanError, match="positive"):
            resolve_axis_sizes({"data": 0}, 8)

    def test_distributed_entrypoint_delegates_here(self):
        # resolve_mesh_axes is now a thin wrapper over resolve_axis_sizes;
        # MeshPlanError is a ValueError so pre-existing callers still
        # catch it.
        from llmtrain_tpu.distributed import resolve_mesh_axes

        cfg = _cfg(distributed={"mesh": {"data": 3}})
        with pytest.raises(MeshPlanError, match="devices"):
            resolve_mesh_axes(cfg.distributed.mesh, 8)
        assert issubclass(MeshPlanError, ValueError)


class TestPlanRules:
    def _plan(self, mesh, caps=CAPS, mb=4, **kw):
        return resolve_plan(
            mesh_sizes=mesh,
            device_count=8,
            caps=caps,
            micro_batch_size=mb,
            **kw,
        )

    def test_pipeline_needs_capability(self):
        with pytest.raises(MeshPlanError, match="pipeline"):
            self._plan({"pipeline": 2, "data": 4})

    def test_pipeline_microbatch_divisibility(self):
        caps = ModelCaps(
            n_heads=4, block_size=16, supports_pipeline=True, pipeline_microbatches=4
        )
        with pytest.raises(MeshPlanError, match="pipeline_microbatches"):
            self._plan({"pipeline": 2, "data": 4}, caps=caps, mb=2)
        plan = self._plan({"pipeline": 2, "data": 4}, caps=caps, mb=4)
        assert plan.axes["pipeline"] == 2

    def test_sequence_dense_is_legal(self):
        # GSPMD handles a sequence axis under dense attention
        # (tests/test_distributed.py pins the layouts agree) — only the
        # ring/ulysses kernels demand exact context shards.
        plan = self._plan({"sequence": 2, "data": 4})
        assert plan.axes["sequence"] == 2

    def test_sequence_ring_needs_exact_shards(self):
        caps = ModelCaps(n_heads=4, block_size=6, attention="ring")
        with pytest.raises(MeshPlanError, match="block_size"):
            self._plan({"sequence": 4, "data": 2}, caps=caps)

    def test_sequence_ulysses_shards_heads_too(self):
        caps = ModelCaps(n_heads=2, block_size=16, attention="ulysses")
        with pytest.raises(MeshPlanError, match="n_heads"):
            self._plan({"sequence": 4, "data": 2}, caps=caps)

    def test_tensor_heads_divisibility(self):
        with pytest.raises(MeshPlanError, match="n_heads"):
            self._plan({"tensor": 8}, caps=ModelCaps(n_heads=6, block_size=16))

    def test_tensor_kv_heads_divisibility(self):
        caps = ModelCaps(n_heads=8, block_size=16, n_kv_heads=2)
        with pytest.raises(MeshPlanError, match="n_kv_heads"):
            self._plan({"tensor": 4, "data": 2}, caps=caps)

    def test_expert_dense_is_legal_batch_axis(self):
        # On a dense model `expert` is one of the ELASTIC data axes
        # (parallel/sharding.py) — it must count toward data_parallel.
        plan = self._plan({"expert": 2, "data": 4})
        assert plan.data_parallel == 8

    def test_expert_moe_divisibility(self):
        caps = ModelCaps(n_heads=4, block_size=16, n_experts=3)
        with pytest.raises(MeshPlanError, match="n_experts"):
            self._plan({"expert": 2, "data": 4}, caps=caps)

    def test_zero_stage_bounds(self):
        with pytest.raises(MeshPlanError, match="zero_stage"):
            self._plan({"data": 8}, zero_stage=3)

    def test_micro_batch_positive(self):
        with pytest.raises(MeshPlanError, match="micro_batch_size"):
            self._plan({"data": 8}, mb=0)


class TestMeshPlanObject:
    def test_key_and_round_trip(self):
        plan = resolve_plan(
            mesh_sizes={"data": -1, "tensor": 2},
            device_count=8,
            caps=CAPS,
            micro_batch_size=4,
            zero_stage=1,
        )
        assert plan.key() == "d4.f1.t2.s1.p1.e1|mb4|remat0|zero1"
        sizes = plan.mesh_axis_sizes()
        assert tuple(sizes) == MESH_AXES  # canonical order, manifest-legal
        assert resolve_axis_sizes(sizes, 8) == sizes  # no wildcard survives
        topo = plan.describe_topology()
        assert topo["mesh"] == sizes
        assert topo["global_micro_batch"] == 4 * plan.data_parallel

    def test_config_overrides_merge_into_valid_config(self):
        cfg = _cfg()
        plan = resolve_plan(
            mesh_sizes={"data": 4, "fsdp": 2},
            device_count=8,
            caps=caps_from_config(cfg),
            micro_batch_size=4,
            remat=True,
            zero_stage=2,
        )
        merged = deep_merge(cfg.model_dump(), plan.config_overrides())
        tuned = RunConfig.model_validate(merged)
        # The emitted config resolves back to the exact same plan — what
        # the tuner measured is what `llmtrain train` later runs.
        assert plan_from_config(tuned, 8).key() == plan.key()

    def test_predict_hbm_monotone_in_sharding(self):
        kw = dict(n_params=10_000_000, d_model=64, n_layers=2, vocab_size=256,
                  block_size=16)
        dense = resolve_plan(
            mesh_sizes={"data": 1}, device_count=1, caps=CAPS, micro_batch_size=4
        )
        sharded = resolve_plan(
            mesh_sizes={"fsdp": 8}, device_count=8, caps=CAPS, micro_batch_size=4
        )
        assert (
            predict_hbm_bytes(sharded, **kw)["total_bytes"]
            < predict_hbm_bytes(dense, **kw)["total_bytes"]
        )

    def test_predict_hbm_logits_term_per_loss_impl(self):
        """The logits-buffer table (docs/perf.md "Fused lm-head + CE"):
        dense charges tokens x V, chunked a tokens x min(ce_chunk, V)
        block, fused_ce nothing — the planner's verdict must track what
        the adapter's loss path actually allocates."""
        plan = resolve_plan(
            mesh_sizes={"data": 1}, device_count=1, caps=CAPS, micro_batch_size=4
        )
        kw = dict(n_params=1_000_000, d_model=64, n_layers=2, vocab_size=50_000,
                  block_size=16)
        tokens = 4 * 16
        table = {
            "dense": tokens * 50_000 * 4.0,
            "chunked_ce": tokens * 8192 * 4.0,  # default ce_chunk
            "fused_ce": 0.0,
        }
        for impl, want in table.items():
            hbm = predict_hbm_bytes(plan, loss_impl=impl, **kw)
            assert hbm["loss_impl"] == impl
            assert hbm["logits_bytes"] == want, impl
        # an oversized chunk clamps at the vocab — never charges more
        # than the dense buffer
        clamped = predict_hbm_bytes(
            plan, loss_impl="chunked_ce", ce_chunk=1 << 20, **kw
        )
        assert clamped["logits_bytes"] == table["dense"]

    def test_config_loss_impl_matches_adapter_resolution(self):
        # small vocab, nothing requested -> dense
        assert config_loss_impl(_cfg()) == ("dense", 8192)
        # explicit fused without Pallas degrades exactly like the adapter
        cfg = _cfg(model={"extra": {"loss_impl": "fused_ce"}})
        assert config_loss_impl(cfg)[0] == "chunked_ce"
        # ...and holds with the interpret escape hatch
        cfg = _cfg(
            model={"extra": {"loss_impl": "fused_ce", "pallas_interpret": True}}
        )
        assert config_loss_impl(cfg) == ("fused_ce", 8192)
        # invalid explicit value is config validation's error to raise,
        # not the planner's: estimate conservatively as dense
        cfg = _cfg(model={"extra": {"loss_impl": "typo", "ce_chunk": 64}})
        assert config_loss_impl(cfg) == ("dense", 64)


class TestSearch:
    def test_deterministic_seeded_order(self):
        cfg = _cfg()
        first = [c.key() for c in enumerate_candidates(cfg, 8, seed=7)]
        again = [c.key() for c in enumerate_candidates(cfg, 8, seed=7)]
        other = [c.key() for c in enumerate_candidates(cfg, 8, seed=8)]
        assert first == again
        assert sorted(first) == sorted(other)  # same grid...
        assert first != other  # ...different order

    def test_dense_model_skips_expert_shapes(self):
        # Dense expert>1 shapes are exact semantic twins of data-axis
        # shapes already in the grid — enumerating them would waste probes.
        cands = enumerate_candidates(_cfg(), 8, seed=0)
        assert cands
        assert all(c.mesh_sizes["expert"] == 1 for c in cands)

    def test_search_knobs_pin_dimensions(self):
        cfg = _cfg()
        cands = enumerate_candidates(
            cfg, 8, seed=0, search_mesh=False, search_remat=False, search_zero=False,
            microbatch_candidates=[4],
        )
        keys = {c.key() for c in cands}
        assert keys == {"d8.f1.t1.s1.p1.e1|mb4|remat0|zero0"}

    def test_prune_accounts_for_every_candidate(self):
        cfg = _cfg()
        cands = enumerate_candidates(cfg, 8, seed=0)
        res = prune_candidates(
            cands,
            cfg,
            device_count=8,
            caps=caps_from_config(cfg),
            peaks=resolve_peaks("cpu"),
            hbm_limit_bytes=resolve_hbm_limit("cpu"),
            max_probes=2,
        )
        assert res["enumerated"] == len(cands)
        # No silent caps: every enumerated candidate is a survivor or a
        # pruned entry with a named reason.
        assert len(res["survivors"]) + len(res["pruned"]) == res["enumerated"]
        assert len(res["survivors"]) <= 2
        reasons = [p["reason"] for p in res["pruned"]]
        assert all(r for r in reasons)
        # n_heads=2 makes tensor=8 shapes illegal -> recorded, not skipped.
        assert any(r.startswith("topology-illegal") for r in reasons)
        assert any(r.startswith("dominated") for r in reasons)
        assert any(r.startswith("probe-budget") for r in reasons)
        # Survivors come back best-predicted-first.
        times = [c.predicted["predicted_us_per_token"] for c in res["survivors"]]
        assert times == sorted(times)

    def test_prune_infeasible_hbm(self):
        cfg = _cfg()
        cands = enumerate_candidates(cfg, 8, seed=0)
        res = prune_candidates(
            cands,
            cfg,
            device_count=8,
            caps=caps_from_config(cfg),
            peaks=resolve_peaks("cpu"),
            hbm_limit_bytes=1.0,  # nothing fits in one byte
            max_probes=4,
        )
        assert res["survivors"] == []
        assert any(
            p["reason"].startswith("infeasible-hbm") for p in res["pruned"]
        )

    def test_ranking_is_per_token_not_per_step(self):
        # A half-size microbatch "wins" raw step time while losing
        # throughput; the pruner must rank on time per token so the
        # larger batch (which amortizes param traffic) comes first.
        cfg = _cfg()
        mesh = dict.fromkeys(MESH_AXES, 1)
        mesh["data"] = 8
        cands = [
            Candidate(mesh_sizes=dict(mesh), micro_batch_size=mb,
                      remat=False, zero_stage=0)
            for mb in (2, 4)
        ]
        res = prune_candidates(
            cands,
            cfg,
            device_count=8,
            caps=caps_from_config(cfg),
            peaks=resolve_peaks("cpu"),
            hbm_limit_bytes=resolve_hbm_limit("cpu"),
            max_probes=10,
        )
        assert res["survivors"][0].micro_batch_size == 4
        by_mb = {c.micro_batch_size: c.predicted for c in cands if c.predicted}
        assert (
            by_mb[4]["predicted_us_per_token"] < by_mb[2]["predicted_us_per_token"]
        )

    def test_preserve_topology_prunes_resume_illegal(self):
        cfg = _cfg()
        baseline = resolve_plan(
            mesh_sizes={"data": 8},
            device_count=8,
            caps=caps_from_config(cfg),
            micro_batch_size=2,
        )
        res = prune_candidates(
            enumerate_candidates(cfg, 8, seed=0),
            cfg,
            device_count=8,
            caps=caps_from_config(cfg),
            peaks=resolve_peaks("cpu"),
            hbm_limit_bytes=resolve_hbm_limit("cpu"),
            max_probes=8,
            baseline_topology=baseline.describe_topology(),
        )
        assert any(
            "(resume)" in p["reason"] for p in res["pruned"]
        )
        # Whatever survives really is adoptable by the running checkpoint.
        from llmtrain_tpu.resilience.elastic import classify_topology_change

        for cand in res["survivors"]:
            classify_topology_change(
                baseline.describe_topology(), cand.plan.describe_topology()
            )

    def test_resolve_hbm_limit(self):
        assert resolve_hbm_limit("TPU v5 lite") == DEVICE_HBM_BYTES["v5 lite"]
        assert resolve_hbm_limit("tpu v5p") == DEVICE_HBM_BYTES["v5p"]
        assert resolve_hbm_limit("weird accelerator") == DEVICE_HBM_BYTES["cpu"]
        assert resolve_hbm_limit("v4", override=123.0) == 123.0


class TestFailFast:
    @pytest.fixture(autouse=True)
    def _registries(self):
        initialize_registries()

    def test_mesh_plan_error_maps_to_config_exit(self):
        from llmtrain_tpu.resilience.exit_codes import (
            EXIT_CONFIG_ERROR,
            exit_code_for_exception,
        )

        assert exit_code_for_exception(MeshPlanError("boom")) == EXIT_CONFIG_ERROR
        wrapped = RuntimeError("trainer setup failed")
        wrapped.__cause__ = MeshPlanError("axis")
        assert exit_code_for_exception(wrapped) == EXIT_CONFIG_ERROR

    def test_trainer_fails_fast_on_untileable_mesh(self):
        # Regression: a mesh that cannot tile the device count must die as
        # a named MeshPlanError during trainer setup, before any mesh or
        # params materialize — not as an opaque pjit/XLA error later.
        from llmtrain_tpu.tracking import NullTracker
        from llmtrain_tpu.training import Trainer

        cfg = _cfg(distributed={"mesh": {"data": 3}})
        with pytest.raises(MeshPlanError, match="devices"):
            Trainer(cfg, None, NullTracker(), None)


class TestPlanCLI:
    def _write(self, tmp_path, **overrides):
        dump = _cfg(**overrides).model_dump()
        path = tmp_path / "cfg.yaml"
        path.write_text(yaml.safe_dump(dump, sort_keys=False))
        return str(path)

    def test_plan_feasible_exit_zero(self, tmp_path, capsys):
        from llmtrain_tpu.cli import main

        rc = main(["plan", "--config", self._write(tmp_path), "--devices", "8",
                   "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["feasible"] is True
        assert payload["plan"]["key"].startswith("d")
        assert payload["roofline"]["class"] in {"compute", "memory", "comms"}
        assert payload["predicted_hbm"]["total_bytes"] > 0
        assert payload["predicted_hbm"]["total_bytes"] <= payload["hbm_limit_bytes"]

    def test_plan_prints_assumed_loss_impl(self, tmp_path, capsys):
        from llmtrain_tpu.cli import main

        cfg_path = self._write(
            tmp_path,
            model={"extra": {"loss_impl": "fused_ce", "pallas_interpret": True}},
        )
        rc = main(["plan", "--config", cfg_path, "--devices", "8", "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["plan"]["loss_impl"] == "fused_ce"
        assert payload["predicted_hbm"]["loss_impl"] == "fused_ce"
        assert payload["predicted_hbm"]["logits_bytes"] == 0.0
        rc = main(["plan", "--config", cfg_path, "--devices", "8"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "loss      fused_ce (logits buffer 0.0 MiB)" in out

    def test_plan_infeasible_mesh_exit_two(self, tmp_path, capsys):
        from llmtrain_tpu.cli import main

        cfg_path = self._write(tmp_path, distributed={"mesh": {"data": 3}})
        rc = main(["plan", "--config", cfg_path, "--devices", "8"])
        assert rc == 2
        assert "infeasible plan" in capsys.readouterr().err

    def test_plan_hbm_over_limit_exit_two(self, tmp_path, capsys):
        from llmtrain_tpu.cli import main

        cfg_path = self._write(tmp_path, tune={"hbm_limit_bytes": 1.0})
        rc = main(["plan", "--config", cfg_path, "--devices", "8"])
        assert rc == 2
        assert "HBM" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Probe-fit e2e (@slow): real subprocess probes, real report.json scoring.
# ---------------------------------------------------------------------------


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


@pytest.mark.slow
class TestTuneEndToEnd:
    def test_tune_then_train_round_trip(self, tmp_path):
        workdir = tmp_path / "tune"
        tuned = tmp_path / "tuned.yaml"
        proc = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "tune",
                "--config", str(SMOKE_PRESET),
                "--workdir", str(workdir),
                "--output", str(tuned),
                "--json",
            ],
            capture_output=True,
            text=True,
            env=_env(),
            cwd=tmp_path,
            timeout=500,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads((workdir / "tune_report.json").read_text())

        # Observability contract: enumerated == survivors + pruned, every
        # pruned entry names its reason, the log shows the funnel.
        assert report["enumerated"] == len(report["survivors"]) + len(
            report["pruned"]
        )
        assert all(p["reason"] for p in report["pruned"])

        # The baseline probe ran and the winner's measured MFU is >= the
        # untuned config's (baseline is always probed, so a regression
        # can only happen by picking a worse measured candidate).
        baseline = report["baseline"]
        winner = report["winner"]
        assert baseline["status"] == "ok", baseline
        assert winner["status"] == "ok"
        assert winner["mfu"] >= baseline["mfu"]

        # The emitted YAML validates and trains unchanged.
        assert tuned.exists()
        merged = yaml.safe_load(tuned.read_text())
        RunConfig.model_validate(merged)
        train = subprocess.run(
            [
                sys.executable, "-m", "llmtrain_tpu", "train",
                "--config", str(tuned),
                "--run-id", "tuned_rt",
                "--json",
            ],
            capture_output=True,
            text=True,
            env=_env(),
            cwd=tmp_path,
            timeout=300,
        )
        assert train.returncode == 0, train.stderr[-2000:]
        rt_report = json.loads(
            (tmp_path / "runs" / "tuned_rt" / "report.json").read_text()
        )
        mfu = (rt_report.get("perf_attribution") or {}).get("mfu", {}).get(
            "measured"
        )
        assert mfu is not None and mfu > 0
