"""Tracker contract tests (parity with reference tests/test_cli.py:628-704's
MLflow coverage, adapted for an environment without the optional mlflow
dependency: the module is stubbed, and the full call sequence is asserted)."""

import sys
import types
from unittest.mock import Mock

import pytest

from llmtrain_tpu.tracking import MLflowTracker, NullTracker
from llmtrain_tpu.tracking.mlflow import _flatten_params


class TestFlattenParams:
    def test_nested_dicts_become_dot_keys(self):
        flat = _flatten_params({"a": {"b": {"c": 1}}, "d": 2})
        assert flat == {"a.b.c": 1, "d": 2}

    def test_lists_json_encoded(self):
        flat = _flatten_params({"a": [1, 2], "b": ("x", "y")})
        assert flat == {"a": "[1, 2]", "b": '["x", "y"]'}

    def test_scalars_passthrough(self):
        flat = _flatten_params({"s": "v", "i": 3, "f": 0.5, "n": None, "t": True})
        assert flat == {"s": "v", "i": 3, "f": 0.5, "n": None, "t": True}


class TestNullTracker:
    def test_all_methods_noop(self):
        t = NullTracker()
        t.start_run("rid", None)
        t.log_params({"a": 1})
        t.log_metrics({"m": 1.0}, step=1)
        t.log_artifact("/nope")
        t.end_run("FINISHED")


@pytest.fixture()
def fake_mlflow(monkeypatch):
    """Inject a recording stub as the ``mlflow`` module."""
    stub = types.ModuleType("mlflow")
    mock = Mock()
    for name in (
        "set_tracking_uri",
        "set_experiment",
        "start_run",
        "set_tag",
        "log_params",
        "log_metrics",
        "log_artifact",
        "end_run",
        "get_experiment_by_name",
        "search_runs",
    ):
        setattr(stub, name, getattr(mock, name))
    # Default: no experiment yet -> no join-search -> fresh run.
    mock.get_experiment_by_name.return_value = None
    monkeypatch.setitem(sys.modules, "mlflow", stub)
    return mock


class TestMLflowTracker:
    def test_missing_dependency_raises_clear_error(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "mlflow", None)  # forces ImportError
        t = MLflowTracker("file:./mlruns", "exp")
        with pytest.raises(RuntimeError, match="mlflow is not installed"):
            t.start_run("rid")

    def test_lifecycle_call_sequence(self, fake_mlflow):
        t = MLflowTracker("sqlite:///x.db", "exp", run_name="pretty")
        t.start_run("rid-1")
        fake_mlflow.set_tracking_uri.assert_called_once_with("sqlite:///x.db")
        fake_mlflow.set_experiment.assert_called_once_with("exp")
        fake_mlflow.start_run.assert_called_once_with(run_name="pretty")
        fake_mlflow.set_tag.assert_called_once_with("llmtrain.run_id", "rid-1")

        t.log_params({"model": {"d_model": 8}})
        fake_mlflow.log_params.assert_called_once_with({"model.d_model": 8})

        t.log_metrics({"train/loss": 1.5}, step=3)
        fake_mlflow.log_metrics.assert_called_once_with({"train/loss": 1.5}, step=3)

        t.log_artifact("/tmp/config.yaml")
        fake_mlflow.log_artifact.assert_called_once_with(
            "/tmp/config.yaml", artifact_path=None
        )

        t.end_run("FINISHED")
        fake_mlflow.end_run.assert_called_once_with(status="FINISHED")

    def test_methods_inactive_before_start(self, fake_mlflow):
        t = MLflowTracker("file:./mlruns", "exp")
        t.log_params({"a": 1})
        t.log_metrics({"m": 1.0}, step=1)
        t.log_artifact("/x")
        t.end_run()
        fake_mlflow.log_params.assert_not_called()
        fake_mlflow.log_metrics.assert_not_called()
        fake_mlflow.log_artifact.assert_not_called()
        fake_mlflow.end_run.assert_not_called()

    def test_end_run_deactivates(self, fake_mlflow):
        t = MLflowTracker("file:./mlruns", "exp")
        t.start_run("rid")
        t.end_run("FAILED")
        fake_mlflow.end_run.assert_called_once_with(status="FAILED")
        t.log_metrics({"m": 1.0}, step=1)
        fake_mlflow.log_metrics.assert_not_called()

    def test_run_id_used_when_no_run_name(self, fake_mlflow):
        t = MLflowTracker("file:./mlruns", "exp")
        t.start_run("rid-9")
        fake_mlflow.start_run.assert_called_once_with(run_name="rid-9")

    def test_reattaches_to_run_with_matching_tag(self, fake_mlflow):
        """A relaunch with the same framework run id (--auto-resume) must
        CONTINUE the original MLflow run, keyed by the llmtrain.run_id tag."""
        exp = Mock()
        exp.experiment_id = "7"
        fake_mlflow.get_experiment_by_name.return_value = exp
        found = Mock()
        found.info.run_id = "mlflow-abc"
        fake_mlflow.search_runs.return_value = [found]

        t = MLflowTracker("sqlite:///x.db", "exp")
        t.start_run("rid-stable")
        fake_mlflow.search_runs.assert_called_once_with(
            experiment_ids=["7"],
            filter_string="tags.\"llmtrain.run_id\" = 'rid-stable'",
            max_results=1,
            output_format="list",
        )
        fake_mlflow.start_run.assert_called_once_with(run_id="mlflow-abc")
        fake_mlflow.set_tag.assert_not_called()  # tag already on the run

    def test_search_failure_falls_back_to_fresh_run(self, fake_mlflow):
        fake_mlflow.get_experiment_by_name.side_effect = RuntimeError("backend down")
        t = MLflowTracker("sqlite:///x.db", "exp")
        t.start_run("rid-2")
        fake_mlflow.start_run.assert_called_once_with(run_name="rid-2")
        fake_mlflow.set_tag.assert_called_once_with("llmtrain.run_id", "rid-2")
