"""Tracker contract tests (parity with reference tests/test_cli.py:628-704's
MLflow coverage, adapted for an environment without the optional mlflow
dependency: the module is stubbed, and the full call sequence is asserted)."""

import sys
import types
from unittest.mock import Mock

import pytest

from llmtrain_tpu.tracking import MLflowTracker, NullTracker
from llmtrain_tpu.tracking.mlflow import _flatten_params


class TestFlattenParams:
    def test_nested_dicts_become_dot_keys(self):
        flat = _flatten_params({"a": {"b": {"c": 1}}, "d": 2})
        assert flat == {"a.b.c": 1, "d": 2}

    def test_lists_json_encoded(self):
        flat = _flatten_params({"a": [1, 2], "b": ("x", "y")})
        assert flat == {"a": "[1, 2]", "b": '["x", "y"]'}

    def test_scalars_passthrough(self):
        flat = _flatten_params({"s": "v", "i": 3, "f": 0.5, "n": None, "t": True})
        assert flat == {"s": "v", "i": 3, "f": 0.5, "n": None, "t": True}


class TestNullTracker:
    def test_all_methods_noop(self):
        t = NullTracker()
        t.start_run("rid", None)
        t.log_params({"a": 1})
        t.log_metrics({"m": 1.0}, step=1)
        t.log_artifact("/nope")
        t.end_run("FINISHED")


@pytest.fixture()
def fake_mlflow(monkeypatch):
    """Inject a recording stub as the ``mlflow`` module."""
    stub = types.ModuleType("mlflow")
    mock = Mock()
    for name in (
        "set_tracking_uri",
        "set_experiment",
        "start_run",
        "set_tag",
        "log_params",
        "log_metrics",
        "log_artifact",
        "end_run",
    ):
        setattr(stub, name, getattr(mock, name))
    monkeypatch.setitem(sys.modules, "mlflow", stub)
    return mock


class TestMLflowTracker:
    def test_missing_dependency_raises_clear_error(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "mlflow", None)  # forces ImportError
        t = MLflowTracker("file:./mlruns", "exp")
        with pytest.raises(RuntimeError, match="mlflow is not installed"):
            t.start_run("rid")

    def test_lifecycle_call_sequence(self, fake_mlflow):
        t = MLflowTracker("sqlite:///x.db", "exp", run_name="pretty")
        t.start_run("rid-1")
        fake_mlflow.set_tracking_uri.assert_called_once_with("sqlite:///x.db")
        fake_mlflow.set_experiment.assert_called_once_with("exp")
        fake_mlflow.start_run.assert_called_once_with(run_name="pretty")
        fake_mlflow.set_tag.assert_called_once_with("llmtrain.run_id", "rid-1")

        t.log_params({"model": {"d_model": 8}})
        fake_mlflow.log_params.assert_called_once_with({"model.d_model": 8})

        t.log_metrics({"train/loss": 1.5}, step=3)
        fake_mlflow.log_metrics.assert_called_once_with({"train/loss": 1.5}, step=3)

        t.log_artifact("/tmp/config.yaml")
        fake_mlflow.log_artifact.assert_called_once_with(
            "/tmp/config.yaml", artifact_path=None
        )

        t.end_run("FINISHED")
        fake_mlflow.end_run.assert_called_once_with(status="FINISHED")

    def test_methods_inactive_before_start(self, fake_mlflow):
        t = MLflowTracker("file:./mlruns", "exp")
        t.log_params({"a": 1})
        t.log_metrics({"m": 1.0}, step=1)
        t.log_artifact("/x")
        t.end_run()
        fake_mlflow.log_params.assert_not_called()
        fake_mlflow.log_metrics.assert_not_called()
        fake_mlflow.log_artifact.assert_not_called()
        fake_mlflow.end_run.assert_not_called()

    def test_end_run_deactivates(self, fake_mlflow):
        t = MLflowTracker("file:./mlruns", "exp")
        t.start_run("rid")
        t.end_run("FAILED")
        fake_mlflow.end_run.assert_called_once_with(status="FAILED")
        t.log_metrics({"m": 1.0}, step=1)
        fake_mlflow.log_metrics.assert_not_called()

    def test_run_id_used_when_no_run_name(self, fake_mlflow):
        t = MLflowTracker("file:./mlruns", "exp")
        t.start_run("rid-9")
        fake_mlflow.start_run.assert_called_once_with(run_name="rid-9")
