"""Compiled-Pallas correctness on real TPU hardware (VERDICT r1 #3).

Interpret-mode tests (tests/test_ops.py) validate kernel math on CPU; a
kernel that passes interpreted can still fail or misbehave when actually
lowered (tiling, VMEM limits, dtype rules). These tests run the compiled
kernels against the dense reference at bf16 tolerance, sweeping the
VMEM-relevant block shapes — they skip everywhere except a TPU backend and
run for real in the bench environment.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _on_tpu(), reason="requires a TPU backend")


def _qkv(b=2, t=512, h=4, d=64, dtype=jnp.bfloat16, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype=dtype) for k in keys)


def _dense_ref(q, k, v):
    from llmtrain_tpu.models.gpt import dense_attention

    return dense_attention(q, k, v, attention_mask=None)


class TestCompiledForward:
    def test_matches_dense_bf16(self):
        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv()
        out = jax.device_get(pallas_flash_attention(q, k, v))
        ref = jax.device_get(_dense_ref(q, k, v))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    @pytest.mark.parametrize(
        "block_q,block_k",
        [(128, 128), (128, 256), (256, 128), (256, 256), (512, 512)],
    )
    def test_block_shape_sweep(self, block_q, block_k):
        """VMEM-relevant tilings: every (block_q, block_k) must lower and
        agree with the dense reference."""
        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(t=512, seed=1)
        out = jax.device_get(
            pallas_flash_attention(q, k, v, block_q=block_q, block_k=block_k)
        )
        ref = jax.device_get(_dense_ref(q, k, v))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    def test_f32_tight_tolerance(self):
        """With MXU passes forced to full f32 (the TPU default is bf16
        multiplies even for f32 inputs), kernel and dense agree tightly."""
        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(t=256, dtype=jnp.float32, seed=2)
        with jax.default_matmul_precision("highest"):
            out = jax.device_get(pallas_flash_attention(q, k, v))
            ref = jax.device_get(_dense_ref(q, k, v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


class TestCompiledMaskedAndGQA:
    """Round-3 kernel capabilities lowered for real: in-kernel padding
    masks and native grouped-query K/V (tests/test_ops.py has the
    interpret-mode equivalents)."""

    def test_masked_forward_matches_dense(self):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(t=512, seed=11)
        lens = np.asarray([512, 300, 512, 77])[: q.shape[0]]
        mask = jnp.asarray((np.arange(512)[None, :] < lens[:, None]).astype(np.int32))
        out = jax.device_get(pallas_flash_attention(q, k, v, mask))
        ref = jax.device_get(dense_attention(q, k, v, attention_mask=mask))
        m = np.asarray(mask)[:, :, None, None]
        np.testing.assert_allclose(
            np.asarray(out, np.float32) * m, np.asarray(ref, np.float32) * m, atol=2e-2
        )

    def test_masked_backward_matches_dense_grads(self):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(t=256, dtype=jnp.float32, seed=12)
        lens = np.asarray([256, 100])[: q.shape[0]]
        mask = jnp.asarray((np.arange(256)[None, :] < lens[:, None]).astype(np.int32))
        g = jax.random.normal(jax.random.key(13), q.shape, jnp.float32)
        g = g * mask[:, :, None, None].astype(jnp.float32)

        def loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, attention_mask=mask) * g)

        with jax.default_matmul_precision("highest"):
            out, lse = pallas_flash_attention_fwd(q, k, v, mask)
            dq, dk, dv = pallas_flash_attention_bwd(q, k, v, out, lse, g, mask)
            rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for got, want in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(got)), np.asarray(jax.device_get(want)),
                atol=1e-3,
            )

    @pytest.mark.parametrize("hkv", [1, 2], ids=["mqa", "gqa2"])
    def test_gqa_forward_and_backward(self, hkv):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        b, t, h, d = 2, 256, 4, 64
        ks = jax.random.split(jax.random.key(14), 3)
        q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
        kn = jax.random.normal(ks[1], (b, t, hkv, d), jnp.float32)
        vn = jax.random.normal(ks[2], (b, t, hkv, d), jnp.float32)
        reps = h // hkv
        g = jax.random.normal(jax.random.key(15), q.shape, jnp.float32)

        def loss(q, kn, vn):
            kw = jnp.repeat(kn, reps, axis=2)
            vw = jnp.repeat(vn, reps, axis=2)
            return jnp.sum(dense_attention(q, kw, vw, attention_mask=None) * g)

        with jax.default_matmul_precision("highest"):
            out, lse = pallas_flash_attention_fwd(q, kn, vn)
            dq, dk, dv = pallas_flash_attention_bwd(q, kn, vn, out, lse, g)
            rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, kn, vn)
            # Reference must run INSIDE the precision context: on TPU the
            # default is bf16 MXU passes even for f32 inputs, and a
            # default-precision dense ref vs highest-precision kernel
            # differs by ~1e-3 relative (r4 chip run caught exactly that).
            ref = dense_attention(
                q, jnp.repeat(kn, reps, axis=2), jnp.repeat(vn, reps, axis=2),
                attention_mask=None,
            )
        assert dk.shape == kn.shape and dv.shape == vn.shape
        np.testing.assert_allclose(
            np.asarray(jax.device_get(out)), np.asarray(jax.device_get(ref)),
            atol=1e-4,
        )
        for got, want in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(got)), np.asarray(jax.device_get(want)),
                atol=1e-3,
            )


class TestCompiledSegments:
    """Round-4 segment masking (packed cross-document) lowered for real
    (tests/test_packing.py has the interpret-mode equivalents)."""

    def test_segment_forward_matches_dense(self):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(t=512, seed=61)
        seg = np.ones((q.shape[0], 512), np.int32)
        seg[:, 200:420] = 2
        seg[:, 420:] = 3
        seg = jnp.asarray(seg)
        out = jax.device_get(pallas_flash_attention(q, k, v, seg))
        ref = jax.device_get(dense_attention(q, k, v, attention_mask=seg))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    def test_segment_backward_matches_dense_grads(self):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(t=256, dtype=jnp.float32, seed=62)
        seg = np.ones((q.shape[0], 256), np.int32)
        seg[:, 100:] = 2
        seg = jnp.asarray(seg)
        g = jax.random.normal(jax.random.key(63), q.shape, jnp.float32)

        def loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, attention_mask=seg) * g)

        with jax.default_matmul_precision("highest"):
            out, lse = pallas_flash_attention_fwd(q, k, v, seg)
            dq, dk, dv = pallas_flash_attention_bwd(q, k, v, out, lse, g, seg)
            rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for got, want in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(got)), np.asarray(jax.device_get(want)),
                atol=1e-3,
            )


class TestCompiledSlidingWindow:
    """Round-4 sliding-window kernels lowered for real (tests/test_ops.py
    TestSlidingWindow has the interpret-mode equivalents)."""

    def test_windowed_forward_matches_dense(self):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import pallas_flash_attention

        q, k, v = _qkv(t=512, seed=51)
        out = jax.device_get(pallas_flash_attention(q, k, v, window=300))
        ref = jax.device_get(dense_attention(q, k, v, attention_mask=None, window=300))
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    def test_windowed_backward_matches_dense_grads(self):
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(t=256, dtype=jnp.float32, seed=52)
        g = jax.random.normal(jax.random.key(53), q.shape, jnp.float32)

        def loss(q, k, v):
            return jnp.sum(
                dense_attention(q, k, v, attention_mask=None, window=100) * g
            )

        with jax.default_matmul_precision("highest"):
            out, lse = pallas_flash_attention_fwd(q, k, v, window=100)
            dq, dk, dv = pallas_flash_attention_bwd(
                q, k, v, out, lse, g, window=100
            )
            rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for got, want in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(got)), np.asarray(jax.device_get(want)),
                atol=1e-3,
            )


class TestCompiledBackward:
    @pytest.mark.parametrize("block_q,block_k", [(128, 128), (256, 256)])
    def test_fused_bwd_matches_dense_grads(self, block_q, block_k):
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(t=256, dtype=jnp.float32, seed=3)
        g = jax.random.normal(jax.random.key(7), q.shape, jnp.float32)

        def loss(q, k, v):
            return jnp.sum(_dense_ref(q, k, v) * g)

        # Force full-f32 MXU passes in both paths: the TPU default is bf16
        # multiplies even for f32 inputs, which dominates a 1e-3 tolerance.
        with jax.default_matmul_precision("highest"):
            out, lse = pallas_flash_attention_fwd(
                q, k, v, block_q=block_q, block_k=block_k
            )
            dq, dk, dv = pallas_flash_attention_bwd(
                q, k, v, out, lse, g, block_q=block_q, block_k=block_k
            )
            rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(dq)), np.asarray(jax.device_get(rq)), atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(jax.device_get(dk)), np.asarray(jax.device_get(rk)), atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(jax.device_get(dv)), np.asarray(jax.device_get(rv)), atol=1e-3
        )

    def test_fused_bwd_bf16_mha(self):
        """The default training dtype: bf16 MHA backward must lower (the
        group==1 output refs keep the narrow dtype — a f32 store into a
        bf16 ref is a Mosaic error) and agree loosely with dense grads."""
        from llmtrain_tpu.models.gpt import dense_attention
        from llmtrain_tpu.ops.pallas_attention import (
            pallas_flash_attention_bwd,
            pallas_flash_attention_fwd,
        )

        q, k, v = _qkv(t=256, dtype=jnp.bfloat16, seed=8)
        g = jax.random.normal(jax.random.key(9), q.shape, jnp.bfloat16)
        out, lse = pallas_flash_attention_fwd(q, k, v)
        dq, dk, dv = pallas_flash_attention_bwd(q, k, v, out, lse, g)
        assert dk.dtype == jnp.bfloat16 and dv.dtype == jnp.bfloat16
        qf, kf, vf, gf = (x.astype(jnp.float32) for x in (q, k, v, g))

        def loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, attention_mask=None) * gf)

        rq, rk, rv = jax.grad(loss, argnums=(0, 1, 2))(qf, kf, vf)
        for got, want in ((dq, rq), (dk, rk), (dv, rv)):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(got), np.float32),
                np.asarray(jax.device_get(want)),
                atol=0.1, rtol=0.1,
            )

    def test_custom_vjp_dispatch_uses_pallas_bwd(self, monkeypatch):
        """flash_attention's grad on TPU goes through the fused kernels and
        agrees with the blockwise-recompute path (the A/B knob)."""
        from llmtrain_tpu.ops.flash_attention import flash_attention

        q, k, v = _qkv(t=256, dtype=jnp.float32, seed=4)

        def loss(q):
            return flash_attention(q, k, v).sum()

        with jax.default_matmul_precision("highest"):
            g_fused = jax.device_get(jax.grad(loss)(q))
            monkeypatch.setenv("LLMTRAIN_FLASH_BWD", "blockwise")
            g_recompute = jax.device_get(jax.grad(loss)(q))
        np.testing.assert_allclose(
            np.asarray(g_fused), np.asarray(g_recompute), atol=1e-3
        )


class TestCompiledTrainStep:
    def test_gpt_flash_train_step_runs(self):
        """One real optimizer step of the flagship GPT with attention=flash,
        compiled on the chip — the end-to-end smoke the bench relies on."""
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.models.gpt import GPTAdapter
        from llmtrain_tpu.training.optimizer import build_optimizer
        from llmtrain_tpu.training.train_step import create_train_state, make_train_step

        cfg = RunConfig.model_validate(
            {
                "run": {"name": "tpu-smoke", "device": "tpu"},
                "model": {
                    "name": "gpt",
                    "block_size": 256,
                    "d_model": 128,
                    "n_layers": 2,
                    "n_heads": 4,
                    "d_ff": 512,
                    "dropout": 0.0,
                    "vocab_size": 1024,
                    "dtype": "bfloat16",
                    "attention": "flash",
                },
                "data": {"name": "dummy_text"},
                "trainer": {"micro_batch_size": 4, "grad_accum_steps": 1, "warmup_steps": 0},
            }
        )
        adapter = GPTAdapter()
        model = adapter.build_model(cfg)
        tx = build_optimizer(cfg.trainer)
        rng = jax.random.key(0)
        params = adapter.init_params(model, cfg, rng)
        state = create_train_state(params, tx)
        step_fn = jax.jit(
            make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False)
        )
        tokens = np.random.default_rng(0).integers(0, 1024, size=(1, 4, 256), dtype=np.int32)
        batch = {
            "input_ids": jnp.asarray(tokens),
            "labels": jnp.asarray(tokens),
            "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
        }
        state, metrics = step_fn(state, batch, rng)
        loss = float(jax.device_get(metrics["loss"]))
        assert np.isfinite(loss) and loss > 0


class TestCompiledChunkedCE:
    """ops/chunked_ce.py lowered for real: the scan + custom_vjp must
    compile on the chip and agree with the dense CE at bf16 tolerance."""

    def test_value_and_grads_match_dense(self):
        from llmtrain_tpu.ops.chunked_ce import chunked_ce_components

        b, t, d, v = 4, 256, 128, 50257
        k1, k2 = jax.random.split(jax.random.key(5))
        hidden = jax.random.normal(k1, (b, t, d), jnp.bfloat16)
        w = (jax.random.normal(k2, (v, d), jnp.float32) * 0.02).astype(jnp.float32)
        labels = jax.random.randint(jax.random.key(6), (b, t), 0, v)
        mask = jnp.ones((b, t), jnp.float32)

        def loss_chunked(h, w_):
            s, tok = chunked_ce_components(h, w_, labels, mask, chunk=8192)
            return jnp.sum(s) / jnp.sum(tok)

        def loss_dense(h, w_):
            logits = jnp.einsum("btd,vd->btv", h, w_.astype(h.dtype))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            per = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
            return jnp.mean(per)

        lc, (gch, gcw) = jax.jit(jax.value_and_grad(loss_chunked, argnums=(0, 1)))(
            hidden, w
        )
        ld, (gdh, gdw) = jax.jit(jax.value_and_grad(loss_dense, argnums=(0, 1)))(
            hidden, w
        )
        assert abs(float(lc) - float(ld)) < 5e-2
        np.testing.assert_allclose(
            np.asarray(jax.device_get(gch), np.float32),
            np.asarray(jax.device_get(gdh), np.float32),
            atol=5e-2,
        )
        np.testing.assert_allclose(
            np.asarray(jax.device_get(gcw)),
            np.asarray(jax.device_get(gdw)),
            atol=5e-2,
        )

    def test_train_step_with_chunked_ce(self):
        """One compiled optimizer step of GPT with loss_impl=chunked_ce at
        the real GPT-2 vocab — the config the bench CE sweep runs."""
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.models.gpt import GPTAdapter
        from llmtrain_tpu.training.optimizer import build_optimizer
        from llmtrain_tpu.training.train_step import create_train_state, make_train_step

        cfg = RunConfig.model_validate(
            {
                "run": {"name": "tpu-cce", "device": "tpu"},
                "model": {
                    "name": "gpt",
                    "block_size": 256,
                    "d_model": 128,
                    "n_layers": 2,
                    "n_heads": 4,
                    "d_ff": 512,
                    "dropout": 0.0,
                    "vocab_size": 50257,
                    "dtype": "bfloat16",
                    "attention": "flash",
                    "extra": {"loss_impl": "chunked_ce"},
                },
                "data": {"name": "dummy_text"},
                "trainer": {"micro_batch_size": 4, "grad_accum_steps": 1, "warmup_steps": 0},
            }
        )
        adapter = GPTAdapter()
        model = adapter.build_model(cfg)
        tx = build_optimizer(cfg.trainer)
        rng = jax.random.key(0)
        params = adapter.init_params(model, cfg, rng)
        state = create_train_state(params, tx)
        step_fn = jax.jit(
            make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False)
        )
        tokens = np.random.default_rng(0).integers(
            0, 50257, size=(1, 4, 256), dtype=np.int32
        )
        batch = {
            "input_ids": jnp.asarray(tokens),
            "labels": jnp.asarray(tokens),
            "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
        }
        state, metrics = step_fn(state, batch, rng)
        loss = float(jax.device_get(metrics["loss"]))
        assert np.isfinite(loss) and loss > 0


class TestCompiledRound5Serving:
    """Round-5 serving features lowered for real: int8 weights via the
    __jax_array__ dequant, the int8 KV cache, and the qwen2/gemma family
    deltas — all CPU-validated (tests/test_quant.py, test_qwen2.py,
    test_gemma.py); these pin the on-chip compiles."""

    def _tiny(self, name="gpt", **extra):
        from llmtrain_tpu.config.schemas import RunConfig
        from llmtrain_tpu.models.lora import build_adapter
        from llmtrain_tpu.registry import initialize_registries

        initialize_registries()

        cfg = RunConfig.model_validate(
            {
                "run": {"name": f"tpu-{name}", "device": "tpu"},
                "model": {
                    "name": name,
                    "block_size": 128,
                    "d_model": 128,
                    "n_layers": 2,
                    "n_heads": 4,
                    "d_ff": 256,
                    "dropout": 0.0,
                    "vocab_size": 1024,
                    "dtype": "bfloat16",
                    "extra": {"tokenizer": "byte", **extra},
                },
                "data": {"name": "dummy_text"},
                "trainer": {"micro_batch_size": 2, "grad_accum_steps": 1,
                            "warmup_steps": 0},
            }
        )
        adapter = build_adapter(cfg)
        model = adapter.build_model(cfg)
        params = adapter.init_params(model, cfg, jax.random.key(0))
        from flax.core import meta as nn_meta

        return model, nn_meta.unbox(params)

    def test_int8_weights_compile_and_track_full(self):
        from llmtrain_tpu.ops.quant import quantize_tree

        model, params = self._tiny()
        ids = jnp.asarray(
            np.random.default_rng(1).integers(0, 1024, (2, 64), np.int32)
        )
        f = jax.jit(lambda p, i: model.apply({"params": p}, i, deterministic=True))
        full = jax.device_get(f(params, ids))
        quant = jax.device_get(f(quantize_tree(params), ids))
        a = np.asarray(full, np.float64).reshape(-1)
        b = np.asarray(quant, np.float64).reshape(-1)
        cos = (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos > 0.99

    def test_int8_kv_cache_decode_compiles(self):
        from llmtrain_tpu.generation import generate

        model, params = self._tiny(kv_cache_dtype="int8")
        out = generate(
            model, params, np.asarray([[1, 2, 3]], np.int32),
            max_new_tokens=8, temperature=0.0, use_cache=True,
        )
        arr = np.asarray(out)
        assert arr.shape == (1, 11) and ((arr >= 0) & (arr < 1024)).all()

    @pytest.mark.parametrize("family", ["qwen2", "gemma"])
    def test_new_family_forward_compiles(self, family):
        model, params = self._tiny(name=family, n_kv_heads=2)
        ids = jnp.asarray(
            np.random.default_rng(2).integers(0, 1024, (2, 64), np.int32)
        )
        logits = jax.device_get(
            jax.jit(
                lambda p, i: model.apply({"params": p}, i, deterministic=True)
            )(params, ids)
        )
        assert np.isfinite(np.asarray(logits, np.float32)).all()
