"""Distributed-state + mesh tests on the 8-virtual-device CPU platform.

Mirrors the reference test tiers (tests/test_distributed.py): pure-unit state
invariants, real single-process setup/idempotency/teardown, env-beats-config
resolution — with the multi-rank tier exercised as *real* shardings over the
forced 8-device host platform instead of mocked collectives.
"""

import jax
import numpy as np
import pytest

from llmtrain_tpu.config import DistributedConfig, MeshConfig
from llmtrain_tpu.distributed import (
    DistState,
    active_state,
    build_mesh,
    resolve_mesh_axes,
    resolve_topology,
    setup_distributed,
    teardown_distributed,
)


class TestDistState:
    def test_valid(self):
        s = DistState(process_index=0, num_processes=2, local_device_count=1, is_main=True)
        assert s.rank == 0 and s.world_size == 2

    def test_is_main_invariant(self):
        with pytest.raises(ValueError, match="is_main"):
            DistState(process_index=1, num_processes=2, local_device_count=1, is_main=True)

    def test_rank_bounds(self):
        with pytest.raises(ValueError):
            DistState(process_index=2, num_processes=2, local_device_count=1, is_main=False)
        with pytest.raises(ValueError):
            DistState(process_index=0, num_processes=0, local_device_count=1, is_main=True)


class TestTopologyResolution:
    def test_env_beats_config(self, monkeypatch):
        monkeypatch.setenv("RANK", "1")
        monkeypatch.setenv("WORLD_SIZE", "4")
        monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
        monkeypatch.setenv("MASTER_PORT", "12345")
        cfg = DistributedConfig(process_id=0, num_processes=2, coordinator_addr="cfg-host")
        pid, n, coord = resolve_topology(cfg)
        assert (pid, n, coord) == (1, 4, "10.0.0.1:12345")

    def test_jax_native_env_beats_torch_names(self, monkeypatch):
        monkeypatch.setenv("RANK", "1")
        monkeypatch.setenv("JAX_PROCESS_ID", "2")
        monkeypatch.setenv("JAX_NUM_PROCESSES", "8")
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "coord:1234")
        pid, n, coord = resolve_topology(DistributedConfig())
        assert (pid, n, coord) == (2, 8, "coord:1234")

    def test_config_fallback(self):
        cfg = DistributedConfig(
            process_id=1, num_processes=2, coordinator_addr="host", coordinator_port=999
        )
        pid, n, coord = resolve_topology(cfg)
        assert (pid, n, coord) == (1, 2, "host:999")

    def test_defaults(self):
        assert resolve_topology(DistributedConfig()) == (0, 1, None)

    def test_bad_env_int(self, monkeypatch):
        monkeypatch.setenv("WORLD_SIZE", "banana")
        with pytest.raises(ValueError, match="not an integer"):
            resolve_topology(DistributedConfig())

    def test_multiprocess_unset_process_id_fails_fast(self, monkeypatch):
        monkeypatch.setenv("WORLD_SIZE", "4")
        monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
        with pytest.raises(ValueError, match="process id is unset"):
            resolve_topology(DistributedConfig())

    def test_empty_coordinator_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "")
        monkeypatch.setenv("MASTER_ADDR", "10.0.0.2")
        _, _, coord = resolve_topology(DistributedConfig())
        assert coord == "10.0.0.2:29500"


class TestSetup:
    def test_single_process_setup_and_teardown(self):
        state = setup_distributed(DistributedConfig())
        assert state.num_processes == 1 and state.is_main
        assert state.local_device_count == 8  # forced host platform
        assert active_state() is state
        teardown_distributed()
        assert active_state() is None

    def test_idempotent_returns_same_state(self):
        s1 = setup_distributed(DistributedConfig())
        s2 = setup_distributed(DistributedConfig())
        assert s1 is s2

    def test_multiprocess_requires_coordinator(self):
        with pytest.raises(ValueError, match="coordinator"):
            setup_distributed(DistributedConfig(num_processes=2, process_id=0))

    def test_tpu_autodetect_gate(self, monkeypatch):
        """Bare jax.distributed.initialize() only for MULTI-host TPU slices
        with no explicit topology (the GKE pod-slice path, docs/k8s.md)."""
        from llmtrain_tpu.distributed import _tpu_autodetect_available

        cfg = DistributedConfig()
        monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
        assert not _tpu_autodetect_available(cfg)
        # Single-host slice (what the axon tunnel env looks like): no init.
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
        assert not _tpu_autodetect_available(cfg)
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1,host-2,host-3")
        assert _tpu_autodetect_available(cfg)
        # Explicit topology always wins over auto-detection.
        monkeypatch.setenv("WORLD_SIZE", "4")
        assert not _tpu_autodetect_available(cfg)
        monkeypatch.delenv("WORLD_SIZE")
        assert not _tpu_autodetect_available(
            DistributedConfig(num_processes=4, process_id=0)
        )


class TestMesh:
    def test_wildcard_resolution(self):
        sizes = resolve_mesh_axes(MeshConfig(), 8)
        assert sizes["data"] == 8 and sizes["tensor"] == 1

    def test_explicit_axes(self):
        sizes = resolve_mesh_axes(MeshConfig(data=2, tensor=4), 8)
        assert sizes == {
            "data": 2, "fsdp": 1, "tensor": 4, "sequence": 1, "pipeline": 1, "expert": 1,
        }

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            resolve_mesh_axes(MeshConfig(data=-1, tensor=3), 8)

    def test_mismatched_product_raises(self):
        with pytest.raises(ValueError, match="devices"):
            resolve_mesh_axes(MeshConfig(data=2, tensor=2), 8)

    def test_build_mesh_and_psum(self):
        """A real psum over the data axis of a real 8-device mesh."""
        mesh = build_mesh(MeshConfig(data=4, tensor=2))
        assert mesh.shape["data"] == 4 and mesh.shape["tensor"] == 2

        from jax.sharding import NamedSharding, PartitionSpec as P

        x = np.arange(8, dtype=np.float32)
        sharded = jax.device_put(x, NamedSharding(mesh, P(("data", "tensor"))))

        @jax.jit
        def total(v):
            return jax.numpy.sum(v)

        assert float(total(sharded)) == float(x.sum())

    def test_build_mesh_sharded_matmul(self):
        """Tensor-parallel matmul: weight sharded on 'tensor', XLA all-gathers."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = build_mesh(MeshConfig(data=2, tensor=4))
        w = np.ones((16, 8), dtype=np.float32)
        x = np.ones((4, 16), dtype=np.float32)
        ws = jax.device_put(w, NamedSharding(mesh, P(None, "tensor")))
        xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        out = jax.jit(lambda a, b: a @ b)(xs, ws)
        np.testing.assert_allclose(np.asarray(out), x @ w)


class TestParallelismEquivalence:
    """Different mesh layouts must compute the same training run.

    The TPU-native analogue of the reference's DDP-correctness concern.
    Parameters (same seed), global batch content (same sampler stream) and
    math are identical across layouts; only the sharding differs, so losses
    must agree to fp-reduction tolerance. Config caveat: dummy_text sizes
    its dataset as max_steps*micro_batch_size capped at 128 — the chosen
    max_steps/micro pairs drive every layout to the 128 cap so the datasets
    (and therefore the wrapped sampler streams) are identical too.
    """

    def _run(self, mesh_axes: dict, micro_batch_size: int, attention: str = "dense"):
        from unittest.mock import Mock

        from llmtrain_tpu.config import RunConfig
        from llmtrain_tpu.registry import initialize_registries
        from llmtrain_tpu.training import Trainer

        initialize_registries()
        cfg = RunConfig.model_validate(
            {
                "run": {"name": "eq", "seed": 11, "deterministic": True},
                "model": {
                    "name": "gpt",
                    "block_size": 8,
                    "vocab_size": 32,
                    "dropout": 0.0,
                    "d_model": 16,
                    "n_heads": 4,
                    "d_ff": 32,
                    "n_layers": 1,
                    "attention": attention,
                },
                "data": {"name": "dummy_text"},
                "trainer": {
                    "max_steps": 16,
                    "micro_batch_size": micro_batch_size,
                    "grad_accum_steps": 2,
                    "lr": 3e-3,
                    "warmup_steps": 0,
                    "log_every_steps": 16,
                    "eval_every_steps": 16,
                    "save_every_steps": 100,
                },
                "distributed": {"mesh": mesh_axes},
                "mlflow": {"enabled": False},
            }
        )
        result = Trainer(cfg, None, Mock(), None).fit()
        return result.first_step_loss, result.final_loss

    def test_layouts_agree(self):
        # micro_batch_size is per data shard: scale it so the GLOBAL batch
        # (micro x data-parallel degree = 64) — and hence the deterministic
        # sampler's index stream — is identical across layouts. 16 steps x
        # these micro sizes all reach dummy_text's 128-example cap.
        dp = self._run({"data": 8}, micro_batch_size=8)  # dp degree 8
        mixed = self._run(
            {"data": 2, "fsdp": 2, "tensor": 2}, micro_batch_size=16
        )  # dp degree 4
        sp = self._run({"data": 4, "sequence": 2}, micro_batch_size=16)  # dp 4
        # Step 1 is a single forward/backward on identical params+batch:
        # any disagreement beyond reduction-order noise is a sharding bug.
        assert abs(dp[0] - mixed[0]) < 1e-5, (dp, mixed)
        assert abs(dp[0] - sp[0]) < 1e-5, (dp, sp)
        # Final losses drift only by fp-noise amplification through training.
        assert abs(dp[1] - mixed[1]) < 5e-3, (dp, mixed)
        assert abs(dp[1] - sp[1]) < 5e-3, (dp, sp)

    def test_ring_attention_matches_dense(self):
        """Ring attention over the sequence axis computes the same training
        run as dense attention on the same mesh (exact-attention claim)."""
        dense = self._run({"data": 4, "sequence": 2}, micro_batch_size=16)
        ring = self._run(
            {"data": 4, "sequence": 2}, micro_batch_size=16, attention="ring"
        )
        assert abs(dense[0] - ring[0]) < 1e-5, (dense, ring)
        assert abs(dense[1] - ring[1]) < 5e-3, (dense, ring)

    def test_ulysses_attention_matches_dense(self):
        """Ulysses (all-to-all SP) computes the same training run as dense
        attention on the same mesh — the exact-attention claim for the
        second sequence-parallel scheme (ops/ulysses_attention.py)."""
        dense = self._run({"data": 4, "sequence": 2}, micro_batch_size=16)
        uly = self._run(
            {"data": 4, "sequence": 2}, micro_batch_size=16, attention="ulysses"
        )
        assert abs(dense[0] - uly[0]) < 1e-5, (dense, uly)
        assert abs(dense[1] - uly[1]) < 5e-3, (dense, uly)
