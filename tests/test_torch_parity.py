"""Cross-framework architecture parity: flax GPT vs a torch mirror.

The north-star for this framework is loss parity with the reference's
torch GPT (BASELINE.md:24-26). The reference model is specified by
SURVEY.md §2.1: learned token+position embeddings, pre-norm blocks
(LN -> attn -> residual, LN -> MLP(GELU) -> residual), explicit causal
attention with f32 softmax, final LN, lm_head with optional weight tying
(reference models/gpt.py:99-146 as behavior spec — the mirror below is
written from that spec, not copied).

These tests build the torch mirror, transplant the flax parameters into
it, and assert the two frameworks produce the same logits and the same
masked-CE loss on the same batch. This pins architecture equivalence
numerically: any divergence in attention math, GELU flavor, init-time
shape conventions, or weight-tying surfaces here as a logits mismatch,
without needing a multi-hour training-run comparison.

One intentional divergence is normalized away explicitly: flax LayerNorm
defaults to eps=1e-6 while torch defaults to 1e-5, so the mirror pins
eps=1e-6 (documented in docs/parity.md).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from flax.linen import meta as nn_meta  # noqa: E402

from llmtrain_tpu.models.base import masked_ce_components  # noqa: E402
from llmtrain_tpu.models.gpt import GPT  # noqa: E402

V, T, D, L, H, FF = 97, 16, 32, 2, 4, 64


class _TorchAttn(tnn.Module):
    """Mirror of the reference CausalSelfAttention's module surface
    (gpt.py:27-33): fused qkv_proj/out_proj Linears plus the persistent
    causal_mask buffer — so state_dict keys match the reference's."""

    def __init__(self) -> None:
        super().__init__()
        self.qkv_proj = tnn.Linear(D, 3 * D)
        self.out_proj = tnn.Linear(D, D)
        causal = torch.triu(torch.ones(T, T, dtype=torch.bool), diagonal=1)
        self.register_buffer("causal_mask", causal.view(1, 1, T, T))

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b, t, _ = x.shape
        q, k, v = self.qkv_proj(x).chunk(3, dim=-1)
        hd = D // H

        def heads(a: torch.Tensor) -> torch.Tensor:
            return a.view(b, t, H, hd).transpose(1, 2)  # (B, H, T, hd)

        q, k, v = heads(q), heads(k), heads(v)
        scores = (q @ k.transpose(-2, -1)) / math.sqrt(hd)
        scores = scores.masked_fill(
            self.causal_mask[:, :, :t, :t], torch.finfo(scores.dtype).min
        )
        att = F.softmax(scores, dim=-1) @ v  # (B, H, T, hd)
        att = att.transpose(1, 2).reshape(b, t, D)
        return self.out_proj(att)


class _TorchBlock(tnn.Module):
    def __init__(self) -> None:
        super().__init__()
        self.ln_1 = tnn.LayerNorm(D, eps=1e-6)
        self.attn = _TorchAttn()
        self.ln_2 = tnn.LayerNorm(D, eps=1e-6)
        self.mlp_fc = tnn.Linear(D, FF)
        self.mlp_proj = tnn.Linear(FF, D)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        x = x + self.attn(self.ln_1(x))
        h = self.ln_2(x)
        h = self.mlp_proj(F.gelu(self.mlp_fc(h), approximate="none"))
        return x + h


class _TorchGPT(tnn.Module):
    def __init__(self, tie: bool) -> None:
        super().__init__()
        self.token_embedding = tnn.Embedding(V, D)
        self.position_embedding = tnn.Embedding(T, D)
        self.blocks = tnn.ModuleList(_TorchBlock() for _ in range(L))
        self.ln_f = tnn.LayerNorm(D, eps=1e-6)
        self.tie = tie
        # Like the reference (gpt.py:143-146): lm_head always exists and
        # tying shares the tensor, so lm_head.weight is always in the
        # state dict.
        self.lm_head = tnn.Linear(D, V, bias=False)
        if tie:
            self.lm_head.weight = self.token_embedding.weight

    def forward(self, ids: torch.Tensor) -> torch.Tensor:
        t = ids.shape[1]
        x = self.token_embedding(ids) + self.position_embedding(torch.arange(t))[None]
        for blk in self.blocks:
            x = blk(x)
        return self.lm_head(self.ln_f(x))


def _to_torch(a: jax.Array) -> torch.Tensor:
    return torch.from_numpy(np.array(a, dtype=np.float32))


def _transplant(params: dict, model: _TorchGPT) -> None:
    """Copy flax params into the torch mirror.

    Flax Dense kernels are (in, out) — torch Linear weights are (out, in).
    The fused qkv DenseGeneral kernel is (D, 3, H, hd): C-order flatten of
    the output axes makes row-chunking in torch recover q/k/v in the same
    order as ``qkv[:, :, i]`` does in flax (models/gpt.py:74-85). The
    out_proj kernel is (H, hd, D) contracting (H, hd) — the same C-order
    as torch's ``reshape(b, t, D)`` after the head transpose.
    """
    with torch.no_grad():
        model.token_embedding.weight.copy_(_to_torch(params["token_embedding"]["embedding"]))
        model.position_embedding.weight.copy_(
            _to_torch(params["position_embedding"]["embedding"])
        )
        for i, blk in enumerate(model.blocks):
            p = params[f"block_{i}"]
            blk.ln_1.weight.copy_(_to_torch(p["ln_1"]["scale"]))
            blk.ln_1.bias.copy_(_to_torch(p["ln_1"]["bias"]))
            blk.ln_2.weight.copy_(_to_torch(p["ln_2"]["scale"]))
            blk.ln_2.bias.copy_(_to_torch(p["ln_2"]["bias"]))
            att = p["attn"]
            blk.attn.qkv_proj.weight.copy_(
                _to_torch(att["qkv_proj"]["kernel"]).reshape(D, 3 * D).T
            )
            blk.attn.qkv_proj.bias.copy_(_to_torch(att["qkv_proj"]["bias"]).reshape(3 * D))
            blk.attn.out_proj.weight.copy_(
                _to_torch(att["out_proj"]["kernel"]).reshape(D, D).T
            )
            blk.attn.out_proj.bias.copy_(_to_torch(att["out_proj"]["bias"]))
            blk.mlp_fc.weight.copy_(_to_torch(p["mlp_fc"]["kernel"]).T)
            blk.mlp_fc.bias.copy_(_to_torch(p["mlp_fc"]["bias"]))
            blk.mlp_proj.weight.copy_(_to_torch(p["mlp_proj"]["kernel"]).T)
            blk.mlp_proj.bias.copy_(_to_torch(p["mlp_proj"]["bias"]))
        model.ln_f.weight.copy_(_to_torch(params["ln_f"]["scale"]))
        model.ln_f.bias.copy_(_to_torch(params["ln_f"]["bias"]))
        if not model.tie:
            model.lm_head.weight.copy_(_to_torch(params["lm_head"]["kernel"]).T)


def _flax_gpt(tie: bool) -> tuple[GPT, dict]:
    model = GPT(
        vocab_size=V,
        block_size=T,
        d_model=D,
        n_layers=L,
        n_heads=H,
        d_ff=FF,
        dropout=0.0,
        tie_embeddings=tie,
    )
    ids = jnp.zeros((1, T), jnp.int32)
    params = nn_meta.unbox(model.init(jax.random.key(0), ids, deterministic=True))["params"]
    return model, params


@pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
def test_logits_match_torch_mirror(tie):
    model, params = _flax_gpt(tie)
    mirror = _TorchGPT(tie)
    _transplant(params, mirror)

    ids = np.random.default_rng(7).integers(0, V, size=(3, T), dtype=np.int64)
    flax_logits = np.asarray(
        model.apply({"params": params}, jnp.asarray(ids, jnp.int32), deterministic=True)
    )
    with torch.no_grad():
        torch_logits = mirror(torch.from_numpy(ids)).numpy()

    np.testing.assert_allclose(flax_logits, torch_logits, atol=2e-5, rtol=2e-5)


def test_masked_ce_loss_matches_torch():
    """Same weights, same batch, same mask: the two frameworks' token-
    weighted CE losses agree (reference gpt.py:256-269 semantics)."""
    model, params = _flax_gpt(True)
    mirror = _TorchGPT(True)
    _transplant(params, mirror)

    rng = np.random.default_rng(11)
    ids = rng.integers(0, V, size=(2, T), dtype=np.int64)
    labels = rng.integers(0, V, size=(2, T), dtype=np.int64)
    mask = np.ones((2, T), dtype=np.int64)
    mask[0, T // 2 :] = 0  # padded tail on row 0

    flax_logits = model.apply(
        {"params": params},
        jnp.asarray(ids, jnp.int32),
        attention_mask=jnp.asarray(mask, jnp.int32),
        deterministic=True,
    )
    loss_sum, tokens = masked_ce_components(
        flax_logits, jnp.asarray(labels, jnp.int32), jnp.asarray(mask, jnp.int32)
    )
    flax_loss = float(jnp.sum(loss_sum) / jnp.sum(tokens))

    with torch.no_grad():
        tl = mirror(torch.from_numpy(ids))
        per_tok = F.cross_entropy(
            tl.reshape(-1, V), torch.from_numpy(labels).reshape(-1), reduction="none"
        ).reshape(2, T)
        tmask = torch.from_numpy(mask).float()
        torch_loss = float((per_tok * tmask).sum() / tmask.sum())

    assert abs(flax_loss - torch_loss) < 1e-5


def test_gradients_match_torch_mirror():
    """Backward parity: d(loss)/d(params) agree across frameworks.

    Logits parity alone leaves the backward unchecked — a wrong custom-vjp
    or dtype cast in the grad path would still train to a different loss.
    Comparing the gradient of the same masked-CE loss on the same weights
    pins the full fwd+bwd math (tied embeddings accumulate both the lookup
    and the lm_head contributions in both frameworks)."""
    model, params = _flax_gpt(True)
    mirror = _TorchGPT(True)
    _transplant(params, mirror)

    rng = np.random.default_rng(13)
    ids = rng.integers(0, V, size=(2, T), dtype=np.int64)
    labels = rng.integers(0, V, size=(2, T), dtype=np.int64)

    def loss_fn(p):
        logits = model.apply(
            {"params": p}, jnp.asarray(ids, jnp.int32), deterministic=True
        )
        loss_sum, tokens = masked_ce_components(
            logits, jnp.asarray(labels, jnp.int32), None
        )
        return jnp.sum(loss_sum) / jnp.sum(tokens)

    flax_grads = jax.grad(loss_fn)(params)

    tl = mirror(torch.from_numpy(ids))
    torch_loss = F.cross_entropy(tl.reshape(-1, V), torch.from_numpy(labels).reshape(-1))
    torch_loss.backward()

    def close(flax_g, torch_param, transform=lambda a: a):
        np.testing.assert_allclose(
            transform(np.array(flax_g, dtype=np.float32)),
            torch_param.grad.numpy(),
            atol=1e-5,
            rtol=1e-4,
        )

    close(flax_grads["token_embedding"]["embedding"], mirror.token_embedding.weight)
    close(flax_grads["position_embedding"]["embedding"], mirror.position_embedding.weight)
    close(flax_grads["ln_f"]["scale"], mirror.ln_f.weight)
    for i, blk in enumerate(mirror.blocks):
        g = flax_grads[f"block_{i}"]
        close(
            g["attn"]["qkv_proj"]["kernel"],
            blk.attn.qkv_proj.weight,
            lambda a: a.reshape(D, 3 * D).T,
        )
        close(g["attn"]["qkv_proj"]["bias"], blk.attn.qkv_proj.bias, lambda a: a.reshape(3 * D))
        close(
            g["attn"]["out_proj"]["kernel"],
            blk.attn.out_proj.weight,
            lambda a: a.reshape(D, D).T,
        )
        close(g["mlp_fc"]["kernel"], blk.mlp_fc.weight, lambda a: a.T)
        close(g["mlp_proj"]["kernel"], blk.mlp_proj.weight, lambda a: a.T)
        close(g["ln_1"]["scale"], blk.ln_1.weight)
        close(g["ln_2"]["scale"], blk.ln_2.weight)


def test_optimizer_trajectory_matches_torch():
    """Update parity: N optimizer steps land on the same weights.

    Runs the exact production optax chain (clip-by-global-norm -> AdamW
    with the warmup-cosine schedule, training/optimizer.py) against
    torch AdamW + clip_grad_norm_ + LambdaLR stepped after the optimizer
    (reference trainer.py:93-121,390-395). Five steps cross the
    warmup->cosine boundary, so schedule indexing (reference is
    1-indexed with the scheduler stepped after) is exercised too. With
    fwd/bwd parity pinned above, this closes the loop: the whole
    training step is numerically the reference's.
    """
    import optax

    from llmtrain_tpu.config.schemas import TrainerConfig
    from llmtrain_tpu.training.optimizer import build_optimizer, lr_schedule

    tcfg = TrainerConfig(
        max_steps=5, warmup_steps=2, lr=1e-3, weight_decay=0.1, max_grad_norm=1.0
    )

    model, params = _flax_gpt(True)
    mirror = _TorchGPT(True)
    _transplant(params, mirror)

    tx = build_optimizer(tcfg)
    opt_state = tx.init(params)

    sched = lr_schedule(tcfg)
    opt = torch.optim.AdamW(
        mirror.parameters(), lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.1
    )
    lam = torch.optim.lr_scheduler.LambdaLR(opt, lambda c: float(sched(c)) / 1e-3)

    rng = np.random.default_rng(17)
    for _ in range(5):
        ids = rng.integers(0, V, size=(2, T), dtype=np.int64)
        labels = rng.integers(0, V, size=(2, T), dtype=np.int64)

        def loss_fn(p):
            logits = model.apply(
                {"params": p}, jnp.asarray(ids, jnp.int32), deterministic=True
            )
            ls, tk = masked_ce_components(logits, jnp.asarray(labels, jnp.int32), None)
            return jnp.sum(ls) / jnp.sum(tk)

        grads = jax.grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        opt.zero_grad()
        tl = mirror(torch.from_numpy(ids))
        F.cross_entropy(tl.reshape(-1, V), torch.from_numpy(labels).reshape(-1)).backward()
        torch.nn.utils.clip_grad_norm_(mirror.parameters(), 1.0)
        opt.step()
        lam.step()

    fresh = _TorchGPT(True)
    _transplant(params, fresh)  # flax params after 5 steps, in torch layout
    for (name, got), (_, want) in zip(
        fresh.named_parameters(), mirror.named_parameters(), strict=True
    ):
        np.testing.assert_allclose(
            got.detach().numpy(), want.detach().numpy(), atol=3e-5, rtol=1e-3,
            err_msg=name,
        )
