"""Real-dataset slow tests (parity with reference
tests/test_hf_text_integration.py:32-81 and the real-download case in
tests/test_hf_text_data.py:68). Marked slow: they download WikiText-2 and
the tiktoken gpt2 encoding, so they only run with network access
(``pytest -m slow``); the fast gate (``make test``) excludes them."""

import json
import os
import socket
import subprocess
import sys

import pytest
import yaml


pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _require_egress():
    """Skip when the HF hub is unreachable — these tests need downloads.

    A fixture (not module-level skipif) so the probe runs only when a test
    here is actually selected, with a bounded timeout, and tests actual
    connectability rather than DNS alone."""
    try:
        socket.create_connection(("huggingface.co", 443), timeout=5).close()
    except OSError:
        pytest.skip("no network egress (downloads required)")

CFG = {
    "schema_version": 1,
    "run": {"name": "wikitext-it", "seed": 7, "device": "cpu", "deterministic": True},
    "model": {
        "name": "gpt",
        "block_size": 64,
        "d_model": 64,
        "n_layers": 2,
        "n_heads": 4,
        "d_ff": 128,
        "dropout": 0.0,
    },
    "data": {
        "name": "hf_text",
        "dataset_name": "wikitext",
        "dataset_config": "wikitext-2-raw-v1",
        "text_column": "text",
        "cache_dir": ".cache/datasets",
    },
    "trainer": {
        "max_steps": 30,
        "micro_batch_size": 4,
        "grad_accum_steps": 1,
        "lr": 0.001,
        "warmup_steps": 5,
        "log_every_steps": 10,
        "eval_every_steps": 30,
        "save_every_steps": 30,
    },
    "mlflow": {"enabled": False},
    "output": {"root_dir": "runs"},
}


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def test_wikitext_cli_train_improves(tmp_path):
    """Full CLI train on WikiText-2: exit 0, finite and decreasing loss."""
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text(yaml.safe_dump(CFG))
    proc = subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", "train", "--config", "config.yaml", "--json"],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        env=_env(),
        timeout=1800,
    )
    assert proc.returncode == 0, proc.stderr
    tr = json.loads(proc.stdout)["train_result"]
    assert tr["final_step"] == 30
    assert tr["first_step_loss"] > 0 and tr["final_loss"] > 0
    assert tr["final_loss"] < tr["first_step_loss"]  # learning happened
    assert tr["final_val_loss"] is not None


def test_hf_text_real_download_window_shapes(tmp_path):
    """hf_text against the real dataset + tiktoken: window shape contract."""
    import tiktoken

    from llmtrain_tpu.config import RunConfig
    from llmtrain_tpu.data.hf_text import HFTextDataModule

    cfg = RunConfig.model_validate(
        {**CFG, "data": {**CFG["data"], "cache_dir": str(tmp_path / "cache")}}
    )
    module = HFTextDataModule()
    module.setup(cfg, tiktoken.get_encoding("gpt2"))
    train = module.train_dataset()
    assert len(train) > 100
    import numpy as np

    batch = train.get_examples(np.asarray([0, 1]))
    assert batch["input_ids"].shape == (2, 64)
    assert batch["labels"].shape == (2, 64)
    # labels are inputs shifted by one inside each window
    np.testing.assert_array_equal(batch["input_ids"][0, 1:], batch["labels"][0, :-1])
