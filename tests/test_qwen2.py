"""Qwen2-family tests (models/qwen2.py).

Beyond-reference model family (the reference ships GPT only). Qwen2 is
the llama stack with q/k/v biases and a 1e6 rope base, so these tests
cover exactly the deltas — bias placement, adapter defaults, HF
round-trip incl. the bias tensors — plus numerical parity against HF
transformers' torch Qwen2, the family's ground truth (mirroring
tests/test_llama.py's HF-parity strategy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.registry.models import get_model_adapter
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training.trainer import Trainer

V, T, D, H, F = 64, 16, 32, 4, 88


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _cfg(_max_steps=25, **model_extra):
    return RunConfig.model_validate(
        {
            "run": {"name": "qwen2-t", "seed": 0, "device": "cpu"},
            "model": {
                "name": "qwen2",
                "block_size": T,
                "d_model": D,
                "n_layers": 2,
                "n_heads": H,
                "d_ff": F,
                "dropout": 0.0,
                "vocab_size": V,
                "tie_embeddings": False,
                "extra": model_extra,
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": _max_steps,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "lr": 5e-3,
                "warmup_steps": 0,
                "log_every_steps": 10,
                "eval_every_steps": 100,
                "save_every_steps": 100,
            },
            "mlflow": {"enabled": False},
        }
    )


def _build(**model_extra):
    cfg = _cfg(**model_extra)
    adapter = get_model_adapter("qwen2")()
    model = adapter.build_model(cfg)
    params = nn_meta.unbox(
        model.init(
            jax.random.key(0), jnp.zeros((1, 4), jnp.int32), deterministic=True
        )["params"]
    )
    return cfg, adapter, model, params


class TestArchitecture:
    def test_bias_on_qkv_only(self):
        _, _, model, params = _build()
        att = params["block_0"]["attn"]
        assert "bias" in att["qkv_proj"]
        assert att["qkv_proj"]["bias"].shape == (3, H, D // H)
        assert "bias" not in att["out_proj"]
        assert "bias" not in params["block_0"]["mlp_gate"]
        assert "bias" not in params["block_0"]["mlp_down"]

    def test_gqa_split_tree_biases(self):
        _, _, model, params = _build(n_kv_heads=2)
        att = params["block_0"]["attn"]
        assert att["q_proj"]["bias"].shape == (H, D // H)
        assert att["kv_proj"]["bias"].shape == (2, 2, D // H)
        assert "bias" not in att["out_proj"]

    def test_llama_stays_bias_free(self):
        """The qkv_bias knob must not leak into the llama family."""
        from llmtrain_tpu.models.llama import Llama

        m = Llama(
            vocab_size=V, block_size=T, d_model=D, n_layers=1, n_heads=H,
            d_ff=F, dropout=0.0,
        )
        p = nn_meta.unbox(
            m.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
        )
        assert "bias" not in p["block_0"]["attn"]["qkv_proj"]

    def test_rope_theta_defaults_to_1e6(self):
        _, _, model, _ = _build()
        assert model.rope_theta == 1_000_000.0
        _, _, override, _ = _build(rope_theta=5000.0)
        assert override.rope_theta == 5000.0

    def test_loss_decreases_under_trainer(self):
        trainer = Trainer(_cfg(), None, NullTracker(), None)
        res = trainer.fit()
        assert res.final_loss < res.first_step_loss


class TestHFRoundtrip:
    def test_export_import_identity_with_biases(self):
        from llmtrain_tpu.interop import (
            llama_params_from_hf_state_dict,
            llama_params_to_hf_state_dict,
        )

        _, _, _, params = _build(n_kv_heads=2)
        sd = llama_params_to_hf_state_dict(params)
        for n in ("q", "k", "v"):
            assert f"model.layers.0.self_attn.{n}_proj.bias" in sd
        assert "model.layers.0.self_attn.o_proj.bias" not in sd
        back = llama_params_from_hf_state_dict(sd, params)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(back)[0],
        ):
            assert pa == pb
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)

    def test_exported_dict_loads_into_hf_qwen2(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        from llmtrain_tpu.interop import llama_params_to_hf_state_dict

        _, _, _, params = _build(n_kv_heads=2)
        sd = {
            k: torch.from_numpy(v)
            for k, v in llama_params_to_hf_state_dict(params).items()
        }
        hf_cfg = transformers.Qwen2Config(
            vocab_size=V,
            hidden_size=D,
            intermediate_size=F,
            num_hidden_layers=2,
            num_attention_heads=H,
            num_key_value_heads=2,
            max_position_embeddings=T,
            rms_norm_eps=1e-6,
            rope_theta=1_000_000.0,
            use_sliding_window=False,
            tie_word_embeddings=False,
        )
        hf = transformers.Qwen2ForCausalLM(hf_cfg)
        hf.load_state_dict(sd, strict=True)


class TestHFParity:
    """Numerics pinned against transformers' torch Qwen2 (fwd logits)."""

    @pytest.fixture(scope="class")
    def pair(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        initialize_registries()
        hf_cfg = transformers.Qwen2Config(
            vocab_size=V,
            hidden_size=D,
            intermediate_size=F,
            num_hidden_layers=2,
            num_attention_heads=H,
            num_key_value_heads=2,
            max_position_embeddings=T,
            rms_norm_eps=1e-6,
            rope_theta=10000.0,
            use_sliding_window=False,
            tie_word_embeddings=False,
        )
        torch.manual_seed(0)
        hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()

        cfg = _cfg(n_kv_heads=2, rope_theta=10000.0)
        adapter = get_model_adapter("qwen2")()
        ours = adapter.build_model(cfg)
        p = nn_meta.unbox(
            ours.init(
                jax.random.key(0), jnp.zeros((1, 4), jnp.int32),
                deterministic=True,
            )["params"]
        )

        from llmtrain_tpu.interop import llama_params_from_hf_state_dict

        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        new = llama_params_from_hf_state_dict(sd, p)
        assert jax.tree.map(jnp.shape, p) == jax.tree.map(jnp.shape, new)
        return hf, ours, new

    def test_logits_match(self, pair):
        torch = pytest.importorskip("torch")
        hf, ours, params = pair
        ids = np.asarray([[1, 5, 9, 2, 40, 3, 0, 63]], np.int32)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids).long()).logits.numpy()
        got = np.asarray(
            ours.apply({"params": params}, jnp.asarray(ids), deterministic=True)
        )
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_generate_greedy_runs(self, pair):
        """KV-cache decode works with biased projections end to end."""
        from llmtrain_tpu.generation import generate

        _, ours, params = pair
        out = generate(
            ours,
            params,
            np.array([[1, 2, 3]], np.int32),
            max_new_tokens=4,
            temperature=0.0,
        )
        assert np.asarray(out).shape == (1, 7)
