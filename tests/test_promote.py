"""Promotion lifecycle (llmtrain_tpu/lifecycle/ + `llmtrain promote`).

Tier-1 keeps to pure units — ledger append/replay/torn-tail semantics,
checkpoint-watch edge cases against a real CheckpointManager (manifest
published mid-poll, pre-manifest adoption, heartbeat liveness), the
controller's full decision surface over fakes (promote, eval/SLO/soak
rollback, abort, partial-fleet-swap fleet rollback, SIGKILL-replay
idempotence), the /healthz 503 contract, and goodput attribution of the
promotions ledger. The chaos drill that compiles the tiny model — a
poisoned checkpoint canaried on a real 2-replica fleet, detected and
rolled back under live traffic with bitwise parity on admitted params,
then a clean checkpoint promoted fleet-wide — runs under
``@pytest.mark.slow`` via ``make verify-promote``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from llmtrain_tpu.config.schemas import PromoteConfig
from llmtrain_tpu.lifecycle import (
    CheckpointWatcher,
    PromotionController,
    PromotionLedger,
    RouterFleet,
    TERMINAL_DECISIONS,
)

# ---------------------------------------------------------------------------
# promotions.jsonl: append / replay / crash semantics
# ---------------------------------------------------------------------------


class TestPromotionLedger:
    def test_append_assigns_seq_and_fsyncs_one_line_each(self, tmp_path):
        ledger = PromotionLedger(tmp_path / "promotions.jsonl")
        ledger.append("canary_start", step=10, checkpoint="a.ckpt")
        ledger.append("promote", step=10, checkpoint="a.ckpt", scores={"x": 1})
        entries = ledger.entries()
        assert [e["seq"] for e in entries] == [0, 1]
        assert entries[1]["scores"] == {"x": 1}
        # A fresh reader resumes the seq counter, never reuses one.
        again = PromotionLedger(ledger.path)
        again.append("canary_start", step=20)
        assert again.entries()[-1]["seq"] == 2

    def test_unknown_decision_refused(self, tmp_path):
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        with pytest.raises(ValueError, match="unknown promotion decision"):
            ledger.append("demote", step=1)

    def test_torn_tail_line_is_skipped_not_fatal(self, tmp_path):
        """A SIGKILL mid-write leaves at worst one torn trailing line;
        replay must skip it and keep every committed decision."""
        path = tmp_path / "promotions.jsonl"
        ledger = PromotionLedger(path)
        ledger.append("canary_start", step=5)
        ledger.append("rollback", step=5, reason="eval_regression")
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"seq": 2, "decision": "prom')  # torn mid-json
        replay = PromotionLedger(path)
        assert [e["decision"] for e in replay.entries()] == [
            "canary_start", "rollback",
        ]
        assert replay.decided_steps() == {5}

    def test_decided_steps_are_terminal_only(self, tmp_path):
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        ledger.append("canary_start", step=5)
        ledger.append("rollback", step=5, reason="slo")
        ledger.append("canary_start", step=9)
        ledger.append("promote", step=9)
        ledger.append("canary_start", step=12)  # open — still being judged
        assert ledger.decided_steps() == {5, 9}
        assert "canary_start" not in TERMINAL_DECISIONS

    def test_pending_canary_is_the_unclosed_window(self, tmp_path):
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        assert ledger.pending_canary() is None
        ledger.append("canary_start", step=5)
        ledger.append("abort", step=5, reason="load failed")
        assert ledger.pending_canary() is None  # closed by a terminal
        ledger.append("canary_start", step=9)
        pending = ledger.pending_canary()
        assert pending is not None and pending["step"] == 9

    def test_last_promoted_and_summary(self, tmp_path):
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        ledger.append("canary_start", step=5)
        ledger.append("promote", step=5, checkpoint="s5.ckpt")
        ledger.append("canary_start", step=9)
        ledger.append("rollback", step=9, reason="eval")
        assert ledger.last_promoted()["checkpoint"] == "s5.ckpt"
        s = ledger.summary()
        assert s["entries"] == 4
        assert s["decisions"] == {
            "canary_start": 2, "promote": 1, "rollback": 1, "abort": 0,
        }
        assert s["last_promoted_step"] == 5
        assert s["last_promoted_checkpoint"] == "s5.ckpt"


# ---------------------------------------------------------------------------
# checkpoint-stream watcher: durable-artifact edge cases
# ---------------------------------------------------------------------------


def _host_state() -> dict:
    return {
        "params": {"w": np.arange(4, dtype=np.float32)},
        "opt_state": {"m": np.zeros(4, dtype=np.float32)},
    }


def _commit(ckpt_dir: Path, step: int) -> Path:
    from llmtrain_tpu.training.checkpoint import CheckpointManager

    return CheckpointManager(ckpt_dir, keep_last_k=10).save_host(
        step, _host_state(), {}
    )


class TestCheckpointWatcher:
    def test_empty_dir_polls_none(self, tmp_path):
        watcher = CheckpointWatcher(tmp_path / "run" / "checkpoints")
        assert watcher.poll() is None

    def test_poll_sees_commits_and_respects_the_floor(self, tmp_path):
        ckpt_dir = tmp_path / "checkpoints"
        _commit(ckpt_dir, 10)
        watcher = CheckpointWatcher(ckpt_dir)
        ckpt, step = watcher.poll()
        assert step == 10 and ckpt.name == "step_000010.ckpt"
        assert watcher.poll(after_step=10) is None
        # A manifest published mid-poll appears atomically on the next
        # poll — and the HEAD of the stream wins, intermediate commits
        # that landed while a candidate soaked are skipped.
        _commit(ckpt_dir, 20)
        _commit(ckpt_dir, 30)
        ckpt, step = watcher.poll(after_step=10)
        assert step == 30

    def test_uncommitted_stage_is_invisible(self, tmp_path):
        """A payload whose manifest rename has not landed yet (the
        trainer mid-save, or a kill inside the write window) must never
        be offered as a candidate."""
        ckpt_dir = tmp_path / "checkpoints"
        _commit(ckpt_dir, 10)
        staged = _commit(ckpt_dir, 20)
        from llmtrain_tpu.training.checkpoint import manifest_path

        manifest_path(staged).unlink()  # 20 is now an uncommitted stage
        watcher = CheckpointWatcher(ckpt_dir)
        ckpt, step = watcher.poll()
        assert step == 10, "uncommitted stage leaked into selection"

    def test_pre_manifest_dir_is_adopted(self, tmp_path):
        """A run dir holding only pre-manifest checkpoints (legacy
        layout / hand-assembled snapshot) is adopted by its first scan
        and its newest verifying payload becomes the candidate."""
        from llmtrain_tpu.training.checkpoint import manifest_path

        ckpt_dir = tmp_path / "checkpoints"
        a = _commit(ckpt_dir, 5)
        b = _commit(ckpt_dir, 8)
        manifest_path(a).unlink()
        manifest_path(b).unlink()
        watcher = CheckpointWatcher(ckpt_dir)
        ckpt, step = watcher.poll()
        assert step == 8
        # Adoption synthesized a manifest: the next scan is manifest-driven.
        assert manifest_path(b).is_file()

    def test_finished_and_heartbeat_liveness(self, tmp_path):
        run_dir = tmp_path / "run"
        ckpt_dir = run_dir / "checkpoints"
        ckpt_dir.mkdir(parents=True)
        watcher = CheckpointWatcher(ckpt_dir, run_dir=run_dir)
        assert not watcher.training_finished()
        # No heartbeat at all counts dead: a static adopted snapshot
        # drains its head commit, then promote exits instead of waiting.
        assert watcher.heartbeat_age_sec() is None
        assert not watcher.training_alive(stale_sec=3600.0)
        hb = run_dir / "heartbeat"
        hb.write_text("1")
        assert watcher.training_alive(stale_sec=60.0)
        # Stale heartbeat: mtime pushed into the past.
        old = time.time() - 120.0
        os.utime(hb, (old, old))
        assert not watcher.training_alive(stale_sec=60.0)
        assert watcher.heartbeat_age_sec() >= 100.0
        # Per-rank heartbeat.rN files count too; freshest wins.
        (run_dir / "heartbeat.r1").write_text("1")
        assert watcher.training_alive(stale_sec=60.0)
        (run_dir / "report.json").write_text("{}")
        assert watcher.training_finished()


# ---------------------------------------------------------------------------
# controller decision surface over fakes
# ---------------------------------------------------------------------------


_SOAK_OK = {
    "requests": 4, "completed": 4, "failed": 0, "timed_out": 0,
    "ttft_p50_ms": 8.0, "ttft_p95_ms": 10.0,
    "per_token_p50_ms": 4.0, "per_token_p99_ms": 5.0,
}


class ScriptedWatcher:
    """Head-of-stream poll over a fixed (path, step) script."""

    def __init__(self, events, *, finished=True, alive=False):
        self.events = list(events)
        self.finished = finished
        self.alive = alive

    def poll(self, *, after_step=-1):
        newer = [(p, s) for p, s in self.events if s > after_step]
        if not newer:
            return None
        path, step = newer[-1]
        return Path(path), step

    def training_finished(self):
        return self.finished

    def training_alive(self, *, stale_sec):
        return self.alive


class SequentialWatcher:
    """Commits arrive one at a time, like a live training run: the next
    event is revealed only after the previous step has been decided."""

    def __init__(self, events):
        self.events = list(events)

    def poll(self, *, after_step=-1):
        while self.events and self.events[0][1] <= after_step:
            self.events.pop(0)
        if not self.events:
            return None
        path, step = self.events[0]
        return Path(path), step

    def training_finished(self):
        return not self.events

    def training_alive(self, *, stale_sec):
        return True


class FakeFleet:
    """The controller's fleet verbs, with scriptable soak/swap outcomes."""

    def __init__(self, n=2, baseline="base-params"):
        self.replica_count = n
        self.params = [baseline] * n
        self.steps: list[int | None] = [None] * n
        self.calls: list[tuple] = []
        self.soak_by_idx: dict[int, dict] = {}
        self.fleet_swap_errors: set[int] = set()
        self.canary_swap_error: str | None = None
        self.split: tuple | None = None

    def canary_swap(self, idx, params, step, ckpt):
        self.calls.append(("canary_swap", idx, step))
        if self.canary_swap_error is not None:
            raise RuntimeError(self.canary_swap_error)
        self.params[idx] = params
        self.steps[idx] = step

    def fleet_swap(self, params, step, ckpt):
        self.calls.append(("fleet_swap", step))
        out = []
        for i in range(self.replica_count):
            if i in self.fleet_swap_errors:
                out.append({"replica": f"r{i}", "error": "reload exploded"})
            else:
                self.params[i] = params
                self.steps[i] = step
                out.append({"replica": f"r{i}", "step": step})
        return out

    def set_traffic_split(self, idx, frac, seed):
        self.split = (idx, frac, seed)
        self.calls.append(("set_split", idx, frac))

    def clear_traffic_split(self):
        self.split = None
        self.calls.append(("clear_split",))

    def param_steps(self):
        return list(self.steps)

    def soak(self, idx, *, requests, seed, timeout_sec):
        self.calls.append(("soak", idx, seed))
        out = dict(_SOAK_OK)
        out.update(self.soak_by_idx.get(idx, {}))
        return out


def _cfg(**kw) -> PromoteConfig:
    base = dict(poll_sec=0.001, idle_timeout_sec=5.0, soak_requests=4)
    base.update(kw)
    return PromoteConfig(**base)


def _controller(cfg, watcher, fleet, ledger, **kw):
    kw.setdefault("baseline_params", "base-params")
    kw.setdefault("baseline_step", 0)
    kw.setdefault("baseline_checkpoint", "base.ckpt")
    kw.setdefault("sleep", lambda s: None)
    return PromotionController(
        cfg=cfg, watcher=watcher, fleet=fleet, ledger=ledger, **kw
    )


class TestPromotionController:
    def test_clean_candidate_promotes_fleet_wide(self, tmp_path):
        from llmtrain_tpu.telemetry.prometheus import render_prometheus
        from llmtrain_tpu.telemetry.registry import MetricsRegistry

        fleet = FakeFleet()
        ledger = PromotionLedger(tmp_path / "promotions.jsonl")
        registry = MetricsRegistry(None)
        losses = {"base.ckpt": 2.0, "s10.ckpt": 1.98}
        ctl = _controller(
            _cfg(), ScriptedWatcher([("s10.ckpt", 10)]), fleet, ledger,
            load_params=lambda p: f"params-{p.name}",
            evaluator=lambda p: losses[p.name],
            registry=registry,
        )
        result = ctl.run()
        assert result.status == "training_finished"
        assert result.promotions == 1 and result.rollbacks == 0
        assert result.last_promoted_step == 10
        assert fleet.params == ["params-s10.ckpt"] * 2
        assert fleet.param_steps() == [10, 10]
        decisions = [e["decision"] for e in ledger.entries()]
        assert decisions == ["canary_start", "promote"]
        promo = ledger.entries()[-1]
        assert promo["scores"]["eval_loss"] == 1.98
        assert promo["scores"]["baseline_eval_loss"] == 2.0
        assert all("error" not in r for r in promo["scores"]["fleet_swap"])
        # Soak ran on the canary AND a reference replica, same seed.
        soaks = [c for c in fleet.calls if c[0] == "soak"]
        assert [c[1] for c in soaks] == [0, 1]
        assert soaks[0][2] == soaks[1][2]
        # Gauges + counters reach Prometheus under llmtrain_promote_*.
        text = render_prometheus(
            dict(registry.latest()), registry.counters(), {}
        )
        assert "llmtrain_promote_promotions_total" in text
        assert "llmtrain_promote_last_promoted_step 10.0" in text
        assert "llmtrain_promote_candidates_total 1.0" in text

    def test_eval_regression_rolls_the_canary_back(self, tmp_path):
        fleet = FakeFleet()
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        losses = {"base.ckpt": 2.0, "bad.ckpt": 2.5}
        ctl = _controller(
            _cfg(max_eval_loss_delta=0.05),
            ScriptedWatcher([("bad.ckpt", 10)]), fleet, ledger,
            load_params=lambda p: f"params-{p.name}",
            evaluator=lambda p: losses[p.name],
        )
        result = ctl.run()
        assert result.promotions == 0 and result.rollbacks == 1
        entry = ledger.entries()[-1]
        assert entry["decision"] == "rollback"
        assert entry["reason"].startswith("eval_regression")
        assert entry["scores"]["eval_loss_delta"] == pytest.approx(0.5)
        # Canary restored to the promoted baseline; fleet never swapped.
        assert fleet.params == ["base-params"] * 2
        assert fleet.steps[0] == 0
        assert not any(c[0] == "fleet_swap" for c in fleet.calls)
        # The rollback restore happened INSIDE the traffic-split window:
        # a regressed candidate must not rejoin live placement first.
        restore = fleet.calls.index(("canary_swap", 0, 0))
        assert fleet.calls[restore + 1 :].count(("clear_split",)) == 1

    def test_slo_regression_rolls_back(self, tmp_path):
        fleet = FakeFleet()
        fleet.soak_by_idx[0] = {"ttft_p95_ms": 100.0}  # reference: 10ms
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        ctl = _controller(
            _cfg(ttft_p95_slowdown=2.0),
            ScriptedWatcher([("slow.ckpt", 10)]), fleet, ledger,
        )
        result = ctl.run()
        assert result.rollbacks == 1
        assert ledger.entries()[-1]["reason"].startswith(
            "slo_regression: ttft_p95_ms"
        )

    def test_soak_failures_fail_fast_before_eval(self, tmp_path):
        fleet = FakeFleet()
        fleet.soak_by_idx[0] = {"failed": 2, "completed": 2}
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        evals = []
        ctl = _controller(
            _cfg(allow_failed_requests=0),
            ScriptedWatcher([("crashy.ckpt", 10)]), fleet, ledger,
            evaluator=lambda p: evals.append(p) or 2.0,
        )
        result = ctl.run()
        assert result.rollbacks == 1
        assert ledger.entries()[-1]["reason"] == "canary_request_failures: 2"
        assert evals == []  # the expensive eval never ran

    def test_unloadable_checkpoint_aborts_without_touching_the_fleet(
        self, tmp_path
    ):
        fleet = FakeFleet()
        ledger = PromotionLedger(tmp_path / "p.jsonl")

        def load(_path):
            raise ValueError("truncated msgpack")

        ctl = _controller(
            _cfg(), ScriptedWatcher([("torn.ckpt", 10)]), fleet, ledger,
            load_params=load,
        )
        result = ctl.run()
        assert result.aborts == 1 and result.rollbacks == 0
        assert ledger.entries()[-1]["decision"] == "abort"
        assert "truncated msgpack" in ledger.entries()[-1]["reason"]
        assert not any(
            c[0] in ("canary_swap", "fleet_swap") for c in fleet.calls
        )

    def test_partial_fleet_swap_rolls_the_whole_fleet_back(self, tmp_path):
        """The mixed-epoch hazard: replica 1 admits the candidate,
        replica 0 fails its reload. The controller must converge DOWN —
        every replica back to the promoted baseline."""
        fleet = FakeFleet(n=3)
        fleet.fleet_swap_errors = {0}
        ledger = PromotionLedger(tmp_path / "p.jsonl")
        ctl = _controller(
            _cfg(canary_replica=1),
            ScriptedWatcher([("s10.ckpt", 10)]), fleet, ledger,
            load_params=lambda p: "cand-params",
        )
        result = ctl.run()
        assert result.promotions == 0 and result.rollbacks == 1
        entry = ledger.entries()[-1]
        assert entry["decision"] == "rollback"
        assert entry["reason"] == "partial_fleet_swap: r0"
        assert len(entry["scores"]["fleet_swap"]) == 3
        assert len(entry["scores"]["fleet_restore"]) == 3
        # r0's restore also errored (scripted), but r1/r2 converged back.
        assert fleet.params[1] == "base-params"
        assert fleet.params[2] == "base-params"

    def test_replay_is_idempotent_after_sigkill(self, tmp_path):
        """Run, 'SIGKILL', re-run over the same stream: decided steps are
        never re-judged and the ledger gains no duplicate entries."""
        ledger_path = tmp_path / "promotions.jsonl"
        events = [("s10.ckpt", 10)]
        ctl = _controller(
            _cfg(), ScriptedWatcher(events), FakeFleet(),
            PromotionLedger(ledger_path),
            load_params=lambda p: "cand",
        )
        assert ctl.run().promotions == 1
        before = (tmp_path / "promotions.jsonl").read_text()
        # A new process replays the ledger; step 10 is already decided.
        fleet2 = FakeFleet()
        ctl2 = _controller(
            _cfg(), ScriptedWatcher(events), fleet2,
            PromotionLedger(ledger_path),
            load_params=lambda p: "cand",
        )
        result = ctl2.run()
        assert result.status == "training_finished"
        assert result.promotions == 0
        assert (tmp_path / "promotions.jsonl").read_text() == before
        assert fleet2.calls == []  # the fleet was never touched

    def test_pending_canary_window_is_reopened_on_resume(self, tmp_path):
        """A promote SIGKILLed between canary_start and its terminal
        decision must re-judge that candidate, not skip it."""
        ledger_path = tmp_path / "promotions.jsonl"
        seed = PromotionLedger(ledger_path)
        seed.append("canary_start", step=10, checkpoint="s10.ckpt")
        fleet = FakeFleet()
        ctl = _controller(
            _cfg(), ScriptedWatcher([("s10.ckpt", 10)]), fleet,
            PromotionLedger(ledger_path),
            load_params=lambda p: "cand",
            baseline_step=10,  # resume floor would otherwise skip step 10
        )
        result = ctl.run()
        assert result.promotions == 1
        decisions = [e["decision"] for e in PromotionLedger(ledger_path).entries()]
        # The second canary_start is the resume marker.
        assert decisions == ["canary_start", "canary_start", "promote"]

    def test_training_death_exits_with_taxonomy_status(self, tmp_path):
        now = [0.0]

        def clock():
            now[0] += 2.0
            return now[0]

        ctl = _controller(
            _cfg(idle_timeout_sec=5.0),
            ScriptedWatcher([], finished=False, alive=False),
            FakeFleet(), PromotionLedger(tmp_path / "p.jsonl"),
            clock=clock,
        )
        result = ctl.run()
        assert result.status == "training_dead"
        assert result.promotions == 0

    def test_live_heartbeat_keeps_an_idle_stream_waiting(self, tmp_path):
        """Heartbeat fresh but no commits: promote keeps polling (the
        trainer is between save_every_steps windows), then exits cleanly
        when report.json lands."""
        watcher = ScriptedWatcher([], finished=False, alive=True)
        polls = [0]

        def sleep(_s):
            polls[0] += 1
            if polls[0] >= 3:
                watcher.finished = True

        ctl = _controller(
            _cfg(idle_timeout_sec=0.5),
            watcher, FakeFleet(), PromotionLedger(tmp_path / "p.jsonl"),
            clock=lambda: polls[0] * 10.0,  # way past idle_timeout
            sleep=sleep,
        )
        assert ctl.run().status == "training_finished"
        assert polls[0] == 3

    def test_max_promotions_caps_the_run(self, tmp_path):
        ctl = _controller(
            _cfg(max_promotions=1),
            ScriptedWatcher([("a.ckpt", 10), ("b.ckpt", 20)], finished=False),
            FakeFleet(), PromotionLedger(tmp_path / "p.jsonl"),
            load_params=lambda p: "cand",
        )
        result = ctl.run()
        assert result.status == "max_promotions"
        # Head-of-stream: the single promotion judged step 20, not 10.
        assert result.promotions == 1 and result.last_promoted_step == 20

    def test_canary_replica_must_exist(self, tmp_path):
        with pytest.raises(ValueError, match="out of range"):
            _controller(
                _cfg(canary_replica=2), ScriptedWatcher([]), FakeFleet(n=2),
                PromotionLedger(tmp_path / "p.jsonl"),
            )


class TestPromoteConfig:
    def test_defaults_and_strictness(self):
        cfg = PromoteConfig()
        assert cfg.poll_sec == 2.0 and cfg.max_promotions == 0
        with pytest.raises(Exception):
            PromoteConfig(promote_every=3)  # unknown key: strict schema

    def test_bounds(self):
        with pytest.raises(Exception):
            PromoteConfig(traffic_split=1.5)
        with pytest.raises(Exception):
            PromoteConfig(ttft_p95_slowdown=1.0)  # must be > 1x
        assert PromoteConfig(ttft_p95_slowdown=None).ttft_p95_slowdown is None

    def test_rides_in_run_config(self):
        from llmtrain_tpu.config.schemas import RunConfig

        cfg = RunConfig.model_validate(
            {
                "run": {"name": "t", "seed": 0, "device": "cpu"},
                "model": {"name": "dummy_gpt"},
                "data": {"name": "dummy_text"},
                "trainer": {"max_steps": 1, "warmup_steps": 0},
                "promote": {"max_promotions": 2, "traffic_split": 0.5},
            }
        )
        assert cfg.promote.max_promotions == 2
        assert cfg.promote.traffic_split == 0.5


# ---------------------------------------------------------------------------
# /healthz liveness contract (serving/http.py + scheduler beacon)
# ---------------------------------------------------------------------------


class TestHealthzLiveness:
    def _state(self, scheduler, stale=30.0):
        from llmtrain_tpu.serving.http import ServerState

        return ServerState(
            model=object(), params=None, tokenizer=None, step=0,
            checkpoint="c", scheduler=scheduler, liveness_stale_sec=stale,
        )

    def test_scheduler_alive_predicate(self):
        from llmtrain_tpu.serving.scheduler import ContinuousBatchingScheduler

        sched = object.__new__(ContinuousBatchingScheduler)
        sched._thread = None
        sched._beacon = time.monotonic()
        assert sched.alive(0.001)  # never started: tests drive step()
        dead = threading.Thread(target=lambda: None)
        dead.start()
        dead.join()
        sched._thread = dead
        assert not sched.alive(3600.0)
        live = threading.Thread(target=time.sleep, args=(1.0,))
        live.start()
        try:
            sched._thread = live
            sched._beacon = time.monotonic()
            assert sched.alive(30.0)
            sched._beacon = time.monotonic() - 100.0
            assert not sched.alive(30.0)  # wedged: thread up, beacon stale
        finally:
            live.join()

    def test_healthz_503_on_dead_or_stale_scheduler(self):
        from llmtrain_tpu.serving.http import _handle_health

        class Sched:
            def __init__(self, ok):
                self.ok = ok
                self.asked_with = None

            def stats(self):
                return {"policy": "paged"}

            def alive(self, stale_sec):
                self.asked_with = stale_sec
                return self.ok

        ok = Sched(True)
        code, payload = _handle_health(self._state(ok, stale=45.0))
        assert code == 200 and payload["status"] == "ok"
        assert ok.asked_with == 45.0  # serving.liveness_stale_sec flows in
        code, payload = _handle_health(self._state(Sched(False)))
        assert code == 503 and payload["status"] == "unhealthy"
        assert "scheduler" in payload  # stats still attached for debugging

    def test_healthz_503_when_the_whole_fleet_is_evicted(self):
        from llmtrain_tpu.serving.http import _handle_health

        class RouterLike:  # no alive(): health = any replica healthy
            def stats(self):
                return {"router": {"replicas_healthy": 0}}

        code, payload = _handle_health(self._state(RouterLike()))
        assert code == 503

        class HealthyRouter:
            def stats(self):
                return {"router": {"replicas_healthy": 2}}

        code, _ = _handle_health(self._state(HealthyRouter()))
        assert code == 200


# ---------------------------------------------------------------------------
# goodput attribution of the promotions ledger
# ---------------------------------------------------------------------------


class TestGoodputPromotions:
    def _timeline(self, run_dir: Path) -> None:
        events = [
            {
                "name": "segment_start", "ph": "seg", "segment_id": 0,
                "start_unix_time": 1000.0, "process_index": 0, "pid": 1,
            },
            {
                "name": "host_dispatch", "cat": "train", "ph": "X",
                "ts_us": int(2e6), "dur_us": int(1e6), "step": 1,
                "thread": "MainThread",
            },
            {
                "name": "segment_end", "ph": "seg", "segment_id": 0,
                "end_unix_time": 1010.0,
            },
        ]
        path = run_dir / "telemetry" / "timeline.jsonl"
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(
            "".join(json.dumps(e) + "\n" for e in events), encoding="utf-8"
        )

    def test_ledger_attributed_and_rendered(self, tmp_path):
        from llmtrain_tpu.telemetry.goodput import (
            compute_goodput,
            goodput_gauges,
            render_goodput_md,
        )

        self._timeline(tmp_path)
        ledger = PromotionLedger(tmp_path / "promotions.jsonl")
        ledger.append("canary_start", step=10, checkpoint="a.ckpt")
        ledger.append("rollback", step=10, reason="eval_regression: 0.5")
        ledger.append("canary_start", step=20, checkpoint="b.ckpt")
        ledger.append("promote", step=20, checkpoint="b.ckpt")
        out = compute_goodput(tmp_path)
        assert out is not None
        block = out["promotions"]
        assert block["decisions"]["promote"] == 1
        assert block["decisions"]["rollback"] == 1
        assert block["last_promoted_step"] == 20
        assert [e["decision"] for e in block["events"]] == [
            "canary_start", "rollback", "canary_start", "promote",
        ]
        gauges = goodput_gauges(out)
        assert gauges["goodput/promotions_promote"] == 1.0
        assert gauges["goodput/promoted_step"] == 20.0
        md = render_goodput_md(out)
        assert "promote" in md and "eval_regression" in md

    def test_no_ledger_no_block(self, tmp_path):
        from llmtrain_tpu.telemetry.goodput import compute_goodput

        self._timeline(tmp_path)
        out = compute_goodput(tmp_path)
        assert out is not None and "promotions" not in out


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------


class TestPromoteCLI:
    def test_parser_accepts_promote(self):
        from llmtrain_tpu.cli import build_parser

        args = build_parser().parse_args(
            [
                "promote", "--config", "c.yaml", "--watch", "runs/r1",
                "--replicas", "3", "--max-promotions", "2", "--no-eval",
                "--json",
            ]
        )
        assert args.command == "promote"
        assert args.watch == "runs/r1"
        assert args.replicas == 3
        assert args.max_promotions == 2
        assert args.no_eval is True

    def test_preset_parses_with_promote_section(self):
        from llmtrain_tpu.config import load_and_validate_config

        out = load_and_validate_config(
            "configs/presets/gpt_promote_smoke.yaml"
        )
        cfg = out[0]
        assert cfg.promote.max_promotions == 1
        assert cfg.promote.traffic_split == 0.25
        assert cfg.serving.router.replicas == 2


# ---------------------------------------------------------------------------
# slow: the chaos drill — real engines, poisoned canary, live traffic
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestPromoteDrill:
    def test_poisoned_canary_rolls_back_then_clean_promotes(self, tmp_path):
        """The acceptance drill (ISSUE 16): while live traffic flows
        through a real 2-replica router, a poisoned checkpoint is
        canaried, detected by the eval gate, and rolled back — zero
        failed live requests, bitwise parity on the params each request
        was ADMITTED under, poisoned params never admitted for live
        traffic. A clean checkpoint then promotes fleet-wide and the
        fleet converges (epoch_divergence back to 0), with every
        transition durable in promotions.jsonl and visible as
        llmtrain_promote_* gauges."""
        import jax

        from llmtrain_tpu.serving import (
            ContinuousBatchingScheduler,
            InProcessReplica,
            PagedDecodeEngine,
            build_requests,
            run_loadgen,
        )
        from llmtrain_tpu.serving.router import ReplicaRouter
        from llmtrain_tpu.telemetry.prometheus import render_prometheus
        from llmtrain_tpu.telemetry.registry import MetricsRegistry
        from tests.test_router import _reference, _tiny_stack

        model, params, params2 = _tiny_stack()
        # "Poisoned": structurally loadable, numerically garbage — the
        # shape of a bad data batch or an optimizer blowup.
        poisoned = jax.tree.map(lambda x: x * 0.0 + 1e3, params2)

        def mk(i):
            eng = PagedDecodeEngine(
                model, params, block_tokens=4, max_batch_slots=4,
                prompt_buckets=[8, 16], batch_buckets=[2, 4],
                prefix_cache=True,
            )
            return InProcessReplica(
                ContinuousBatchingScheduler(eng).start(), f"replica{i}"
            )

        registry = MetricsRegistry(None)
        router = ReplicaRouter([mk(0), mk(1)], registry=registry)
        try:
            params_by_name = {"poison.ckpt": poisoned, "clean.ckpt": params2}
            losses = {"base.ckpt": 2.0, "poison.ckpt": 11.0, "clean.ckpt": 1.9}
            ledger = PromotionLedger(tmp_path / "promotions.jsonl")
            fleet = RouterFleet(router, vocab_size=32, max_new_tokens=4)
            ctl = PromotionController(
                cfg=PromoteConfig(
                    poll_sec=0.01,
                    soak_requests=4,
                    soak_timeout_sec=120.0,
                    soak_seed=7,
                    traffic_split=0.0,  # live traffic never meets the canary
                    max_eval_loss_delta=0.05,
                    ttft_p95_slowdown=None,  # timing gates are unit-tested;
                    per_token_p99_slowdown=None,  # CPU CI timing is noise
                ),
                watcher=SequentialWatcher(
                    [("poison.ckpt", 100), ("clean.ckpt", 200)]
                ),
                fleet=fleet,
                ledger=ledger,
                baseline_params=params,
                baseline_step=0,
                baseline_checkpoint="base.ckpt",
                load_params=lambda p: params_by_name[p.name],
                evaluator=lambda p: losses[p.name],
                registry=registry,
            )

            live = build_requests(
                num_requests=12, seed=3, vocab_size=32,
                prompt_tokens_min=4, prompt_tokens_max=8, max_new_tokens=4,
            )
            block: dict = {}

            def drive():
                block.update(
                    run_loadgen(router, live, rate_rps=30.0, seed=5,
                                timeout_sec=300.0)
                )

            t = threading.Thread(target=drive)
            t.start()
            result = ctl.run()
            t.join()

            # Decisions: poisoned rolled back, clean promoted.
            assert result.status == "training_finished"
            assert result.promotions == 1 and result.rollbacks == 1
            assert result.last_promoted_step == 200
            entries = ledger.entries()
            assert [(e["decision"], e["step"]) for e in entries] == [
                ("canary_start", 100), ("rollback", 100),
                ("canary_start", 200), ("promote", 200),
            ]
            assert entries[1]["reason"].startswith("eval_regression")
            # Soak itself saw zero failures both rounds (the canary
            # serves; it just serves garbage).
            for e in entries:
                for side in ("canary", "reference"):
                    soak = e.get("scores", {}).get(side)
                    if soak:
                        assert soak["failed"] == 0 and soak["timed_out"] == 0

            # Live traffic: zero failures, bitwise parity on admitted
            # params, poisoned step NEVER admitted for a live request.
            assert block["requests"]["failed"] == 0
            assert block["requests"]["timed_out"] == 0
            assert block["requests"]["completed"] == len(live)
            by_step = {0: params, 200: params2, None: params}
            for r in live:
                assert r.params_step != 100, "poisoned params served live"
                assert r.tokens == _reference(model, by_step[r.params_step], r)

            # The fleet converged on the promoted step.
            assert fleet.param_steps() == [200, 200]
            stats = router.stats()
            assert stats["router"]["epoch_divergence"] == 0
            assert stats["router"]["canary"]["index"] is None
            assert router.canary_index is None

            text = render_prometheus(
                dict(registry.latest()), registry.counters(), {}
            )
            assert "llmtrain_promote_promotions_total 1.0" in text
            assert "llmtrain_promote_rollbacks_total 1.0" in text
            assert "llmtrain_promote_last_promoted_step 200.0" in text
            assert "llmtrain_router_epoch_divergence 0.0" in text
        finally:
            router.close()
