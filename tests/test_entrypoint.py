"""k8s/entrypoint.sh rank/coordinator derivation (VERDICT r2 #5).

The one shell component on the critical multi-host path (reference
counterpart k8s/entrypoint.sh:42-82): these subprocess tests run the real
script with a stubbed environment — a fake ``python`` that dumps the
exported JAX_* env and argv instead of training, a fake ``curl`` serving
a canned pods response, and a temp serviceaccount dir — and assert the
env contract that llmtrain_tpu.distributed.setup_distributed consumes.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from pathlib import Path

import pytest

SCRIPT = Path(__file__).resolve().parents[1] / "k8s" / "entrypoint.sh"

pytestmark = pytest.mark.skipif(
    shutil.which("bash") is None, reason="requires bash"
)

FAKE_PYTHON = """#!/usr/bin/env bash
echo "ARGS=$*"
echo "JAX_PROCESS_ID=${JAX_PROCESS_ID:-}"
echo "JAX_NUM_PROCESSES=${JAX_NUM_PROCESSES:-}"
echo "JAX_COORDINATOR_ADDRESS=${JAX_COORDINATOR_ADDRESS:-}"
"""

FAKE_CURL = """#!/usr/bin/env bash
cat "$FAKE_PODS_JSON"
"""


def _stub_bin(tmp_path: Path, *, with_curl: bool = False) -> Path:
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir(exist_ok=True)
    (bin_dir / "python").write_text(FAKE_PYTHON)
    (bin_dir / "python").chmod(0o755)
    if with_curl:
        (bin_dir / "curl").write_text(FAKE_CURL)
        (bin_dir / "curl").chmod(0o755)
    return bin_dir


def _sa_dir(tmp_path: Path) -> Path:
    sa = tmp_path / "sa"
    sa.mkdir(exist_ok=True)
    (sa / "namespace").write_text("trainer-ns")
    (sa / "token").write_text("fake-token")
    (sa / "ca.crt").write_text("fake-ca")
    return sa


def _run(tmp_path: Path, env: dict[str, str], *, with_curl: bool = False):
    bin_dir = _stub_bin(tmp_path, with_curl=with_curl)
    full_env = {
        "PATH": f"{bin_dir}{os.pathsep}{os.environ['PATH']}",
        "HOME": str(tmp_path),
        **env,
    }
    return subprocess.run(
        ["bash", str(SCRIPT)],
        capture_output=True,
        text=True,
        env=full_env,
        timeout=120,
    )


def _parse(stdout: str) -> dict[str, str]:
    out = {}
    for line in stdout.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            out[k] = v
    return out


class TestCoordinatorRank:
    def test_rank0_exports_own_pod_ip(self, tmp_path):
        proc = _run(
            tmp_path,
            {
                "JOB_COMPLETION_INDEX": "0",
                "NUM_PROCESSES": "4",
                "POD_IP": "10.0.0.5",
                "LLMTRAIN_CONFIG": "/config/train.yaml",
            },
        )
        assert proc.returncode == 0, proc.stderr
        got = _parse(proc.stdout)
        assert got["JAX_PROCESS_ID"] == "0"
        assert got["JAX_NUM_PROCESSES"] == "4"
        assert got["JAX_COORDINATOR_ADDRESS"] == "10.0.0.5:29500"
        assert got["ARGS"] == "-m llmtrain_tpu train --config /config/train.yaml"

    def test_coordinator_port_override(self, tmp_path):
        proc = _run(
            tmp_path,
            {
                "JOB_COMPLETION_INDEX": "0",
                "NUM_PROCESSES": "2",
                "POD_IP": "10.0.0.5",
                "COORDINATOR_PORT": "19999",
            },
        )
        assert proc.returncode == 0, proc.stderr
        assert _parse(proc.stdout)["JAX_COORDINATOR_ADDRESS"] == "10.0.0.5:19999"

    def test_rank0_requires_pod_ip(self, tmp_path):
        proc = _run(
            tmp_path, {"JOB_COMPLETION_INDEX": "0", "NUM_PROCESSES": "2"}
        )
        assert proc.returncode != 0
        assert "POD_IP" in proc.stderr

    def test_run_id_enables_auto_resume(self, tmp_path):
        proc = _run(
            tmp_path,
            {
                "JOB_COMPLETION_INDEX": "0",
                "NUM_PROCESSES": "2",
                "POD_IP": "10.0.0.5",
                "LLMTRAIN_RUN_ID": "stable-run",
            },
        )
        assert proc.returncode == 0, proc.stderr
        assert (
            "--run-id stable-run --auto-resume" in _parse(proc.stdout)["ARGS"]
        )


class TestWorkerRank:
    def _worker_env(self, tmp_path, pods_json: dict) -> dict[str, str]:
        pods = tmp_path / "pods.json"
        pods.write_text(json.dumps(pods_json))
        return {
            "JOB_COMPLETION_INDEX": "2",
            "NUM_PROCESSES": "4",
            "JOB_NAME": "llmtrain-job",
            "LLMTRAIN_SA_DIR": str(_sa_dir(tmp_path)),
            "FAKE_PODS_JSON": str(pods),
            "LLMTRAIN_DISCOVERY_TRIES": "3",
            "LLMTRAIN_DISCOVERY_SLEEP": "0",
        }

    def test_worker_discovers_coordinator_ip(self, tmp_path):
        env = self._worker_env(
            tmp_path, {"items": [{"status": {"podIP": "10.0.0.9"}}]}
        )
        proc = _run(tmp_path, env, with_curl=True)
        assert proc.returncode == 0, proc.stderr
        got = _parse(proc.stdout)
        assert got["JAX_PROCESS_ID"] == "2"
        assert got["JAX_NUM_PROCESSES"] == "4"
        assert got["JAX_COORDINATOR_ADDRESS"] == "10.0.0.9:29500"

    def test_worker_fails_when_no_coordinator_pod(self, tmp_path):
        env = self._worker_env(tmp_path, {"items": []})
        proc = _run(tmp_path, env, with_curl=True)
        assert proc.returncode != 0
        assert "coordinator discovery failed" in proc.stderr

    def test_worker_waits_for_pending_pod_ip(self, tmp_path):
        """A scheduled-but-not-ready coordinator pod (no podIP yet) keeps
        polling rather than exporting an empty address."""
        env = self._worker_env(tmp_path, {"items": [{"status": {}}]})
        proc = _run(tmp_path, env, with_curl=True)
        assert proc.returncode != 0
        assert proc.stderr.count("waiting for coordinator pod IP") == 3


class TestPreconditions:
    def test_requires_job_completion_index(self, tmp_path):
        proc = _run(tmp_path, {"NUM_PROCESSES": "2"})
        assert proc.returncode == 1
        assert "JOB_COMPLETION_INDEX missing" in proc.stderr

    def test_requires_num_processes(self, tmp_path):
        proc = _run(tmp_path, {"JOB_COMPLETION_INDEX": "0"})
        assert proc.returncode == 1
        assert "NUM_PROCESSES missing" in proc.stderr
