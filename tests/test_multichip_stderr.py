"""Regression: the multichip dryrun legs compile without SPMD
"Involuntary full rematerialization" warnings (VERDICT r4 item 3).

The warning (XLA spmd_partitioner.cc:652) means GSPMD gave up on an
efficient reshard and replicated a tensor — wasted HBM + ICI every step
on real hardware. Round 4's llama leg hit it on {fsdp, tensor, data}
meshes: with the dense loss, the tied-embedding grad's sharding
propagates embed-over-fsdp into the saved final-norm activation, which
GSPMD cannot convert from batch-sharded efficiently. The fix keeps the
lm_head backward on chunked CE's explicit-einsum custom_vjp
(__graft_entry__._dryrun_llama); this test pins the property.

XLA emits the warning from C++ on fd 2, so plain capsys cannot see it —
``capfd`` captures at the file-descriptor level.
"""

from __future__ import annotations

import pytest

import __graft_entry__ as graft
from llmtrain_tpu.registry import initialize_registries


@pytest.mark.slow
def test_llama_fsdp_tensor_data_leg_no_spmd_remat_warning(capfd, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # run dirs land in the test sandbox
    initialize_registries()
    graft._dryrun_llama(8)
    err = capfd.readouterr().err
    assert "Involuntary full rematerialization" not in err, err[-2000:]
    assert "spmd_partitioner" not in err, err[-2000:]
