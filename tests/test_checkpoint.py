"""Checkpoint tests (parity with reference tests/test_checkpoint.py):
file naming, prune-to-k, latest selection, save cadence, resume-spec
resolution, config-mismatch warning, and the flagship resume == continuous
loss-parity guarantee (reference :301-320, tolerance 1e-5)."""

import logging

import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import (
    CheckpointError,
    CheckpointManager,
    Trainer,
    resolve_resume_path,
)


def _cfg(tmp_path=None, **overrides):
    base = {
        "run": {"name": "t", "seed": 7},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 48,
            "n_heads": 2,
            "d_ff": 96,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 20,
            "micro_batch_size": 2,
            "grad_accum_steps": 2,
            "lr": 3e-3,
            "warmup_steps": 0,
            "log_every_steps": 50,
            "eval_every_steps": 50,
            "save_every_steps": 5,
        },
        "mlflow": {"enabled": False},
    }
    if tmp_path is not None:
        base["output"] = {"root_dir": str(tmp_path)}
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _run_dir(tmp_path, name="run_a"):
    d = tmp_path / name
    d.mkdir(parents=True, exist_ok=True)
    return d


class TestCheckpointManager:
    def test_naming_and_cadence(self, tmp_path):
        run_dir = _run_dir(tmp_path)
        cfg = _cfg(tmp_path)
        Trainer(cfg, run_dir, NullTracker(), None).fit()
        names = [p.name for p in (run_dir / "checkpoints").glob("step_*.ckpt")]
        # save_every=5, max=20, keep_last_k default 3 -> steps 10, 15, 20
        assert sorted(names) == ["step_000010.ckpt", "step_000015.ckpt", "step_000020.ckpt"]
        # Every retained checkpoint carries its sha-256 integrity sidecar;
        # pruned ones took their sidecars with them.
        sidecars = [p.name for p in (run_dir / "checkpoints").glob("*.sha256")]
        assert sorted(sidecars) == [n + ".sha256" for n in sorted(names)]

    def test_keep_last_k_override(self, tmp_path):
        run_dir = _run_dir(tmp_path)
        cfg = _cfg(tmp_path, trainer={"extra": {"keep_last_k": 1}})
        Trainer(cfg, run_dir, NullTracker(), None).fit()
        names = [p.name for p in (run_dir / "checkpoints").glob("step_*.ckpt")]
        assert names == ["step_000020.ckpt"]

    def test_latest_checkpoint(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "c")
        assert mgr.latest_checkpoint() is None
        (tmp_path / "c").mkdir()
        for step in (10, 2, 30):
            (tmp_path / "c" / f"step_{step:06d}.ckpt").write_bytes(b"x")
        assert mgr.latest_checkpoint().name == "step_000030.ckpt"

    def test_load_validates_keys(self, tmp_path):
        from flax import serialization

        bad = tmp_path / "step_000001.ckpt"
        bad.write_bytes(serialization.msgpack_serialize({"step": np.int64(1)}))
        with pytest.raises(CheckpointError, match="missing required keys"):
            CheckpointManager.load(bad)

    def test_load_missing_file(self, tmp_path):
        with pytest.raises(CheckpointError, match="not found"):
            CheckpointManager.load(tmp_path / "nope.ckpt")

    def test_async_save_is_durable_after_wait(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "c")
        host_state = {"step": 1, "params": {"w": np.ones(3)}, "opt_state": {}}
        mgr.save_host_async(1, host_state, {"a": 1})
        mgr.wait_pending()
        assert (tmp_path / "c" / "step_000001.ckpt").is_file()
        payload = CheckpointManager.load(tmp_path / "c" / "step_000001.ckpt")
        assert int(payload["step"]) == 1

    def test_async_save_error_surfaces_on_wait(self, tmp_path):
        target = tmp_path / "c"
        target.write_text("a file where the checkpoint dir should be")
        mgr = CheckpointManager(target)
        host_state = {"step": 1, "params": {}, "opt_state": {}}
        mgr.save_host_async(1, host_state, {})
        with pytest.raises(OSError):
            mgr.wait_pending()

    def test_poll_surfaces_async_error_nonblocking(self, tmp_path):
        """poll() re-raises a finished write's error without blocking; the
        trainer calls it each log interval (ADVICE r1 item 4)."""
        import time

        target = tmp_path / "c"
        target.write_text("a file where the checkpoint dir should be")
        mgr = CheckpointManager(target)
        mgr.save_host_async(1, {"step": 1, "params": {}, "opt_state": {}}, {})
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                mgr.poll()
            except OSError:
                break
            time.sleep(0.01)
        else:
            pytest.fail("poll() never surfaced the async write failure")
        mgr.poll()  # drained: subsequent polls are clean no-ops
        mgr.close()

    def test_async_queue_drains_previous_before_next(self, tmp_path, monkeypatch):
        """Single write in flight: queueing save N+1 blocks until N finished."""
        import threading

        release = threading.Event()
        order = []
        seen_at_step2 = []
        real_save = CheckpointManager.save_host

        def slow_save(self, step, host_state, cfg, **kwargs):
            if step == 1:
                release.wait(timeout=10)
            if step == 2:
                # Snapshot on the worker thread itself — no race with main.
                seen_at_step2.append(list(order))
            order.append(step)
            return real_save(self, step, host_state, cfg, **kwargs)

        monkeypatch.setattr(CheckpointManager, "save_host", slow_save)
        mgr = CheckpointManager(tmp_path / "c", keep_last_k=5)
        state = lambda s: {"step": s, "params": {"w": np.full(2, s)}, "opt_state": {}}  # noqa: E731

        mgr.save_host_async(1, state(1), {})  # worker blocked on the event
        # Queueing the second save must first drain save 1; release it from
        # a timer shortly after this call starts waiting.
        threading.Timer(0.2, release.set).start()
        mgr.save_host_async(2, state(2), {})
        mgr.close()
        # Save 1 had fully completed before save 2 began.
        assert seen_at_step2 == [[1]]
        assert order == [1, 2]
        names = sorted(p.name for p in (tmp_path / "c").glob("step_*.ckpt"))
        assert names == ["step_000001.ckpt", "step_000002.ckpt"]


def _host_state(step):
    return {"step": step, "params": {"w": np.full(4, step, np.float32)}, "opt_state": {}}


class TestCheckpointIntegrity:
    """sha-256 sidecars, backward-scanning latest_valid_checkpoint, and the
    prune rule that must never delete the last verified checkpoint."""

    def test_save_writes_verifiable_sidecar(self, tmp_path):
        import hashlib

        mgr = CheckpointManager(tmp_path / "c")
        target = mgr.save_host(1, _host_state(1), {"a": 1})
        side = target.with_name(target.name + ".sha256")
        assert side.is_file()
        digest, name = side.read_text().split()
        assert name == target.name
        assert digest == hashlib.sha256(target.read_bytes()).hexdigest()
        assert mgr.verify(target)

    def test_verify_detects_truncation_and_garbage(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "c")
        target = mgr.save_host(1, _host_state(1), {})
        data = target.read_bytes()
        target.write_bytes(data[: len(data) // 2])
        assert not mgr.verify(target)
        with pytest.raises(CheckpointError, match="integrity"):
            CheckpointManager.load(target)

    def test_verify_without_sidecar_deep_parses(self, tmp_path):
        """Legacy checkpoints (pre-sidecar) verify via a full msgpack parse;
        arbitrary junk does not."""
        mgr = CheckpointManager(tmp_path / "c")
        target = mgr.save_host(1, _host_state(1), {})
        target.with_name(target.name + ".sha256").unlink()
        # New manager: no warm verify cache.
        assert CheckpointManager(tmp_path / "c").verify(target)
        junk = tmp_path / "c" / "step_000002.ckpt"
        junk.write_bytes(b"not a checkpoint")
        assert not CheckpointManager(tmp_path / "c").verify(junk)

    def test_latest_valid_skips_corrupt_newest(self, tmp_path, caplog):
        mgr = CheckpointManager(tmp_path / "c")
        mgr.save_host(1, _host_state(1), {})
        newest = mgr.save_host(2, _host_state(2), {})
        data = newest.read_bytes()
        newest.write_bytes(data[: len(data) // 2])
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            got = CheckpointManager(tmp_path / "c").latest_valid_checkpoint()
        assert got.name == "step_000001.ckpt"
        assert any("integrity" in r.message for r in caplog.records)

    def test_latest_valid_before_step_restriction(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "c", keep_last_k=10)
        for step in (1, 2, 3):
            mgr.save_host(step, _host_state(step), {})
        assert mgr.latest_valid_checkpoint(before_step=3).name == "step_000002.ckpt"
        assert mgr.latest_valid_checkpoint(before_step=1) is None

    def test_resolve_resume_tolerates_truncated_newest(self, tmp_path, caplog):
        """--resume on a dir whose newest checkpoint was cut mid-write must
        warn and restore the previous valid one, not raise mid-restore."""
        d = tmp_path / "ckpts"
        mgr = CheckpointManager(d)
        mgr.save_host(3, _host_state(3), {})
        newest = mgr.save_host(9, _host_state(9), {})
        newest.write_bytes(newest.read_bytes()[:10])
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            got = resolve_resume_path(str(d), tmp_path)
        assert got.name == "step_000003.ckpt"
        payload = CheckpointManager.load(got)
        assert int(payload["step"]) == 3

    def test_prune_never_deletes_last_verified_checkpoint(self, tmp_path):
        """keep_last_k retention alone would leave only the corrupt newest
        file; the verified-valid rule keeps the restorable one alive."""
        d = tmp_path / "c"
        mgr = CheckpointManager(d, keep_last_k=3)
        mgr.save_host(1, _host_state(1), {})
        newest = mgr.save_host(2, _host_state(2), {})
        newest.write_bytes(b"garbage")

        pruner = CheckpointManager(d, keep_last_k=1)
        pruner._prune()
        survivors = sorted(p.name for p in d.glob("step_*.ckpt"))
        # step 1 (the only verified file) survives despite k=1.
        assert "step_000001.ckpt" in survivors
        assert pruner.latest_valid_checkpoint().name == "step_000001.ckpt"

    def test_prune_removes_sidecars_with_their_checkpoints(self, tmp_path):
        d = tmp_path / "c"
        mgr = CheckpointManager(d, keep_last_k=1)
        for step in (1, 2, 3):
            mgr.save_host(step, _host_state(step), {})
        assert sorted(p.name for p in d.glob("step_*.ckpt")) == ["step_000003.ckpt"]
        assert sorted(p.name for p in d.glob("*.sha256")) == [
            "step_000003.ckpt.sha256"
        ]


class TestResumeResolution:
    def test_explicit_file(self, tmp_path):
        f = tmp_path / "step_000005.ckpt"
        f.write_bytes(b"x")
        assert resolve_resume_path(str(f), tmp_path) == f

    def test_directory_latest(self, tmp_path):
        d = tmp_path / "ckpts"
        d.mkdir()
        for step in (1, 9):
            (d / f"step_{step:06d}.ckpt").write_bytes(b"x")
        assert resolve_resume_path(str(d), tmp_path).name == "step_000009.ckpt"

    def test_missing_ckpt_file_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            resolve_resume_path("nope.ckpt", tmp_path)

    def test_run_id_resolution(self, tmp_path):
        d = tmp_path / "my_run" / "checkpoints"
        d.mkdir(parents=True)
        (d / "step_000003.ckpt").write_bytes(b"x")
        assert resolve_resume_path("my_run", tmp_path).name == "step_000003.ckpt"

    def test_run_directory_descends_into_checkpoints(self, tmp_path):
        """A run DIRECTORY path (not just its id) also resolves — it holds
        no .ckpt files itself but has a checkpoints/ subdir."""
        d = tmp_path / "my_run" / "checkpoints"
        d.mkdir(parents=True)
        (d / "step_000007.ckpt").write_bytes(b"x")
        got = resolve_resume_path(str(tmp_path / "my_run"), tmp_path)
        assert got.name == "step_000007.ckpt"

    def test_unknown_run_id_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="neither"):
            resolve_resume_path("ghost_run", tmp_path)


class TestResumeParity:
    def test_resume_matches_continuous(self, tmp_path):
        """Train 20 straight vs 10 + resume 10: final loss within 1e-5."""
        cfg = _cfg(tmp_path, trainer={"save_every_steps": 10})

        run_a = _run_dir(tmp_path, "continuous")
        res_full = Trainer(cfg, run_a, NullTracker(), None).fit()

        run_b = _run_dir(tmp_path, "resumed")
        Trainer(cfg, run_b, NullTracker(), None).fit(max_steps_override=10)
        resumed_trainer = Trainer(cfg, run_b, NullTracker(), None)
        res_resumed = resumed_trainer.fit(
            resume_from=str(run_b / "checkpoints" / "step_000010.ckpt")
        )

        assert res_resumed.resumed_from_step == 10
        assert res_resumed.final_loss == pytest.approx(res_full.final_loss, abs=1e-5)

    def test_resume_with_dropout_parity(self, tmp_path):
        """Stateless fold_in RNG means dropout streams also line up."""
        cfg = _cfg(tmp_path, model={"dropout": 0.1}, trainer={"save_every_steps": 10})
        run_a = _run_dir(tmp_path, "cont_do")
        res_full = Trainer(cfg, run_a, NullTracker(), None).fit()
        run_b = _run_dir(tmp_path, "res_do")
        Trainer(cfg, run_b, NullTracker(), None).fit(max_steps_override=10)
        res_resumed = Trainer(cfg, run_b, NullTracker(), None).fit(
            resume_from=str(run_b / "checkpoints")
        )
        assert res_resumed.final_loss == pytest.approx(res_full.final_loss, abs=1e-5)

    def test_resume_past_end_reports_restored_state(self, tmp_path, caplog):
        """Resume at step >= max_steps: no steps run, and the summary must
        carry the restored step and a measured loss — not max_steps / 0.0."""
        cfg = _cfg(tmp_path, trainer={"save_every_steps": 10})
        run_a = _run_dir(tmp_path, "past_end")
        Trainer(cfg, run_a, NullTracker(), None).fit(max_steps_override=10)

        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            res = Trainer(cfg, None, NullTracker(), None).fit(
                max_steps_override=10, resume_from=str(run_a / "checkpoints")
            )
        assert any("no training steps will run" in r.message for r in caplog.records)
        assert res.resumed_from_step == 10
        assert res.final_step == 10
        assert res.final_loss > 0.0
        assert np.isfinite(res.final_loss)

    def test_config_mismatch_warns(self, tmp_path, caplog):
        cfg = _cfg(tmp_path)
        run_a = _run_dir(tmp_path, "warn_run")
        Trainer(cfg, run_a, NullTracker(), None).fit(max_steps_override=10)
        changed = _cfg(tmp_path, trainer={"lr": 1e-4})
        with caplog.at_level(logging.WARNING, logger="llmtrain"):
            Trainer(changed, None, NullTracker(), None).fit(
                resume_from=str(run_a / "checkpoints")
            )
        assert any("config differs" in r.message for r in caplog.records)
