"""Async input pipeline (data/prefetch.py) + trainer integration.

The acceptance pillar is bitwise determinism: the prefetcher overlaps
batch assembly + H2D with device compute but must never change WHAT is
assembled — the loss trajectory with ``prefetch_depth: 2`` must equal the
synchronous path (``prefetch_depth: 0``) exactly, including across a
resume and an injected loss-spike rollback. The shutdown pillars: a
SIGTERM with a full queue stops cleanly, and the hang watchdog still
catches a hang injected INSIDE the prefetch thread (the consumer starves
on the queue instead of blocking in the loop).

Also covers the persistent-compilation-cache satellite: the
env-beats-config-beats-default resolution of the cache directory.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import yaml

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.data.prefetch import BatchPrefetcher, PrefetcherClosedError
from llmtrain_tpu.distributed import resolve_compilation_cache_dir
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.resilience import EXIT_HANG_DETECTED
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import Trainer


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _cfg(tmp_path=None, *, prefetch_depth=2, **overrides):
    base = {
        "run": {"name": "pf", "seed": 11},
        "model": {
            "name": "dummy_gpt",
            "block_size": 8,
            "vocab_size": 32,
            "dropout": 0.0,
            "d_model": 48,
            "n_heads": 2,
            "d_ff": 96,
            "n_layers": 1,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "max_steps": 12,
            "micro_batch_size": 2,
            "grad_accum_steps": 1,
            "lr": 3e-3,
            "warmup_steps": 0,
            "log_every_steps": 2,
            "eval_every_steps": 100,
            "save_every_steps": 5,
            "prefetch_depth": prefetch_depth,
        },
        "mlflow": {"enabled": False},
    }
    if tmp_path is not None:
        base["output"] = {"root_dir": str(tmp_path)}
    for section, values in overrides.items():
        base[section] = {**base.get(section, {}), **values}
    return RunConfig.model_validate(base)


class RecordingTracker(NullTracker):
    """Capture every log_metrics call for exact trajectory comparison."""

    def __init__(self):
        self.records: list[tuple[int | None, dict]] = []

    def log_metrics(self, metrics, step=None):
        self.records.append((step, dict(metrics)))

    def series(self, key: str) -> list[tuple[int | None, float]]:
        return [(s, m[key]) for s, m in self.records if key in m]


def _no_live_prefetch_threads():
    return not any(
        t.name.startswith("batch-prefetch") and t.is_alive()
        for t in threading.enumerate()
    )


# --------------------------------------------------------------------------
# prefetcher unit behavior (no trainer, no jax arrays)
# --------------------------------------------------------------------------


class TestBatchPrefetcherUnit:
    def test_in_order_delivery(self):
        pf = BatchPrefetcher(lambda s: ("batch", s), depth=2, start_step=1)
        try:
            for step in range(1, 8):
                assert pf.get(step) == ("batch", step)
        finally:
            pf.close()
        assert _no_live_prefetch_threads()

    def test_depth_zero_is_rejected(self):
        with pytest.raises(ValueError, match="depth"):
            BatchPrefetcher(lambda s: s, depth=0, start_step=1)

    def test_reseek_invalidates_stale_batches(self):
        """Batches assembled under pre-reseek state must never reach the
        consumer — the rollback correctness invariant."""
        offset = [0]
        pf = BatchPrefetcher(lambda s: (s, offset[0]), depth=3, start_step=1)
        try:
            assert pf.get(1) == (1, 0)
            # Simulate the rollback protocol: mutate state, THEN reseek.
            offset[0] = 42
            pf.reseek(2)
            for step in (2, 3, 4):
                assert pf.get(step) == (step, 42)
        finally:
            pf.close()

    def test_error_surfaces_after_good_batches(self):
        """An assembly failure at step N must not mask batches for steps
        < N already queued: the run fails at the same step the synchronous
        path would have failed at."""
        boom = RuntimeError("bad fetch")

        def assemble(s):
            if s == 3:
                raise boom
            return s

        pf = BatchPrefetcher(assemble, depth=4, start_step=1)
        try:
            assert pf.get(1) == 1
            assert pf.get(2) == 2
            with pytest.raises(RuntimeError, match="bad fetch") as exc_info:
                pf.get(3)
            assert exc_info.value is boom  # original object, not a wrapper
        finally:
            pf.close()

    def test_reseek_revives_a_producer_killed_by_a_stale_error(self):
        """An assembly failure during look-ahead belongs to the generation
        a rollback just invalidated: reseek must clear it and restart the
        producer, so the replay runs exactly as the synchronous path
        (which would re-assemble the window and succeed) would."""
        fail_step = [3]

        def assemble(s):
            if s == fail_step[0]:
                raise RuntimeError("transient pre-rollback failure")
            return s

        pf = BatchPrefetcher(assemble, depth=2, start_step=1)
        try:
            assert pf.get(1) == 1
            assert pf.get(2) == 2
            # Rollback protocol: mutate state (here: the failure is gone,
            # as a re-assembly under the advanced offset would be), reseek.
            fail_step[0] = -1
            pf.reseek(2)
            for step in (2, 3, 4):
                assert pf.get(step) == step
        finally:
            pf.close()

    def test_close_with_full_queue_unblocks_producer(self):
        pf = BatchPrefetcher(lambda s: s, depth=1, start_step=1)
        time.sleep(0.2)  # let the producer fill the queue and block in put
        pf.close()
        assert pf.closed
        assert _no_live_prefetch_threads()
        with pytest.raises(PrefetcherClosedError):
            pf.get(1)

    def test_close_abandons_a_wedged_assembly(self):
        """A producer blocked inside a hung fetch cannot be joined; close
        must return within its bound instead of deadlocking the exit."""
        release = threading.Event()

        def assemble(s):
            if s >= 2:
                release.wait()
            return s

        pf = BatchPrefetcher(assemble, depth=2, start_step=1)
        try:
            assert pf.get(1) == 1
            start = time.monotonic()
            pf.close(timeout=0.3)
            assert time.monotonic() - start < 5.0
        finally:
            release.set()  # let the abandoned daemon thread die


# --------------------------------------------------------------------------
# bitwise determinism: prefetch on vs off
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sync_baseline(tmp_path_factory):
    """One synchronous (depth 0) full run: the reference trajectory every
    prefetch variant must reproduce bit for bit."""
    initialize_registries()
    tmp = tmp_path_factory.mktemp("sync_base")
    tracker = RecordingTracker()
    res = Trainer(_cfg(tmp, prefetch_depth=0), None, tracker, None).fit()
    return res, tracker


class TestBitwiseDeterminism:
    def test_prefetch_matches_synchronous_path(self, tmp_path, sync_baseline):
        sync_res, sync_tracker = sync_baseline
        tracker = RecordingTracker()
        res = Trainer(_cfg(tmp_path, prefetch_depth=2), None, tracker, None).fit()
        assert res.final_loss == sync_res.final_loss  # bitwise, no tolerance
        assert res.first_step_loss == sync_res.first_step_loss
        assert tracker.series("train/loss") == sync_tracker.series("train/loss")
        assert _no_live_prefetch_threads()

    def test_deep_queue_matches_too(self, tmp_path, sync_baseline):
        """Depth only bounds look-ahead memory; any depth is the same run."""
        sync_res, sync_tracker = sync_baseline
        tracker = RecordingTracker()
        res = Trainer(_cfg(tmp_path, prefetch_depth=6), None, tracker, None).fit()
        assert res.final_loss == sync_res.final_loss
        assert tracker.series("train/loss") == sync_tracker.series("train/loss")

    def test_host_overlap_metrics_are_logged(self, tmp_path):
        tracker = RecordingTracker()
        Trainer(_cfg(tmp_path, prefetch_depth=2), None, tracker, None).fit()
        waits = tracker.series("train/data_wait_ms")
        dispatch = tracker.series("train/host_dispatch_ms")
        assert waits and dispatch  # logged at every boundary
        assert all(v >= 0.0 for _, v in waits)
        assert all(v >= 0.0 for _, v in dispatch)

    def test_eval_pool_is_released_when_fit_returns(self, tmp_path):
        cfg = _cfg(tmp_path, trainer={"eval_every_steps": 4})
        trainer = Trainer(cfg, None, NullTracker(), None)
        trainer.fit()
        assert trainer._eval_pool is None
        assert not any(
            t.name.startswith("eval-data") and t.is_alive()
            for t in threading.enumerate()
        )

    def test_resume_mid_run_matches_uninterrupted(self, tmp_path, sync_baseline):
        """Stop a prefetching run at the step-5 checkpoint, resume with
        prefetching to 12: final loss and all fully-aligned log intervals
        equal the uninterrupted synchronous run."""
        sync_res, sync_tracker = sync_baseline
        run_dir = tmp_path / "part"
        (run_dir / "checkpoints").mkdir(parents=True)
        # max_steps_override, not a max_steps=5 config: dummy_text sizes
        # its dataset from trainer.max_steps, and the partial run must
        # sample the SAME data stream as the full one.
        part = Trainer(_cfg(tmp_path), run_dir, NullTracker(), None).fit(
            max_steps_override=5
        )
        assert part.final_step == 5
        tracker = RecordingTracker()
        res = Trainer(_cfg(tmp_path), None, tracker, None).fit(
            resume_from=str(run_dir / "checkpoints")
        )
        assert res.resumed_from_step == 5
        assert res.final_loss == sync_res.final_loss
        # Boundary 6 covers steps 5-6 in the full run but only step 6 in
        # the resumed one (different interval mean); 8/10/12 align exactly.
        full = dict(sync_tracker.series("train/loss"))
        resumed = dict(tracker.series("train/loss"))
        for boundary in (8, 10, 12):
            assert resumed[boundary] == full[boundary]

    def test_resume_with_different_prefetch_depth_matches(
        self, tmp_path, sync_baseline
    ):
        """The saving run's prefetch_depth is a pure performance knob: a
        checkpoint saved with depth 2 must resume bitwise-identically under
        depth 0 (prefetch on→off) and a different nonzero depth. The
        manifest records the saving depth (crash-consistency layer), and
        resume must treat the difference as a non-event."""
        from llmtrain_tpu.training.checkpoint import read_manifest

        sync_res, sync_tracker = sync_baseline
        run_dir = tmp_path / "saved_d2"
        (run_dir / "checkpoints").mkdir(parents=True)
        part = Trainer(_cfg(tmp_path, prefetch_depth=2), run_dir, NullTracker(), None).fit(
            max_steps_override=5
        )
        assert part.final_step == 5
        manifest = read_manifest(run_dir / "checkpoints" / "step_000005.ckpt")
        assert manifest["data"]["prefetch_depth"] == 2

        full = dict(sync_tracker.series("train/loss"))
        for depth in (0, 3):
            tracker = RecordingTracker()
            res = Trainer(
                _cfg(tmp_path, prefetch_depth=depth), None, tracker, None
            ).fit(resume_from=str(run_dir / "checkpoints"))
            assert res.resumed_from_step == 5
            assert res.final_loss == sync_res.final_loss  # bitwise
            resumed = dict(tracker.series("train/loss"))
            # Boundary 6 straddles the resume point (partial interval);
            # the fully-aligned intervals must match bit for bit.
            for boundary in (8, 10, 12):
                assert resumed[boundary] == full[boundary]

    def test_resume_off_to_on_matches(self, tmp_path, sync_baseline):
        """The mirror direction: saved synchronously, resumed prefetching."""
        sync_res, _ = sync_baseline
        run_dir = tmp_path / "saved_d0"
        (run_dir / "checkpoints").mkdir(parents=True)
        Trainer(_cfg(tmp_path, prefetch_depth=0), run_dir, NullTracker(), None).fit(
            max_steps_override=5
        )
        res = Trainer(_cfg(tmp_path, prefetch_depth=2), None, RecordingTracker(), None).fit(
            resume_from=str(run_dir / "checkpoints")
        )
        assert res.resumed_from_step == 5
        assert res.final_loss == sync_res.final_loss  # bitwise

    def test_spike_rollback_replay_matches_synchronous(self, tmp_path):
        """An injected spike rolls both variants back to the step-5
        checkpoint; the replayed window (advanced data offset, rollback-
        folded RNG) must be identical with prefetch on vs off."""

        def run(depth, sub):
            run_dir = tmp_path / sub
            (run_dir / "checkpoints").mkdir(parents=True)
            tracker = RecordingTracker()
            cfg = _cfg(
                tmp_path,
                prefetch_depth=depth,
                resilience={
                    "spike_detection": True,
                    "spike_factor": 4.0,
                    "spike_min_history": 4,
                    "max_rollbacks": 2,
                    "faults": {"spike_loss_at_step": 8, "spike_loss_scale": 100.0},
                },
            )
            res = Trainer(cfg, run_dir, tracker, None).fit()
            return res, tracker

        sync_res, sync_tracker = run(0, "sync")
        pf_res, pf_tracker = run(2, "prefetch")
        assert sync_res.rollbacks == pf_res.rollbacks == 1
        assert pf_res.final_loss == sync_res.final_loss
        assert pf_res.final_step == sync_res.final_step == 12
        assert pf_tracker.series("train/loss") == sync_tracker.series("train/loss")


# --------------------------------------------------------------------------
# shutdown: SIGTERM preemption with a full queue
# --------------------------------------------------------------------------


class _SigtermAtFirstInterval(NullTracker):
    """First log boundary delivers SIGTERM on the training thread — the
    deterministic in-process preemption trigger (tests/test_preemption.py)."""

    def __init__(self):
        self.fired = False

    def log_metrics(self, metrics, step=None):
        if not self.fired and step and step >= 1:
            self.fired = True
            os.kill(os.getpid(), signal.SIGTERM)


class TestPreemptionShutdown:
    def test_sigterm_with_full_queue_stops_cleanly(self, tmp_path):
        """At the preemption break the producer holds a full queue; fit
        must still save, return, and leave no live prefetch thread."""
        cfg = _cfg(
            tmp_path, prefetch_depth=4, trainer={"max_steps": 4000}
        )
        run_dir = tmp_path / "preempt"
        (run_dir / "checkpoints").mkdir(parents=True)
        before = signal.getsignal(signal.SIGTERM)
        res = Trainer(cfg, run_dir, _SigtermAtFirstInterval(), None).fit()
        assert res.preempted is True
        assert 0 < res.final_step < cfg.trainer.max_steps
        assert np.isfinite(res.final_loss)
        ckpt = run_dir / "checkpoints" / f"step_{res.final_step:06d}.ckpt"
        assert ckpt.exists()
        assert _no_live_prefetch_threads()
        assert signal.getsignal(signal.SIGTERM) == before


# --------------------------------------------------------------------------
# watchdog catches a hang inside the prefetch thread (e2e subprocess)
# --------------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    return env


class TestWatchdogCatchesPrefetcherHang:
    def test_hang_in_prefetcher_exits_retryable_with_report(self, tmp_path):
        """A wedged prefetch thread starves the consumer on the queue: no
        step dispatches, the beacon stalls, and the armed watchdog must
        end the run exactly as it would for a host-loop hang — retryable
        exit, all-thread stack report naming the blocked prefetch thread."""
        raw = _cfg().model_dump()
        raw["output"] = {"root_dir": "runs"}
        raw["resilience"] = {
            **raw["resilience"],
            "watchdog": {
                "enabled": True,
                "stall_timeout_sec": 0.8,
                "heartbeat_interval_sec": 0.0,
            },
            "faults": {"hang_at_step": 3, "hang_in_prefetcher": True},
        }
        (tmp_path / "pfhang.yaml").write_text(yaml.safe_dump(raw))
        proc = subprocess.run(
            [sys.executable, "-m", "llmtrain_tpu", "train", "--config",
             "pfhang.yaml", "--run-id", "pfhang"],
            capture_output=True,
            text=True,
            cwd=tmp_path,
            env=_cli_env(),
            timeout=420,
        )
        assert proc.returncode == EXIT_HANG_DETECTED, (
            f"expected exit {EXIT_HANG_DETECTED}, got {proc.returncode}\n"
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
        reports = list((tmp_path / "runs" / "pfhang").glob("hang_report_*.txt"))
        assert len(reports) == 1, proc.stderr
        text = reports[0].read_text()
        assert "batch-prefetch" in text  # the wedged producer's stack
        assert "maybe_hang" in text  # ... at the actual stall site
        assert "MainThread" in text  # the starved consumer's stack
        assert "HANG DETECTED" in proc.stderr


# --------------------------------------------------------------------------
# persistent compilation cache: dir resolution precedence
# --------------------------------------------------------------------------


class TestCompilationCacheResolution:
    def test_env_beats_config_beats_default(self, monkeypatch):
        monkeypatch.setenv("LLMTRAIN_COMPILATION_CACHE", "/from/env")
        assert resolve_compilation_cache_dir("/from/config") == "/from/env"
        monkeypatch.delenv("LLMTRAIN_COMPILATION_CACHE")
        assert resolve_compilation_cache_dir("/from/config") == "/from/config"
        default = resolve_compilation_cache_dir(None)
        assert default is not None and default.endswith(os.path.join("llmtrain_tpu", "jax"))

    def test_env_off_disables_even_with_config_dir(self, monkeypatch):
        monkeypatch.setenv("LLMTRAIN_COMPILATION_CACHE", "off")
        assert resolve_compilation_cache_dir("/from/config") is None

    def test_boolish_enable_uses_config_dir(self, monkeypatch):
        """on/1/true mean "enable", not "a directory named true" — with a
        config dir present they resolve to it."""
        monkeypatch.setenv("LLMTRAIN_COMPILATION_CACHE", "on")
        assert resolve_compilation_cache_dir("/from/config") == "/from/config"

    def test_run_section_accepts_cache_dir(self):
        cfg = _cfg(run={"compilation_cache_dir": "/tmp/jaxcache"})
        assert cfg.run.compilation_cache_dir == "/tmp/jaxcache"


class TestConfigSchema:
    def test_prefetch_depth_default_and_bounds(self):
        assert _cfg().trainer.prefetch_depth == 2
        assert _cfg(prefetch_depth=0).trainer.prefetch_depth == 0
        with pytest.raises(Exception):
            _cfg(prefetch_depth=-1)
