"""Speculative decoding (llmtrain_tpu/speculative.py).

The exactness contract IS the test strategy: greedy speculative output
must be bit-identical to plain greedy decoding from the target alone —
for any draft model, any gamma, any family/cache layout — and sampled
speculative output must follow the target's sampling distribution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.generation import generate
from llmtrain_tpu.models.gpt import GPT
from llmtrain_tpu.models.llama import Llama
from llmtrain_tpu.speculative import speculative_generate

V = 32


def _gpt(n_layers=2, d_model=32, seed=0, **kw):
    m = GPT(
        vocab_size=V, block_size=64, d_model=d_model, n_layers=n_layers,
        n_heads=4, d_ff=2 * d_model, dropout=0.0, **kw,
    )
    p = nn_meta.unbox(
        m.init(jax.random.key(seed), jnp.zeros((1, 4), jnp.int32),
               deterministic=True)["params"]
    )
    return m, p


def _llama(n_layers=2, d_model=32, seed=0, **kw):
    m = Llama(
        vocab_size=V, block_size=64, d_model=d_model, n_layers=n_layers,
        n_heads=4, d_ff=3 * d_model, dropout=0.0, **kw,
    )
    p = nn_meta.unbox(
        m.init(jax.random.key(seed), jnp.zeros((1, 4), jnp.int32),
               deterministic=True)["params"]
    )
    return m, p


PROMPT = np.asarray([[3, 1, 4]], np.int32)


class TestGreedyExactness:
    def test_self_draft_matches_plain(self):
        """Draft == target: every proposal accepted, output identical."""
        m, p = _gpt()
        want = generate(m, p, PROMPT, max_new_tokens=10, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, m, p, PROMPT, max_new_tokens=10,
                                   gamma=4)
        assert got.tolist() == want.tolist()

    def test_weak_draft_matches_plain(self):
        """A differently-initialized draft disagrees often — the output
        must STILL equal the target's own greedy decode."""
        m, p = _gpt(seed=0)
        d, dp = _gpt(n_layers=1, seed=7)
        want = generate(m, p, PROMPT, max_new_tokens=12, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, d, dp, PROMPT, max_new_tokens=12,
                                   gamma=4)
        assert got.tolist() == want.tolist()

    @pytest.mark.parametrize("gamma", [1, 2, 3, 5])
    def test_gamma_invariance(self, gamma):
        m, p = _gpt(seed=1)
        d, dp = _gpt(n_layers=1, seed=9)
        want = generate(m, p, PROMPT, max_new_tokens=9, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, d, dp, PROMPT, max_new_tokens=9,
                                   gamma=gamma)
        assert got.tolist() == want.tolist()

    @pytest.mark.slow  # budget: tier-1 siblings test_self_draft/test_weak_draft_matches_plain + gamma_invariance
    def test_single_token_prompt(self):
        """tp == 1 skips prefill (the cursor invariant's edge case)."""
        m, p = _gpt(seed=2)
        d, dp = _gpt(n_layers=1, seed=3)
        prompt = np.asarray([[5]], np.int32)
        want = generate(m, p, prompt, max_new_tokens=8, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, d, dp, prompt, max_new_tokens=8,
                                   gamma=3)
        assert got.tolist() == want.tolist()

    def test_gqa_target(self):
        m, p = _gpt(seed=4, n_kv_heads=2)
        d, dp = _gpt(n_layers=1, seed=5)
        want = generate(m, p, PROMPT, max_new_tokens=10, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, d, dp, PROMPT, max_new_tokens=10,
                                   gamma=4)
        assert got.tolist() == want.tolist()

    def test_self_draft_accepts_everything(self):
        """Draft == target must accept ALL gamma proposals every
        iteration: ceil(max_new/(gamma+1)) target forwards. This pins the
        draft-cache completeness invariant — the r4 bug (the last draft
        token's K/V never written on full acceptance) kept outputs exact
        but decayed acceptance after the first hole."""
        m, p = _gpt(seed=20)
        gamma, new = 3, 12
        out, stats = speculative_generate(
            m, p, m, p, PROMPT, max_new_tokens=new, gamma=gamma,
            return_stats=True,
        )
        assert stats["target_forwards"] == -(-new // (gamma + 1))  # ceil
        assert out.shape == (1, PROMPT.shape[1] + new)

    def test_llama_rolling_window_target(self):
        """Windowed llama target: the ROLLING cache's cursor rollback and
        stale-slot semantics hold under speculative rejection."""
        m, p = _llama(seed=6, sliding_window=5, n_kv_heads=2)
        d, dp = _llama(n_layers=1, seed=8, sliding_window=5)
        want = generate(m, p, PROMPT, max_new_tokens=14, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, d, dp, PROMPT, max_new_tokens=14,
                                   gamma=3)
        assert got.tolist() == want.tolist()


class TestMoETarget:
    @pytest.mark.slow  # ~11s: niche MoE-target x speculative combo; the
    # exactness contract stays tier-1 on the dense target, and MoE
    # routing correctness lives in test_moe.py.
    def test_llama_moe_target_matches_plain(self):
        """Mixtral-class target (SwiGLU experts, top-2 routing, GQA,
        window): expert routing re-evaluates per decode step, and the
        exactness contract must survive it."""
        m = Llama(
            vocab_size=V, block_size=64, d_model=32, n_layers=2, n_heads=4,
            d_ff=64, dropout=0.0, n_experts=4, router_top_k=2,
            capacity_factor=2.0, n_kv_heads=2, sliding_window=6,
        )
        p = nn_meta.unbox(
            m.init(jax.random.key(40), jnp.zeros((1, 4), jnp.int32),
                   deterministic=True)["params"]
        )
        d, dp = _llama(n_layers=1, seed=41)
        want = generate(m, p, PROMPT, max_new_tokens=10, temperature=0.0,
                        use_cache=True)
        got = speculative_generate(m, p, d, dp, PROMPT, max_new_tokens=10,
                                   gamma=3)
        assert got.tolist() == want.tolist()


class TestEosParity:
    def test_eos_stop_matches_plain(self):
        """Pick a token the greedy chain actually emits as 'eos': both
        paths must stop there and eos-fill the tail identically."""
        m, p = _gpt(seed=30)
        d, dp = _gpt(n_layers=1, seed=31)
        free = generate(m, p, PROMPT, max_new_tokens=10, temperature=0.0,
                        use_cache=True)
        eos = int(free[0, PROMPT.shape[1] + 3])  # 4th generated token
        want = generate(m, p, PROMPT, max_new_tokens=10, temperature=0.0,
                        eos_token_id=eos, use_cache=True)
        got = speculative_generate(
            m, p, d, dp, PROMPT, max_new_tokens=10, gamma=4,
            eos_token_id=eos,
        )
        assert got.tolist() == want.tolist()
        # The tail from the first eos onward is eos-filled.
        first = int(np.argmax(got[0, PROMPT.shape[1] :] == eos))
        tail = got[0, PROMPT.shape[1] + first :]
        assert (tail == eos).all()

    def test_eos_never_emitted_is_noop(self):
        m, p = _gpt(seed=32)
        free = speculative_generate(m, p, m, p, PROMPT, max_new_tokens=8,
                                    gamma=3)
        unused_set = set(range(V)) - set(int(t) for t in free[0])
        assert unused_set  # 11 tokens over V=32 cannot cover the vocab
        unused = min(unused_set)
        guarded = speculative_generate(
            m, p, m, p, PROMPT, max_new_tokens=8, gamma=3,
            eos_token_id=unused,
        )
        assert guarded.tolist() == free.tolist()


class TestSamplingDistribution:
    @pytest.mark.slow
    def test_marginal_matches_analytic_target(self):
        """First sampled token over many seeds vs the ANALYTIC filtered
        target distribution (top-k=4 concentrates the mass, so noise-only
        TV at n=600 is ~0.03 while a biased acceptance rule would show
        up an order of magnitude larger).

        @slow: 600 sequential speculative_generate calls ≈ 49 s of host
        dispatch — the single most expensive tier-1 test, moved out to
        hold the suite under the ~830 s reported-time ceiling (same
        precedent as the serving sampled-parity soak; the greedy
        exactness + knob-convention tests above stay tier-1)."""
        from llmtrain_tpu.speculative import _filtered_logprobs

        m, p = _gpt(seed=10, n_layers=1, d_model=16)
        d, dp = _gpt(seed=11, n_layers=1, d_model=16)
        n = 600

        logits = m.apply({"params": p}, jnp.asarray(PROMPT), deterministic=True)
        analytic = np.exp(
            np.asarray(
                _filtered_logprobs(
                    logits[:, -1].astype(jnp.float32),
                    temperature=1.0, top_k=4, top_p=None,
                )[0]
            )
        )

        counts = np.zeros(V)
        for s in range(n):
            out = speculative_generate(
                m, p, d, dp, PROMPT, max_new_tokens=1, gamma=2,
                temperature=1.0, top_k=4, rng=jax.random.key(s),
            )
            counts[int(out[0, PROMPT.shape[1]])] += 1
        tv = 0.5 * np.abs(counts / n - analytic).sum()
        assert tv < 0.08, f"total variation vs analytic {tv:.3f}"

    def test_topk_topp_compose(self):
        """Filtered sampling runs and emits only in-vocab tokens."""
        m, p = _gpt(seed=12)
        d, dp = _gpt(n_layers=1, seed=13)
        out = speculative_generate(
            m, p, d, dp, PROMPT, max_new_tokens=8, gamma=3,
            temperature=0.8, top_k=8, top_p=0.9, rng=jax.random.key(0),
        )
        assert out.shape == (1, PROMPT.shape[1] + 8)
        assert ((out >= 0) & (out < V)).all()

    def test_out_of_band_knobs_mean_disabled(self):
        """Library callers passing top_k=0 / top_p=0 or 1 get the same
        'filter disabled' conventions as generate() (generation.py:283-289)
        instead of lax.top_k(x, 0) under jit (ADVICE r4)."""
        m, p = _gpt(seed=12)
        ref = speculative_generate(
            m, p, m, p, PROMPT, max_new_tokens=6, gamma=2,
            temperature=0.8, top_k=None, top_p=None, rng=jax.random.key(7),
        )
        got = speculative_generate(
            m, p, m, p, PROMPT, max_new_tokens=6, gamma=2,
            temperature=0.8, top_k=0, top_p=0.0, rng=jax.random.key(7),
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


class TestValidation:
    def test_batch_one_only(self):
        m, p = _gpt()
        two = np.tile(PROMPT, (2, 1))
        with pytest.raises(ValueError, match="batch size 1"):
            speculative_generate(m, p, m, p, two, max_new_tokens=4)

    def test_gamma_positive(self):
        m, p = _gpt()
        with pytest.raises(ValueError, match="gamma"):
            speculative_generate(m, p, m, p, PROMPT, max_new_tokens=4, gamma=0)

    def test_block_size_overflow(self):
        m, p = _gpt()
        with pytest.raises(ValueError, match="block_size"):
            speculative_generate(m, p, m, p, PROMPT, max_new_tokens=100,
                                 gamma=4)

    def test_zero_new_tokens_returns_prompt(self):
        m, p = _gpt()
        out = speculative_generate(m, p, m, p, PROMPT, max_new_tokens=0)
        assert out.tolist() == PROMPT.tolist()
