"""Smoke coverage for the repo-root measurement tools.

The chip runbook (tools/run_chip_evidence.sh) depends on these CLIs
working; a refactor that breaks an import or a flag should fail here on
CPU rather than on the first live-TPU session.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]

pytestmark = pytest.mark.slow


def _run(args, timeout=540):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=timeout,
    )


def test_bench_decode_smoke():
    proc = _run(
        ["tools/bench_decode.py", "--batches", "1,2", "--kv-heads", "0",
         "--new-tokens", "8", "--repeats", "1"]
    )
    assert proc.returncode == 0, proc.stderr
    lines = [json.loads(x) for x in proc.stdout.splitlines() if x.strip()]
    cells = [x for x in lines if "batch" in x]
    assert {c["batch"] for c in cells} == {1, 2}
    assert all(c["tokens_per_sec"] > 0 for c in cells)


def test_bench_longctx_smoke():
    proc = _run(["tools/bench_longctx.py", "--seqs", "512", "--cpu-smoke",
                 "--steps", "1"])
    assert proc.returncode == 0, proc.stderr
    row = json.loads(proc.stdout.splitlines()[-1])
    assert row["seq"] == 512 and "error" not in row
    assert row["tokens_per_sec"] > 0
    # A 0.0 peak must self-diagnose (VERDICT r4 item 7): CPU PJRT reports
    # no memory stats, so the row carries the keys the device DOES expose.
    if row["peak_hbm_gb"] == 0:
        assert "memory_stats keys" in row.get("hbm_note", ""), row


def test_bench_cpu_sweep_smoke():
    proc = _run(["tools/bench_cpu_sweep.py", "--shapes", "64,1,2"])
    assert proc.returncode == 0, proc.stderr
    row = json.loads(proc.stdout.splitlines()[-1])
    assert "error" not in row, row
    assert row["mfu"] > 0 and row["tokens_per_sec"] > 0


def test_bench_interleave_smoke():
    proc = _run(["tools/bench_interleave.py", "--steps", "6"], timeout=560)
    assert proc.returncode == 0, proc.stderr
    lines = [json.loads(x) for x in proc.stdout.splitlines() if x.strip()]
    assert {r.get("virtual_chunks") for r in lines if "virtual_chunks" in r} == {1, 2}


def test_bench_family_smoke():
    proc = _run(["tools/bench_family.py", "--cpu-smoke", "--steps", "1"])
    assert proc.returncode == 0, proc.stderr
    rows = [json.loads(x) for x in proc.stdout.splitlines() if x.strip()]
    assert {r.get("family") for r in rows} == {"gpt", "llama", "qwen2", "gemma"}
    assert all("error" not in r and r["tokens_per_sec"] > 0 for r in rows)


def test_bench_speculative_smoke():
    proc = _run(["tools/bench_speculative.py", "--cpu-smoke", "--new-tokens",
                 "8", "--repeats", "1"])
    assert proc.returncode == 0, proc.stderr
    rows = [json.loads(x) for x in proc.stdout.splitlines() if x.strip()]
    assert {r["cell"] for r in rows} == {
        "plain", "speculative_self_draft", "speculative_fresh_draft",
    }
    assert all("error" not in r for r in rows)


def test_bench_lora_smoke():
    proc = _run(["tools/bench_lora.py", "--cpu-smoke", "--steps", "2"])
    assert proc.returncode == 0, proc.stderr
    lines = [json.loads(x) for x in proc.stdout.splitlines()]
    cells = {r["cell"]: r for r in lines if "cell" in r}
    assert cells["full"]["trainable_params"] == cells["full"]["params"]
    assert cells["lora_r8"]["trainable_params"] < cells["lora_r8"]["params"]
    summary = lines[-1]
    assert summary["predicted_speedup"] > 1.0


def test_interleave_attribution_smoke():
    proc = _run(
        ["tools/bench_interleave.py", "--no-trainer", "--attribute",
         "--repeats", "2"],
        timeout=560,
    )
    assert proc.returncode == 0, proc.stderr
    row = json.loads(proc.stdout.splitlines()[-1])["attribution"]
    assert row["phases"]["v1"]["ticks"] == 7
    assert row["phases"]["v2"]["ticks"] == 11
    assert row["predicted_compute_ratio_v2_v1"] == pytest.approx(11 / 14, abs=1e-3)


def test_phase2_script_aborts_cleanly_without_tpu():
    """The phase-2 runbook's compile-verifying start gate must fail fast
    when no TPU backend exists. (The resume/stand-down logic has its own
    fast coverage in tests/test_chip_runbook.py.)"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        ["bash", "tools/run_chip_phase2.sh", "/tmp/chipp2-test"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=300,
    )
    assert proc.returncode == 1
    assert "tunnel dead before step start" in proc.stderr


def test_chip_evidence_script_aborts_cleanly_without_tpu():
    """The runbook's probe must fail fast (not hang) when no TPU backend
    exists — forced here by pinning the probe subprocess to CPU."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # the probe asserts backend == tpu -> abort
    proc = subprocess.run(
        ["bash", "tools/run_chip_evidence.sh", "/tmp/chipev-test"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=300,
    )
    assert proc.returncode == 1
    assert "unreachable" in proc.stderr
