.PHONY: test test-all lint verify-resilience verify-watchdog verify-prefetch verify-telemetry verify-elastic verify-serving verify-router verify-promote verify-overload verify-trace verify-zero verify-fleet verify-profile verify-quant verify-fusedce verify-goodput verify-tune verify-offload train-smoke train-multiproc bench \
	chip-evidence mlflow \
	k8s-cluster k8s-cluster-delete k8s-build k8s-train k8s-serve k8s-fleet k8s-logs k8s-clean \
	k8s-full k8s-e2e

# -n auto: xdist parallelism scales the gate to the host (1 worker on a
# 1-core box, 8+ on CI); the persistent compilation cache (conftest.py)
# is shared across workers, so compile-heavy tests pay each shape once.
test:
	python -m pytest tests/ -q -m "not slow" -n auto

test-serial:
	python -m pytest tests/ -q -m "not slow"

# Fast fault-injection suite: every resilience recovery path (non-finite
# guard, spike rollback, checkpoint integrity, SIGTERM, retry) end to end.
# These tests are deliberately unmarked so plain `make test` runs them too.
verify-resilience:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py \
		tests/test_checkpoint.py tests/test_preemption.py -q -m "not slow"

# Hang watchdog + exit-code taxonomy suite: injected REAL host hang killed
# with a retryable exit + all-thread stack report, heartbeat freshness,
# straggler telemetry, bounded drain of a wedged checkpoint write.
verify-watchdog:
	JAX_PLATFORMS=cpu python -m pytest tests/test_watchdog.py -q -m "not slow"

# Async input pipeline suite: prefetch-on/off loss bitwise equality (incl.
# resume and spike-rollback replay), SIGTERM shutdown with a full queue,
# watchdog catching a hang injected inside the prefetch thread, and the
# compilation-cache dir resolution precedence.
verify-prefetch:
	JAX_PLATFORMS=cpu python -m pytest tests/test_prefetch.py -q -m "not slow"

# Crash consistency + elastic resume suite (docs/robustness.md): atomic
# manifest commits, orphan-stage GC, pre-manifest migration, emulated
# world-size-change resume, topology-mismatch exit codes — PLUS the seeded
# chaos harness (5 SIGKILL/resume cycles incl. one inside the async
# checkpoint write, bitwise-parity against an uninterrupted reference).
# The chaos drills are @pytest.mark.slow so plain `make test` skips them;
# this target runs everything except the env-gated soak
# (LLMTRAIN_CHAOS_SOAK=1 enables it).
verify-elastic:
	JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q

# ZeRO sharded-optimizer-state suite (docs/perf.md "Sharded optimizer
# state"): opt_state_shardings partition specs, bitwise loss-trajectory
# parity zero on/off (stage 1) incl. host offload, checkpoint round-trips
# zero<->non-zero, elastic ws2<->ws1 resume with sharded state, the
# indivisible-leaf replicated fallback warning, and the report.json
# opt_state_bytes accounting. Includes the @pytest.mark.slow cases plain
# `make test` skips.
verify-zero:
	JAX_PLATFORMS=cpu python -m pytest tests/test_zero.py -q

# Multi-tenant fleet suite (docs/robustness.md "Fleet: many tenants,
# shared capacity"): the deterministic scheduling-policy tables, tenant
# state machine, and SIGTERM->SIGKILL escalation ladder units — PLUS the
# @pytest.mark.slow drills plain `make test` skips: the 3-tenant seeded
# preemption storm (capacity drop + evictions + one mid-checkpoint kill,
# per-tenant bitwise parity vs uninterrupted references), the
# twice-evicted resume_count==2 fairness pin, the elastic 1->2-device
# resize, and the `llmtrain fleet` CLI round-trip.
verify-fleet:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q

# Telemetry subsystem suite (docs/observability.md): runs a real smoke fit
# and asserts report.json + report.md + a Perfetto-loadable trace.json are
# produced, train/mfu + mem/hbm_peak + span metrics land in the tracker AND
# in a live Prometheus scrape, timeline rollback tagging, and the
# failing-tracker degrade-to-warning regression.
verify-telemetry:
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q -m "not slow"

# Cost-attribution + roofline suite (docs/observability.md "Attribution and
# rooflines"): XLA cost-table extraction, HLO top-ops parsing, roofline
# classification, MFU reconciliation, serve-latency percentile gauges, and
# the perf_gate regression rules (self-test included). The slow e2e pieces
# (fit-path attribution, `llmtrain profile` CLI) ride `make test-all`.
verify-profile:
	JAX_PLATFORMS=cpu python -m pytest tests/test_profiling.py -q -m "not slow"
	python tools/perf_gate.py --self-test

# Mesh planner + auto-tuner suite (docs/perf.md "Mesh planning and
# auto-tuning"): wildcard/divisibility plan resolution, capability rules,
# dominated-candidate pruning with reasons, deterministic seeded candidate
# order, and the `llmtrain plan` exit-code contract. The @pytest.mark.slow
# probe-fit e2e and tune->train round-trip ride `make test-all`.
verify-tune:
	JAX_PLATFORMS=cpu python -m pytest tests/test_autotune.py -q -m "not slow"
	python tools/perf_gate.py --self-test

# Quantized-training suite (docs/perf.md "Quantized training"):
# per-channel scale/STE-vjp units, QuantDense-vs-Dense drop-in parity,
# knob validation + fp8 capability fallback, chunked-CE auto-select, the
# perf_gate matrix rules — PLUS the @pytest.mark.slow fits plain
# `make test` skips: int8-vs-f32 N-step loss-parity on a tiny GPT,
# grad-finiteness under the non-finite guard, and the checkpoint/elastic
# -resume round-trip with matmul_precision int8. Ends with the gate's
# own self-test (new-key/removed-key/degraded-parity matrix cases).
verify-quant:
	JAX_PLATFORMS=cpu python -m pytest tests/test_quant_train.py -q
	python tools/perf_gate.py --self-test

# Fused lm-head + CE suite (docs/perf.md "Fused lm-head + CE"):
# interpret-mode Pallas kernel parity (fwd per-token loss + dhidden/dW)
# vs chunked_ce and dense across tied/untied heads, z_loss on/off and
# non-block-multiple shapes, the fused residual-add+LayerNorm kernel,
# loss_impl/fused_norm resolution + capability fallbacks, and the
# planner's logits-buffer accounting — PLUS the @pytest.mark.slow fits
# plain `make test` skips: 5-step fused-vs-dense loss parity, the
# checkpoint resume with loss_impl flipped across the boundary, and the
# attribution pin (no dot materializes the [B,T,V] logits under
# fused_ce). Ends with the perf gate's self-test (fused matrix cases).
verify-fusedce:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fused_ce.py -q
	python tools/perf_gate.py --self-test

# Activation-tier suite (docs/perf.md "Activation tiers and host
# offload"): spec grammar, per-layer jaxpr remat boundaries, forward
# bitwise parity, the remat->tiers deprecation shim, the per-tier HBM
# model + ladder enumeration, and the @slow Trainer fits (offload
# fallback warning, resume with tiers changed) — plus the perf-gate
# offload scenario contract.
verify-offload:
	JAX_PLATFORMS=cpu python -m pytest tests/test_activation_tiers.py -q
	python tools/perf_gate.py --self-test

# Goodput-ledger suite (docs/observability.md "Goodput"): synthetic-
# timeline taxonomy tables (exact second splits), the ledger-balances
# invariant through the real Telemetry facade + `llmtrain goodput` CLI,
# suspension-window carving — PLUS the @pytest.mark.slow drills plain
# `make test` skips: a mid-interval SIGKILL leaving a torn timeline that
# still balances, the 3-cycle chaos drill with recomputed_sec > 0 and
# post-mortem CLI reproducibility, and the fleet-storm goodput floor.
# Ends with the perf gate's own self-test (goodput regression cases).
verify-goodput:
	JAX_PLATFORMS=cpu python -m pytest tests/test_goodput.py -q
	python tools/perf_gate.py --self-test

# Continuous-batching serving suite (docs/serving.md): paged-KV pool
# invariants, batched-vs-generate() bitwise parity (greedy, per-request
# sampled knobs, speculative policy), bounded compile budget, continuous
# join/evict, the seeded open-loop load soak, and the full CLI round-trip
# (train -> serve-bench --verify-parity -> serve over HTTP). Includes the
# @pytest.mark.slow soaks plain `make test` skips.
verify-serving:
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving_engine.py \
		tests/test_serving.py -q

# Fleet serving tier (docs/serving.md "Fleet tier"): prefix-cache
# content addressing + refcount/COW/eviction invariants, router
# placement/affinity/eviction/failover, chunked prefill, checkpoint
# hot-swap epoch pinning, batched speculative parity — plus the
# @pytest.mark.slow 2-replica drill (mid-drill rolling hot swap, zero
# failed requests, bitwise parity on the params each request was
# admitted under) that plain `make test` skips.
verify-router:
	JAX_PLATFORMS=cpu python -m pytest tests/test_router.py -q

# Promotion-lifecycle drill (docs/robustness.md "Canary, promote,
# rollback"): ledger replay/idempotence, checkpoint-watch edge cases,
# controller decision units — plus the @pytest.mark.slow chaos drill
# (poisoned checkpoint canaried on a real 2-replica fleet, detected,
# rolled back with zero failed requests and bitwise parity on the
# admitted params; clean checkpoint promotes fleet-wide, every
# transition durable in promotions.jsonl) that plain `make test` skips.
verify-promote:
	JAX_PLATFORMS=cpu python -m pytest tests/test_promote.py -q

# Overload-control drill (docs/serving.md "Overload and SLOs"): token
# buckets, EWMA admission, weighted-class queue, brownout hysteresis,
# retry budget, shed-mid-prefill pool accounting — plus the
# @pytest.mark.slow seeded 10x-burst drill against a 2-replica router
# (fast 429s with the documented reason taxonomy, bitwise parity on
# accepted requests, brownout entry AND exit, exact pool accounting)
# that plain `make test` skips.
verify-overload:
	JAX_PLATFORMS=cpu python -m pytest tests/test_overload.py -q

# Distributed-tracing drill (docs/observability.md "Distributed request
# tracing"): traceparent round-trips, tail-sampling decisions, tracer
# flush, collector tree assembly — plus the @pytest.mark.slow 2-replica
# HTTP fleet drill (one forced failover; the merged trace must
# reconstruct the router→replica span tree via the propagated
# traceparent, the critical path must tile the end-to-end latency, and
# /metrics must carry exemplar trace ids) that plain `make test` skips.
verify-trace:
	JAX_PLATFORMS=cpu python -m pytest tests/test_tracing.py tests/test_trace_e2e.py -q

# Static gate (reference: pre-commit ruff+mypy, .pre-commit-config.yaml:1-24).
# Runs ruff+mypy when installed; otherwise the stdlib fallback checker.
lint:
	@if python -c "import ruff" 2>/dev/null; then \
		python -m ruff format --check llmtrain_tpu tests && \
		python -m ruff check llmtrain_tpu tests; \
	elif command -v ruff >/dev/null; then \
		ruff format --check llmtrain_tpu tests && \
		ruff check llmtrain_tpu tests; \
	else \
		echo "ruff not installed; using stdlib fallback"; \
	fi
	@if python -c "import mypy" 2>/dev/null; then \
		python -m mypy --config-file=pyproject.toml llmtrain_tpu; \
	else \
		echo "mypy not installed; using stdlib fallback"; \
	fi
	@JAX_PLATFORMS=cpu python tools/static_check.py

test-all:
	python -m pytest tests/ -q

train-smoke:
	JAX_PLATFORMS=cpu python -m llmtrain_tpu train --config configs/presets/gpt_smoke.yaml

# Two real OS processes forming a JAX distributed runtime on localhost
# (the analogue of the reference's `torchrun --nproc_per_node=2`).
train-multiproc:
	JAX_PLATFORMS=cpu WORLD_SIZE=2 MASTER_ADDR=127.0.0.1 MASTER_PORT=29511 \
		bash -c 'RANK=1 python -m llmtrain_tpu train --config configs/presets/ddp_smoke.yaml & \
		RANK=0 python -m llmtrain_tpu train --config configs/presets/ddp_smoke.yaml; wait'

# GPipe pipeline parallelism on the 8-virtual-device CPU mesh.
train-pipeline:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m llmtrain_tpu train --config configs/presets/gpt_pipeline_smoke.yaml

# Mixture-of-Experts with a 4-way expert-parallel mesh axis.
train-moe:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m llmtrain_tpu train --config configs/presets/gpt_moe_smoke.yaml

bench:
	python bench.py

# Full on-chip measurement backlog, one command (probes first; aborts
# cleanly when the TPU tunnel is down). Artifacts in chip_evidence/.
chip-evidence:
	bash tools/run_chip_evidence.sh

mlflow:
	mlflow ui --backend-store-uri sqlite:///./mlflow.db

# --------------------------------------------------------------------------
# Kubernetes (kind) targets
# --------------------------------------------------------------------------

k8s-cluster:
	mkdir -p runs mlflow-k8s
	kind create cluster --name llmtrain-tpu --config k8s/kind-config.yaml

k8s-cluster-delete:
	kind delete cluster --name llmtrain-tpu

k8s-build:
	docker build -t llmtrain-tpu:dev -f k8s/Dockerfile .
	kind load docker-image llmtrain-tpu:dev --name llmtrain-tpu

k8s-train:
	kubectl apply -f k8s/infra.yaml -f k8s/configmap.yaml -f k8s/job.yaml

# Inference tier (docs/serving.md): Deployment + Service serving the
# training Job's committed checkpoint with continuous batching.
k8s-serve:
	kubectl apply -f k8s/infra.yaml -f k8s/configmap.yaml -f k8s/serve.yaml

# Multi-tenant fleet supervisor Job (docs/robustness.md "Fleet: many
# tenants, shared capacity"): one pod schedules the ConfigMap's fleet
# tenants onto an emulated device pool with preemption-aware scheduling.
k8s-fleet:
	kubectl apply -f k8s/infra.yaml -f k8s/configmap.yaml -f k8s/fleet.yaml

k8s-logs:
	kubectl logs -l app=llmtrain-tpu --all-containers --prefix -f

k8s-clean:
	kubectl delete -f k8s/job.yaml -f k8s/configmap.yaml -f k8s/infra.yaml \
		--ignore-not-found

k8s-full: k8s-cluster k8s-build k8s-train k8s-logs

k8s-e2e:
	bash k8s/test_e2e.sh
