# E2E assertion functions, factored out of test_e2e.sh so the fast test
# suite can validate the assertion LOGIC without docker/kind
# (tests/test_k8s_e2e_assertions.py runs them against a real run dir
# produced by a CLI train). test_e2e.sh sources this file; the functions
# use pass/fail hooks the caller defines (or the defaults below).
#
# Contract: every assert_* function prints PASS/FAIL lines via pass/fail
# and returns 0 iff all its assertions passed (FAILURES increments per
# fail, so callers may also sum over multiple calls).

FAILURES=${FAILURES:-0}

# Defaults only: a caller that defines pass/fail BEFORE sourcing keeps
# its own hooks (e.g. CI annotation emitters).
if ! declare -f pass >/dev/null; then
    pass() { printf '  PASS: %s\n' "$*"; }
fi
if ! declare -f fail >/dev/null; then
    fail() { printf '  FAIL: %s\n' "$*" >&2; FAILURES=$((FAILURES + 1)); }
fi

# rank-0 pod logs must show the training summary and the entrypoint's
# exec handoff (k8s/entrypoint.sh prints it before exec'ing python).
assert_rank0_logs() {
    local logs="$1" before="$FAILURES"
    grep -q "final_step" <<<"$logs" \
        && pass "rank-0 logs report final_step" \
        || fail "no final_step in rank-0 logs"
    grep -q "entrypoint: exec python" <<<"$logs" \
        && pass "entrypoint exec line present" \
        || fail "entrypoint exec line missing"
    [ "$FAILURES" -eq "$before" ]
}

# The run directory the hostPath PV surfaces must contain the artifact
# tree the Trainer writes (utils/run_dir.py layout).
assert_artifact_tree() {
    local run_dir="$1" before="$FAILURES" rel
    if [ -z "$run_dir" ] || [ ! -d "$run_dir" ]; then
        fail "no run directory (got '${run_dir:-}')"
        return 1
    fi
    pass "run dir $run_dir exists"
    for rel in checkpoints logs/train.log config.yaml meta.json; do
        [ -e "$run_dir/$rel" ] && pass "$rel present" || fail "$rel missing in $run_dir"
    done
    [ "$FAILURES" -eq "$before" ]
}

# The tracking DB must exist, be non-empty, and actually contain a
# finished run (a 0-byte or schema-only file means tracking silently
# recorded nothing — the bug class this assertion exists for).
assert_tracking_db() {
    local db="$1" before="$FAILURES"
    if [ ! -s "$db" ]; then
        fail "tracking db missing/empty: $db"
        return 1
    fi
    pass "tracking db non-empty"
    if command -v python >/dev/null 2>&1; then
        if python - "$db" <<'PY'
import sqlite3, sys
conn = sqlite3.connect(sys.argv[1])
try:
    n = conn.execute(
        "SELECT COUNT(*) FROM runs WHERE status IN ('FINISHED','RUNNING')"
    ).fetchone()[0]
except sqlite3.Error:
    sys.exit(1)
sys.exit(0 if n > 0 else 1)
PY
        then pass "tracking db has a recorded run"
        else fail "tracking db has no recorded run (or unreadable schema)"
        fi
    fi
    [ "$FAILURES" -eq "$before" ]
}

# The watchdog's progress beacon must have produced a heartbeat file (the
# livenessProbe contract, docs/k8s.md) and touched it no longer than
# max_age seconds ago — the same freshness computation the probe's exec
# performs in k8s/job.yaml.
assert_heartbeat() {
    local hb="$1" max_age="${2:-600}" before="$FAILURES" mtime age
    if [ ! -f "$hb" ]; then
        fail "heartbeat file missing: $hb"
        return 1
    fi
    pass "heartbeat file exists: $hb"
    mtime=$(stat -c %Y "$hb" 2>/dev/null || stat -f %m "$hb" 2>/dev/null || echo 0)
    age=$(( $(date +%s) - mtime ))
    if [ "$age" -lt "$max_age" ]; then
        pass "heartbeat fresh (${age}s old)"
    else
        fail "heartbeat stale (${age}s old >= ${max_age}s)"
    fi
    [ "$FAILURES" -eq "$before" ]
}

# Telemetry artifacts (docs/observability.md): every completed run must
# carry report.json/report.md plus a Perfetto-LOADABLE trace.json, a
# non-empty timeline.jsonl, and the Prometheus textfile snapshot with
# llmtrain_ gauges in it.
assert_telemetry_artifacts() {
    local run_dir="$1" before="$FAILURES" rel
    if [ -z "$run_dir" ] || [ ! -d "$run_dir" ]; then
        fail "no run directory for telemetry assertions (got '${run_dir:-}')"
        return 1
    fi
    for rel in report.json report.md telemetry/trace.json telemetry/timeline.jsonl \
               telemetry/metrics.prom; do
        [ -s "$run_dir/$rel" ] && pass "$rel present" || fail "$rel missing/empty in $run_dir"
    done
    # python3-only hosts (no python-is-python3) must still validate; a
    # host with NEITHER binary gets a visible skip line, not silence.
    local pybin
    pybin=$(command -v python3 || command -v python || true)
    if [ -z "$pybin" ]; then
        printf '  SKIP: no python/python3 on PATH; report/trace JSON not validated\n'
    else
        if "$pybin" - "$run_dir" <<'PY'
import json, sys, pathlib
run = pathlib.Path(sys.argv[1])
report = json.loads((run / "report.json").read_text())
assert report["loss"]["final"] is not None, "report has no final loss"
assert report["spans"], "report has no span breakdown"
trace = json.loads((run / "telemetry" / "trace.json").read_text())
assert isinstance(trace["traceEvents"], list) and trace["traceEvents"], "empty trace"
PY
        then pass "report.json + trace.json validate"
        else fail "report.json/trace.json failed validation"
        fi
    fi
    grep -q "llmtrain_" "$run_dir/telemetry/metrics.prom" 2>/dev/null \
        && pass "metrics.prom carries llmtrain_ gauges" \
        || fail "no llmtrain_ gauges in metrics.prom"
    [ "$FAILURES" -eq "$before" ]
}

# Atomic-commit contract (docs/robustness.md "Crash consistency"): the
# checkpoints dir must hold at least one committed step — a
# step_N.manifest.json whose listed files exist with the recorded sha-256
# — and the newest committed payload must be the one selection returns.
# This is what makes a mid-run pod kill survivable: anything without a
# manifest is an invisible partial commit.
assert_manifest() {
    local ckpt_dir="$1" before="$FAILURES"
    if [ -z "$ckpt_dir" ] || [ ! -d "$ckpt_dir" ]; then
        fail "no checkpoints directory for manifest assertions (got '${ckpt_dir:-}')"
        return 1
    fi
    local manifests
    manifests=$(ls "$ckpt_dir"/step_*.manifest.json 2>/dev/null | wc -l)
    if [ "$manifests" -ge 1 ]; then
        pass "checkpoint commit manifest present ($manifests)"
    else
        fail "no step_*.manifest.json in $ckpt_dir"
        return 1
    fi
    local pybin
    pybin=$(command -v python3 || command -v python || true)
    if [ -z "$pybin" ]; then
        printf '  SKIP: no python/python3 on PATH; manifest digests not validated\n'
    else
        if "$pybin" - "$ckpt_dir" <<'PY'
import hashlib, json, pathlib, sys
ckpts = pathlib.Path(sys.argv[1])
manifests = sorted(ckpts.glob("step_*.manifest.json"))
assert manifests, "no manifests"
newest = json.loads(manifests[-1].read_text())
assert newest.get("files"), "manifest lists no files"
for entry in newest["files"]:
    blob = (ckpts / entry["name"]).read_bytes()
    assert len(blob) == entry["bytes"], f"{entry['name']}: size mismatch"
    if entry.get("sha256"):
        digest = hashlib.sha256(blob).hexdigest()
        assert digest == entry["sha256"], f"{entry['name']}: sha mismatch"
PY
        then pass "newest manifest's files verify (sizes + sha-256)"
        else fail "newest manifest failed verification"
        fi
    fi
    [ "$FAILURES" -eq "$before" ]
}

# Serving SLO contract (docs/serving.md): a report.json produced by the
# load harness must carry the serving block — p50/p95/p99 TTFT and
# per-token latency, >= 2 sequences concurrently in flight (continuous
# batching actually batched), and a decode-loop compile count within the
# configured bucket budget.
assert_serving_report() {
    local report="$1" before="$FAILURES"
    if [ ! -s "$report" ]; then
        fail "no serving report at ${report:-<unset>}"
        return 1
    fi
    pass "serving report present"
    local pybin
    pybin=$(command -v python3 || command -v python || true)
    if [ -z "$pybin" ]; then
        printf '  SKIP: no python/python3 on PATH; serving block not validated\n'
    else
        if "$pybin" - "$report" <<'PY'
import json, sys
report = json.loads(open(sys.argv[1]).read())
serving = report["serving"]
for metric in ("ttft_ms", "per_token_ms"):
    for q in ("p50", "p95", "p99"):
        assert serving["slo"][metric][q] is not None, f"{metric}.{q} missing"
assert serving["requests"]["completed"] >= 1, "no completed requests"
assert serving["requests"]["failed"] == 0, "failed requests in the run"
assert serving["occupancy"]["peak"] >= 2, (
    f"peak occupancy {serving['occupancy']['peak']} < 2: never batched"
)
assert serving["compile"]["within_budget"] is True, "compile budget exceeded"
assert serving["throughput"]["tokens_per_sec"], "no throughput recorded"
PY
        then pass "serving block: SLO percentiles + occupancy>=2 + compile budget"
        else fail "serving block failed validation in $report"
        fi
    fi
    [ "$FAILURES" -eq "$before" ]
}

# A captured scrape of the INFERENCE server's /metrics must carry the
# llmtrain_serve_* family (queue depth, occupancy, KV-pool utilization,
# requests counter) — the serving observability surface.
assert_serving_scrape() {
    local scrape_file="$1" before="$FAILURES" metric
    if [ ! -s "$scrape_file" ]; then
        fail "no captured serving scrape at ${scrape_file:-<unset>}"
        return 1
    fi
    pass "serving scrape captured"
    for metric in llmtrain_serve_requests_total llmtrain_serve_queue_depth \
                  llmtrain_serve_batch_occupancy llmtrain_serve_kv_pool_utilization; do
        grep -q "^$metric" "$scrape_file" \
            && pass "$metric present" \
            || fail "$metric missing from the serving scrape"
    done
    [ "$FAILURES" -eq "$before" ]
}

# A captured /metrics scrape (file) must carry llmtrain_ gauges and the
# run-info labels — proves a machine could consume the run's metrics over
# HTTP while it was training.
assert_prometheus_scrape() {
    local scrape_file="$1" before="$FAILURES"
    if [ ! -s "$scrape_file" ]; then
        fail "no captured prometheus scrape at ${scrape_file:-<unset>}"
        return 1
    fi
    pass "prometheus scrape captured"
    grep -q "^llmtrain_" "$scrape_file" \
        && pass "scrape carries llmtrain_ gauges" \
        || fail "no llmtrain_ gauges in the scrape"
    grep -q "llmtrain_run_info" "$scrape_file" \
        && pass "scrape carries llmtrain_run_info" \
        || fail "llmtrain_run_info missing from the scrape"
    [ "$FAILURES" -eq "$before" ]
}
